"""Headline benchmark: robust aggregation throughput at 1M-dim on TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline value: Multi-Krum grads/sec on a 64 x 1,048,576 gradient matrix
(the BASELINE.json north-star config: "robust-agg grads/sec (Krum,
CW-Median) at 1M-dim").

``vs_baseline``: geometric-mean speedup over the reference's best published
ActorPool latencies on the two matched workloads it does publish
(Multi-Krum 80x65,536 f=20 q=12 -> 26.30 ms; CW-Median 64x65,536 ->
37 ms; BASELINE.md / reference benchmarks/README.md:16-17).
"""

from __future__ import annotations

import json
import os
from functools import partial

import jax
import jax.numpy as jnp

from byzpy_tpu.ops import robust

# Persistent XLA compile cache: a prior run (e.g. the recovery watcher's
# rerun bundle) leaves the driver's bench invocation starting warm — the
# first 1M-dim compile otherwise costs tens of seconds through the
# tunnel. Same mechanism the test conftest uses; override/disable via
# JAX_COMPILATION_CACHE_DIR.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def timed(fn, *args, warmup: int = 2, repeat: int = 20) -> float:
    """Mean wall seconds per call; tunnel-hardened (see
    ``byzpy_tpu.utils.metrics.timed_call_s``)."""
    from byzpy_tpu.utils.metrics import timed_call_s

    return timed_call_s(fn, *args, warmup=warmup, repeat=repeat)


def grads(key, n, d, dtype=jnp.float32):
    return jax.random.normal(key, (n, d), dtype)


def main() -> None:
    # Bounded device probe first: a dead accelerator tunnel otherwise hangs
    # the whole bench. On failure, emit an honest machine-readable line
    # (value null, the outage named, and the last committed measurement
    # for context — benchmarks/RESULTS.md has the full methodology).
    from byzpy_tpu.cli import _devices_with_timeout

    try:
        _devices_with_timeout(jax, timeout_s=60.0)
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        print(json.dumps({
            "metric": "multi_krum_64x1M_stream_grads_per_sec",
            "value": None,
            "unit": "grads/sec",
            "vs_baseline": None,
            "error": f"device unavailable: {type(exc).__name__}: {exc}",
            "last_measured_in_session": {
                "value": 81704.0, "bf16": 150281.0, "stream_K": 32,
                "provenance": "benchmarks/results/overrides.jsonl "
                              "(round-4 driver-session tunnel measurement, "
                              "post phase-parked output maps)",
            },
            "cpu_measured_this_round": {
                "robust_learning_mean_vs_trimmed_under_signflip": [0.087, 0.915],
                "provenance": "benchmarks/ROBUST_LEARNING.md + BREAKDOWN.md "
                              "(real-data accuracy studies, CPU mesh)",
            },
        }))
        return

    key = jax.random.PRNGKey(0)

    # Headline: Krum at 1M-dim (north-star config), measured as a stream of
    # K rounds per dispatch — the shape a real training loop has; a
    # standalone dispatch pays the full host->device launch round-trip,
    # comparable to (or larger than) the whole aggregate. The stream runs
    # as ONE fused Pallas launch (selection_mean_stream_pallas via
    # multi_krum_stream): 2K HBM sweeps, no per-round slice copies.
    K = 32
    xs_1m = jax.random.normal(key, (K, 64, 1_048_576), jnp.float32)
    stream = jax.jit(partial(robust.multi_krum_stream, f=8, q=12))
    stream_kernel = "selection_mean_stream_pallas"
    try:
        t_krum_1m = timed(stream, xs_1m, repeat=40) / K
    except Exception:
        # never leave the round without a headline: fall back to the XLA
        # scan stream if the fused kernel fails to compile/run on this
        # libtpu (the result is labeled so the regression is visible)
        stream_kernel = "xla_scan_fallback"
        agg = partial(robust.multi_krum, f=8, q=12)
        stream = jax.jit(partial(robust.aggregate_stream, agg))
        t_krum_1m = timed(stream, xs_1m, repeat=40) / K
    value = 64 / t_krum_1m  # gradients aggregated per second

    # bf16 variant (halves the two-pass HBM traffic; f32 accumulation)
    t_bf16 = timed(stream, xs_1m.astype(jnp.bfloat16), repeat=40) / K

    # Matched reference workloads for vs_baseline.
    x_krum = grads(key, 80, 65_536)
    t_krum = timed(jax.jit(partial(robust.multi_krum, f=20, q=12)), x_krum)
    x_med = grads(key, 64, 65_536)
    t_med = timed(jax.jit(robust.coordinate_median), x_med)

    ref_best = {"krum": 26.30e-3, "median": 37e-3}  # BASELINE.md best-pool
    speedup = ((ref_best["krum"] / t_krum) * (ref_best["median"] / t_med)) ** 0.5

    # Single-dispatch latency for comparability with round-1's per-call
    # metric (BENCH_r01.json) and BASELINE.md's per-call numbers.
    t_single = timed(jax.jit(partial(robust.multi_krum, f=8, q=12)), xs_1m[0])

    print(json.dumps({
        "metric": "multi_krum_64x1M_stream_grads_per_sec",
        "value": round(value, 2),
        "unit": "grads/sec",
        "vs_baseline": round(speedup, 2),
        "stream_K": K,
        "stream_kernel": stream_kernel,
        "bf16_stream_grads_per_sec": round(64 / t_bf16, 2),
        "single_dispatch_grads_per_sec": round(64 / t_single, 2),
    }))


if __name__ == "__main__":
    main()

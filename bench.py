"""Headline benchmark: robust aggregation throughput at 1M-dim on TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline value: Multi-Krum grads/sec on a 64 x 1,048,576 gradient matrix
(the BASELINE.json north-star config: "robust-agg grads/sec (Krum,
CW-Median) at 1M-dim").

``vs_baseline``: geometric-mean speedup over the reference's best published
ActorPool latencies on the two matched workloads it does publish
(Multi-Krum 80x65,536 f=20 q=12 -> 26.30 ms; CW-Median 64x65,536 ->
37 ms; BASELINE.md / reference benchmarks/README.md:16-17).
"""

from __future__ import annotations

import json
import os
from functools import partial

import jax
import jax.numpy as jnp

from byzpy_tpu.ops import robust

# Persistent XLA compile cache: a prior run (e.g. the recovery watcher's
# rerun bundle) leaves the driver's bench invocation starting warm — the
# first 1M-dim compile otherwise costs tens of seconds through the
# tunnel. Same mechanism the test conftest uses; override/disable via
# JAX_COMPILATION_CACHE_DIR.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def timed(fn, *args, warmup: int = 2, repeat: int = 20) -> float:
    """Mean wall seconds per call; tunnel-hardened (see
    ``byzpy_tpu.utils.metrics.timed_call_s``)."""
    from byzpy_tpu.utils.metrics import timed_call_s

    return timed_call_s(fn, *args, warmup=warmup, repeat=repeat)


def grads(key, n, d, dtype=jnp.float32):
    return jax.random.normal(key, (n, d), dtype)


def main() -> None:
    # Bounded device probe first: a dead accelerator tunnel otherwise hangs
    # the whole bench. On failure, emit an honest machine-readable line
    # (value null, the outage named, and the last committed measurement
    # for context — benchmarks/RESULTS.md has the full methodology).
    from byzpy_tpu.cli import _devices_with_timeout

    try:
        _devices_with_timeout(jax, timeout_s=60.0)
        # a CPU-only device set means the accelerator plugin failed fast
        # and jax fell back — the headline is an ON-CHIP number, and
        # grinding the 64x1M stream on the host for many minutes would
        # only produce a number the metric does not mean. Same honest
        # null as a hung tunnel.
        if jax.default_backend() == "cpu":
            raise RuntimeError(
                "accelerator platform absent (jax fell back to cpu)"
            )
    except Exception as exc:  # noqa: BLE001 — report, don't crash
        print(json.dumps({
            "metric": "multi_krum_64x1M_stream_grads_per_sec",
            "value": None,
            "unit": "grads/sec",
            "vs_baseline": None,
            "error": f"device unavailable: {type(exc).__name__}: {exc}",
            "last_measured_in_session": {
                "value": 81704.0, "bf16": 150281.0, "stream_K": 32,
                "provenance": "benchmarks/results/overrides.jsonl "
                              "(round-4 driver-session tunnel measurement, "
                              "post phase-parked output maps)",
            },
            "cpu_measured_this_round": {
                "meamed_64x65536_cpu_speedup": 2.4,
                "multi_krum_80x65536_cpu_speedup": 1.3,
                "provenance": "benchmarks/results/hotpath_cpu.jsonl + "
                              "grid_cpu.jsonl + roofline_cpu.jsonl "
                              "(int32-key sort + conditional-mask "
                              "selection, JAX_PLATFORMS=cpu; see "
                              "benchmarks/RESULTS.md §CPU grid)",
            },
            "second_metric": {
                "metric": "ps_mnist_trimmed_mean_steps_per_sec",
                "value": None,
                "unit": "steps/sec",
                "vs_baseline": None,
                "error": "device unavailable (same outage as headline)",
            },
            # the serving tier runs on a CPU mesh by design — it reports
            # a real number straight through an accelerator outage
            "serving_metric": _serving_metric(),
        }))
        return

    key = jax.random.PRNGKey(0)

    # Headline: Krum at 1M-dim (north-star config), measured as a stream of
    # K rounds per dispatch — the shape a real training loop has; a
    # standalone dispatch pays the full host->device launch round-trip,
    # comparable to (or larger than) the whole aggregate. The stream runs
    # as ONE fused Pallas launch (selection_mean_stream_pallas via
    # multi_krum_stream): 2K HBM sweeps, no per-round slice copies.
    K = 32
    xs_1m = jax.random.normal(key, (K, 64, 1_048_576), jnp.float32)
    stream = jax.jit(partial(robust.multi_krum_stream, f=8, q=12))
    stream_kernel = "selection_mean_stream_pallas"
    try:
        t_krum_1m = timed(stream, xs_1m, repeat=40) / K
    except Exception:
        # never leave the round without a headline: fall back to the XLA
        # scan stream if the fused kernel fails to compile/run on this
        # libtpu (the result is labeled so the regression is visible)
        stream_kernel = "xla_scan_fallback"
        agg = partial(robust.multi_krum, f=8, q=12)
        stream = jax.jit(partial(robust.aggregate_stream, agg))
        t_krum_1m = timed(stream, xs_1m, repeat=40) / K
    value = 64 / t_krum_1m  # gradients aggregated per second

    # bf16 variant (halves the two-pass HBM traffic; f32 accumulation)
    t_bf16 = timed(stream, xs_1m.astype(jnp.bfloat16), repeat=40) / K

    # Matched reference workloads for vs_baseline.
    x_krum = grads(key, 80, 65_536)
    t_krum = timed(jax.jit(partial(robust.multi_krum, f=20, q=12)), x_krum)
    x_med = grads(key, 64, 65_536)
    t_med = timed(jax.jit(robust.coordinate_median), x_med)

    ref_best = {"krum": 26.30e-3, "median": 37e-3}  # BASELINE.md best-pool
    speedup = ((ref_best["krum"] / t_krum) * (ref_best["median"] / t_med)) ** 0.5

    # Single-dispatch latency for comparability with round-1's per-call
    # metric (BENCH_r01.json) and BASELINE.md's per-call numbers.
    t_single = timed(jax.jit(partial(robust.multi_krum, f=8, q=12)), xs_1m[0])

    # Achieved-vs-roofline fraction for the headline (the ROADMAP "as
    # fast as the hardware allows" scorecard; full per-aggregator grid:
    # `python -m byzpy_tpu.profiling`).
    roofline = None
    try:
        from byzpy_tpu.profiling import detect_hardware, roofline_s

        # calibrate on CPU (same policy as profiler.profile_call): the
        # static cpu-default spec would score against invented limits
        spec = detect_hardware(calibrate=jax.default_backend() == "cpu")
        n, d = 64, 1 << 20
        floor_s = roofline_s(
            2.0 * n * n * d,  # the Gram contraction's FLOPs
            n * d * 4 + d * 4,  # read the round once, write the aggregate
            dtype="float32", spec=spec,
        )
        roofline = {
            "achieved_fraction": round(floor_s / t_krum_1m, 4),
            "roofline_ms_per_round": round(floor_s * 1e3, 4),
            "hardware": spec.name,
        }
    except Exception:  # noqa: BLE001 — the headline must not die on this
        pass

    print(json.dumps({
        "metric": "multi_krum_64x1M_stream_grads_per_sec",
        "value": round(value, 2),
        "unit": "grads/sec",
        "vs_baseline": round(speedup, 2),
        "stream_K": K,
        "stream_kernel": stream_kernel,
        "bf16_stream_grads_per_sec": round(64 / t_bf16, 2),
        "single_dispatch_grads_per_sec": round(64 / t_single, 2),
        "roofline": roofline,
        "second_metric": _ps_steps_metric(),
        "serving_metric": _serving_metric(),
    }))


def _serving_metric() -> dict:
    """Serving-tier metric (ISSUE 6): sustained submissions/sec into the
    ragged-cohort front end with a 10k-simulated-client swarm on a CPU
    mesh, p99 round latency, and the bucketed-vs-naive jit-cache win
    (``benchmarks/serving_bench.py`` in a subprocess — CPU pinned, so
    the accelerator backend of this process stays untouched and the
    number survives a tunnel outage)."""
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(here, "benchmarks", "serving_bench.py"),
                "--duration-s", "4.0", "--bucket-rounds", "16",
            ],
            capture_output=True, text=True, timeout=560, env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"serving bench exited {proc.returncode}: "
                f"{proc.stderr[-300:]}"
            )
        headline = None
        for line in proc.stdout.strip().splitlines():
            row = json.loads(line)
            if row.get("lane") == "headline":
                headline = row
        if headline is None:
            raise RuntimeError("no headline lane in serving bench output")
        return {
            "metric": "serving_submissions_per_sec",
            "value": headline["value"],
            "unit": "submissions/sec",
            "clients": headline["clients"],
            "p99_round_latency_ms": headline["p99_round_latency_ms"],
            "rounds": headline["rounds"],
            "bucketed_vs_naive_speedup": headline[
                "bucketed_vs_naive_speedup"
            ],
            "config": "trimmed-mean f=2, d=1024, window 10ms, cohort cap "
                      "256, bounded queue 4096, CPU mesh "
                      "(benchmarks/serving_bench.py)",
        }
    except Exception as exc:  # noqa: BLE001 — report, keep the headline
        return {
            "metric": "serving_submissions_per_sec",
            "value": None,
            "unit": "submissions/sec",
            "error": f"{type(exc).__name__}: {exc}",
        }


def _ps_steps_metric() -> dict:
    """BASELINE.json's second north-star metric: PS steps/sec (MNIST MLP,
    trimmed mean, sign-flip — BASELINE config #3), measured single-chip
    on the fused SPMD round, with the HLO-derived 8→128-chip weak-scaling
    projection attached (``benchmarks/ps_scaling_probe.py`` runs the
    collective accounting on a CPU-mesh subprocess so this process keeps
    its accelerator backend untouched)."""
    import subprocess
    import sys

    from byzpy_tpu.models import mnist_mlp, synthetic_classification
    from byzpy_tpu.ops import attack_ops, robust as robust_ops
    from byzpy_tpu.parallel.ps import PSStepConfig, jit_ps_train_step

    try:
        n, n_byz, batch = 8, 2, 64
        bundle = mnist_mlp()
        x, y = synthetic_classification(n_samples=n * batch, seed=3)
        xs = x.reshape(n, batch, 28, 28, 1)
        ys = y.reshape(n, batch)
        cfg = PSStepConfig(n_nodes=n, n_byzantine=n_byz)
        step, opt0 = jit_ps_train_step(
            bundle,
            lambda m: robust_ops.trimmed_mean(m, f=n_byz),
            cfg,
            attack=lambda honest, key: attack_ops.sign_flip(
                jnp.mean(honest, axis=0)
            ),
            donate=False,
        )
        key = jax.random.PRNGKey(0)
        t_round = timed(step, bundle.params, opt0, xs, ys, key, repeat=30)
        steps_per_sec = 1.0 / t_round
    except Exception as exc:  # noqa: BLE001 — report, keep the headline
        return {
            "metric": "ps_mnist_trimmed_mean_steps_per_sec",
            "value": None,
            "unit": "steps/sec",
            "vs_baseline": None,
            "error": f"{type(exc).__name__}: {exc}",
        }

    projection = None
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # probe pins cpu itself
        # the chip admits ONE process: a child that registers the
        # accelerator plugin while this process holds the device can
        # deadlock at import (same guard ProcessContext applies)
        env["PALLAS_AXON_POOL_IPS"] = ""
        probe = subprocess.run(
            [sys.executable, os.path.join(here, "benchmarks", "ps_scaling_probe.py")],
            capture_output=True, text=True, timeout=600, env=env,
        )
        if probe.returncode != 0:
            raise RuntimeError(
                f"probe exited {probe.returncode}: {probe.stderr[-400:]}"
            )
        info = json.loads(probe.stdout.strip().splitlines()[-1])
        comm = {int(k): v for k, v in info["comm_seconds_per_round"].items()}
        # Weak scaling: the measured 1-chip round computes all n nodes'
        # gradients serially; on n>=8 chips each chip computes one node's
        # share, so per-chip compute is t_measured/8 and the round time
        # adds the (pessimistic, unoverlapped) HLO-derived comm term.
        compute_s = t_round / 8.0
        eff = {
            nn: compute_s / (compute_s + c) for nn, c in sorted(comm.items())
        }
        projection = {
            "hlo_wire_bytes_per_device_n8": info["hlo_wire_bytes_per_device_n8"],
            "per_opcode_bytes_n8": info["per_opcode_bytes_n8"],
            "assumptions": info["assumptions"],
            "projected_steps_per_sec": {
                str(nn): round(8.0 * steps_per_sec * e, 2)
                for nn, e in eff.items()
            },
            "efficiency_vs_linear": {
                str(nn): round(e, 4) for nn, e in eff.items()
            },
            "retention_8_to_128": round(eff[128] / eff[8], 4),
        }
    except Exception as exc:  # noqa: BLE001 — projection is best-effort
        projection = {"error": f"{type(exc).__name__}: {exc}"}

    return {
        "metric": "ps_mnist_trimmed_mean_steps_per_sec",
        "value": round(steps_per_sec, 2),
        "unit": "steps/sec",
        # ref: actor-mode PS MNIST round, best measured 42 ms/round
        # (BASELINE.md; reference benchmarks) -> 23.8 steps/sec
        "vs_baseline": round(steps_per_sec / (1.0 / 42e-3), 2),
        "round_ms": round(t_round * 1e3, 3),
        "config": "MNIST MLP 784-128-10, n=8 nodes (2 byzantine), "
                  "trimmed-mean f=2, sign-flip, batch 64/node, "
                  "fused SPMD round on one chip",
        "scaling_8_to_128": projection,
    }


if __name__ == "__main__":
    main()

"""Shared plotting helpers for the benchmarks tree (Agg backend + jsonl IO)."""

import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: F401,E402 — re-exported for callers

HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS = os.path.join(HERE, "results")


def load_jsonl(path):
    """All rows of a jsonl file, skipping blanks and '#' comment lines."""
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            rows.append(json.loads(line))
    return rows

"""Shared timing harness for the benchmark scripts."""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict

import jax


def timed_ms(fn: Callable, *args: Any, warmup: int = 2, repeat: int = 20) -> float:
    """Mean wall milliseconds per call (see
    :func:`byzpy_tpu.utils.metrics.timed_call_s` for the tunnel-measurement
    hazards this defends against)."""
    from byzpy_tpu.utils.metrics import timed_call_s

    return timed_call_s(fn, *args, warmup=warmup, repeat=repeat) * 1e3


def report(name: str, ms: float, **extra: Any) -> Dict[str, Any]:
    row = {"workload": name, "ms": round(ms, 3), **extra}
    print(json.dumps(row))
    print(f"{name:48s} {ms:10.3f} ms  {extra or ''}", file=sys.stderr)
    return row


def force_cpu_platform(n_devices: int = 1) -> None:
    """Rebuild jax on the CPU platform in-process (optionally with virtual
    devices). Env vars are inoperative once a platform is pre-registered
    (e.g. by a sitecustomize), so the switch goes through jax.config +
    clear_backends. One copy for every benchmark script;
    ``__graft_entry__._ensure_devices`` stays self-contained by design
    (the driver runs it without this package on the path)."""
    from jax.extend import backend as jeb

    jax.config.update("jax_platforms", "cpu")
    jeb.clear_backends()
    if n_devices > 1:
        jax.config.update("jax_num_cpu_devices", n_devices)
        jeb.clear_backends()


__all__ = ["timed_ms", "report", "force_cpu_platform"]

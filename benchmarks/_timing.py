"""Shared timing harness for the benchmark scripts."""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict

import jax


def timed_ms(fn: Callable, *args: Any, warmup: int = 2, repeat: int = 20) -> float:
    """Mean wall milliseconds per call (see
    :func:`byzpy_tpu.utils.metrics.timed_call_s` for the tunnel-measurement
    hazards this defends against)."""
    from byzpy_tpu.utils.metrics import timed_call_s

    return timed_call_s(fn, *args, warmup=warmup, repeat=repeat) * 1e3


def report(name: str, ms: float, **extra: Any) -> Dict[str, Any]:
    row = {"workload": name, "ms": round(ms, 3), **extra}
    print(json.dumps(row))
    print(f"{name:48s} {ms:10.3f} ms  {extra or ''}", file=sys.stderr)
    return row


__all__ = ["timed_ms", "report"]

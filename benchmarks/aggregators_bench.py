"""Aggregator latency grid (ref: ``byzpy/benchmarks/README.md:10-30``).

Reference workloads (their best pooled CPU latencies in BASELINE.md) plus
the 1M-dim north-star shapes. One JSON line per row.
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

from functools import partial

import jax
import jax.numpy as jnp

from _timing import report, timed_ms
from byzpy_tpu.aggregators import MinimumDiameterAveraging
from byzpy_tpu.ops import robust


def grads(n, d, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (n, d), jnp.float32)


_mda_op = MinimumDiameterAveraging(f=5)


def mda(x):
    return _mda_op.aggregate(x)


def main():
    # the reference's published grid
    report("cw_median_64x65536", timed_ms(jax.jit(robust.coordinate_median), grads(64, 65536)),
           ref_best_ms=37.0)
    report("cw_trimmed_mean_64x65536",
           timed_ms(jax.jit(partial(robust.trimmed_mean, f=15)), grads(64, 65536)),
           ref_best_ms=43.0)
    report("multi_krum_80x65536_f20_q12",
           timed_ms(jax.jit(partial(robust.multi_krum, f=20, q=12)), grads(80, 65536)),
           ref_best_ms=26.30)
    report("geometric_median_64x65536",
           timed_ms(jax.jit(partial(robust.geometric_median, max_iter=64)), grads(64, 65536)))
    report("centered_clipping_64x65536",
           timed_ms(jax.jit(partial(robust.centered_clipping, c_tau=10.0, M=10)), grads(64, 65536)))
    report("cge_64x65536", timed_ms(jax.jit(partial(robust.cge, f=15)), grads(64, 65536)))
    report("monna_64x65536", timed_ms(jax.jit(partial(robust.monna, f=15)), grads(64, 65536)))
    report("mda_30x2048_f5", timed_ms(mda, grads(30, 2048)))

    # north-star 1M-dim shapes
    report("cw_median_64x1M", timed_ms(jax.jit(robust.coordinate_median), grads(64, 1 << 20)))
    report("multi_krum_64x1M_f8_q12",
           timed_ms(jax.jit(partial(robust.multi_krum, f=8, q=12)), grads(64, 1 << 20)))


if __name__ == "__main__":
    main()

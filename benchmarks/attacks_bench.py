"""Attack crafting latency (ref: ``byzpy/benchmarks/pytorch/*_actor_pool.py``
attack sweeps): time to produce one malicious vector from 64×65,536 honest
gradients."""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

from functools import partial

import jax
import jax.numpy as jnp

from _timing import report, timed_ms
from byzpy_tpu.ops import attack_ops


def main():
    honest = jax.random.normal(jax.random.PRNGKey(0), (64, 65536), jnp.float32)
    base = honest[0]
    key = jax.random.PRNGKey(1)

    report("sign_flip_64x65536",
           timed_ms(jax.jit(partial(attack_ops.sign_flip, scale=-1.0)), base))
    report("empire_64x65536",
           timed_ms(jax.jit(partial(attack_ops.empire, scale=-1.0)), honest))
    report("little_64x65536",
           timed_ms(jax.jit(partial(attack_ops.little, f=15, n_total=64)), honest))
    report("gaussian_64x65536",
           timed_ms(jax.jit(lambda k: attack_ops.gaussian(k, (65536,))), key))
    report("mimic_64x65536",
           timed_ms(jax.jit(partial(attack_ops.mimic, epsilon=0)), honest))


if __name__ == "__main__":
    main()

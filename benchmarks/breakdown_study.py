"""Breakdown study: accuracy as the byzantine fraction grows.

Robust aggregators have theoretical breakdown points (trimmed-mean/median
at f < n/2, Krum at f < (n-2)/2, ...); this study shows where they
actually stop rescuing training on real data: sign-flip colluders at
f = 0..3 of n = 8 nodes, final held-out accuracy per (aggregator, f).

Writes ``benchmarks/BREAKDOWN.md``. Reference analogue: the ByzFL sweeps
vary the byzantine count the same way (``benchmarks/byzfl/*_compare.py``).

Run: ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu python benchmarks/breakdown_study.py --write``
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rounds", type=int, default=200)
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--max-byzantine", type=int, default=3)
    parser.add_argument("--attack", default="sign_flip")
    parser.add_argument(
        "--aggregators", default="mean,median,trimmed_mean,multi_krum"
    )
    parser.add_argument("--write", action="store_true")
    args = parser.parse_args()

    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    from functools import partial

    from byzpy_tpu.models.data import load_digits_dataset
    from byzpy_tpu.models.nets import digits_mlp
    from byzpy_tpu.utils.robust_study import StudyConfig, run_cell

    aggs = args.aggregators.split(",")
    data = load_digits_dataset(seed=0)
    rows = {}
    for f in range(0, args.max_byzantine + 1):
        cfg = StudyConfig(
            n_nodes=args.nodes,
            n_byzantine=f,
            rounds=args.rounds,
            eval_every=args.rounds,
        )
        for agg in aggs:
            cell = run_cell(
                partial(digits_mlp, seed=0), data, agg, args.attack, cfg
            )
            rows[(agg, f)] = cell.final_accuracy
            print(f"f={f} {agg:<14} acc={cell.final_accuracy:.3f}", flush=True)

    import jax

    lines = [
        "# Breakdown study: accuracy vs byzantine fraction",
        "",
        f"Device: `{jax.devices()[0]}`",
        "",
        f"Real digits, {args.nodes} nodes, colluding **{args.attack}**",
        f"attackers, {args.rounds} rounds; cells = final held-out accuracy",
        "(f = 0 is the clean baseline). Aggregators trim/select with the",
        "TRUE f — this measures the algorithm at its declared operating",
        "point, not mis-specification.",
        "",
        "| aggregator | " + " | ".join(f"f={f}" for f in range(args.max_byzantine + 1)) + " |",
        "|---" * (args.max_byzantine + 2) + "|",
    ]
    for agg in aggs:
        cells = " | ".join(
            f"{rows[(agg, f)]:.3f}" for f in range(args.max_byzantine + 1)
        )
        lines.append(f"| {agg} | {cells} |")
    lines += [
        "",
        "Reproduce: `python benchmarks/breakdown_study.py --write`;",
        "plot: `python benchmarks/plot_robust_learning.py` ->",
        "![breakdown](results/breakdown.png)",
        "",
    ]
    table = "\n".join(lines)
    print("\n" + table)
    if args.write:
        import json

        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BREAKDOWN.md"), "w") as fh:
            fh.write(table)
        os.makedirs(os.path.join(here, "results"), exist_ok=True)
        with open(os.path.join(here, "results", "breakdown.jsonl"), "a") as fh:
            for (agg, f), acc in sorted(rows.items()):
                fh.write(json.dumps({
                    "aggregator": agg, "n_byzantine": f,
                    "final_accuracy": round(acc, 4),
                    "attack": args.attack, "rounds": args.rounds,
                    "n_nodes": args.nodes, "device": str(jax.devices()[0]),
                }) + "\n")
        print("wrote BREAKDOWN.md + results/breakdown.jsonl")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

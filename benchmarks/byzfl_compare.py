"""Live ByzFL comparison harness (one file for the whole grid).

The reference regenerates its ByzFL column by RUNNING ByzFL in-process,
one script per operator (``/root/reference/benchmarks/byzfl/*_compare.py``);
`BASELINE.md` only cites its published table. This harness makes the
column locally reproducible: it times the ByzFL implementation of every
grid workload that ByzFL ships (same shapes/hyper-parameters as
``benchmarks/RESULTS.md`` and the reference defaults), appending rows to
``results/byzfl_local.jsonl`` with provenance.

ByzFL is an OPTIONAL dependency (torch-based, CPU here). When it is not
installed the harness exits 0 with a machine-readable skip line — CI and
the bench driver treat that as "column unavailable", never as a failure.

Run: ``python benchmarks/byzfl_compare.py [--repeat N] [--budget SEC]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))

# (label, module, class, ctor kwargs, n, dim)
# Shapes/params mirror the reference harness defaults and the RESULTS.md
# grid rows; labels match RESULTS.md so the columns line up.
WORKLOADS = [
    ("multi_krum_80x65536_f20", "byzfl.aggregators.aggregators", "MultiKrum",
     {"f": 20}, 80, 65_536),
    ("cwtm_64x65536_f8", "byzfl.aggregators.aggregators", "TrMean",
     {"f": 8}, 64, 65_536),
    ("meamed_64x65536_f8", "byzfl.aggregators.aggregators", "Meamed",
     {"f": 8}, 64, 65_536),
    ("monna_64x65536_f8", "byzfl.aggregators.aggregators", "MoNNA",
     {"f": 8, "idx": 0}, 64, 65_536),
    ("caf_64x65536_f8", "byzfl.aggregators.aggregators", "CAF",
     {"f": 8}, 64, 65_536),
    ("centered_clipping_64x65536", "byzfl.aggregators.aggregators",
     "CenteredClipping", {"m": None, "L": 10, "tau": 0.1}, 64, 65_536),
    ("mda_18x2048_f6", "byzfl.aggregators.aggregators", "MDA",
     {"f": 6}, 18, 2_048),
    ("smea_12x1024_f3", "byzfl.aggregators.aggregators", "SMEA",
     {"f": 3}, 12, 1_024),
    ("nnm_196x4096_f32", "byzfl.aggregators.preaggregators", "NNM",
     {"f": 32}, 196, 4_096),
    ("arc_256x65536_f8", "byzfl.aggregators.preaggregators", "ARC",
     {"f": 8}, 256, 65_536),
    ("clipping_256x65536_tau2", "byzfl.aggregators.preaggregators",
     "Clipping", {"c": 2.0}, 256, 65_536),
    ("bucketing_512x16384_s32", "byzfl.aggregators.preaggregators",
     "Bucketing", {"s": 32}, 512, 16_384),
    ("little_96x65536", "byzfl.attacks.attacks", "ALittleIsEnough",
     {}, 96, 65_536),
    ("gaussian_64x65536", "byzfl.attacks.attacks", "Gaussian",
     {"mu": 0.0, "sigma": 1.0}, 64, 65_536),
    ("inf_64x65536", "byzfl.attacks.attacks", "Inf", {}, 64, 65_536),
    ("ipm_64x65536_tau2", "byzfl.attacks.attacks",
     "InnerProductManipulation", {"tau": 2.0}, 64, 65_536),
    ("mimic_64x65536", "byzfl.attacks.attacks", "Mimic",
     {"epsilon": 0}, 64, 65_536),
]


def _load(module: str, name: str):
    import importlib

    return getattr(importlib.import_module(module), name)


def _time_row(op, grads, *, repeat: int, budget: float) -> dict:
    t0 = time.perf_counter()
    op(grads)  # warmup / correctness touch
    first = time.perf_counter() - t0
    if first > budget:
        return {"status": "timeout", "first_call_s": round(first, 3)}
    times = []
    for _ in range(repeat):
        if time.perf_counter() - t0 > budget:
            break
        s = time.perf_counter()
        op(grads)
        times.append(time.perf_counter() - s)
    if not times:
        times = [first]
    return {"status": "ok", "ms": round(1e3 * sum(times) / len(times), 2),
            "reps": len(times)}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--repeat", type=int, default=3)
    parser.add_argument("--budget", type=float, default=120.0,
                        help="wall-clock budget per row, seconds")
    parser.add_argument("--rows", nargs="*", default=None,
                        help="subset of row labels to run")
    args = parser.parse_args()

    try:
        import byzfl  # noqa: F401
    except ImportError:
        print(json.dumps({
            "status": "skipped",
            "reason": "byzfl not installed (optional dependency); "
                      "pip install byzfl to regenerate the column",
        }))
        return 0

    import torch

    out_path = os.path.join(HERE, "results", "byzfl_local.jsonl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    rows = 0
    with open(out_path, "a") as sink:
        for label, module, cls_name, kwargs, n, dim in WORKLOADS:
            if args.rows and label not in args.rows:
                continue
            gen = torch.Generator(device="cpu")
            gen.manual_seed(0)
            grads = [
                torch.randn(dim, generator=gen, dtype=torch.float32)
                for _ in range(n)
            ]
            try:
                op = _load(module, cls_name)(**kwargs)
                rec = _time_row(
                    op, grads, repeat=args.repeat, budget=args.budget
                )
            except Exception as exc:  # noqa: BLE001 — report per-row
                rec = {"status": "error", "error": f"{type(exc).__name__}: {exc}"}
            rec.update({
                "row": label, "n": n, "dim": dim,
                "impl": f"{module}.{cls_name}", "device": "cpu",
                "provenance": "local byzfl run (benchmarks/byzfl_compare.py)",
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            })
            print(json.dumps(rec))
            sink.write(json.dumps(rec) + "\n")
            rows += 1
    print(json.dumps({"status": "done", "rows": rows, "out": out_path}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

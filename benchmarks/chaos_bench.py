"""Chaos grid: the standing (attack × fault × aggregator × precision)
regression wall.

Four lanes, each emitting JSON rows (stdout + ``--out`` JSONL):

* ``grid`` — every (attack × fault × aggregator) cell runs one
  declarative :class:`~byzpy_tpu.chaos.Scenario` through the chaos
  harness (direct masked-aggregate engine), paired with its attack-free
  twin for the contained/breached verdict. Each row carries the cell's
  event-trace digest — the replay pin: a future PR that changes any
  cell's behavior changes its digest, and `--smoke` asserts zero
  harness-crashed cells. A second pass replays the fault="none" plane
  at ``precision=int8`` (the PR-3 wire codec) — the grid's precision
  axis.
* ``adaptive`` — the head-to-head: each adaptive attacker vs its static
  counterpart on the aggregators it targets, reporting the influence
  uplift and exclusion-round gap (the ROADMAP's "adaptive attackers
  that optimize their next submission" made measurable).
* ``serving`` — staleness-window abuse against the REAL serving
  frontend admission path (virtual clock): the attacker stamps at the
  cutoff and pre-inflates by 1/discount so the tier's staleness
  discount cancels; outcome per aggregator reported as contained or
  breached vs the attack-free baseline (threat model: docs/serving.md).
* ``swarm`` — thousands of simulated clients (default 3,000) through
  the production admission gates under bursty arrivals, crashes and a
  partition, with adaptive byzantine clients riding along: sustained
  submissions/sec, rounds closed, zero failed rounds, full rejection
  accounting.
* ``recovery`` — REAL faults, not scenario events: per seed, a durable
  TCP frontend subprocess is SIGKILLed mid-round and recovered
  (``byzpy_tpu.resilience.drill``), asserting no accepted-then-lost
  submissions, exactly-once folding of replayed ``(client, seq)``
  frames, monotonic round numbering and digest continuity; plus an
  in-process ack-drop/retry cycle asserting round-aggregate bit parity
  against the no-fault twin. The standing wall runs ≥ 20 seeds.
* ``forensics`` — detector scoring for the PR-10 attribution plane
  (``byzpy_tpu.forensics``): every PR-7 adaptive attacker
  (influence-ascent, Krum-evasion, staleness-abuse) plus the static
  sign-flip/outlier attacks, run with the forensics plane attached —
  per-cell byzantine recall, first-flag round (must beat
  ``DETECT_BUDGET``), precision, and honest-contamination rate; an
  honest-only sweep pinning the false-positive rate under
  ``FP_BOUND``; trace-digest parity forensics-on vs forensics-off
  (the plane is a pure observer); and an end-to-end audit leg — a
  REAL durable serving frontend under staleness abuse, evidence
  verified present in the WAL (``python -m byzpy_tpu.forensics``
  report path) and on a live Prometheus scrape of the TCP ingress.
  The headline criterion: the staleness-abuse breach that was
  operator-invisible in PR 7 (trimmed-mean 8.4×, Multi-Krum 47×) now
  raises ``staleness_inflation`` flags within ``DETECT_BUDGET``
  rounds at a pinned honest false-positive rate.

* ``subint8`` — the adversarial-residual lane (round 15): the
  residual-shaping attacker (an encoder-controlling client inflating
  its per-block scales by κ and steering the coarse grid's rounding
  error through error feedback) through the REAL serving admission
  path per aggregator × sub-int8 fabric ({fp8, s4}), measured for
  influence vs its unshaped influence-ascent twin and screened by the
  forensics ``residual_shaping`` detector (pre-decode per-block
  inflation ratio — honest encoders sit at exactly 1.0) with the
  honest false-positive rate pinned under ``FP_BOUND``; plus the
  per-aggregator × attack precision-floor table (Byzantine tolerance
  over wire-quantization error, int8 → fp8 → fp8_e5m2 → s4).

* ``sanitize`` — the runtime invariant sanitizer
  (``byzpy_tpu.analysis.sanitize``, ISSUE 20) as a pure observer: one
  serving-engine cell runs hooks-off then hooks-on; the sanitized run
  must record zero violations, exercise the exactly-once fold audit
  (nonzero counters), and keep the event-trace digest bit-identical
  to the unsanitized twin.

``--smoke`` shrinks everything for CI and asserts the contracts (zero
harness-crashed cells, cell replay determinism, swarm liveness, zero
recovery-invariant violations). ``--lanes`` selects a subset (e.g.
``--lanes recovery``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU mesh: the chaos fabric is host-side machinery measured on the CPU
# mesh by design (same policy as serving_bench) — a dead accelerator
# tunnel must not hang the regression wall.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from byzpy_tpu.chaos import (  # noqa: E402
    ArrivalModel,
    AttackSpec,
    ChaosHarness,
    CrashModel,
    FaultPlan,
    PartitionEvent,
    Scenario,
    StragglerModel,
)


def _emit(row: dict, out_path: str | None) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    if out_path:
        with open(out_path, "a") as fh:
            fh.write(line + "\n")


# ---------------------------------------------------------------------------
# grid lane
# ---------------------------------------------------------------------------

ATTACK_CELLS = [
    # reference sign convention (attacks/sign_flip.py, attacks/empire.py):
    # negative scale = inverted direction
    ("sign_flip", {"scale": -4.0}),
    ("empire", {"scale": -1.1}),
    ("little", {"scale": 1.0}),
    ("outlier", {"scale": 50.0}),
    ("influence_ascent", {"grow": 1.8, "scale0": 0.1}),
    ("krum_evasion", {}),
]

FAULT_CELLS = {
    "none": FaultPlan(),
    "stragglers": FaultPlan(
        stragglers=StragglerModel(
            kind="bimodal", mu=-4.0, sigma=0.5, tail_prob=0.25, tail_s=0.5
        )
    ),
    "crash_restart": FaultPlan(
        crash=CrashModel(prob_per_round=0.03, restart_after_rounds=4)
    ),
    "partition": FaultPlan(
        partitions=(PartitionEvent(start_round=6, end_round=14, fraction=0.25),)
    ),
}

AGG_CELLS = [
    ("trimmed_mean", {"f": 3}),
    ("multi_krum", {"f": 3, "q": 4}),
    ("cge", {"f": 3}),
]

#: breached = the attack dragged the final params more than this factor
#: past the attack-free twin's error (plus an absolute floor so a
#: near-zero baseline can't declare breaches on noise)
BREACH_RATIO = 3.0
BREACH_FLOOR = 0.15


def _base_scenario(args, fault_name: str, **kwargs) -> Scenario:
    return Scenario(
        seed=args.seed,
        n_clients=args.clients_grid,
        dim=args.dim,
        rounds=args.rounds,
        faults=FAULT_CELLS[fault_name],
        **kwargs,
    )


def _verdict(err: float, baseline: float) -> str:
    return (
        "breached"
        if err > max(BREACH_RATIO * baseline, baseline + BREACH_FLOOR)
        else "contained"
    )


def _run_cell(scenario: Scenario, baseline_err: float) -> dict:
    """One grid cell, crash-guarded: the wall must report a broken cell,
    not die on it."""
    try:
        report = ChaosHarness(scenario).run()
        row = report.summary()
        row["baseline_error"] = round(baseline_err, 6)
        row["error_ratio"] = round(
            report.final_error / max(baseline_err, 1e-9), 3
        )
        row["verdict"] = _verdict(report.final_error, baseline_err)
        row["harness_crashed"] = False
    except Exception as exc:  # noqa: BLE001 — the wall reports, not dies
        row = {
            "scenario": scenario.name,
            "attack": scenario.attack.name,
            "aggregator": scenario.aggregator,
            "precision": scenario.precision,
            "harness_crashed": True,
            "error": f"{type(exc).__name__}: {exc}",
        }
    return row


def run_grid(args, out) -> list:
    rows = []
    for fault_name in args.faults:
        for agg_name, agg_params in args.aggregators:
            base = _base_scenario(
                args,
                fault_name,
                name=f"baseline/{fault_name}/{agg_name}",
                aggregator=agg_name,
                aggregator_params=agg_params,
            )
            baseline = ChaosHarness(base).run()
            for attack_name, attack_params in args.attacks:
                cell = base.with_(
                    name=f"grid/{attack_name}/{fault_name}/{agg_name}",
                    n_byzantine=args.byzantine,
                    attack=AttackSpec(name=attack_name, params=attack_params),
                )
                row = {"lane": "grid", "fault": fault_name}
                row.update(_run_cell(cell, baseline.final_error))
                rows.append(row)
                _emit(row, out)
    # precision axis: the fault-free plane again through the int8 wire
    # codec — robust verdicts must hold on compressed submissions
    for agg_name, agg_params in args.aggregators:
        base = _base_scenario(
            args,
            "none",
            name=f"baseline/int8/{agg_name}",
            aggregator=agg_name,
            aggregator_params=agg_params,
            precision="int8",
        )
        baseline = ChaosHarness(base).run()
        for attack_name, attack_params in args.attacks:
            cell = base.with_(
                name=f"grid/{attack_name}/none+int8/{agg_name}",
                n_byzantine=args.byzantine,
                attack=AttackSpec(name=attack_name, params=attack_params),
            )
            row = {"lane": "grid", "fault": "none"}
            row.update(_run_cell(cell, baseline.final_error))
            rows.append(row)
            _emit(row, out)
    return rows


# ---------------------------------------------------------------------------
# adaptive head-to-head lane
# ---------------------------------------------------------------------------

#: (adaptive, static counterpart, aggregator) triples: the same attack
#: budget, blind vs observing
PAIRS = [
    ("influence_ascent", {"grow": 1.8, "scale0": 0.1},
     "outlier", {"scale": 50.0}, "multi_krum", {"f": 3, "q": 4}),
    ("influence_ascent", {"grow": 1.8, "scale0": 0.1},
     "outlier", {"scale": 50.0}, "cge", {"f": 3}),
    ("krum_evasion", {}, "outlier", {"scale": 50.0},
     "multi_krum", {"f": 3, "q": 4}),
]


def run_adaptive(args, out) -> list:
    rows = []
    for a_name, a_params, s_name, s_params, agg, agg_params in PAIRS:
        reports = {}
        for name, params in ((a_name, a_params), (s_name, s_params)):
            cell = _base_scenario(
                args,
                "none",
                name=f"adaptive/{name}/{agg}",
                aggregator=agg,
                aggregator_params=agg_params,
                n_byzantine=args.byzantine,
                attack=AttackSpec(name=name, params=params),
            )
            reports[name] = ChaosHarness(cell).run()
        adaptive, static = reports[a_name], reports[s_name]
        row = {
            "lane": "adaptive",
            "aggregator": agg,
            "adaptive": a_name,
            "static": s_name,
            "adaptive_influence_mean": round(adaptive.influence_mean, 6),
            "static_influence_mean": round(static.influence_mean, 6),
            "influence_uplift": round(
                adaptive.influence_mean / max(static.influence_mean, 1e-9), 2
            ),
            "adaptive_last_selected_round": adaptive.last_selected_round,
            "static_last_selected_round": static.last_selected_round,
            "adaptive_final_error": round(adaptive.final_error, 6),
            "static_final_error": round(static.final_error, 6),
            "adaptive_beats_static": bool(
                adaptive.influence_mean > static.influence_mean
                or adaptive.last_selected_round > static.last_selected_round
            ),
        }
        rows.append(row)
        _emit(row, out)
    return rows


# ---------------------------------------------------------------------------
# serving staleness-abuse lane
# ---------------------------------------------------------------------------


def run_serving(args, out) -> list:
    rows = []
    cutoff, gamma = 4, 0.5
    for agg_name, agg_params in args.aggregators:
        common = dict(
            seed=args.seed,
            n_clients=args.clients_grid,
            dim=args.dim,
            rounds=args.rounds,
            engine="serving",
            aggregator=agg_name,
            aggregator_params=agg_params,
            staleness_kind="exponential",
            staleness_gamma=gamma,
            staleness_cutoff=cutoff,
        )
        baseline = ChaosHarness(
            Scenario(name=f"serving-baseline/{agg_name}", **common)
        ).run()
        abuse = ChaosHarness(
            Scenario(
                name=f"serving-abuse/{agg_name}",
                n_byzantine=args.byzantine,
                attack=AttackSpec(
                    name="staleness_abuse",
                    params={"kind": "exponential", "gamma": gamma,
                            "cutoff": cutoff, "scale": 2.0},
                ),
                **common,
            )
        ).run()
        row = {
            "lane": "serving",
            "aggregator": agg_name,
            "attack": "staleness_abuse",
            "staleness": {"kind": "exponential", "gamma": gamma,
                          "cutoff": cutoff},
            "inflation": round((1.0 / gamma) ** cutoff, 1),
            "rounds": abuse.rounds_completed,
            "verdicts": dict(abuse.verdict_counts),
            "influence_mean": round(abuse.influence_mean, 6),
            "baseline_error": round(baseline.final_error, 6),
            "final_error": round(abuse.final_error, 6),
            "error_ratio": round(
                abuse.final_error / max(baseline.final_error, 1e-9), 3
            ),
            "outcome": _verdict(abuse.final_error, baseline.final_error),
            "trace_digest": abuse.trace.digest(),
        }
        rows.append(row)
        _emit(row, out)
    return rows


# ---------------------------------------------------------------------------
# recovery lane (real faults: SIGKILL + wire drops)
# ---------------------------------------------------------------------------


def run_recovery(args, out) -> dict:
    import tempfile

    from byzpy_tpu.resilience import drill as rdrill

    kill_rows, wire_rows = [], []
    for i in range(args.recovery_runs):
        seed = args.seed + i
        with tempfile.TemporaryDirectory() as tmp:
            row = rdrill.run_kill_recover(seed, tmp)
        kill_rows.append(row)
        _emit(row, out)
        wrow = rdrill.run_wire_drop(seed)
        wire_rows.append(wrow)
        _emit(wrow, out)
    summary = {
        "lane": "recovery_summary",
        "runs": args.recovery_runs,
        "kill_violations": sum(r["violations"] for r in kill_rows),
        "wire_violations": sum(r["violations"] for r in wire_rows),
        "acked_accepted_total": sum(r["acked_accepted"] for r in kill_rows),
        "lost_total": sum(r["lost"] for r in kill_rows),
        "double_folded_total": sum(r["double_folded"] for r in kill_rows),
        "duplicates_absorbed_total": sum(
            r["duplicates_absorbed"] for r in kill_rows + wire_rows
        ),
        "bit_parity_runs": sum(1 for r in wire_rows if r["bit_parity"]),
        "mean_kill_recover_wall_s": round(
            float(np.mean([r["wall_s"] for r in kill_rows])), 3
        ),
        "recovery_metric_exported": all(
            r["recovery_metric_exported"] for r in kill_rows
        ),
        "checkpoint_metric_exported": all(
            r["checkpoint_metric_exported"] for r in kill_rows
        ),
        # the registry counter is process-cumulative: the last run's
        # reading IS the lane total (summing would double-count)
        "retry_total": wire_rows[-1]["retry_total"] if wire_rows else 0.0,
    }
    _emit(summary, out)
    return summary


# ---------------------------------------------------------------------------
# forensics lane (detector scoring for the attribution plane)
# ---------------------------------------------------------------------------

#: Detection budget: every adaptive attacker must raise its first flag
#: within this many rounds (the PR-7 serving-lane breach was invisible
#: for the WHOLE run).
DETECT_BUDGET = 6
#: Pinned honest-only false-positive bound (fraction of honest
#: client-round records carrying any flag; measured worst across the
#: committed sweep: 0.014).
FP_BOUND = 0.02

_SERVING_STALENESS = dict(
    engine="serving",
    staleness_kind="exponential",
    staleness_gamma=0.5,
    staleness_cutoff=4,
)

#: (attack, params, aggregator, agg_params, scenario extras, adaptive?)
FORENSICS_CELLS = [
    ("influence_ascent", {"grow": 1.8, "scale0": 0.1},
     "multi_krum", {"f": 3, "q": 4}, {}, True),
    ("influence_ascent", {"grow": 1.8, "scale0": 0.1},
     "cge", {"f": 3}, {}, True),
    ("krum_evasion", {}, "multi_krum", {"f": 3, "q": 4}, {}, True),
    ("staleness_abuse",
     {"kind": "exponential", "gamma": 0.5, "cutoff": 4, "scale": 2.0},
     "trimmed_mean", {"f": 3}, _SERVING_STALENESS, True),
    ("staleness_abuse",
     {"kind": "exponential", "gamma": 0.5, "cutoff": 4, "scale": 2.0},
     "multi_krum", {"f": 3, "q": 4}, _SERVING_STALENESS, True),
    ("sign_flip", {"scale": -4.0}, "trimmed_mean", {"f": 3}, {}, False),
    ("outlier", {"scale": 50.0}, "multi_krum", {"f": 3, "q": 4}, {}, False),
]

HONEST_CONFIGS = [
    ("trimmed_mean", {"f": 3}, {}),
    ("multi_krum", {"f": 3, "q": 4}, {}),
    ("cge", {"f": 3}, {}),
    ("trimmed_mean", {"f": 3}, _SERVING_STALENESS),
]


def _forensics_config():
    from byzpy_tpu.forensics import ForensicsConfig

    return ForensicsConfig()


def run_forensics(args, out) -> dict:
    rows = []
    fc = _forensics_config()
    # -- attack cells: recall / first-flag / precision ------------------
    for att, ap, agg, agp, extra, adaptive in args.forensics_cells:
        cell = Scenario(
            name=f"forensics/{att}/{agg}",
            seed=args.seed,
            n_clients=args.clients_grid,
            n_byzantine=args.byzantine,
            dim=args.dim,
            rounds=args.rounds,
            aggregator=agg,
            aggregator_params=agp,
            attack=AttackSpec(name=att, params=ap),
            **extra,
        )
        report = ChaosHarness(cell, forensics=fc).run()
        s = report.forensics_summary()
        row = {
            "lane": "forensics",
            "attack": att,
            "adaptive": adaptive,
            "aggregator": agg,
            "engine": cell.engine,
            "rounds": report.rounds_completed,
            "byz_present": s["byz_present"],
            "byz_flagged": s["byz_flagged"],
            "recall": s["recall"],
            "precision": s["precision"],
            "first_byz_flag_round": s["first_byz_flag_round"],
            "honest_fp_rate": round(s["honest_fp_rate"], 4),
            "flags_by_detector": s["flags_by_detector"],
            "detect_budget": DETECT_BUDGET,
            "within_budget": (
                s["first_byz_flag_round"] is not None
                and s["first_byz_flag_round"] <= DETECT_BUDGET
            ),
            "final_error": round(report.final_error, 6),
            "trace_digest": report.trace.digest(),
        }
        rows.append(row)
        _emit(row, out)
    # -- honest-only sweep: pinned false-positive bound -----------------
    worst_fp = 0.0
    honest_runs = 0
    for i in range(args.forensics_honest_seeds):
        for agg, agp, extra in args.honest_configs:
            cell = Scenario(
                name=f"forensics-honest/{agg}",
                seed=args.seed + i,
                n_clients=args.clients_grid,
                dim=args.dim,
                rounds=args.rounds,
                aggregator=agg,
                aggregator_params=agp,
                **extra,
            )
            s = ChaosHarness(cell, forensics=fc).run().forensics_summary()
            worst_fp = max(worst_fp, s["honest_fp_rate"])
            honest_runs += 1
    # -- digest parity: the plane is a pure observer --------------------
    parity_cell = Scenario(
        name="forensics-parity",
        seed=args.seed,
        n_clients=args.clients_grid,
        n_byzantine=args.byzantine,
        dim=args.dim,
        rounds=args.rounds,
        aggregator="multi_krum",
        aggregator_params={"f": 3, "q": 4},
        attack=AttackSpec(
            name="influence_ascent", params={"grow": 1.8, "scale0": 0.1}
        ),
    )
    with_f = ChaosHarness(parity_cell, forensics=fc).run()
    without = ChaosHarness(parity_cell).run()
    digest_parity = (
        with_f.trace.digest() == without.trace.digest()
        and with_f.final_error == without.final_error
    )
    # -- end-to-end audit: durable frontend + WAL + Prometheus ----------
    audit_row = _forensics_audit_leg(args)
    _emit(audit_row, out)
    summary = {
        "lane": "forensics_summary",
        "cells": len(rows),
        "adaptive_cells": sum(1 for r in rows if r["adaptive"]),
        "adaptive_all_flagged": all(
            r["byz_flagged"] == r["byz_present"]
            for r in rows
            if r["adaptive"]
        ),
        "adaptive_within_budget": all(
            r["within_budget"] for r in rows if r["adaptive"]
        ),
        "staleness_first_flag": {
            r["aggregator"]: r["first_byz_flag_round"]
            for r in rows
            if r["attack"] == "staleness_abuse"
        },
        "honest_runs": honest_runs,
        "honest_worst_fp_rate": round(worst_fp, 4),
        "fp_bound": FP_BOUND,
        "fp_within_bound": worst_fp <= FP_BOUND,
        "digest_parity": digest_parity,
        "wal_audit_ok": audit_row["wal_audit_ok"],
        "prometheus_ok": audit_row["prometheus_ok"],
    }
    _emit(summary, out)
    return summary


def _forensics_audit_leg(args) -> dict:
    """A REAL durable ServingFrontend under staleness abuse: evidence
    must land in the write-ahead log (readable by the forensics CLI's
    audit path) and the forensics metric families must answer on a
    live Prometheus scrape of the TCP wire ingress."""
    import asyncio
    import tempfile

    import numpy as np

    from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean
    from byzpy_tpu.forensics import ForensicsConfig, TrustPolicy, audit
    from byzpy_tpu.serving import (
        DurabilityConfig,
        ServingFrontend,
        StalenessPolicy,
        TenantConfig,
    )

    rounds = max(6, min(10, args.rounds))
    dim = 16

    async def drive(tmp: str) -> dict:
        fe = ServingFrontend(
            [
                TenantConfig(
                    name="m0",
                    aggregator=CoordinateWiseTrimmedMean(f=1),
                    dim=dim,
                    staleness=StalenessPolicy(
                        kind="exponential", gamma=0.5, cutoff=4
                    ),
                    forensics=ForensicsConfig(
                        trust=TrustPolicy(alpha=0.5, readmit_after_rounds=4),
                        quarantine=True,
                    ),
                )
            ],
            # prune=False keeps the full forensic history on disk —
            # the audit must see every round's evidence
            durability=DurabilityConfig(directory=tmp, prune=False),
        )
        rng = np.random.default_rng(args.seed)
        untrusted_acks = 0
        for r in range(rounds):
            for i in range(6):
                ok, reason = fe.submit(
                    "m0", f"c{i}", r,
                    rng.normal(1.0, 0.1, dim).astype(np.float32),
                )
                assert ok, reason
            # the staleness abuser: stamps at the cutoff, pre-inflates
            # by 1/discount(4) = 16x so the discount cancels at fold
            inflated = (16.0 * rng.normal(1.0, 0.1, dim)).astype(np.float32)
            ok, reason = fe.submit("m0", "byz0", max(0, r - 4), inflated)
            if reason == "rejected_untrusted":
                untrusted_acks += 1
            assert fe.close_round_nowait("m0") is not None
        host, port = await fe.serve()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
        await writer.drain()
        scrape = (await reader.read(-1)).decode()
        writer.close()
        stats = fe.stats()["m0"]
        await fe.close()
        return {"scrape": scrape, "stats": stats, "untrusted": untrusted_acks}

    with tempfile.TemporaryDirectory() as tmp:
        res = asyncio.run(drive(tmp))
        report = audit.wal_timeline(os.path.join(tmp, "m0"))
    byz_entry = report["clients"].get("byz0", {})
    wal_ok = (
        report["evidence_rounds"] > 0
        and not report["digest_mismatches"]
        and bool(byz_entry.get("flags"))
        and any(t["event"] == "quarantine" for t in report["transitions"])
    )
    prom_ok = all(
        name in res["scrape"]
        for name in (
            "byzpy_anomaly_flags_total",
            "byzpy_trust_score",
            "byzpy_client_excluded_total",
            "byzpy_quarantined_clients",
        )
    )
    return {
        "lane": "forensics_audit",
        "rounds": rounds,
        "wal_evidence_rounds": report["evidence_rounds"],
        "wal_digest_mismatches": len(report["digest_mismatches"]),
        "byz_flags": dict(byz_entry.get("flags", {})),
        "quarantine_transitions": len(report["transitions"]),
        "rejected_untrusted_acks": res["untrusted"],
        "wal_audit_ok": wal_ok,
        "prometheus_ok": prom_ok,
    }


# ---------------------------------------------------------------------------
# swarm lane
# ---------------------------------------------------------------------------


def run_ragged(args, out) -> dict:
    """Ragged-door parity cell (PR 11): one serving-engine cell replayed
    through the DEFAULT ragged dispatcher and again through the
    bucket-ladder escape hatch (``BYZPY_TPU_RAGGED=0``) — the event
    traces fold every round's exact aggregate bits into their digests,
    so digest equality IS the bit-parity pin keeping the regression
    wall honest about which door served it. Asserted unconditionally
    (the cell is cheap; a parity break must never ride a green wall)."""
    agg_name, agg_params = args.aggregators[0]
    scenario = Scenario(
        name=f"ragged-door/{agg_name}",
        seed=args.seed,
        n_clients=args.clients_grid,
        n_byzantine=args.byzantine,
        dim=args.dim,
        rounds=args.rounds,
        engine="serving",
        aggregator=agg_name,
        aggregator_params=agg_params,
        staleness_kind="exponential",
        staleness_gamma=0.5,
        staleness_cutoff=4,
        attack=AttackSpec(
            name="staleness_abuse",
            params={"kind": "exponential", "gamma": 0.5,
                    "cutoff": 4, "scale": 2.0},
        ),
    )
    prev = os.environ.get("BYZPY_TPU_RAGGED")
    try:
        os.environ.pop("BYZPY_TPU_RAGGED", None)
        ragged = ChaosHarness(scenario).run()
        os.environ["BYZPY_TPU_RAGGED"] = "0"
        bucketed = ChaosHarness(scenario).run()
    finally:
        if prev is None:
            os.environ.pop("BYZPY_TPU_RAGGED", None)
        else:
            os.environ["BYZPY_TPU_RAGGED"] = prev
    row = {
        "lane": "ragged",
        "aggregator": agg_name,
        "rounds": ragged.rounds_completed,
        "ragged_digest": ragged.trace.digest(),
        "bucketed_digest": bucketed.trace.digest(),
        "digest_match": ragged.trace.digest() == bucketed.trace.digest(),
    }
    _emit(row, out)
    assert row["digest_match"], (
        "ragged door diverged from the bucket ladder: "
        f"{row['ragged_digest']} != {row['bucketed_digest']}"
    )
    return row


def run_shard(args, out) -> dict:
    """Sharded-tier cell (ISSUE 12): (1) hierarchical-fold BIT PARITY —
    the same deterministic client population served by a 2-shard
    :class:`~byzpy_tpu.serving.ShardedCoordinator` and by ONE
    :class:`~byzpy_tpu.serving.ServingFrontend` fed the concatenated
    (shard-order) cohorts must produce digest-identical aggregates
    every round; (2) the compromised-shard adversary — a Byzantine
    shard forging its PartialFold (rows tampered after the digest, a
    ghost-client claim, poisoned extras) must be flagged by the root's
    evidence-digest cross-check every round it forges, with the merged
    aggregate bit-identical to the honest-shards-only reference.
    Asserted unconditionally (a parity or detection break must never
    ride a green wall)."""
    from byzpy_tpu.aggregators import MultiKrum
    from byzpy_tpu.chaos.shards import CompromisedShard
    from byzpy_tpu.forensics.evidence import evidence_digest
    from byzpy_tpu.serving import (
        ServingFrontend,
        ShardedCoordinator,
        TenantConfig,
    )
    from byzpy_tpu.serving.sharded import shard_for
    from byzpy_tpu.serving.staleness import StalenessPolicy

    dim = args.dim
    rounds = max(4, args.rounds // 4)
    n_clients = max(8, args.clients_grid)
    rng = np.random.default_rng(args.seed)
    clients = [f"c{i:04d}" for i in range(n_clients)]
    grads = {c: rng.normal(size=dim).astype(np.float32) for c in clients}

    def mk_tenants():
        return [
            TenantConfig(
                name="m0",
                aggregator=MultiKrum(f=args.byzantine, q=args.byzantine + 1),
                dim=dim,
                cohort_cap=max(n_clients, 8),
                staleness=StalenessPolicy(
                    kind="exponential", gamma=0.5, cutoff=8
                ),
            )
        ]

    # -- parity cell: 2 shards vs one frontend, digest equality ----------
    n_shards = 2
    co = ShardedCoordinator(mk_tenants(), n_shards, quorum=1)
    co_s = ShardedCoordinator(mk_tenants(), n_shards, quorum=1)
    co_c = ShardedCoordinator(mk_tenants(), n_shards, quorum=1)
    fe = ServingFrontend(mk_tenants())
    order = [
        c
        for s in range(n_shards)
        for c in clients
        if shard_for(c, n_shards) == s
    ]
    parity_digests = []
    for r in range(rounds):
        for c in clients:
            ok, reason = co.submit("m0", c, r, grads[c], seq=r)
            assert ok, (c, reason)
            ok, reason = co_s.submit("m0", c, r, grads[c], seq=r)
            assert ok, (c, reason)
            ok, reason = co_c.submit("m0", c, r, grads[c], seq=r)
            assert ok, (c, reason)
        res = co.close_round_nowait("m0")
        assert res is not None
        # streaming twin: each partial cross-checked AT ARRIVAL
        # (reverse arrival order — arrival order must not matter),
        # then merged with the cached verdicts (ISSUE 18)
        stream_parts = [
            co_s.shards[s].close_partial("m0") for s in range(n_shards)
        ]
        assert all(p is not None for p in stream_parts)
        prechecked = {
            id(p): co_s.check_partial("m0", p, inflight=True)
            for p in reversed(stream_parts)
        }
        res_s = co_s.merge_partials(
            "m0", stream_parts, prechecked=prechecked
        )
        assert res_s is not None, r
        # close-path twin (ISSUE 19): check + STAGE at arrival (dedup
        # verdict parked, cross-Gram blocks computed on the 'reader'
        # side), the close promotes — digest-identical, reverse order
        cp_parts = [
            co_c.shards[s].close_partial("m0") for s in range(n_shards)
        ]
        assert all(p is not None for p in cp_parts)
        cp_pre = {}
        for p in reversed(cp_parts):
            chk = co_c.check_partial("m0", p, inflight=True)
            cp_pre[id(p)] = chk
            assert chk[0] and co_c.stage_partial("m0", p, chk)
        res_c = co_c.merge_partials(
            "m0", cp_parts, prechecked=cp_pre
        )
        assert res_c is not None, r
        for c in order:
            ok, reason = fe.submit("m0", c, r, grads[c], seq=r)
            assert ok, (c, reason)
        ref = fe.close_round_nowait("m0")
        assert ref is not None
        sharded_digest = evidence_digest(res[2])
        single_digest = evidence_digest(ref[2])
        stream_digest = evidence_digest(res_s[2])
        parity_digests.append(
            {"round": r, "sharded": sharded_digest, "single": single_digest}
        )
        assert sharded_digest == single_digest, (
            f"hierarchical fold diverged at round {r}: "
            f"{sharded_digest} != {single_digest}"
        )
        assert stream_digest == sharded_digest, (
            f"streaming merge diverged at round {r}: "
            f"{stream_digest} != {sharded_digest}"
        )
        closepath_digest = evidence_digest(res_c[2])
        assert closepath_digest == sharded_digest, (
            f"close-path merge diverged at round {r}: "
            f"{closepath_digest} != {sharded_digest}"
        )
    assert co_s.stats()["root"]["m0"]["partial_checks"] == (
        rounds * n_shards
    )
    assert co_s.stats()["root"]["m0"]["partials_inflight"] == 0
    # close-path accounting at the combinatorial floor: every close
    # consumed the arrival-staged accumulator, the cross-Gram blocks
    # are exactly rounds·k·(k−1)/2, and no shard's shipped Gram was
    # ever recomputed (zero redundant extras recomputes, counter-pinned)
    cp_st = co_c.stats()["root"]["m0"]
    assert cp_st["staged_closes"] == rounds, cp_st
    assert cp_st["dedup_promoted"] == rounds * n_shards, cp_st
    assert cp_st["dedup_restaged"] == 0, cp_st
    assert cp_st["gram_cross_blocks"] == (
        rounds * n_shards * (n_shards - 1) // 2
    ), cp_st
    assert cp_st["partial_transforms"] == 0, cp_st
    assert cp_st["partials_inflight"] == 0, cp_st

    # -- compromised-shard cells: each forgery mode vs the root ----------
    forge_rows = {}
    for mode in ("bitflip", "ghost_clients", "extras"):
        n3 = 3
        co3 = ShardedCoordinator(
            mk_tenants(), n3, quorum=1, extras_policy="verify"
        )
        co3s = ShardedCoordinator(
            mk_tenants(), n3, quorum=1, extras_policy="verify"
        )
        byz = 2
        co3.shards[byz] = CompromisedShard(
            co3.shards[byz], mode=mode, seed=args.seed, n_shards=n3
        )
        co3s.shards[byz] = CompromisedShard(
            co3s.shards[byz], mode=mode, seed=args.seed, n_shards=n3
        )
        honest_clients = [c for c in clients if shard_for(c, n3) != byz]
        ref_co = ShardedCoordinator(mk_tenants(), n3, quorum=1)
        stream_forged = 0
        for r in range(rounds):
            for c in clients:
                ok, _ = co3.submit("m0", c, r, grads[c], seq=r)
                assert ok
                ok, _ = co3s.submit("m0", c, r, grads[c], seq=r)
                assert ok
            for c in honest_clients:
                ok, _ = ref_co.submit("m0", c, r, grads[c], seq=r)
                assert ok
            res = co3.close_round_nowait("m0")
            ref = ref_co.close_round_nowait("m0")
            assert res is not None and ref is not None
            # the forged partial was excluded: the merged aggregate is
            # bit-identical to the honest-shards-only deployment
            assert np.array_equal(res[2], ref[2]), (mode, r)
            # streaming twin: the forged frame fails its ARRIVAL-time
            # cross-check, and the cached verdict excludes it at the
            # close without poisoning the incremental merge state
            parts = [
                co3s.shards[s].close_partial("m0") for s in range(n3)
            ]
            assert all(p is not None for p in parts)
            prechecked = {
                id(p): co3s.check_partial("m0", p, inflight=True)
                for p in parts
            }
            forged_now = sum(
                1 for ok_chk, _m in prechecked.values() if not ok_chk
            )
            assert forged_now == 1, (mode, r, forged_now)
            stream_forged += forged_now
            res_s = co3s.merge_partials(
                "m0", parts, prechecked=prechecked
            )
            assert res_s is not None, (mode, r)
            assert np.array_equal(res_s[2], ref[2]), (mode, r)
        detected = co3.stats()["root"]["m0"]["forged_partials"]
        events = [
            e for e in co3.shard_events if e["event"] == "shard_forged"
        ]
        assert detected == rounds, (mode, detected, rounds)
        assert len(events) == rounds and all(
            e["shard"] == byz for e in events
        ), mode
        s_detected = co3s.stats()["root"]["m0"]["forged_partials"]
        assert s_detected == rounds, (mode, s_detected, rounds)
        assert co3s.stats()["root"]["m0"]["partials_inflight"] == 0
        forge_rows[mode] = {
            "rounds": rounds,
            "forged_detected": detected,
            "evidence_events": len(events),
            "aggregate_parity_vs_honest_only": "bit-identical",
            "streaming_forged_detected": stream_forged,
            "streaming_parity_vs_honest_only": "bit-identical",
        }

    row = {
        "lane": "shard",
        "aggregator": "multi-krum",
        "clients": n_clients,
        "shards_parity_cell": n_shards,
        "rounds": rounds,
        "parity": "bit-identical",
        "parity_digest_last": parity_digests[-1]["sharded"],
        "streaming_parity": "bit-identical",
        "streaming_checks": rounds * n_shards,
        "closepath_parity": "bit-identical",
        "closepath_staged_closes": cp_st["staged_closes"],
        "closepath_gram_cross_blocks": cp_st["gram_cross_blocks"],
        "closepath_partial_transforms": cp_st["partial_transforms"],
        "forgery": forge_rows,
    }
    _emit(row, out)
    return row


def run_speculative(args, out) -> dict:
    """Speculative quorum close + late-arrival repair (ISSUE 17): the
    always-on round door must be FORENSICALLY equivalent to the barrier
    it replaces.  Cells, asserted unconditionally:

    (1) repair BIT PARITY across seeds — a 3-shard coordinator with the
        repair horizon armed closes every round degraded (one straggler
        past the barrier), then folds the straggler's late partial
        through :meth:`ShardedCoordinator.repair_round`; the repaired
        aggregate must be bit-identical to a barrier twin that waited
        for all three shards, every round, every seed (late arrival
        must not change a single aggregate bit — same shard-order
        merge, same staleness discounts the rows were stamped with at
        their ORIGINAL round);
    (2) staleness abuse — replaying the already-repaired partial (the
        double-fold inflation an abuser would smuggle through the
        repair window) is rejected as a protocol violation without
        touching the aggregate;
    (3) forged late arrival — a compromised straggler's tampered
        partial is excluded by the same digest cross-check the barrier
        runs (the repair horizon is not a forensics bypass), with an
        evidence event and the degraded close left standing."""
    from byzpy_tpu.aggregators import MultiKrum
    from byzpy_tpu.chaos.shards import CompromisedShard
    from byzpy_tpu.forensics.evidence import evidence_digest
    from byzpy_tpu.serving import ShardedCoordinator, TenantConfig
    from byzpy_tpu.serving.staleness import StalenessPolicy

    dim = args.dim
    rounds = max(4, args.rounds // 4)
    n_clients = max(12, args.clients_grid)
    n_shards, straggler = 3, 2
    clients = [f"c{i:04d}" for i in range(n_clients)]

    def mk_tenants():
        return [
            TenantConfig(
                name="m0",
                aggregator=MultiKrum(f=args.byzantine, q=args.byzantine + 1),
                dim=dim,
                cohort_cap=max(n_clients, 8),
                staleness=StalenessPolicy(
                    kind="exponential", gamma=0.5, cutoff=8
                ),
            )
        ]

    seeds = [args.seed + k for k in range(3)]
    parity_rounds = 0
    for seed in seeds:
        rng = np.random.default_rng(seed)
        grads = {
            c: rng.normal(size=dim).astype(np.float32) for c in clients
        }
        co = ShardedCoordinator(
            mk_tenants(), n_shards, quorum=2, repair_horizon_rounds=2
        )
        twin = ShardedCoordinator(mk_tenants(), n_shards, quorum=1)
        for r in range(rounds):
            for c in clients:
                ok, reason = co.submit("m0", c, r, grads[c], seq=r)
                assert ok, (c, reason)
                ok, reason = twin.submit("m0", c, r, grads[c], seq=r)
                assert ok, (c, reason)
            ref = twin.close_round_nowait("m0")
            assert ref is not None
            # the straggler DRAINED at the barrier (its cohort is round
            # r's), but its reply is late: the root closes degraded at
            # quorum with the horizon armed...
            late = co.shards[straggler].close_partial("m0")
            assert late is not None
            present = [
                co.shards[s].close_partial("m0")
                for s in range(n_shards)
                if s != straggler
            ]
            res = co.merge_partials(
                "m0", [p for p in present if p is not None],
                missing=[straggler],
            )
            assert res is not None, (seed, r)
            # ...and the late arrival folds as a WAL-recorded repair
            # delta, bit-identical to the barrier twin's full close
            rep = co.repair_round("m0", late)
            assert rep is not None, (seed, r)
            assert rep[0] == r and ref[0] == r, (rep[0], ref[0])
            assert np.array_equal(rep[2], ref[2]), (
                f"repair diverged from barrier twin at seed {seed} "
                f"round {r}: {evidence_digest(rep[2])} != "
                f"{evidence_digest(ref[2])}"
            )
            parity_rounds += 1
            # staleness-abuse: replaying the repaired partial (double-
            # fold inflation) is a protocol violation — rejected, and
            # the aggregate does not move
            replay = co.repair_round("m0", late)
            assert replay is None, (seed, r)
        st = co.stats()["root"]["m0"]
        assert st["speculative_closes"] == rounds, st
        assert st["repairs"] == rounds, st
        assert st["open_repairs"] == 0, st

    # streaming repair (ISSUE 18): the late partial is cross-checked at
    # ARRIVAL and repair_round reuses the cached verdict — a repair
    # costs ZERO additional verifies at fold time, and the repaired
    # aggregate stays bit-identical to the barrier twin
    rng = np.random.default_rng(args.seed)
    grads = {c: rng.normal(size=dim).astype(np.float32) for c in clients}
    co_st = ShardedCoordinator(
        mk_tenants(), n_shards, quorum=2, repair_horizon_rounds=2
    )
    twin_st = ShardedCoordinator(mk_tenants(), n_shards, quorum=1)
    streaming_repair_rounds = 0
    for r in range(rounds):
        for c in clients:
            ok, _ = co_st.submit("m0", c, r, grads[c], seq=r)
            assert ok
            ok, _ = twin_st.submit("m0", c, r, grads[c], seq=r)
            assert ok
        ref = twin_st.close_round_nowait("m0")
        assert ref is not None
        late = co_st.shards[straggler].close_partial("m0")
        assert late is not None
        late_chk = co_st.check_partial("m0", late, inflight=True)
        present = [
            co_st.shards[s].close_partial("m0")
            for s in range(n_shards)
            if s != straggler
        ]
        prechecked = {
            id(p): co_st.check_partial("m0", p, inflight=True)
            for p in present
        }
        # close-path: the present partials stage at arrival (verdict +
        # fold + cross-Gram accumulation); the late straggler does NOT
        # stage — it repairs after the degraded close, exactly as before
        for p in present:
            chk = prechecked[id(p)]
            assert chk[0] and co_st.stage_partial("m0", p, chk), r
        res = co_st.merge_partials(
            "m0", present, missing=[straggler], prechecked=prechecked
        )
        assert res is not None, r
        checks_at_close = co_st.stats()["root"]["m0"]["partial_checks"]
        rep = co_st.repair_round("m0", late, prechecked=late_chk)
        assert rep is not None, r
        assert np.array_equal(rep[2], ref[2]), (
            f"streaming repair diverged at round {r}: "
            f"{evidence_digest(rep[2])} != {evidence_digest(ref[2])}"
        )
        # the repair consumed the arrival-time verdict: no new verify
        assert (
            co_st.stats()["root"]["m0"]["partial_checks"]
            == checks_at_close
        ), r
        streaming_repair_rounds += 1
    st_cp = co_st.stats()["root"]["m0"]
    assert st_cp["partials_inflight"] == 0
    # close-path pins: every degraded close consumed its staged
    # accumulator (verdicts promoted, zero restages), and the round's
    # Gram work is exactly the irreducible block set — one cross block
    # per staged close (2 present shards) plus the repair's re-merge
    # (C(3,2) blocks over present+late), with ZERO redundant diagonal
    # transforms (every partial shipped its Gram; nothing recomputed)
    assert st_cp["staged_closes"] == rounds, st_cp
    assert st_cp["dedup_promoted"] == rounds * (n_shards - 1), st_cp
    assert st_cp["dedup_restaged"] == 0, st_cp
    assert st_cp["partial_transforms"] == 0, st_cp
    assert st_cp["gram_cross_blocks"] == rounds * (
        1 + n_shards * (n_shards - 1) // 2
    ), st_cp

    # forged late arrival: the compromised straggler tampers its rows
    # after the digest — repair_round must exclude it with evidence,
    # and the degraded close's broadcast stands
    rng = np.random.default_rng(args.seed)
    grads = {c: rng.normal(size=dim).astype(np.float32) for c in clients}
    co = ShardedCoordinator(
        mk_tenants(), n_shards, quorum=2, repair_horizon_rounds=2
    )
    co.shards[straggler] = CompromisedShard(
        co.shards[straggler], mode="bitflip", seed=args.seed,
        n_shards=n_shards,
    )
    forged_rejected = 0
    for r in range(rounds):
        for c in clients:
            ok, _ = co.submit("m0", c, r, grads[c], seq=r)
            assert ok
        late = co.shards[straggler].close_partial("m0")
        assert late is not None
        present = [
            co.shards[s].close_partial("m0")
            for s in range(n_shards)
            if s != straggler
        ]
        res = co.merge_partials(
            "m0", [p for p in present if p is not None],
            missing=[straggler],
        )
        assert res is not None, r
        before = np.asarray(res[2]).copy()
        rep = co.repair_round("m0", late)
        assert rep is None, f"forged late partial folded at round {r}"
        forged_rejected += 1
        rt_last = co._roots["m0"].last_aggregate
        assert np.array_equal(np.asarray(rt_last), before), r
    events = [
        e for e in co.shard_events if e["event"] == "shard_forged"
    ]
    assert len(events) == rounds and all(
        e["shard"] == straggler for e in events
    ), events

    row = {
        "lane": "speculative",
        "aggregator": "multi-krum",
        "clients": n_clients,
        "shards": n_shards,
        "rounds": rounds,
        "seeds": len(seeds),
        "repair_parity_rounds": parity_rounds,
        "repair_parity": "bit-identical",
        "streaming_repair_rounds": streaming_repair_rounds,
        "streaming_repair_parity": "bit-identical",
        "streaming_repair_verify_cost": "arrival-cached",
        "closepath_staged_closes": st_cp["staged_closes"],
        "closepath_partial_transforms": st_cp["partial_transforms"],
        "closepath_gram_cross_blocks": st_cp["gram_cross_blocks"],
        "replay_rejected": "all",
        "forged_late_rejected": forged_rejected,
        "evidence_events": len(events),
    }
    _emit(row, out)
    return row


def run_swarm(args, out) -> dict:
    scenario = Scenario(
        name="swarm",
        seed=args.seed,
        n_clients=args.clients_swarm,
        n_byzantine=max(1, args.clients_swarm // 100),
        dim=args.dim,
        rounds=args.swarm_rounds,
        engine="serving",
        aggregator="trimmed_mean",
        aggregator_params={"f": max(1, args.clients_swarm // 100)},
        attack=AttackSpec(
            name="staleness_abuse",
            params={"kind": "exponential", "gamma": 0.5, "cutoff": 4},
        ),
        arrivals=ArrivalModel(kind="bernoulli", p=0.5),
        faults=FaultPlan(
            stragglers=StragglerModel(kind="bimodal", tail_prob=0.1),
            crash=CrashModel(prob_per_round=0.001, restart_after_rounds=3),
            partitions=(
                PartitionEvent(
                    start_round=args.swarm_rounds // 3,
                    end_round=2 * args.swarm_rounds // 3,
                    fraction=0.1,
                ),
            ),
        ),
        staleness_kind="exponential",
        staleness_gamma=0.5,
        staleness_cutoff=4,
        credit_rate_per_s=200.0,
        credit_burst=8.0,
    )
    t0 = time.monotonic()
    report = ChaosHarness(scenario).run()
    elapsed = time.monotonic() - t0
    submitted = sum(report.verdict_counts.values())
    # the actor-fabric twin: the same population through the real
    # actor-mode ParameterServer round loop (asyncio fan-out per node,
    # adaptive byzantine nodes on the observation channel) — the
    # Podracer claim that simulated thousands are cheap on BOTH fabrics
    actor = ChaosHarness(
        scenario.with_(
            name="swarm-actor",
            engine="actor",
            n_clients=args.clients_actor,
            n_byzantine=max(1, args.clients_actor // 100),
            aggregator_params={"f": max(1, args.clients_actor // 100)},
            rounds=max(3, args.swarm_rounds // 3),
            attack=AttackSpec(
                name="influence_ascent", params={"grow": 1.8, "scale0": 0.1}
            ),
            faults=FaultPlan(),
            arrivals=ArrivalModel(),
        )
    )
    ta = time.monotonic()
    actor_report = actor.run()
    actor_elapsed = time.monotonic() - ta
    actor_row = {
        "lane": "swarm_actor",
        "clients": args.clients_actor,
        "rounds": actor_report.rounds_completed,
        "wall_s": round(actor_elapsed, 3),
        "gradients_per_sec": round(
            args.clients_actor
            * actor_report.rounds_completed
            / max(actor_elapsed, 1e-9),
            1,
        ),
        # no influence metric here: the actor engine publishes only what
        # the real PS publishes (the aggregate), and the leave-out
        # reference needs the cohort matrix the PS never exposes
        "final_error": round(actor_report.final_error, 6),
    }
    _emit(actor_row, out)
    row = {
        "lane": "swarm",
        "clients": scenario.n_clients,
        "byzantine": scenario.n_byzantine,
        "rounds": report.rounds_completed,
        "wall_s": round(elapsed, 3),
        "submissions": submitted,
        "submissions_per_sec": round(submitted / max(elapsed, 1e-9), 1),
        "verdicts": dict(report.verdict_counts),
        "events": report.trace.counts(),
        "final_error": round(report.final_error, 6),
        "influence_mean": round(report.influence_mean, 6),
        "trace_digest": report.trace.digest(),
    }
    _emit(row, out)
    return row


#: Sub-int8 fabric precisions the adversarial-residual lane drives
#: (ISSUE 15); the attack shapes the matching integer grid (s4 on the
#: s4 fabric, the 8-bit grid on fp8 — fp8 shaping is the same
#: scale-inflation signature).
SUBINT8_PRECISIONS = ("fp8", "s4")
SUBINT8_FLOOR_MODES = ("int8", "fp8", "fp8_e5m2", "s4")


def _subint8_floor_rows(args, out) -> list:
    """Precision floor per aggregator x attack: how far each wire mode's
    quantization error sits below the Byzantine perturbation the
    aggregator already tolerates (the PR-3 robustness-study rule,
    extended down the precision ladder). ``margin`` = tolerance / wire
    error; the floor DIES where margin < 1 — that boundary is the lane's
    deliverable, not an assertion."""
    import jax
    import jax.numpy as jnp

    from byzpy_tpu.ops import attack_ops, robust
    from byzpy_tpu.parallel import quantization as qz

    n, f = args.clients_grid * 2, args.byzantine
    d = 2048 if not args.smoke else 512
    aggs = {
        "trimmed_mean": partial(robust.trimmed_mean, f=f),
        "multi_krum": partial(robust.multi_krum, f=f, q=n - f - 2),
        "cge": partial(robust.cge, f=f),
    }
    key = jax.random.PRNGKey(args.seed)
    k1, k2, kg = jax.random.split(key, 3)
    signal = jax.random.normal(kg, (1, d), jnp.float32)
    x_clean = signal + jax.random.normal(k1, (n, d), jnp.float32)
    x_clean2 = signal + jax.random.normal(k2, (n, d), jnp.float32)

    def attacked(kind):
        honest = x_clean[: n - f]
        if kind == "empire":
            vec = attack_ops.empire(honest, scale=-1.1)
        elif kind == "little":
            vec = attack_ops.little(honest, f=f, n_total=n)
        else:
            vec = attack_ops.sign_flip(jnp.mean(honest, axis=0), scale=-4.0)
        return jnp.concatenate(
            [honest, jnp.broadcast_to(vec, (f, d)).astype(honest.dtype)],
            axis=0,
        )

    rows = []
    for agg_name, agg in aggs.items():
        agg_j = jax.jit(agg)
        base_clean = agg_j(x_clean)
        resample = float(jnp.linalg.norm(agg_j(x_clean2) - base_clean))
        for att in ("sign_flip", "little", "empire"):
            x_att = attacked(att)
            base_att = agg_j(x_att)
            tolerance = max(
                float(jnp.linalg.norm(base_att - base_clean)), resample
            )
            margins = {}
            floor = None
            floor_open = True
            for mode in SUBINT8_FLOOR_MODES:
                wire = qz.dequantize_blockwise(qz.encode_blockwise(x_att, mode))
                err = float(jnp.linalg.norm(agg_j(wire) - base_att))
                margin = tolerance / err if err > 0 else float("inf")
                margins[mode] = round(margin, 3)
                # the floor is the coarsest rung reachable WITHOUT
                # crossing a failed finer rung (the ladder's error
                # bounds overlap — e5m2 and s4 share absmax/14 — so a
                # non-monotone pass past a failure must not relabel
                # the failed rung as safe); boundary rule margin >= 1
                # == the robustness study's err/tolerance <= 1
                if floor_open and margin >= 1.0:
                    floor = mode
                else:
                    floor_open = False
            row = {
                "lane": "subint8_floor",
                "aggregator": agg_name,
                "attack": att,
                "n": n, "d": d, "f": f,
                "tolerance": round(tolerance, 6),
                "margin_by_mode": margins,
                "floor": floor,
            }
            rows.append(row)
            _emit(row, out)
    return rows


def run_sanitize(args, out) -> dict:
    """Runtime-sanitizer lane (ISSUE 20): one serving-engine cell runs
    twice — ``byzpy_tpu.analysis.sanitize`` hooks off, then on — and
    the sanitized run must (a) record ZERO invariant violations, (b)
    actually exercise the exactly-once fold audit (nonzero counters —
    a leg that never audited proves nothing), and (c) leave the
    event-trace digest and final error bit-identical to the
    unsanitized twin: the sanitizer is a pure observer, like the
    forensics plane before it."""
    from byzpy_tpu.analysis import sanitize

    cell = Scenario(
        name="sanitize-parity",
        seed=args.seed,
        n_clients=args.clients_grid,
        n_byzantine=args.byzantine,
        dim=args.dim,
        rounds=args.rounds,
        aggregator="trimmed_mean",
        aggregator_params={"f": 3},
        attack=AttackSpec(name="influence_ascent"),
        engine="serving",
    )
    plain = ChaosHarness(cell).run()
    was_enabled = sanitize.enabled()
    sanitize.enable()
    sanitize.reset()
    try:
        sanitized = ChaosHarness(cell).run()
        violations = sanitize.violations()
        counters = sanitize.counters()
    finally:
        if not was_enabled:
            sanitize.disable()
        sanitize.reset()
    row = {
        "lane": "sanitize",
        "engine": cell.engine,
        "rounds": sanitized.rounds_completed,
        "digest_parity": (
            sanitized.trace.digest() == plain.trace.digest()
            and sanitized.final_error == plain.final_error
        ),
        "violations": violations,
        "folds_audited": counters["folds_audited"],
        "loop_ticks": counters["loop_ticks"],
        "drain_checks": counters["drain_checks"],
    }
    _emit(row, out)
    return row


def run_subint8(args, out) -> dict:
    """Adversarial-residual lane (ISSUE 15): the residual-shaping
    attacker — an encoder-controlling client steering its own sub-int8
    quantization error through error feedback — driven through the REAL
    serving admission path per aggregator x fabric precision, measured
    for influence against its unshaped (influence-ascent) twin, and
    screened by the forensics ``residual_shaping`` detector with the
    honest false-positive rate pinned; plus the per-aggregator
    precision-floor table."""
    fc = _forensics_config()
    rows = []
    for agg_name, agg_params in args.aggregators:
        for prec in SUBINT8_PRECISIONS:
            shape_mode = "s4" if prec == "s4" else "int8"
            common = dict(
                seed=args.seed,
                n_clients=args.clients_grid,
                dim=args.dim,
                rounds=args.rounds,
                aggregator=agg_name,
                aggregator_params=agg_params,
                engine="serving",
                precision=prec,
            )
            baseline = ChaosHarness(
                Scenario(name=f"subint8-baseline/{agg_name}/{prec}", **common)
            ).run()
            cell = Scenario(
                name=f"subint8/{agg_name}/{prec}",
                n_byzantine=args.byzantine,
                attack=AttackSpec(
                    name="residual_shaping",
                    params={"mode": shape_mode, "kappa": 4.0,
                            "scale0": 0.05},
                ),
                **common,
            )
            report = ChaosHarness(cell, forensics=fc).run()
            s = report.forensics_summary()
            plain = ChaosHarness(
                Scenario(
                    name=f"subint8-plain/{agg_name}/{prec}",
                    n_byzantine=args.byzantine,
                    attack=AttackSpec(
                        name="influence_ascent", params={"scale0": 0.05}
                    ),
                    **common,
                )
            ).run()
            row = {
                "lane": "subint8",
                "aggregator": agg_name,
                "precision": prec,
                "attack": "residual_shaping",
                "shape_mode": shape_mode,
                "kappa": 4.0,
                "rounds": report.rounds_completed,
                "mean_influence": round(report.influence_mean, 6),
                "max_influence": round(report.influence_max, 6),
                "plain_mean_influence": round(plain.influence_mean, 6),
                "shaping_vs_plain": round(
                    report.influence_mean / max(plain.influence_mean, 1e-9), 3
                ),
                "final_error": round(report.final_error, 6),
                "baseline_error": round(baseline.final_error, 6),
                "verdict": _verdict(report.final_error, baseline.final_error),
                "byz_present": s["byz_present"],
                "byz_flagged": s["byz_flagged"],
                "recall": s["recall"],
                "first_byz_flag_round": s["first_byz_flag_round"],
                "honest_fp_rate": round(s["honest_fp_rate"], 4),
                "flags_by_detector": s["flags_by_detector"],
                "within_budget": (
                    s["first_byz_flag_round"] is not None
                    and s["first_byz_flag_round"] <= DETECT_BUDGET
                ),
                "trace_digest": report.trace.digest(),
            }
            rows.append(row)
            _emit(row, out)
    # honest-only FP pin on the sub-int8 fabrics (every honest frame's
    # pre-decode inflation is exactly 1.0 — the detector must be silent)
    worst_fp = 0.0
    honest_runs = 0
    for i in range(min(args.forensics_honest_seeds, 3)):
        for prec in SUBINT8_PRECISIONS:
            cell = Scenario(
                name=f"subint8-honest/{prec}",
                seed=args.seed + i,
                n_clients=args.clients_grid,
                dim=args.dim,
                rounds=args.rounds,
                aggregator="trimmed_mean",
                aggregator_params={"f": args.byzantine},
                engine="serving",
                precision=prec,
            )
            s = ChaosHarness(cell, forensics=fc).run().forensics_summary()
            worst_fp = max(worst_fp, s["honest_fp_rate"])
            honest_runs += 1
    floor_rows = _subint8_floor_rows(args, out)
    summary = {
        "lane": "subint8_summary",
        "cells": len(rows),
        "shaping_all_flagged": all(
            r["byz_flagged"] == r["byz_present"] for r in rows
        ),
        "shaping_within_budget": all(r["within_budget"] for r in rows),
        "residual_shaping_fired": all(
            r["flags_by_detector"].get("residual_shaping", 0) > 0
            for r in rows
        ),
        "honest_runs": honest_runs,
        "honest_worst_fp_rate": round(worst_fp, 4),
        "fp_within_bound": worst_fp <= FP_BOUND,
        "floor_cells": len(floor_rows),
        "int8_floor_clean": all(
            r["margin_by_mode"]["int8"] >= 1.0 for r in floor_rows
        ),
        "floor_by_aggregator": {
            a: sorted(
                {
                    r["floor"]
                    for r in floor_rows
                    if r["aggregator"] == a and r["floor"] is not None
                }
            )
            for a in {r["aggregator"] for r in floor_rows}
        },
    }
    _emit(summary, out)
    return summary


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--rounds", type=int, default=24)
    ap.add_argument("--clients-grid", type=int, default=12)
    ap.add_argument("--byzantine", type=int, default=3)
    ap.add_argument("--clients-swarm", type=int, default=3000)
    ap.add_argument("--clients-actor", type=int, default=1000)
    ap.add_argument("--swarm-rounds", type=int, default=12)
    ap.add_argument("--recovery-runs", type=int, default=20)
    ap.add_argument(
        "--forensics-honest-seeds", type=int, default=5,
        help="honest-only seeds per config for the FP-rate pin",
    )
    ap.add_argument(
        "--lanes", type=str,
        default=(
            "grid,adaptive,serving,swarm,recovery,forensics,ragged,shard,"
            "speculative,subint8,sanitize"
        ),
        help="comma-separated lane subset",
    )
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI run with contract assertions")
    args = ap.parse_args()

    args.attacks = ATTACK_CELLS
    args.faults = list(FAULT_CELLS)
    args.aggregators = AGG_CELLS
    args.forensics_cells = FORENSICS_CELLS
    args.honest_configs = HONEST_CONFIGS
    if args.smoke:
        args.rounds = 10
        args.dim = 32
        args.clients_swarm = 400
        args.clients_actor = 120
        args.swarm_rounds = 6
        args.recovery_runs = 2
        args.attacks = [ATTACK_CELLS[0], ATTACK_CELLS[4]]
        args.faults = ["none", "crash_restart"]
        args.aggregators = AGG_CELLS[:2]
        # keep every ADAPTIVE forensics cell (the smoke's whole point is
        # "each adaptive attacker gets flagged"); drop the static extras
        args.forensics_cells = [c for c in FORENSICS_CELLS if c[5]]
        args.forensics_honest_seeds = 2
        args.honest_configs = HONEST_CONFIGS[:2] + HONEST_CONFIGS[3:]
    lanes = {s.strip() for s in args.lanes.split(",") if s.strip()}

    meta = {
        "lane": "meta",
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "seed": args.seed,
        "smoke": bool(args.smoke),
    }
    _emit(meta, args.out)

    grid = run_grid(args, args.out) if "grid" in lanes else []
    adaptive = run_adaptive(args, args.out) if "adaptive" in lanes else []
    serving = run_serving(args, args.out) if "serving" in lanes else []
    swarm = run_swarm(args, args.out) if "swarm" in lanes else None
    recovery = run_recovery(args, args.out) if "recovery" in lanes else None
    forensics = run_forensics(args, args.out) if "forensics" in lanes else None
    ragged = run_ragged(args, args.out) if "ragged" in lanes else None
    shard = run_shard(args, args.out) if "shard" in lanes else None
    speculative = (
        run_speculative(args, args.out) if "speculative" in lanes else None
    )
    subint8 = run_subint8(args, args.out) if "subint8" in lanes else None
    sanitized = run_sanitize(args, args.out) if "sanitize" in lanes else None

    crashed = [r for r in grid if r.get("harness_crashed")]
    headline = {
        "lane": "headline",
        "metric": "chaos_grid_cells",
        "value": len(grid),
        "crashed_cells": len(crashed),
        "breached_cells": sum(
            1 for r in grid if r.get("verdict") == "breached"
        ),
        "adaptive_beats_static": sum(
            1 for r in adaptive if r["adaptive_beats_static"]
        ),
        "serving_abuse_outcomes": {
            r["aggregator"]: r["outcome"] for r in serving
        },
        "swarm_submissions_per_sec": (
            swarm["submissions_per_sec"] if swarm else None
        ),
        "recovery_violations": (
            recovery["kill_violations"] + recovery["wire_violations"]
            if recovery
            else None
        ),
        "forensics_adaptive_within_budget": (
            forensics["adaptive_within_budget"] if forensics else None
        ),
        "forensics_honest_worst_fp": (
            forensics["honest_worst_fp_rate"] if forensics else None
        ),
        "ragged_door_digest_match": (
            ragged["digest_match"] if ragged else None
        ),
        "shard_forged_detected": (
            {k: v["forged_detected"] for k, v in shard["forgery"].items()}
            if shard
            else None
        ),
        "speculative_repair_parity": (
            speculative["repair_parity"] if speculative else None
        ),
        "subint8_shaping_flagged": (
            subint8["shaping_all_flagged"] if subint8 else None
        ),
        "subint8_honest_worst_fp": (
            subint8["honest_worst_fp_rate"] if subint8 else None
        ),
        "subint8_floor_by_aggregator": (
            subint8["floor_by_aggregator"] if subint8 else None
        ),
        "sanitize_digest_parity": (
            sanitized["digest_parity"] if sanitized else None
        ),
    }
    _emit(headline, args.out)

    if args.smoke and recovery is not None:
        assert recovery["kill_violations"] == 0, recovery
        assert recovery["wire_violations"] == 0, recovery
        assert recovery["recovery_metric_exported"], recovery
    if args.smoke and "adaptive" in lanes:
        assert headline["adaptive_beats_static"] >= 1, (
            "no adaptive attacker beat its static counterpart"
        )
    if args.smoke and "grid" in lanes:
        assert not crashed, f"harness-crashed cells: {crashed}"
        # replay determinism: rerun one cell, digests must match
        cell = Scenario(
            name="smoke-replay",
            seed=args.seed,
            n_clients=args.clients_grid,
            n_byzantine=args.byzantine,
            dim=args.dim,
            rounds=args.rounds,
            aggregator="trimmed_mean",
            aggregator_params={"f": 3},
            attack=AttackSpec(name="influence_ascent"),
            faults=FAULT_CELLS["crash_restart"],
        )
        d1 = ChaosHarness(cell).run().trace.digest()
        d2 = ChaosHarness(cell).run().trace.digest()
        assert d1 == d2, "chaos cell not replayable"
    if args.smoke and swarm is not None:
        assert swarm["rounds"] > 0 and swarm["submissions"] > 0
    if args.smoke and shard is not None:
        # run_shard asserts parity + detection internally; pin the
        # headline shape so a silently-skipped lane can't look green
        assert shard["parity"] == "bit-identical", shard
        assert all(
            v["forged_detected"] == v["rounds"]
            for v in shard["forgery"].values()
        ), shard
        # streaming root merge (ISSUE 18) must not move a single digit
        # of the lane: arrival-driven verify+fold digest-equal to the
        # barrier path, forgery detection rate unchanged
        assert shard["streaming_parity"] == "bit-identical", shard
        assert all(
            v["streaming_forged_detected"] == v["rounds"]
            for v in shard["forgery"].values()
        ), shard
    if args.smoke and speculative is not None:
        # run_speculative asserts repair parity + replay/forgery
        # rejection internally; pin the headline shape here too
        assert speculative["repair_parity"] == "bit-identical", speculative
        assert speculative["repair_parity_rounds"] > 0, speculative
        assert (
            speculative["forged_late_rejected"] == speculative["rounds"]
        ), speculative
        # streaming composes with the speculative close: the repair
        # reuses the arrival-time verify and stays bit-identical
        assert (
            speculative["streaming_repair_parity"] == "bit-identical"
        ), speculative
        assert (
            speculative["streaming_repair_rounds"]
            == speculative["rounds"]
        ), speculative
    if args.smoke and subint8 is not None:
        assert subint8["shaping_all_flagged"], subint8
        assert subint8["residual_shaping_fired"], subint8
        assert subint8["fp_within_bound"], subint8
        assert subint8["int8_floor_clean"], subint8
    if args.smoke and sanitized is not None:
        # the sanitizer is a pure observer with teeth: bit-identical
        # digests, zero violations, and the audits really ran
        assert sanitized["digest_parity"], sanitized
        assert sanitized["violations"] == [], sanitized
        assert sanitized["folds_audited"] > 0, sanitized
    if args.smoke and forensics is not None:
        assert forensics["adaptive_all_flagged"], forensics
        assert forensics["adaptive_within_budget"], forensics
        assert forensics["fp_within_bound"], forensics
        assert forensics["digest_parity"], forensics
        assert forensics["wal_audit_ok"], forensics
        assert forensics["prometheus_ok"], forensics
    if args.smoke:
        print("chaos smoke OK")


if __name__ == "__main__":
    main()

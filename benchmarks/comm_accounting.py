"""Per-round communication accounting + the 8→128-chip analytic model.

Parses the collectives out of the COMPILED fused PS and gossip steps
(:mod:`byzpy_tpu.parallel.comms` — the byte counts come from XLA's
optimized HLO, not hand math), then projects weak-scaling efficiency
against v5e ICI bandwidth. Writes ``docs/comm_model.md``.

Run: ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu python benchmarks/comm_accounting.py --write``
"""

from __future__ import annotations

import argparse
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fmt_bytes(b: float) -> str:
    """Human bytes, binary units."""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024:
            return f"{b:.1f} {unit}"
        b /= 1024
    return f"{b:.1f} TiB"


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--write", action="store_true")
    parser.add_argument("--d", type=int, default=1_000_000, help="model params")
    args = parser.parse_args()

    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from byzpy_tpu.models.bundle import ModelBundle
    from byzpy_tpu.ops import robust
    from byzpy_tpu.parallel.comms import collective_traffic, scaling_model
    from byzpy_tpu.parallel.gossip import GossipStepConfig, build_ring_gossip_train_step
    from byzpy_tpu.parallel.mesh import node_mesh
    from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step

    n = len(jax.devices())
    mesh = node_mesh(n)
    d = args.d
    dt_bytes = 4

    # A linear model with exactly d parameters: the comm pattern of the PS
    # round depends only on (n, d, dtype), so this stands in for any model
    # of that size while keeping compile fast.
    w0 = jnp.zeros((d,), jnp.float32)

    def apply_fn(params, x):
        return x @ params

    def loss_fn(params, x, y):
        return jnp.mean((x @ params - y) ** 2)

    bundle = ModelBundle(apply_fn=apply_fn, params=w0, loss_fn=loss_fn)

    ps_cfg = PSStepConfig(n_nodes=n, n_byzantine=max(1, n // 4))
    step, opt0 = build_ps_train_step(
        bundle, partial(robust.multi_krum, f=max(1, n // 4), q=n // 2),
        ps_cfg, mesh=mesh,
    )
    xs = jnp.zeros((n, 4, d), jnp.float32)
    ys = jnp.zeros((n, 4), jnp.float32)
    key = jax.random.PRNGKey(0)
    ps_traffic = collective_traffic(step, bundle.params, opt0, xs, ys, key)

    g_cfg = GossipStepConfig(n_nodes=n, n_byzantine=0)
    gstep, ginit = build_ring_gossip_train_step(
        bundle, robust.coordinate_median, g_cfg, mesh, k=1
    )
    gx = jnp.zeros((n, 4, d), jnp.float32)
    gy = jnp.zeros((n, 4), jnp.float32)
    g_traffic = collective_traffic(gstep, ginit(), gx, gy, key)

    rows = []
    for name, tr in (("fused PS round (Multi-Krum)", ps_traffic),
                     ("ring gossip round (median)", g_traffic)):
        per = ", ".join(
            f"{op}: {fmt_bytes(v)}" for op, v in sorted(tr["per_opcode_bytes"].items())
        )
        rows.append((name, tr["wire_bytes_per_device"], per))
        print(f"{name}: {fmt_bytes(tr['wire_bytes_per_device'])}/device/round ({per})")

    # Scaling model for the PS round. Dominant wire terms per device:
    #   gradient transpose (all-to-all): d*dt*(g-1)/g ~ d*dt
    #   result broadcast (all-gather of the (d,) update): d*dt*(g-1)/g
    # Per-device payload is ~2*d*dt, INDEPENDENT of chip count — the
    # (g-1)/g factor saturates — which is what makes the round
    # weak-scalable: efficiency at 128 chips is within a couple % of 8.
    # The ABSOLUTE overhead depends on arithmetic intensity: workloads
    # below span the realistic range (the reference's benchmark models).
    workloads = [
        # (label, params d, fwd FLOPs/sample, batch/node/round, grad bytes)
        ("MLP-1M f32 b64 (low intensity)", 1_000_000, 2.0 * 1_000_000, 64, 4),
        ("ResNet-18 f32 b64", 11_200_000, 1.8e9, 64, 4),
        ("ResNet-18 bf16 b128", 11_200_000, 1.8e9, 128, 2),
        ("ResNet-50 bf16 b128", 25_600_000, 4.1e9, 128, 2),
    ]
    tables = []
    for label, dd, fwd_flops, batch, gbytes in workloads:
        flops = 3.0 * fwd_flops * batch  # fwd + ~2x bwd
        wire_fn = lambda g, dd=dd, gb=gbytes: 2.0 * dd * gb * (g - 1) / g  # noqa: E731
        points = scaling_model(flops_per_chip=flops, wire_bytes_fn=wire_fn)
        tables.append((label, points))
        print(f"\n{label} (v5e ICI 45 GB/s/dir, MFU 0.4):")
        for p in points:
            print(
                f"  {p.n_chips:4d} chips: compute {p.compute_s * 1e6:8.1f} us, "
                f"comm {p.comm_s * 1e6:8.1f} us, efficiency {p.efficiency:.1%}"
            )

    if args.write:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        lines = [
            "# Communication model (measured from compiled HLO)",
            "",
            "Byte counts below are parsed from the OPTIMIZED HLO of the",
            "compiled round steps (`byzpy_tpu.parallel.comms`), so they are",
            "properties of the artifact XLA actually runs, not estimates.",
            f"Mesh: {n} devices; model: d = {d:,} f32 params.",
            "",
            "| step | wire bytes / device / round | by collective |",
            "|---|---|---|",
        ]
        for name, total, per in rows:
            lines.append(f"| {name} | {fmt_bytes(total)} | {per} |")
        lines += [
            "",
            "## Weak-scaling projection (PS round)",
            "",
            "Per-device wire bytes are ~`2 * d * dtype` regardless of chip",
            "count (the all-to-all and all-gather `(g-1)/g` factors",
            "saturate), so the comm term is CONSTANT in N: efficiency at",
            "128 chips stays within ~3% of 8 chips for every workload —",
            "that relative retention is the 8->128 >=90% scaling claim.",
            "The absolute overhead depends on arithmetic intensity",
            "(FLOPs/sample vs gradient bytes): low-intensity dense probes",
            "are comm-bound at small batch, the reference's actual",
            "benchmark models (ResNets) clear 90% absolute at bf16",
            "gradients and batch 128. Assumptions: v5e peak 197 Tf/s bf16",
            "at 40% MFU, ICI 45 GB/s per direction, no compute/comm",
            "overlap (pessimistic).",
            "",
        ]
        for label, points in tables:
            lines += [f"### {label}", "",
                      "| chips | compute/round | exposed comm | efficiency |",
                      "|---|---|---|---|"]
            for p in points:
                lines.append(
                    f"| {p.n_chips} | {p.compute_s * 1e6:.1f} us | "
                    f"{p.comm_s * 1e6:.1f} us | {p.efficiency:.1%} |"
                )
            lines.append("")
        lines += [
            "Byzantine aggregation itself is chip-local after the",
            "transpose (coordinate-wise families) or an (n, n) Gram psum",
            "(geometric families) — both negligible next to the gradient",
            "transpose at d >= 1M.",
            "",
        ]
        out = os.path.join(here, "docs", "comm_model.md")
        with open(out, "w") as fh:
            fh.write("\n".join(lines))
        print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Error-feedback convergence study: sub-int8 compression with and
without residual carry (ISSUE 15 acceptance: EF demonstrably
non-compounding).

One fused PS training run per (precision x error_feedback) cell — the
REAL ``build_ps_train_step`` on the 8-way CPU mesh with the
gradient-transpose fabric AND the params gather compressed — tracked
against the f32 twin for N full-batch rounds in the regime where
blockwise coding actually biases: **outlier-dominated blocks** (every
16th input feature is hot, so one coordinate sets each 256-wide block's
absmax and its quiet neighbors sit in the coarse grid's dead zone —
the embedding/layer-norm gradient shape). ``traj_dist_curve`` is
||params - params_f32|| sampled over rounds.

What the committed rows show (the study's science, reported as
measured):

* **s4 without EF ratchets**: deterministic round-to-nearest on a
  uniform 4-bit grid re-rounds the quiet coordinates the same way
  every round — the trajectory distance to f32 GROWS monotonically all
  run (compounding loss). **s4 with EF plateaus**: the carried
  residual re-injects what the grid lost, the transmitted stream
  telescopes, and the distance flattens — tracking f32 where no-EF
  diverges. The assertion: no-EF/EF final-distance ratio >=
  ``S4_EF_WIN_FLOOR`` AND the no-EF curve is still climbing at the end
  while the EF curve is flat.
* **fp8 is self-limiting**: e4m3's mantissa makes the rounding error
  RELATIVE per value, so quiet coordinates keep proportional accuracy
  and no dead zone forms — fp8 without EF stays bounded near f32, and
  EF only adds dither (parity within ``FP8_EF_PARITY``). That is a
  finding, not a failure: the byte-identical fp8 tier buys accuracy
  headroom instead of needing state, while the half-byte s4 tier needs
  EF to be usable at all — the precision ladder's real trade.

Appends one provenance-stamped JSON line per cell (plus a summary) to
``results/round15_subint8_<platform>.jsonl`` (``--out`` overrides).

Run: ``JAX_PLATFORMS=cpu python benchmarks/ef_convergence_study.py``
(the contract assertions always run; ``--rounds``/``--out`` for local
iteration — there is no ``--smoke`` shrink because the s4 crossover is
a late-round phenomenon).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))

#: s4 no-EF over with-EF final trajectory-distance floor (committed CPU
#: rows sit ~1.15 at 500 rounds and keep widening — no-EF is still
#: climbing when the run ends).
S4_EF_WIN_FLOOR = 1.05
#: fp8 with-EF must stay within this factor of the (already bounded)
#: no-EF distance — EF is optional at fp8, never catastrophic.
FP8_EF_PARITY = 2.0


def main() -> int:
    # no --smoke shrink here, deliberately: the s4 no-EF/EF crossover
    # is a LATE-round phenomenon (the ratchet has to outrun the EF
    # dither) and a shrunk cell sits before it — the model is tiny and
    # compiles dominate, so CI runs the full 500-round study and its
    # hard assertions as-is (--rounds exists for local iteration)
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None, help="JSONL sink override")
    ap.add_argument("--rounds", type=int, default=None)
    args = ap.parse_args()

    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from byzpy_tpu.models.bundle import ModelBundle
    from byzpy_tpu.parallel.mesh import node_mesh
    from byzpy_tpu.parallel.ps import (
        PSStepConfig,
        ShardedUpdateConfig,
        build_ps_train_step,
    )
    from byzpy_tpu.parallel.quantization import CommPrecision

    platform = jax.default_backend()
    rounds = args.rounds or 500
    d_in, d_out = 96, 16
    n = 8
    mesh = node_mesh(8)

    params0 = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (d_in, d_out)) * 0.1
    }
    bundle = ModelBundle(
        apply_fn=lambda p, xb: xb @ p["w"],
        params=params0,
        loss_fn=lambda p, xb, yb: jnp.mean((xb @ p["w"] - yb) ** 2),
    )
    cfg = PSStepConfig(
        n_nodes=n, n_byzantine=0, learning_rate=0.01, momentum=0.0
    )
    w_true = jax.random.normal(jax.random.PRNGKey(1), (d_in, d_out)) * 0.3
    # outlier-dominated blocks: every 16th input feature is 8x hot, so
    # each 256-wide flat block (16 features x 16 outputs, the ravel of
    # w) has one feature whose gradient sets the block absmax and 15
    # quiet neighbors living on the resulting coarse grid
    feat_scales = np.ones(d_in, np.float32)
    feat_scales[::16] = 8.0
    xs = (
        jax.random.normal(jax.random.PRNGKey(2), (n, 32, d_in))
        * jnp.asarray(feat_scales)[None, None, :]
    )
    ys = xs @ w_true + 0.02 * jax.random.normal(
        jax.random.PRNGKey(3), (n, 32, d_out)
    )

    def run_cell(precision):
        su = ShardedUpdateConfig(mode="on", param_gather_precision=precision)
        step, o0 = build_ps_train_step(
            bundle, lambda m: jnp.mean(m, axis=0), cfg,
            mesh=mesh, comm_precision=precision, sharded_update=su,
        )
        jstep = jax.jit(step)
        p, o = bundle.params, o0
        traj, metrics = [], {}
        for r in range(rounds):
            p, o, metrics = jstep(p, o, xs, ys, jax.random.PRNGKey(100 + r))
            if r % 20 == 0 or r == rounds - 1:
                traj.append(np.asarray(p["w"]))
        return traj, metrics

    out_path = args.out or os.path.join(
        HERE, "results", f"round15_subint8_{platform}.jsonl"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    provenance = {
        "platform": platform, "rounds": rounds,
        "d": d_in * d_out, "n": n, "regime": "outlier_blocks",
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    f32_traj, f32_metrics = run_cell("off")
    f32_loss = float(f32_metrics["honest_loss"])
    rows, dists, losses = [], {}, {}
    for mode in ("fp8", "s4"):
        for ef in (False, True):
            traj, metrics = run_cell(
                CommPrecision(mode=mode, error_feedback=ef)
            )
            dist = [
                float(np.linalg.norm(t - ft))
                for t, ft in zip(traj, f32_traj, strict=True)
            ]
            dists[(mode, ef)] = dist
            losses[(mode, ef)] = float(metrics["honest_loss"])
            row = {
                "bench": "ef_convergence", "mode": mode,
                "error_feedback": ef,
                "traj_dist_final": round(dist[-1], 6),
                "traj_dist_mid": round(dist[len(dist) // 2], 6),
                "traj_dist_curve": [round(v, 5) for v in dist],
                "final_loss": round(losses[(mode, ef)], 6),
                "f32_loss": round(f32_loss, 6),
                "loss_excess_vs_f32": round(
                    losses[(mode, ef)] - f32_loss, 6
                ),
                "ef_resid_transpose": (
                    round(float(metrics["ef_transpose_norm"]), 6)
                    if "ef_transpose_norm" in metrics else None
                ),
                "ef_resid_gather": (
                    round(float(metrics["ef_gather_norm"]), 6)
                    if "ef_gather_norm" in metrics else None
                ),
                **provenance,
            }
            rows.append(row)
            print(json.dumps(row))

    def still_climbing(dist):
        return dist[-1] > dist[len(dist) // 2] * 1.02

    s4_ratio = dists[("s4", False)][-1] / max(dists[("s4", True)][-1], 1e-12)
    fp8_ratio = dists[("fp8", True)][-1] / max(
        dists[("fp8", False)][-1], 1e-12
    )
    summary = {
        "bench": "ef_convergence_summary",
        "s4_noef_over_ef_final_dist": round(s4_ratio, 3),
        "s4_noef_still_climbing": still_climbing(dists[("s4", False)]),
        "s4_ef_plateaued": not still_climbing(dists[("s4", True)]),
        "s4_ef_win_floor": S4_EF_WIN_FLOOR,
        "fp8_ef_over_noef_final_dist": round(fp8_ratio, 3),
        "fp8_parity_bound": FP8_EF_PARITY,
        "fp8_noef_bounded": not still_climbing(dists[("fp8", False)]),
        "loss_excess": {
            f"{m}_{'ef' if e else 'noef'}": round(
                losses[(m, e)] - f32_loss, 6
            )
            for (m, e) in losses
        },
        **provenance,
    }
    rows.append(summary)
    print(json.dumps(summary))
    with open(out_path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows -> {out_path}")

    ok = (
        s4_ratio >= S4_EF_WIN_FLOOR
        and summary["s4_noef_still_climbing"]
        and summary["s4_ef_plateaued"]
        and fp8_ratio <= FP8_EF_PARITY
    )
    if not ok:
        print(f"FAIL: EF contract not met: {summary}", file=sys.stderr)
        return 1
    print(
        "EF non-compounding: s4-with-EF tracks f32 where s4-without-EF "
        f"still climbs (ratio {s4_ratio:.2f}); fp8 self-limiting "
        f"(EF parity {fp8_ratio:.2f}) OK"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

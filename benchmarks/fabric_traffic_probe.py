"""Per-fabric HLO collective accounting at an arbitrary mesh size.

``python benchmarks/fabric_traffic_probe.py <fabric> <n>`` compiles one
round of the named fabric over an ``n``-virtual-device CPU mesh and
prints ONE JSON object with the per-device collective bytes parsed from
the optimized HLO (:mod:`byzpy_tpu.parallel.comms`).

Fabrics:

* ``ps`` — fused SPMD parameter-server round (trimmed mean, d=100k
  linear model). Dominant wire terms: gradient-transpose all-to-all +
  update all-gather, both carrying the saturating ``(g-1)/g`` factor.
* ``gossip`` — ring gossip round (``ppermute`` neighbor exchange);
  per-device bytes are CONSTANT in n (each chip talks to 2k neighbors
  regardless of ring size).
* ``ring_attention`` — sequence-parallel LM grad step; K/V blocks
  rotate via ``ppermute`` inside a ``fori_loop``, so the law is
  per-iteration bytes ~ block size (∝ 1/n) times (n-1) trips.

``tests/test_scaling_model.py`` runs this at n ∈ {8, 16, 32} and pins
the measured inventories against those closed-form laws — the evidence
behind ``docs/comm_model.md``'s 8→128 extrapolation.

Run in a SUBPROCESS: the CPU platform + device count are pinned below
before any jax backend touch.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    fabric = sys.argv[1]
    n = int(sys.argv[2])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n}"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp

    from byzpy_tpu.models.bundle import ModelBundle
    from byzpy_tpu.ops import robust
    from byzpy_tpu.parallel.comms import collective_traffic
    from byzpy_tpu.parallel.mesh import node_mesh

    assert len(jax.devices()) == n, jax.devices()
    mesh = node_mesh(n)
    key = jax.random.PRNGKey(0)
    d = 100_000

    w0 = jnp.zeros((d,), jnp.float32)
    bundle = ModelBundle(
        apply_fn=lambda params, x: x @ params,
        params=w0,
        loss_fn=lambda params, x, y: jnp.mean((x @ params - y) ** 2),
    )

    if fabric == "ps":
        from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step

        f = max(1, n // 4)
        cfg = PSStepConfig(n_nodes=n, n_byzantine=0)
        step, opt0 = build_ps_train_step(
            bundle, lambda m: robust.trimmed_mean(m, f=f), cfg, mesh=mesh
        )
        xs = jnp.zeros((n, 4, d), jnp.float32)
        ys = jnp.zeros((n, 4), jnp.float32)
        traffic = collective_traffic(step, bundle.params, opt0, xs, ys, key)
        extra = {"d": d, "dtype_bytes": 4}
    elif fabric == "gossip":
        from byzpy_tpu.parallel.gossip import (
            GossipStepConfig,
            build_ring_gossip_train_step,
        )

        cfg = GossipStepConfig(n_nodes=n, n_byzantine=0)
        gstep, ginit = build_ring_gossip_train_step(
            bundle, robust.coordinate_median, cfg, mesh, k=1
        )
        gx = jnp.zeros((n, 4, d), jnp.float32)
        gy = jnp.zeros((n, 4), jnp.float32)
        traffic = collective_traffic(gstep, ginit(), gx, gy, key)
        extra = {"d": d, "dtype_bytes": 4, "k": 1}
    elif fabric == "ring_attention":
        import optax
        from jax.sharding import PartitionSpec as P

        from byzpy_tpu.models.transformer import TransformerLM
        from byzpy_tpu.parallel.collectives import sharded_fn

        L, vocab, dim, heads = 8 * n, 16, 16, 2
        lm = TransformerLM(
            vocab_size=vocab, dim=dim, depth=1, num_heads=heads, max_len=L,
            attention="ring", ring_axis="nodes",
        )
        params = lm.init(jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32))
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, L), 0, vocab)

        def sp_loss(p, toks):
            def block_loss(tk):
                logits = lm.apply(p, tk[:, :-1])
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, tk[:, 1:]
                )
                return jax.lax.pmean(ce.mean(), "nodes")

            return sharded_fn(
                mesh, "nodes", block_loss, in_spec=P(None, "nodes"),
                out_spec=P(),
            )(toks)

        grad_fn = jax.jit(jax.value_and_grad(sp_loss))
        traffic = collective_traffic(grad_fn, params, tokens)
        extra = {
            "seq_len": L, "dim": dim, "heads": heads, "batch": 2,
            "ring_trips": n - 1,
        }
    else:
        raise SystemExit(f"unknown fabric {fabric!r}")

    print(json.dumps({
        "fabric": fabric,
        "n": n,
        "wire_bytes_per_device": traffic["wire_bytes_per_device"],
        "loop_body_bytes_per_iteration": traffic[
            "loop_body_bytes_per_iteration"
        ],
        "per_opcode_bytes": {
            k: int(v) for k, v in traffic["per_opcode_bytes"].items()
        },
        **extra,
    }))


if __name__ == "__main__":
    main()

"""The full reference benchmark grid, measured on this machine's default
JAX backend (the real TPU chip under the driver).

One row per workload of ``byzpy/benchmarks/README.md:10-30`` — identical
shapes and hyperparameters — plus the 1M-dim north-star shapes. Each JSON
line carries the reference's published CPU latencies (ByzFL, ByzPy direct,
ByzPy best pool; from BASELINE.md, timeouts as None) so speedups are
computed from committed data, not prose.

Usage: python benchmarks/full_grid.py [--repeat N] > benchmarks/results/grid.jsonl
"""

import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

import asyncio
from functools import partial

import jax
import jax.numpy as jnp

from _timing import report, timed_ms
from byzpy_tpu.aggregators import MinimumDiameterAveraging, MultiKrum, SMEA
from byzpy_tpu.engine.parameter_server import ParameterServer
from byzpy_tpu.ops import attack_ops, preagg, robust


def grads(n, d, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (n, d), jnp.float32)


def row(name, ms, byzfl, direct, best_pool, **extra):
    """Emit one grid row with the reference floor and computed speedups.
    "Best" = the reference's best published number: its best pool, or its
    direct time where its own pooling made it slower (same rule as
    generate_plots.py / RESULTS.md)."""
    candidates = [v for v in (best_pool, direct) if v is not None]
    best = min(candidates) if candidates else None
    speedup = round(best / ms, 2) if best else None
    report(
        name, ms,
        ref_byzfl_ms=byzfl, ref_direct_ms=direct, ref_best_pool_ms=best_pool,
        speedup_vs_ref_best=speedup, **extra,
    )


def ps_multi_krum_round_ms(rounds=50):
    """Reference row 12: end-to-end PS with Multi-Krum, 10 honest + 3
    byzantine nodes, 50 rounds (ref benchmarks/README.md:23). Nodes hold
    SmallCNN-scale gradients (d=21,840 ~= the reference's MNIST SmallCNN).

    Node-local gradient computation happens on the HOST (numpy), exactly
    like the reference's CPU nodes — and so do the attack and the robust
    aggregate, via the framework's latency-aware placement policy
    (``utils.placement``): all inputs are host-resident and far below the
    size cap, so the whole round runs on the CPU backend with ZERO
    accelerator traffic. Through a network-tunneled chip this is the
    difference between ~24 ms/round (transfer + dispatch bound, and
    unstable under tunnel backpressure) and a stable single-digit round.
    Device-resident nodes belong to the fused SPMD path (parallel/ps.py)."""
    import numpy as np
    import time

    from byzpy_tpu.attacks import EmpireAttack

    d = 21_840

    class Node:
        def __init__(self, i):
            self.rng = np.random.default_rng(i)
            self.grad = None

        def honest_gradient_for_next_batch(self):
            return [self.rng.standard_normal(d, dtype=np.float32)]

        def apply_server_gradient(self, g):
            self.grad = g

    class Byz(Node):
        attack = EmpireAttack(scale=-1.0)

        def byzantine_gradient_for_next_batch(self, honest):
            return [self.attack.apply_placed(honest_grads=[h[0] for h in honest])]

    ps = ParameterServer(
        honest_nodes=[Node(i) for i in range(10)],
        byzantine_nodes=[Byz(100 + i) for i in range(3)],
        aggregator=MultiKrum(f=3, q=5),
    )

    async def run():
        for _ in range(rounds):
            out = await ps.round()
        jax.block_until_ready(out)

    # warmup (compile)
    asyncio.run(_once(ps))
    t0 = time.perf_counter()
    asyncio.run(run())
    total = time.perf_counter() - t0
    return total / rounds * 1e3


async def _once(ps):
    out = await ps.round()
    jax.block_until_ready(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeat", type=int, default=20)
    args = ap.parse_args()
    r = args.repeat

    t = partial(timed_ms, repeat=r)
    print(f"# backend={jax.default_backend()} device={jax.devices()[0]}",
          file=sys.stderr)

    # -- the reference's 19-workload grid (BASELINE.md rows, same order) -----
    mda = MinimumDiameterAveraging(f=10)
    row("mda_30x2048_f10", t(lambda x: mda.aggregate(x), grads(30, 2048)),
        None, 353, 166)
    smea = SMEA(f=5)
    row("smea_16x4096_f5", t(lambda x: smea.aggregate(x), grads(16, 4096)),
        None, 82, 48.0)
    row("arc_256x65536_f8", t(jax.jit(partial(preagg.arc_clip, f=8)), grads(256, 65536)),
        191.27, 20.77, 50.87)
    row("cw_trimmed_mean_64x65536_f8",
        t(jax.jit(partial(robust.trimmed_mean, f=8)), grads(64, 65536)),
        68.08, 65.52, 15.15)
    row("cw_median_64x65536", t(jax.jit(robust.coordinate_median), grads(64, 65536)),
        None, 52, 37)
    row("multi_krum_80x65536_f20_q12",
        t(jax.jit(partial(robust.multi_krum, f=20, q=12)), grads(80, 65536)),
        78.17, 59.66, 26.30)
    row("geometric_median_64x65536",
        t(jax.jit(robust.geometric_median), grads(64, 65536)),
        None, 398.21, 142.97)
    row("caf_64x65536_f8", t(jax.jit(partial(robust.caf, f=8)), grads(64, 65536)),
        72.65, 54.51, 54.94)
    row("monna_64x65536_f8", t(jax.jit(partial(robust.monna, f=8)), grads(64, 65536)),
        51, 67, 11)
    row("centered_clipping_64x65536_M10",
        t(jax.jit(partial(robust.centered_clipping, c_tau=10.0, M=10)), grads(64, 65536)),
        146, 112, 50)
    row("cge_64x65536_f8", t(jax.jit(partial(robust.cge, f=8)), grads(64, 65536)),
        None, 100, 23)
    row("ps_multi_krum_10h_3b_per_round", ps_multi_krum_round_ms(),
        57, 71, 42, rounds=50)
    row("empire_64x65536",
        t(jax.jit(partial(attack_ops.empire, scale=-1.0)), grads(64, 65536)),
        50, 34, 14)
    row("little_96x65536_f12",
        t(jax.jit(partial(attack_ops.little, f=12, n_total=96)), grads(96, 65536)),
        70.39, 67.03, 32.86)
    row("gaussian_64x65536",
        t(jax.jit(lambda k: attack_ops.gaussian(k, (65536,))), jax.random.PRNGKey(1)),
        44.33, 12.6, 12.3)
    row("nnm_196x4096_f32", t(jax.jit(partial(preagg.nnm, f=32)), grads(196, 4096)),
        58, 12, 137)
    row("meamed_64x65536_f8",
        t(jax.jit(partial(robust.mean_of_medians, f=8)), grads(64, 65536)),
        152, 113, 59)
    perm = jax.random.permutation(jax.random.PRNGKey(2), 512)
    row("bucketing_512x16384_b32",
        t(jax.jit(partial(preagg.bucket_means, bucket_size=32)),
          grads(512, 16384), perm),
        23, 13.4, 21.7)
    row("clipping_256x65536_t2",
        t(jax.jit(partial(preagg.clip_rows, threshold=2.0)), grads(256, 65536)),
        382, 46, 61)

    # -- north-star 1M-dim shapes (no published reference numbers) ----------
    report("cw_median_64x1M", t(jax.jit(robust.coordinate_median), grads(64, 1 << 20)))
    report("multi_krum_64x1M_f8_q12",
           t(jax.jit(partial(robust.multi_krum, f=8, q=12)), grads(64, 1 << 20)))
    report("multi_krum_bf16_64x1M_f8_q12",
           t(jax.jit(partial(robust.multi_krum, f=8, q=12)),
             grads(64, 1 << 20).astype(jnp.bfloat16)))


if __name__ == "__main__":
    main()

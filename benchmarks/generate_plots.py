"""Render the measured grid into comparison plots
(ref: ``byzpy/benchmarks/pytorch/generate_benchmark_plots.py``).

Reads ``benchmarks/results/grid.jsonl`` (written by ``full_grid.py``) and
produces:

* ``results/grid_latency.png`` — per-workload latency, byzpy_tpu vs the
  reference's best published number (log scale);
* ``results/grid_speedup.png`` — speedup bars vs the reference best.

Matplotlib only; no seaborn, no style deps.
"""

import json
import os
import sys

from _plotting import RESULTS, load_jsonl, plt


def load_grid(path=None):
    path = path or os.path.join(RESULTS, "grid.jsonl")
    rows = [
        row for row in load_jsonl(path)
        if "ref_best_pool_ms" in row or "ref_direct_ms" in row
    ]
    # supersede rows with re-measured values (each override carries a
    # provenance note; see results/overrides.jsonl)
    override_path = os.path.join(os.path.dirname(path), "overrides.jsonl")
    if os.path.exists(override_path):
        by_name = {r["workload"]: i for i, r in enumerate(rows)}
        with open(override_path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                ov = json.loads(line)
                if ov["workload"] in by_name:
                    rows[by_name[ov["workload"]]] = ov
    return rows


def ref_best(row):
    """The reference's best published latency for this workload: its best
    pool unless its own pooling made it slower than direct."""
    candidates = [
        v for v in (row.get("ref_best_pool_ms"), row.get("ref_direct_ms"))
        if v is not None
    ]
    return min(candidates) if candidates else None


def main() -> None:
    rows = load_grid(sys.argv[1] if len(sys.argv) > 1 else None)
    rows = [r for r in rows if ref_best(r) is not None]
    rows.sort(key=lambda r: ref_best(r) / r["ms"], reverse=True)
    names = [r["workload"] for r in rows]
    ours = [r["ms"] for r in rows]
    refs = [ref_best(r) for r in rows]

    # latency comparison
    fig, ax = plt.subplots(figsize=(10, 0.42 * len(rows) + 1.5))
    y = range(len(rows))
    ax.barh([i + 0.2 for i in y], refs, height=0.38,
            label="reference (best published, CPU)", color="#b0b7c3")
    ax.barh([i - 0.2 for i in y], ours, height=0.38,
            label="byzpy_tpu (one v5e)", color="#3b6fd4")
    ax.set_yticks(list(y), names, fontsize=8)
    ax.set_xscale("log")
    ax.set_xlabel("latency, ms (log scale; lower is better)")
    ax.legend(loc="lower right", fontsize=8)
    ax.invert_yaxis()
    fig.tight_layout()
    fig.savefig(os.path.join(RESULTS, "grid_latency.png"), dpi=150)

    # speedups
    fig, ax = plt.subplots(figsize=(10, 0.42 * len(rows) + 1.5))
    speedups = [rf / ms for rf, ms in zip(refs, ours, strict=False)]
    colors = ["#2e9e59" if s >= 1 else "#c5483e" for s in speedups]
    ax.barh(list(y), speedups, color=colors, height=0.6)
    ax.axvline(1.0, color="black", linewidth=0.8)
    ax.set_yticks(list(y), names, fontsize=8)
    ax.set_xscale("log")
    ax.set_xlabel("speedup vs reference best (log scale; >1 = faster)")
    for i, s in enumerate(speedups):
        ax.text(s, i, f" {s:.1f}×", va="center", fontsize=7)
    ax.invert_yaxis()
    fig.tight_layout()
    fig.savefig(os.path.join(RESULTS, "grid_speedup.png"), dpi=150)
    print("wrote",
          os.path.join(RESULTS, "grid_latency.png"), "and",
          os.path.join(RESULTS, "grid_speedup.png"))


if __name__ == "__main__":
    main()

"""Headline optimization sweep: Multi-Krum 64x1M grads/sec variants.

The two-pass f32 floor is ~98k grads/sec (x read twice: Gram + selection
matvec = 536 MB at ~819 GB/s = ~0.65 ms per aggregate). This sweep
isolates what the round-2 streamed headline (40.7k) was losing to:

* scan vs vmap batching of the K rounds (scan slices 256 MB per step
  from the stacked input — if XLA materializes that slice it's a whole
  extra read+write per aggregate);
* f32 vs bf16 input (halves both passes' traffic);
* the d2-sort/rank tail (measured via krum_scores alone).

Usage: python benchmarks/headline_sweep.py [--K 8] [--repeat 15]
(~6-8 min at the defaults through the tunnel; the scan-of-kernel rows
dominate — budget 10+ min before assuming a hang)
"""

import argparse
import json
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))

from functools import partial

import jax
import jax.numpy as jnp

from byzpy_tpu.ops import robust
from byzpy_tpu.utils.metrics import timed_call_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--K", type=int, default=8)
    ap.add_argument("--repeat", type=int, default=15)
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--d", type=int, default=1_048_576)
    args = ap.parse_args()
    K, n, d = args.K, args.n, args.d

    t = partial(timed_call_s, warmup=3, repeat=args.repeat)
    agg = partial(robust.multi_krum, f=8, q=12)
    xs = jax.random.normal(jax.random.PRNGKey(0), (K, n, d), jnp.float32)
    xb = xs.astype(jnp.bfloat16)

    rows = {}

    def rec(name, secs, per_agg_div=K):
        per = secs / per_agg_div
        rows[name] = {"ms_per_agg": round(per * 1e3, 3),
                      "grads_per_sec": round(n / per, 1)}
        print(json.dumps({"workload": name, **rows[name]}), flush=True)

    # per-call single dispatch (round-1 comparable)
    rec("single_dispatch_f32", t(jax.jit(agg), xs[0]), per_agg_div=1)

    # K rounds per dispatch: scan (round-2 headline shape)
    scan_fn = jax.jit(partial(robust.aggregate_stream, agg))
    rec("stream_scan_f32", t(scan_fn, xs))

    # K rounds per dispatch: vmap (batched matmuls, no per-step slice)
    vmap_fn = jax.jit(jax.vmap(agg))
    rec("stream_vmap_f32", t(vmap_fn, xs))

    # K rounds as ONE fused Pallas launch (round-3 headline shape):
    # 2 HBM sweeps per round, no per-round slice copies
    fused_fn = jax.jit(partial(robust.multi_krum_stream, f=8, q=12))
    rec("stream_fused_f32", t(fused_fn, xs))

    # bf16 variants
    rec("stream_scan_bf16", t(scan_fn, xb))
    rec("stream_vmap_bf16", t(vmap_fn, xb))
    rec("stream_fused_bf16", t(fused_fn, xb))

    # stage floors
    rec("krum_scores_only_f32",
        t(jax.jit(jax.vmap(partial(robust.krum_scores, f=8))), xs))
    rec("gram_only_f32", t(jax.jit(jax.vmap(robust.gram_matrix)), xs))
    rec("read_sum_floor", t(jax.jit(lambda v: jnp.sum(v, axis=(1, 2))), xs))


if __name__ == "__main__":
    main()

"""Fused/donated hot-path wins, measured on the CPU backend (ISSUE 2).

The accelerator tunnel has been down for three rounds, so this bench
pins the roofline-guided surgery where the driver can always reproduce
it: ``JAX_PLATFORMS=cpu``. For each BASELINE.md grid row it times the
SHIPPED path against the superseded round-5 formulation, reconstructed
inline and clearly labeled:

* coordinate-wise rows (CW median / CwTM / MeaMed) — float-comparator
  ``jnp.sort`` / ``jnp.median`` vs the int32-key ``lax.sort``
  (``ops.robust.sort_rows``);
* selection rows (Multi-Krum / CGE / MoNNA) — unconditionally masked
  ``ranked_mean`` einsum vs the conditional-mask contraction
  (``ops.robust._selection_mean_xla``) fed from a single Gram;
* the streaming Multi-Krum fold — per-arrival list-of-einsums + barrier
  Gram assembly vs the donated staging-buffer matvec
  (``ops.robust.gram_fold_update``).

One JSON line per row: ``{"workload", "old_ms", "new_ms", "speedup"}``
plus provenance. The ISSUE acceptance bar is >= 1.15x on the Multi-Krum
and MeaMed rows with no regression elsewhere (regression guard: every
other row must stay >= 0.95x).

Usage::

    JAX_PLATFORMS=cpu python benchmarks/hotpath_cpu_bench.py \
        [--repeat N] > benchmarks/results/hotpath_cpu.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()

import jax
import jax.numpy as jnp

from _timing import timed_ms
from byzpy_tpu.ops import robust


# -- superseded round-5 formulations (inline, for the A side) -----------


def _old_median(x):
    return jnp.median(x, axis=0)


def _old_trimmed(x, f):
    n = x.shape[0]
    s = jnp.sort(x, axis=0)
    return jnp.mean(s[f : n - f], axis=0)


def _old_meamed(x, f):
    n = x.shape[0]
    k = n - f
    xs = jnp.sort(x, axis=0)
    lo, hi = (n - 1) // 2, n // 2
    half = jnp.asarray(0.5, x.dtype)
    med = xs[lo] * half + xs[hi] * half
    med = jnp.where(jnp.isnan(xs[n - 1]), jnp.asarray(jnp.nan, x.dtype), med)
    radius = jnp.maximum(med[None, :] - xs[: n - k + 1], xs[k - 1 :] - med[None, :])
    dev = jnp.abs(x - med[None, :])
    cut_nonfinite = jnp.where(
        jnp.sum(jnp.where(jnp.isnan(dev), 0, 1), axis=0) >= k,
        jnp.asarray(jnp.inf, x.dtype), jnp.asarray(jnp.nan, x.dtype),
    )
    cut = jnp.where(jnp.isfinite(med), jnp.min(radius, axis=0), cut_nonfinite)
    below = dev < cut[None, :]
    at = dev == cut[None, :]
    quota = k - jnp.sum(below, axis=0)
    take_at = at & (jnp.cumsum(at, axis=0) <= quota[None, :])
    sel = jnp.where(below | take_at, x, jnp.zeros((), x.dtype))
    out = jnp.sum(sel, axis=0) / jnp.asarray(k, x.dtype)
    return jnp.where(jnp.isnan(cut), jnp.asarray(jnp.nan, x.dtype), out)


def _old_multi_krum(x, f, q):
    return robust.ranked_mean(x, robust.krum_scores(x, f=f), q)


def _old_cge(x, f):
    return robust.ranked_mean(x, jnp.sum(x * x, axis=1), x.shape[0] - f)


def _old_monna(x, f):
    diff = x - x[0][None, :]
    return robust.ranked_mean(x, jnp.sum(diff * diff, axis=1), x.shape[0] - f)


def _fold_round_old(rows):
    """Round-5 streaming Multi-Krum fold: per arrival, one einsum per
    already-arrived row (O(n^2) dispatches per round), then the barrier
    Gram assembly."""
    n = len(rows)
    dots = []
    for k, row in enumerate(rows):
        dots.append(jnp.stack(
            [jnp.einsum("d,d->", rows[j], row) for j in range(k)]
            + [jnp.einsum("d,d->", row, row)]
        ))
    gram = jnp.zeros((n, n), rows[0].dtype)
    for k, dvec in enumerate(dots):
        gram = gram.at[k, : k + 1].set(dvec)
    gram = gram + jnp.tril(gram, -1).T
    return gram


def _fold_round_new(rows):
    """This round's fold: donated staging buffer + one matvec dispatch
    per arrival (``robust.gram_fold_update``)."""
    n, d = len(rows), rows[0].shape[0]
    buffer = jnp.zeros((n, d), rows[0].dtype)
    gram = jnp.zeros((n, n), rows[0].dtype)
    for i, row in enumerate(rows):
        buffer, gram = robust.gram_fold_update(buffer, gram, row, i)
    return gram


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeat", type=int, default=10)
    args = ap.parse_args()
    r = args.repeat

    key = jax.random.PRNGKey(0)
    x64 = jax.random.normal(key, (64, 65_536), jnp.float32)
    x80 = jax.random.normal(key, (80, 65_536), jnp.float32)

    prov = {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    rows = [
        ("meamed_64x65536_f8",
         jax.jit(partial(_old_meamed, f=8)),
         jax.jit(partial(robust.mean_of_medians, f=8)), x64),
        ("multi_krum_80x65536_f20_q12",
         jax.jit(partial(_old_multi_krum, f=20, q=12)),
         jax.jit(partial(robust.multi_krum, f=20, q=12)), x80),
        ("cw_median_64x65536",
         jax.jit(_old_median), jax.jit(robust.coordinate_median), x64),
        ("cw_trimmed_mean_64x65536_f8",
         jax.jit(partial(_old_trimmed, f=8)),
         jax.jit(partial(robust.trimmed_mean, f=8)), x64),
        ("cge_64x65536_f8",
         jax.jit(partial(_old_cge, f=8)),
         jax.jit(partial(robust.cge, f=8)), x64),
        ("monna_64x65536_f8",
         jax.jit(partial(_old_monna, f=8)),
         jax.jit(partial(robust.monna, f=8)), x64),
    ]
    for name, old_fn, new_fn, x in rows:
        old_ms = timed_ms(old_fn, x, warmup=2, repeat=r)
        new_ms = timed_ms(new_fn, x, warmup=2, repeat=r)
        print(json.dumps({
            "workload": name,
            "old_ms": round(old_ms, 3),
            "new_ms": round(new_ms, 3),
            "speedup": round(old_ms / new_ms, 3),
            **prov,
        }))
        print(f"{name:40s} {old_ms:9.2f} -> {new_ms:9.2f} ms "
              f"({old_ms / new_ms:.2f}x)", file=sys.stderr)

    # streaming fold (the PS + Multi-Krum row's ingestion path): per-round
    # wall time of the Gram fold at the reference PS gradient scale
    fold_rows = [
        jax.random.normal(jax.random.PRNGKey(i), (21_840,), jnp.float32)
        for i in range(13)
    ]
    old_ms = timed_ms(
        lambda rows_=fold_rows: _fold_round_old(rows_), warmup=2, repeat=r
    )
    # donation consumes the state buffers, so allocate fresh ones inside
    # the timed call — that allocation is part of the honest cost
    new_ms = timed_ms(
        lambda rows_=fold_rows: _fold_round_new(rows_), warmup=2, repeat=r
    )
    print(json.dumps({
        "workload": "gram_fold_round_13x21840",
        "old_ms": round(old_ms, 3),
        "new_ms": round(new_ms, 3),
        "speedup": round(old_ms / new_ms, 3),
        "note": "per-arrival einsum list + barrier assembly vs donated "
                "staging-buffer matvec (gram_fold_update)",
        **prov,
    }))
    print(f"{'gram_fold_round_13x21840':40s} {old_ms:9.2f} -> "
          f"{new_ms:9.2f} ms ({old_ms / new_ms:.2f}x)", file=sys.stderr)


if __name__ == "__main__":
    main()

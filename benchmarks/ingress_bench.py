"""Batched-ingress benchmark: the PR-16 wire-rate front door.

Measures admitted submissions/sec through one shard process for the
SAME pre-encoded frame stream served two ways:

* ``per_frame`` — the historical door: ``wire.decode_with_stats`` per
  frame (full host dequantization of compressed payloads), inflation
  stamp, ``handle_request``, ``encode_reply`` — one frame per call;
* ``batched`` — :meth:`ServingFrontend.serve_frames` over wakeup-sized
  chunks: one vectorized decode pass (amortized HMAC key schedule,
  batch-wide inflation forensics), quantized rows admitted STILL
  COMPRESSED and dequantized inside the ragged fold's jitted program.

Both doors then close identical rounds and the per-round aggregates
are compared BYTE-FOR-BYTE per precision — the speedup is only
claimable at bit parity. Each door is timed best-of-``--reps``
alternating passes (robust on a shared 1-core host). Rows emit as JSON
(stdout + ``--out`` JSONL); the headline is the fp8 speedup — the
regime the batched door exists for: the per-frame path pays a full
ml_dtypes bit-pattern -> f32 host conversion per frame, while the
batched door admits codes+scales untouched (dequantization runs inside
the ragged fold's jitted program) and its forensics pass is one
rank-LUT gather that never materializes f32 code values at all.

``--smoke`` shrinks the stream for CI and asserts >= 1.5x on the fp8
headline; the committed full run (d=16384) clears the 4x acceptance
bar.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean  # noqa: E402
from byzpy_tpu.engine.actor import wire  # noqa: E402
from byzpy_tpu.serving import ServingFrontend, TenantConfig  # noqa: E402

PRECISIONS = ("off", "bf16", "int8", "fp8", "s4")


def _emit(row: dict, out_path: str | None) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    if out_path:
        with open(out_path, "a") as fh:
            fh.write(line + "\n")


def _frontend(args) -> ServingFrontend:
    return ServingFrontend([TenantConfig(
        name="m0", dim=args.dim,
        aggregator=CoordinateWiseTrimmedMean(f=1),
        cohort_cap=args.cohort_cap, window_s=0.01,
        queue_capacity=args.frames + args.cohort_cap,
    )])


def _encode_stream(args, precision: str) -> list:
    os.environ["BYZPY_TPU_WIRE_PRECISION"] = precision
    rng = np.random.default_rng(16)
    return [
        wire.encode({
            "kind": "submit", "tenant": "m0", "client": f"c{i}",
            "round": 0,
            "gradient": rng.normal(size=args.dim).astype(np.float32),
            "seq": 0,
        })[4:]
        for i in range(args.frames)
    ]


def _close_all(fe: ServingFrontend) -> str:
    """Drain every closable round; digest the concatenated aggregate
    bytes (the bit-parity fingerprint for the whole stream)."""
    h = hashlib.sha256()
    while True:
        closed = fe.close_round_nowait("m0")
        if closed is None:
            break
        h.update(np.asarray(closed[2]).tobytes())
    return h.hexdigest()[:16]


def _run_per_frame(fe: ServingFrontend, bodies: list) -> tuple:
    from byzpy_tpu.serving.frontend import encode_reply

    acks = []
    t0 = time.perf_counter()
    for body in bodies:
        request, stats = wire.decode_with_stats(body)
        request.pop("_wire_inflation", None)
        if stats is not None:
            request["_wire_inflation"] = stats["max_inflation"]
        acks.append(encode_reply(fe.handle_request(request)))
    return time.perf_counter() - t0, acks


def _run_batched(fe: ServingFrontend, bodies: list, batch: int) -> tuple:
    acks = []
    t0 = time.perf_counter()
    for i in range(0, len(bodies), batch):
        replies, _served, err = fe.serve_frames(bodies[i:i + batch])
        assert err is None
        acks.extend(replies)
    return time.perf_counter() - t0, acks


def _run_precision(args, precision: str) -> dict:
    bodies = _encode_stream(args, precision)
    frame_bytes = sum(len(b) for b in bodies) + 4 * len(bodies)

    t_pf = t_b = float("inf")
    for _ in range(args.reps):
        fe_p = _frontend(args)
        t, acks_pf = _run_per_frame(fe_p, bodies)
        t_pf = min(t_pf, t)
        fe_b = _frontend(args)
        t, acks_b = _run_batched(fe_b, bodies, args.batch)
        t_b = min(t_b, t)

    # ack parity: decoded reply dicts must match frame-for-frame (the
    # encoded bytes may differ only via pickle memo ordering, so
    # compare the decoded acks)
    assert len(acks_pf) == len(acks_b)
    for a, b in zip(acks_pf, acks_b):
        da, db = wire.decode(a[4:]), wire.decode(b[4:])
        assert da == db, (precision, da, db)

    dig_p = _close_all(fe_p)
    dig_b = _close_all(fe_b)
    assert dig_p == dig_b, (
        f"{precision}: batched aggregates diverged from per-frame "
        f"({dig_b} != {dig_p})"
    )
    accepted = fe_b.stats()["m0"]["ledger"]["totals"].get("accepted", 0)
    assert accepted == args.frames, fe_b.stats()["m0"]["ledger"]
    return {
        "lane": "ingress",
        "precision": precision,
        "frames": args.frames,
        "batch": args.batch,
        "dim": args.dim,
        "frame_bytes": frame_bytes,
        "per_frame_accepted_per_sec": round(args.frames / t_pf, 1),
        "batched_accepted_per_sec": round(args.frames / t_b, 1),
        "speedup": round(t_pf / t_b, 2),
        "parity": "bit-identical",
        "aggregate_digest": dig_b,
        "ingress_max_batch": fe_b.ingress_max_batch,
        "quantized_kept": precision in wire.BLOCKWISE_WIRE_MODES,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dim", type=int, default=16384)
    ap.add_argument("--frames", type=int, default=768)
    ap.add_argument("--batch", type=int, default=64,
                    help="frames per simulated event-loop wakeup")
    ap.add_argument("--cohort-cap", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3,
                    help="alternating passes per door; best-of wins")
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.dim = 4096
        args.frames = 192
        args.batch = 32
        args.cohort_cap = 32
        args.reps = 2

    _emit({
        "lane": "meta",
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count() or 1,
        "smoke": bool(args.smoke),
    }, args.out)

    rows = {}
    for precision in PRECISIONS:
        row = _run_precision(args, precision)
        rows[precision] = row
        _emit(row, args.out)

    headline = {
        "lane": "headline",
        "metric": "batched_ingress_speedup_fp8",
        "value": rows["fp8"]["speedup"],
        "unit": "x vs per-frame door",
        "batched_accepted_per_sec": rows["fp8"]["batched_accepted_per_sec"],
        "per_frame_accepted_per_sec": rows["fp8"]["per_frame_accepted_per_sec"],
        "s4_speedup": rows["s4"]["speedup"],
        "int8_speedup": rows["int8"]["speedup"],
        "parity": "bit-identical (all precisions)",
    }
    _emit(headline, args.out)

    bar = 1.5 if args.smoke else 4.0
    assert rows["fp8"]["speedup"] >= bar, (
        f"fp8 batched-door speedup {rows['fp8']['speedup']} < {bar}x"
    )
    if not args.smoke:
        # the other compressed modes must still win, just by less (their
        # per-frame decode is cheap vectorized numpy, not ml_dtypes)
        assert rows["s4"]["speedup"] >= 1.5, rows["s4"]["speedup"]
        assert rows["int8"]["speedup"] >= 1.2, rows["int8"]["speedup"]
    for row in rows.values():
        assert row["ingress_max_batch"] == args.batch
    print("ingress bench OK")


if __name__ == "__main__":
    main()

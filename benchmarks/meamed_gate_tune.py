"""MeaMed dispatch-gate tuner (ADVICE round-5: fold a tuned floor).

``MEAMED_MIN_DIM`` gates when ``ops.robust.mean_of_medians`` hands a
matrix to the fused single-sweep Pallas kernel instead of the XLA
sort/window/mask pipeline. This script derives/validates that floor:

* **CPU** (``JAX_PLATFORMS=cpu`` — always available): measures the XLA
  path's traffic multiple via XLA's own cost analysis (bytes accessed /
  the read-once-write-once floor; 24.7x at the grid row). The fused
  kernel moves ~1x the floor, so the crossover sits far below the
  generic ``MIN_PALLAS_DIM`` (256k dims, tuned for the ~2-pass sort
  kernels). The committed ``MEAMED_MIN_DIM = 64k`` is the conservative
  1/4-of-generic estimate (the kernel docstrings' ~4 TPU passes); the
  CPU pass-ratio evidence says lower would still win.
* **TPU** (via the recovery bundle, ``rerun_round5.sh`` step 2): times
  BOTH paths across a shape sweep and prints the measured crossover —
  the authoritative number. Commit it to
  ``byzpy_tpu/ops/pallas_kernels.py::MEAMED_MIN_DIM`` when it lands.

The floor is read per call in ``mean_of_medians``'s Python wrapper
(``BYZPY_TPU_MEAMED_MIN_DIM`` override wins), BEFORE anything traces —
flipping it between calls of the same shape redispatches immediately,
so this harness needs no cache clearing.

Run: ``python benchmarks/meamed_gate_tune.py`` (on either backend).
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp

from byzpy_tpu.ops import robust
from byzpy_tpu.ops.pallas_kernels import MEAMED_MIN_DIM, meamed_stream_pallas
from byzpy_tpu.utils.metrics import timed_call_s

SHAPES = [
    (64, 16_384),
    (64, 65_536),
    (64, 262_144),
    (64, 1_048_576),
]


def _cpu_pass_ratio(n: int = 64, d: int = 65_536, f: int = 8) -> dict:
    """XLA path traffic multiple over the read-once floor, from XLA's
    own cost analysis — the CPU-derivable evidence behind the committed
    floor (the fused kernel reads the matrix exactly once)."""
    from byzpy_tpu.profiling.profiler import xla_cost

    x = jax.random.normal(jax.random.PRNGKey(7), (n, d), jnp.float32)
    os.environ["BYZPY_TPU_MEAMED_MIN_DIM"] = str(1 << 60)  # force XLA path
    try:
        cost = xla_cost(functools.partial(robust.mean_of_medians, f=f), x)
    finally:
        os.environ.pop("BYZPY_TPU_MEAMED_MIN_DIM", None)
    floor = (n * d + d) * 4
    ratio = (cost["bytes_accessed"] / floor) if cost["bytes_accessed"] else None
    return {
        "workload": f"meamed_xla_pass_ratio_{n}x{d}_f{f}",
        "xla_bytes_accessed": cost["bytes_accessed"],
        "floor_bytes": floor,
        "pass_ratio": round(ratio, 2) if ratio else None,
        "derived_floor": (
            int(262_144 / ratio) if ratio and ratio > 1 else None
        ),
        "committed_MEAMED_MIN_DIM": MEAMED_MIN_DIM,
    }


def main() -> None:
    on_tpu = jax.default_backend() == "tpu"
    print(json.dumps(_cpu_pass_ratio()))
    if not on_tpu:
        print(json.dumps({
            "note": "CPU run: interpret-mode kernel timings say nothing "
                    "about Mosaic, so no crossover is measured here. The "
                    "pass-ratio row above is the CPU-derived evidence for "
                    f"the committed floor ({MEAMED_MIN_DIM}); the on-chip "
                    "sweep below runs via benchmarks/rerun_round5.sh.",
        }))
        return

    crossover = None
    for n, d in SHAPES:
        x = jax.random.normal(jax.random.PRNGKey(7), (n, d), jnp.float32)
        # XLA path, forced via the floor override (read per call, so no
        # stale-trace hazard)
        os.environ["BYZPY_TPU_MEAMED_MIN_DIM"] = str(1 << 60)
        t_xla = timed_call_s(
            functools.partial(robust.mean_of_medians, f=8), x,
            warmup=2, repeat=20,
        ) * 1e3
        os.environ.pop("BYZPY_TPU_MEAMED_MIN_DIM", None)
        t_fused = timed_call_s(
            lambda a: meamed_stream_pallas(a[None], f=8)[0], x,
            warmup=2, repeat=20,
        ) * 1e3
        win = t_fused < t_xla
        if win and crossover is None:
            crossover = d
        print(json.dumps({
            "workload": f"meamed_{n}x{d}_f8",
            "xla_ms": round(t_xla, 2),
            "fused_ms": round(t_fused, 2),
            "fused_wins": bool(win),
        }))
    print(json.dumps({
        "recommended_MEAMED_MIN_DIM": crossover if crossover else "keep",
        "committed_MEAMED_MIN_DIM": MEAMED_MIN_DIM,
        "note": "set byzpy_tpu/ops/pallas_kernels.py MEAMED_MIN_DIM to the "
                "smallest d where the fused kernel wins, then refresh the "
                "grid row",
    }))


if __name__ == "__main__":
    main()

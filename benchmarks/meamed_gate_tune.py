"""On-chip MeaMed dispatch-gate tuner (VERDICT r4 #2).

The generic Pallas dispatch floor (``MIN_PALLAS_DIM`` = 256k dims) was
tuned for single-sort kernels; MeaMed's XLA fallback pays ~7 HBM passes,
so the fused two-sweep kernel plausibly wins much earlier. This script
measures BOTH paths at a shape sweep around the grid row (64×65,536) and
prints the crossover — set ``MEAMED_MIN_DIM`` in
``byzpy_tpu/ops/pallas_kernels.py`` to the recommendation, then refresh
the grid row with ``python benchmarks/full_grid.py`` (or the single row
via ``aggregators_bench.py``).

Run on the real chip (fresh process, compile cache on):
    python benchmarks/meamed_gate_tune.py
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS even under a plugin sitecustomize

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp

from byzpy_tpu.ops import robust
from byzpy_tpu.ops.pallas_kernels import meamed_stream_pallas
from byzpy_tpu.utils.metrics import timed_call_s

SHAPES = [
    (64, 16_384),
    (64, 65_536),
    (64, 262_144),
    (64, 1_048_576),
]


def main() -> None:
    crossover = None
    for n, d in SHAPES:
        x = jax.random.normal(jax.random.PRNGKey(7), (n, d), jnp.float32)
        # XLA path, forced (the gate may already prefer the kernel)
        os.environ["BYZPY_TPU_PALLAS"] = "0"
        t_xla = timed_call_s(
            jax.jit(functools.partial(robust.mean_of_medians, f=8)), x,
            warmup=2, repeat=20,
        ) * 1e3
        os.environ["BYZPY_TPU_PALLAS"] = "auto"
        t_fused = timed_call_s(
            jax.jit(lambda a: meamed_stream_pallas(a[None], f=8)[0]), x,
            warmup=2, repeat=20,
        ) * 1e3
        win = t_fused < t_xla
        if win and crossover is None:
            crossover = d
        print(json.dumps({
            "workload": f"meamed_{n}x{d}_f8",
            "xla_ms": round(t_xla, 2),
            "fused_ms": round(t_fused, 2),
            "fused_wins": bool(win),
        }))
    print(json.dumps({
        "recommended_MEAMED_MIN_DIM": crossover if crossover else "keep",
        "note": "set byzpy_tpu/ops/pallas_kernels.py MEAMED_MIN_DIM to the "
                "smallest d where the fused kernel wins, then refresh the "
                "grid row",
    }))


if __name__ == "__main__":
    main()

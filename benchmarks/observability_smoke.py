"""Observability smoke: record one serving round end-to-end, verify
the artifacts.

Drives the REAL production path with telemetry enabled — TCP ingress
(actor wire frames) → admission → async cohort scheduler → masked
bucketed aggregate → round close — then asserts the deliverables
exist and are well-formed:

1. a chrome-trace export containing a span for EVERY lifecycle stage
   (ingress decode → admission → cohort close → bucket pad → fold →
   device step → broadcast);
2. a Prometheus scrape of the same TCP port returning the registry's
   counters/gauges/histograms;
3. a non-empty flight-recorder dump, and a clean run of the
   ``python -m byzpy_tpu.observability`` summarizer over the trace +
   metrics JSONL (including the wire-bytes-vs-law residual, which must
   stay within tolerance of ``comms.serving_ingress_bytes``);
4. the critical-path summarizer over the recorded trace: every round
   tree's per-stage blame sums to its makespan within tolerance, and a
   round with an INJECTED slow stage (the aggregator wrapped in a
   sleep) is attributed to that stage, not averaged away;
5. the SLO watchdog path: an impossible latency objective breaches,
   publishes ``byzpy_slo_*``, and triggers a flight-recorder dump
   whose reason names the burned objective and which embeds the
   critical-path + SLO state.

CI runs this as the observability leg; byzlint/ruff cover the package
through their whole-tree gates.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from byzpy_tpu import observability as obs  # noqa: E402
from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean  # noqa: E402
from byzpy_tpu.observability import metrics as obs_metrics  # noqa: E402
from byzpy_tpu.observability import tracing as obs_tracing  # noqa: E402
from byzpy_tpu.observability.__main__ import main as summarize  # noqa: E402
from byzpy_tpu.observability.recorder import FlightRecorder  # noqa: E402
from byzpy_tpu.serving import ServingFrontend, TenantConfig  # noqa: E402
from byzpy_tpu.serving.frontend import ServingClient  # noqa: E402

DIM = 4096  # above the wire codec's lossless floor, so compressed runs measure
ROUNDS = 3
M = 6

LIFECYCLE = (
    "serving.ingress.decode",
    "serving.admission",
    "serving.round",
    "serving.cohort_close",
    "serving.bucket_pad",
    "serving.fold",
    "serving.device_step",
    "serving.broadcast",
)


async def record() -> ServingFrontend:
    fe = ServingFrontend(
        [
            TenantConfig(
                name="smoke",
                aggregator=CoordinateWiseTrimmedMean(f=1),
                dim=DIM,
                window_s=0.02,
                cohort_cap=32,
            )
        ]
    )
    await fe.start()
    host, port = await fe.serve()
    client = ServingClient()
    await client.connect(host, port)
    rng = np.random.default_rng(0)
    for r in range(ROUNDS):
        server_round = fe.round_of("smoke")
        for i in range(M):
            ack = await client.submit(
                "smoke", f"c{i:03d}", server_round,
                rng.normal(size=DIM).astype(np.float32),
            )
            assert ack["accepted"], f"round {r}: {ack}"
        await fe.drain("smoke")
    await client.close()

    # Prometheus scrape on the SAME TCP port the wire frames used
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(b"GET /metrics HTTP/1.1\r\nHost: smoke\r\n\r\n")
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.0 200 OK"), head[:80]
    text = body.decode()
    for needle in (
        "# TYPE byzpy_serving_submissions_total counter",
        'byzpy_serving_rounds_total{tenant="smoke"}',
        "byzpy_serving_round_latency_seconds_bucket",
        'byzpy_serving_queue_depth{tenant="smoke"}',
        "byzpy_wire_info{",
    ):
        assert needle in text, f"scrape missing {needle!r}"

    await fe.close()
    return fe


def main() -> None:
    obs.enable()
    fe = asyncio.run(record())

    stats = fe.stats()["smoke"]
    assert stats["rounds"] >= ROUNDS, stats
    assert stats["failed_rounds"] == 0

    out_dir = tempfile.mkdtemp(prefix="byzpy_obs_smoke_")
    trace_path = os.path.join(out_dir, "trace.json")
    metrics_path = os.path.join(out_dir, "metrics.jsonl")
    dump_path = os.path.join(out_dir, "flight.json")

    # 1) well-formed trace export covering the whole lifecycle
    n_events = obs_tracing.tracer().export_chrome_trace(trace_path)
    with open(trace_path) as fh:
        doc = json.load(fh)
    assert len(doc["traceEvents"]) == n_events > 0
    names = {ev["name"] for ev in doc["traceEvents"]}
    missing = [s for s in LIFECYCLE if s not in names]
    assert not missing, f"lifecycle stages missing from trace: {missing}"

    # 2) non-empty flight-recorder dump
    dump = FlightRecorder(last_rounds=8).dump(dump_path, reason="smoke")
    assert len(dump["events"]) > 0, "flight recorder dump is empty"
    assert any(
        ev["name"] == "serving.round" for ev in dump["events"]
    ), "flight dump lost the round spans"

    # 3) metrics export + summarizer over trace and metrics
    assert obs_metrics.registry().to_jsonl(metrics_path) > 0
    assert summarize([trace_path, "--metrics", metrics_path, "--json"]) == 0

    # wire-bytes law residual: measured submit frames vs the analytic
    # serving_ingress_bytes law (pinned <2% in tests; 5% here for slack)
    from byzpy_tpu.observability.__main__ import wire_residuals

    rows = wire_residuals(metrics_path)
    assert rows, "no wire-residual row (ingress counters missing)"
    (row,) = rows
    assert row["frames"] == ROUNDS * M
    assert abs(row["residual"]) < 0.05, row

    # 4) critical-path attribution over the recorded trace: blame sums
    # to each round's makespan, and an injected slow stage is blamed
    from byzpy_tpu.observability import critical_path as obs_cp

    with open(trace_path) as fh:
        trace_events = json.load(fh)["traceEvents"]
    cp_summary = obs_cp.summarize(trace_events)
    assert cp_summary["rounds"], "no round trees in the recorded trace"
    assert cp_summary["max_blame_residual"] < 1e-6, cp_summary[
        "max_blame_residual"
    ]
    # ...and the slow-stage + SLO-breach leg: an injected slow fold is
    # blamed by the critical path, burns an impossible latency SLO,
    # and the breach triggers a flight dump (the full alarm chain)
    slo_dump_path = os.path.join(out_dir, "slo_flight.json")
    slow_blame, slo_rows = _slow_stage_and_slo_breach(slo_dump_path)

    print(
        json.dumps(
            {
                "lane": "observability_smoke",
                "rounds": stats["rounds"],
                "trace_events": n_events,
                "lifecycle_stages": len(LIFECYCLE),
                "flight_dump_events": len(dump["events"]),
                "wire_residual": row["residual"],
                "critical_path_rounds": len(cp_summary["rounds"]),
                "max_blame_residual": cp_summary["max_blame_residual"],
                "slow_stage_share": slow_blame,
                "slo_breaches": len(slo_rows),
                "out_dir": out_dir,
            }
        )
    )
    print("observability smoke OK")


def _slow_stage_and_slo_breach(dump_path: str):
    """Close one round whose FOLD is artificially slow (the aggregator
    wrapped in a 50 ms sleep) under an impossible latency SLO, then
    assert the whole alarm chain: the critical path blames the slow
    stage (attribution, not averaging), the watchdog breaches,
    ``byzpy_slo_*`` publish, the breach instant lands on the tracer,
    and the flight dump carries the critical-path + SLO state. Returns
    ``(blamed share, breach rows)``."""
    import time

    from byzpy_tpu.observability import critical_path as obs_cp
    from byzpy_tpu.observability.slo import SLOWatchdog, TenantSLO
    from byzpy_tpu.serving import ServingFrontend, TenantConfig

    class _SlowAggregator(CoordinateWiseTrimmedMean):
        def aggregate_masked(self, matrix, valid):
            time.sleep(0.05)
            return super().aggregate_masked(matrix, valid)

    obs_tracing.tracer().clear()
    # the watchdog FIRST: it baselines the registry at construction and
    # scores only what happens on its watch
    watchdog = SLOWatchdog(
        [TenantSLO(tenant="slowstage", accepted_p99_s=1e-9, window_s=60.0)],
        flight_path=dump_path,
    )
    fe = ServingFrontend(
        [
            TenantConfig(
                name="slowstage",
                aggregator=_SlowAggregator(f=1),
                dim=64,
                window_s=0.01,
                cohort_cap=16,
            )
        ]
    )
    rng = np.random.default_rng(1)
    for i in range(4):
        ok, reason = fe.submit(
            "slowstage", f"c{i}", 0, rng.normal(size=64).astype(np.float32)
        )
        assert ok, reason
    assert fe.close_round_nowait("slowstage") is not None

    summary = obs_cp.summarize(obs_tracing.tracer().events())
    (round_row,) = summary["rounds"]
    top = round_row["stages"][0]
    # the sleep lives inside the device_step span (under fold): the
    # critical path must put the round's majority blame there
    assert top["stage"] == "serving.device_step", round_row["stages"]
    assert top["share"] > 0.5, round_row["stages"]

    rows = [r for r in watchdog.evaluate() if r["breached"]]
    assert rows, "impossible SLO did not breach"
    assert watchdog.flight_dumps == 1, "breach did not trigger a flight dump"
    with open(dump_path) as fh:
        dump = json.load(fh)
    assert dump["reason"] == "slo:slowstage:accepted_p99", dump["reason"]
    assert dump["slo"], "dump missing SLO state"
    assert dump.get("critical_path", {}).get("rounds"), (
        "dump missing critical-path summaries"
    )
    text = obs_metrics.registry().prometheus_text()
    assert "byzpy_slo_burn_rate" in text and "byzpy_slo_breaches_total" in text
    breach_instants = [
        ev for ev in obs_tracing.tracer().events() if ev["name"] == "slo.breach"
    ]
    assert breach_instants, "breach instant missing from the tracer"
    watchdog.close()
    return top["share"], rows


if __name__ == "__main__":
    main()

"""Overlapped-round benchmark: serial vs streaming/prefetch PS rounds.

Measures what the overlapped round engine (``byzpy_tpu.engine.overlap``)
buys on a straggler-skewed CPU workload: honest nodes whose
``compute_gradient`` and ``apply_server_gradient`` RPCs each carry
per-(node, round) delay jitter — a base latency, an exponential jitter
term, and one rotating straggler spike per round per leg, the
decorrelated-straggler shape of real fleets (network RTT both
directions, GC pauses, contention). All modes replay the *same*
pre-drawn delay schedule, so steps/sec differences are purely the round
engine's.

Modes:

* ``serial``   — barrier ingestion, no prefetch (the legacy round loop;
  run through ``OverlapConfig(stream=False, prefetch_depth=0)`` so
  ingestion lag is recorded — wall-clock is identical to ``overlap=None``).
* ``stream``   — arrival-order folding only.
* ``prefetch`` — cross-round apply→compute chaining only.
* ``both``     — the full overlapped engine (the default config).

Reports steps/sec per mode, speedup vs serial, and ingestion-lag
percentiles (the time each gradient sits between arrival and
aggregation consuming it — the straggler tax the barrier forces every
early gradient to pay). Appends one provenance-stamped JSON line per
mode to ``results/overlap.jsonl``.

Run: ``JAX_PLATFORMS=cpu python benchmarks/overlap_bench.py``
(``--smoke`` for the CI-sized run).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from byzpy_tpu.aggregators import CoordinateWiseTrimmedMean  # noqa: E402
from byzpy_tpu.engine.overlap import (  # noqa: E402
    OverlapConfig,
    RoundOverlapStats,
)
from byzpy_tpu.engine.parameter_server import ParameterServer  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))

MODES = {
    "serial": OverlapConfig(stream=False, prefetch_depth=0),
    "stream": OverlapConfig(stream=True, prefetch_depth=0),
    "prefetch": OverlapConfig(stream=False, prefetch_depth=1),
    "both": OverlapConfig(stream=True, prefetch_depth=1),
}


class JitterNode:
    """Honest node whose two RPC legs sleep through a pre-drawn
    per-round delay schedule (seconds)."""

    def __init__(self, value: float, d: int, compute_s, apply_s) -> None:
        self.grad = np.full(d, value, np.float32)
        self.compute_s = compute_s
        self.apply_s = apply_s
        self.computes = 0
        self.applies = 0

    async def honest_gradient_for_next_batch(self):
        r = min(self.computes, len(self.compute_s) - 1)
        self.computes += 1
        await asyncio.sleep(self.compute_s[r])
        return self.grad

    async def apply_server_gradient(self, g):
        r = min(self.applies, len(self.apply_s) - 1)
        self.applies += 1
        await asyncio.sleep(self.apply_s[r])


def draw_delays(
    rng: np.random.Generator,
    *,
    nodes: int,
    rounds: int,
    base_ms: float,
    jitter_ms: float,
    straggler_ms: float,
) -> np.ndarray:
    """``(rounds, nodes)`` delay schedule: base + Exp(jitter) + one
    uniformly-drawn straggler per round."""
    d = base_ms + rng.exponential(jitter_ms, size=(rounds, nodes))
    stragglers = rng.integers(0, nodes, size=rounds)
    d[np.arange(rounds), stragglers] += straggler_ms
    return d / 1e3


async def run_mode(
    mode: str,
    cfg: OverlapConfig,
    *,
    nodes: int,
    rounds: int,
    dim: int,
    compute_s: np.ndarray,
    apply_s: np.ndarray,
) -> dict:
    node_objs = [
        JitterNode(float(i + 1), dim, compute_s[:, i], apply_s[:, i])
        for i in range(nodes)
    ]
    ps = ParameterServer(
        honest_nodes=node_objs,
        aggregator=CoordinateWiseTrimmedMean(f=1),
        overlap=cfg,
    )
    lags: list = []

    def on_round(i, aggregated):
        if ps.last_overlap_stats is not None:
            lags.extend(ps.last_overlap_stats.ingest_lags_s)

    t0 = time.perf_counter()
    await ps.run(rounds, on_round=on_round)
    elapsed = time.perf_counter() - t0
    await ps.close()
    # the library's own percentile definition, over all rounds' lags
    agg_stats = RoundOverlapStats(mode=mode, ingest_lags_s=lags)

    def pct_ms(p):
        return 1e3 * agg_stats.lag_percentile(p)

    return {
        "mode": mode,
        "steps_per_sec": rounds / elapsed,
        "elapsed_s": round(elapsed, 3),
        "rounds": rounds,
        "ingest_lag_ms_p50": round(pct_ms(50), 2),
        "ingest_lag_ms_p90": round(pct_ms(90), 2),
        "ingest_lag_ms_p99": round(pct_ms(99), 2),
    }


async def main_async(args) -> list:
    rng = np.random.default_rng(args.seed)
    # +1 round of compute delays: prefetch reaches into round r+1
    compute_s = draw_delays(
        rng, nodes=args.nodes, rounds=args.rounds + 1,
        base_ms=args.base_ms, jitter_ms=args.jitter_ms,
        straggler_ms=args.straggler_ms,
    )
    apply_s = draw_delays(
        rng, nodes=args.nodes, rounds=args.rounds + 1,
        base_ms=args.base_ms, jitter_ms=args.jitter_ms,
        straggler_ms=args.straggler_ms,
    )
    rows = []
    for mode in args.modes:
        rows.append(
            await run_mode(
                mode, MODES[mode],
                nodes=args.nodes, rounds=args.rounds, dim=args.dim,
                compute_s=compute_s, apply_s=apply_s,
            )
        )
    return rows


def report(rows: list, args) -> int:
    """Annotate, persist and print the measured rows (sync host I/O —
    kept out of the async timing loop so the file write never sits on the
    event loop; see the byzlint ASYNC-BLOCKING rule)."""
    serial = next((r for r in rows if r["mode"] == "serial"), rows[0])
    out_path = os.path.join(HERE, "results", "overlap.jsonl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(out_path, "a") as sink:
        for r in rows:
            r["speedup_vs_serial"] = round(
                r["steps_per_sec"] / serial["steps_per_sec"], 3
            )
            r["steps_per_sec"] = round(r["steps_per_sec"], 2)
            r.update({
                "nodes": args.nodes, "dim": args.dim,
                "base_ms": args.base_ms, "jitter_ms": args.jitter_ms,
                "straggler_ms": args.straggler_ms, "seed": args.seed,
                "device": "cpu",
                "provenance": "benchmarks/overlap_bench.py", "ts": stamp,
            })
            sink.write(json.dumps(r) + "\n")
    print(f"{'mode':<9} {'steps/s':>8} {'vs serial':>9} "
          f"{'lag p50':>8} {'lag p90':>8} {'lag p99':>8}  (lag in ms)")
    for r in rows:
        print(f"{r['mode']:<9} {r['steps_per_sec']:>8.2f} "
              f"{r['speedup_vs_serial']:>8.2f}x "
              f"{r['ingest_lag_ms_p50']:>8.2f} {r['ingest_lag_ms_p90']:>8.2f} "
              f"{r['ingest_lag_ms_p99']:>8.2f}")
    both = next((r for r in rows if r["mode"] == "both"), None)
    if both is not None:
        print(f"overlapped-vs-serial speedup: {both['speedup_vs_serial']}x "
              f"(results appended to {os.path.relpath(out_path, HERE)})")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--rounds", type=int, default=30)
    parser.add_argument("--dim", type=int, default=8192)
    parser.add_argument("--base-ms", type=float, default=5.0)
    parser.add_argument("--jitter-ms", type=float, default=5.0,
                        help="mean of the exponential jitter term")
    parser.add_argument("--straggler-ms", type=float, default=60.0,
                        help="extra delay for the per-round straggler")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--modes", nargs="*", default=list(MODES),
                        choices=list(MODES))
    parser.add_argument("--smoke", action="store_true",
                        help="CI-sized run (tiny delays, few rounds)")
    args = parser.parse_args()
    if args.smoke:
        args.rounds = min(args.rounds, 6)
        args.base_ms, args.jitter_ms, args.straggler_ms = 1.0, 1.0, 10.0
        args.dim = min(args.dim, 1024)
    return report(asyncio.run(main_async(args)), args)


if __name__ == "__main__":
    raise SystemExit(main())

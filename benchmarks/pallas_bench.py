"""Pallas sorting-network vs XLA sort across node counts at 1M-dim
(the measurement behind ``pallas_kernels.MAX_NETWORK_ROWS``)."""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

import jax
import jax.numpy as jnp

from _timing import report, timed_ms
from byzpy_tpu.ops.pallas_kernels import median_pallas

D = 1 << 20


def main():
    interpret = jax.default_backend() != "tpu"
    for n in (8, 16, 32, 64, 128):
        x = jax.random.normal(jax.random.PRNGKey(n), (n, D), jnp.float32)
        t_pallas = timed_ms(
            jax.jit(lambda v: median_pallas(v, interpret=interpret)), x, repeat=30
        )
        t_xla = timed_ms(jax.jit(lambda v: jnp.median(v, axis=0)), x, repeat=30)
        report(
            f"median_{n}x1M",
            t_pallas,
            xla_ms=round(t_xla, 3),
            speedup=round(t_xla / t_pallas, 2),
        )


if __name__ == "__main__":
    main()

"""Render the accuracy-under-attack trajectories.

Reads ``benchmarks/results/robust_learning.jsonl`` (written by
``robust_learning.py --write``; the LAST row per (aggregator, attack)
wins) and produces ``results/robust_learning.png`` — one panel per
attack, accuracy-vs-round per aggregator. The visual form of the
reference's ByzFL compare plots (``byzpy/benchmarks/byzfl/*_compare.py``).

Matplotlib only; no seaborn, no style deps.
"""

import os

from _plotting import RESULTS, load_jsonl, plt


def load_cells(path=None, *, mode="ps", grad_dtype="float32"):
    """Last row per (aggregator, attack) cell for ONE study variant —
    the jsonl also accumulates the bf16 and gossip variants' rows
    (tagged ``grad_dtype`` / ``mode``; absent on pre-round-5 rows, which
    were all f32 PS), and mixing variants in a trajectory plot would be
    silently wrong."""
    path = path or os.path.join(RESULTS, "robust_learning.jsonl")
    return {
        (r["aggregator"], r["attack"]): r
        for r in load_jsonl(path)
        if r.get("mode", "ps") == mode
        and r.get("grad_dtype", "float32") == grad_dtype
    }


def main() -> int:
    cells = load_cells()
    attacks = list(dict.fromkeys(a for _, a in cells))
    aggs = list(dict.fromkeys(g for g, _ in cells))
    any_row = next(iter(cells.values()))
    fig, axes = plt.subplots(
        1, len(attacks), figsize=(4 * len(attacks), 3.4), sharey=True
    )
    if len(attacks) == 1:
        axes = [axes]
    for ax, attack in zip(axes, attacks, strict=False):
        for agg in aggs:
            row = cells.get((agg, attack))
            if row is None:
                continue
            rounds = [r for r, _ in row["history"]]
            acc = [a for _, a in row["history"]]
            style = dict(linewidth=2.2) if agg == "mean" else dict(linewidth=1.4)
            ax.plot(rounds, acc, marker="o", markersize=3, label=agg, **style)
        ax.set_title(f"attack: {attack}")
        ax.set_xlabel("round")
        ax.set_ylim(0.0, 1.0)
        ax.grid(alpha=0.3)
    axes[0].set_ylabel("held-out accuracy")
    axes[-1].legend(loc="lower right", fontsize=8)
    fig.suptitle(
        "Robust learning on real digits: accuracy under attack "
        f"({any_row.get('n_nodes', '?')} nodes, "
        f"{any_row.get('n_byzantine', '?')} byzantine)",
        y=1.02,
    )
    fig.tight_layout()
    out = os.path.join(RESULTS, "robust_learning.png")
    fig.savefig(out, dpi=130, bbox_inches="tight")
    print(f"wrote {out}")
    plot_breakdown()
    return 0




def plot_breakdown(path=None):
    """Companion panel: accuracy vs byzantine count per aggregator
    (reads results/breakdown.jsonl; last row per cell wins)."""
    path = path or os.path.join(RESULTS, "breakdown.jsonl")
    if not os.path.exists(path):
        return None
    cells = {}
    for r in load_jsonl(path):
        cells[(r["aggregator"], r["n_byzantine"])] = r
    aggs = list(dict.fromkeys(a for a, _ in cells))
    fs = sorted({f for _, f in cells})
    fig, ax = plt.subplots(figsize=(5, 3.4))
    for agg in aggs:
        acc = [cells[(agg, f)]["final_accuracy"] for f in fs if (agg, f) in cells]
        style = dict(linewidth=2.2) if agg == "mean" else dict(linewidth=1.4)
        ax.plot(fs[: len(acc)], acc, marker="o", label=agg, **style)
    any_row = next(iter(cells.values()))
    ax.set_xlabel("byzantine nodes (of %d)" % any_row.get("n_nodes", 8))
    ax.set_ylabel("held-out accuracy")
    ax.set_ylim(0.0, 1.0)
    ax.set_xticks(fs)
    ax.grid(alpha=0.3)
    ax.legend(fontsize=8)
    ax.set_title(f"breakdown under {any_row.get('attack', '?')}")
    fig.tight_layout()
    out = os.path.join(RESULTS, "breakdown.png")
    fig.savefig(out, dpi=130, bbox_inches="tight")
    print(f"wrote {out}")
    return out
if __name__ == "__main__":
    raise SystemExit(main())

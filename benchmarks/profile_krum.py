"""Decompose the Multi-Krum 64x1M headline: where do the milliseconds go?

Measures each stage of the pipeline independently, plus pure-bandwidth and
dispatch-overhead floors, to localise the gap between the measured aggregate
latency and the HBM roofline (~268 MB of input -> ~0.33 ms at v5e's
~819 GB/s).

Usage:  python benchmarks/profile_krum.py [--trace DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from byzpy_tpu.ops import robust
from byzpy_tpu.utils.metrics import timed_call_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="jax.profiler trace dir")
    ap.add_argument("--repeat", type=int, default=50)
    args = ap.parse_args()

    n, d = 64, 1_048_576
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d), jnp.float32)
    xb = x.astype(jnp.bfloat16)
    nbytes = x.nbytes

    t = partial(timed_call_s, warmup=3, repeat=args.repeat)

    results = {}

    # Floors.
    results["noop_scalar"] = t(jax.jit(lambda v: v[0, 0] * 1.0), x)
    results["read_sum"] = t(jax.jit(lambda v: jnp.sum(v)), x)  # one full HBM read
    results["copy"] = t(jax.jit(lambda v: v * 1.0000001), x)  # read + write

    # Stages.
    results["gram_f32"] = t(jax.jit(robust.gram_matrix), x)
    results["gram_bf16"] = t(jax.jit(robust.gram_matrix), xb)
    results["pairwise_f32"] = t(jax.jit(robust.pairwise_sq_dists), x)
    results["krum_scores"] = t(jax.jit(partial(robust.krum_scores, f=8)), x)
    results["multi_krum"] = t(jax.jit(partial(robust.multi_krum, f=8, q=12)), x)
    results["multi_krum_bf16"] = t(jax.jit(partial(robust.multi_krum, f=8, q=12)), xb)

    # Selection tail in isolation: mean of q gathered rows.
    sel = jnp.arange(12, dtype=jnp.int32)
    results["gather_mean"] = t(jax.jit(lambda v, s: jnp.mean(v[s], axis=0)), x, sel)

    # Coordinate-median headline cousin.
    results["coord_median"] = t(jax.jit(robust.coordinate_median), x)
    results["sort_axis0"] = t(jax.jit(lambda v: jnp.sort(v, axis=0)), x)

    bw = {k: nbytes / v / 1e9 for k, v in results.items() if k in ("read_sum", "gram_f32")}
    print(json.dumps({
        "device": str(jax.devices()[0]),
        "nbytes_MB": round(nbytes / 1e6, 1),
        "ms": {k: round(v * 1e3, 3) for k, v in results.items()},
        "effective_GBps": {k: round(v, 1) for k, v in bw.items()},
    }, indent=2))

    if args.trace:
        from byzpy_tpu.utils.metrics import force_result, trace
        fn = jax.jit(partial(robust.multi_krum, f=8, q=12))
        force_result(fn(x))
        with trace(args.trace):
            for _ in range(10):
                out = fn(x)
            force_result(out)
        print(f"trace written to {args.trace}")


if __name__ == "__main__":
    main()

"""HLO-derived 8→128-chip scaling projection for the fused PS round.

Runs on an 8-virtual-device CPU mesh, compiles the BASELINE config-#3
round (MNIST MLP, coordinate-wise trimmed mean, sign-flip attack) and
parses its per-device collective bytes out of the OPTIMIZED HLO
(:mod:`byzpy_tpu.parallel.comms`). The per-device payload of the round's
collectives follows the saturating ``(g-1)/g`` law, so the n=8
measurement extrapolates exactly to larger meshes; v5e ICI bandwidth and
the MLP's per-chip FLOPs then give the weak-scaling efficiency table.

Prints ONE JSON object (consumed by ``bench.py`` to attach the
``ps_mnist_trimmed_mean_steps_per_sec`` projection; also runnable
standalone). Designed to run in a SUBPROCESS of the TPU-facing bench —
the CPU platform pin below happens before any backend touch.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

from byzpy_tpu.utils.platform import apply_env_platform

apply_env_platform()

import jax
import jax.numpy as jnp

from byzpy_tpu.models import mnist_mlp
from byzpy_tpu.ops import attack_ops, robust
from byzpy_tpu.parallel.comms import (
    collective_traffic,
    measured_opt_state_bytes,
    opt_state_bytes,
)
from byzpy_tpu.parallel.mesh import node_mesh
from byzpy_tpu.parallel.ps import (
    PSStepConfig,
    ShardedUpdateConfig,
    build_ps_train_step,
)

N = 8
BATCH = 64

#: update-shard variants projected alongside the default round:
#: (label, sharded_update argument)
VARIANTS = (
    ("replicated", "off"),
    ("sharded_f32", "on"),
    ("sharded_bf16", ShardedUpdateConfig(mode="on", param_gather_precision="bf16")),
    ("sharded_int8", ShardedUpdateConfig(mode="on", param_gather_precision="int8")),
)


def main() -> None:
    assert len(jax.devices()) == N, jax.devices()
    mesh = node_mesh(N)
    bundle = mnist_mlp()  # 784-128-10, ~101k params — BASELINE config #3
    n_byz = 2
    cfg = PSStepConfig(n_nodes=N, n_byzantine=n_byz)
    xs = jnp.zeros((N, BATCH, 28, 28, 1), jnp.float32)
    ys = jnp.zeros((N, BATCH), jnp.int32)
    key = jax.random.PRNGKey(0)

    def build(sharded_update):
        return build_ps_train_step(
            bundle,
            lambda m: robust.trimmed_mean(m, f=n_byz),
            cfg,
            attack=lambda honest, key: attack_ops.sign_flip(
                jnp.mean(honest, axis=0)
            ),
            mesh=mesh,
            sharded_update=sharded_update,
        )

    d = sum(x.size for x in jax.tree_util.tree_leaves(bundle.params))
    ici = 4.5e10  # v5e: 45 GB/s per direction per link
    chips = (8, 16, 32, 64, 128)

    # Per-device collective payloads in this round all carry the
    # saturating (g-1)/g factor (gradient transpose all-to-all + params /
    # aggregated-gradient all-gather), so
    # bytes(n) = bytes(8) * ((n-1)/n) / (7/8). Per-chip opt-state HBM of
    # the sharded update FALLS as 1/n instead (each chip owns d/n of
    # every moment buffer), which is what lets the model size per chip
    # grow with the mesh.
    variants = {}
    for label, su in VARIANTS:
        step, opt0 = build(su)
        traffic = collective_traffic(step, bundle.params, opt0, xs, ys, key)
        w8 = float(traffic["wire_bytes_per_device"])
        variants[label] = {
            "hlo_wire_bytes_per_device_n8": w8,
            "per_opcode_bytes_n8": {
                k: float(v) for k, v in traffic["per_opcode_bytes"].items()
            },
            "opt_state_bytes_per_chip_n8": measured_opt_state_bytes(opt0),
            "opt_state_bytes_per_chip": {
                str(n): opt_state_bytes(
                    d, slots=1, update_sharded=label != "replicated",
                    n_shards=n,
                )
                for n in chips
            },
            "wire_bytes_per_device": {
                str(n): round(w8 * ((n - 1) / n) / ((N - 1) / N), 1)
                for n in chips
            },
        }

    # the default round (sharded_update="auto") resolves to the sharded
    # f32 program on this mesh — its already-measured variant carries the
    # bench.py-facing projection keys (no fifth compile)
    default = variants["sharded_f32"]
    wire8 = float(default["hlo_wire_bytes_per_device_n8"])

    def wire_fn(n: int) -> float:
        return wire8 * ((n - 1) / n) / ((N - 1) / N)

    out = {
        "config": "PS MNIST MLP (784-128-10) + trimmed-mean + sign-flip, "
                  f"n_nodes=n_chips, batch {BATCH}/node",
        "params": int(d),
        "hlo_wire_bytes_per_device_n8": wire8,
        "per_opcode_bytes_n8": dict(default["per_opcode_bytes_n8"]),
        "assumptions": "weak scaling (n_nodes grows with chips); "
                       "v5e ICI 45 GB/s/dir; no compute/comm overlap "
                       "(pessimistic); per-device collective bytes follow "
                       "the (g-1)/g law measured at n=8; default round = "
                       "feature-sharded weight update (auto), opt-state "
                       "HBM per chip falls 1/n",
        "wire_bytes_per_device": {str(n): round(wire_fn(n), 1) for n in chips},
        "comm_seconds_per_round": {
            str(n): wire_fn(n) / ici for n in chips
        },
        "opt_state_bytes_per_chip_n8": default["opt_state_bytes_per_chip_n8"],
        "update_shard_variants": variants,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()

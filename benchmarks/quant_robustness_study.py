"""Robustness study: aggregator output error under quantized comms vs
the Byzantine perturbation each aggregator already tolerates.

The argument for int8 wire traffic in a *robust* aggregation system is
not "the error is small in absolute terms" — it is that every aggregator
here is built to absorb ADVERSARIAL per-row perturbations, and the
bounded, symmetric, per-coordinate error of blockwise int8 is a far
weaker disturbance than the attacks in its design envelope. This study
measures that claim per aggregator at the BASELINE grid shapes:

* ``byz_shift``  = ||agg(X_attacked) - agg(X_clean)||_2 — how far a real
  attack (within the aggregator's f-tolerance) moves the output: the
  perturbation the aggregator is already accepted to tolerate. A
  selection aggregator can absorb an attack EXACTLY (Krum picking the
  same winner -> shift 0), so the tolerance denominator is
  ``max(byz_shift, resample_shift)`` where ``resample_shift`` is the
  output movement between two legitimate honest draws — the noise floor
  any deployment already accepts per round.
* ``int8_err`` / ``bf16_err`` = ||agg(wire(X_attacked)) - agg(X_attacked)||_2
  where ``wire`` is the quantize->dequantize round trip every row pays
  on a compressed fabric (the worst case: *all* rows quantized, as in
  the PS gradient transpose).
* ``ratio`` = quant error / byz shift. The acceptance bar for this
  round: int8 ratio < 1 for every aggregator/attack pair (in practice
  it sits around 1e-2 — two orders of magnitude below the tolerated
  perturbation).

Appends one provenance-stamped JSON line per (aggregator, attack, mode)
to ``results/quant_robustness_<platform>.jsonl`` (``--out`` overrides)
and prints the summary table committed in ``benchmarks/RESULTS.md``.

Run: ``JAX_PLATFORMS=cpu python benchmarks/quant_robustness_study.py [--smoke]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run: small d, core aggregators")
    ap.add_argument("--out", default=None, help="JSONL sink override")
    ap.add_argument("--d", type=int, default=None)
    args = ap.parse_args()

    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from byzpy_tpu.ops import attack_ops, robust
    from byzpy_tpu.parallel import quantization as qz

    platform = jax.default_backend()
    # BASELINE.md grid row: 64 nodes x 65,536 features, f = 8
    n, f = 64, 8
    d = args.d or (4_096 if args.smoke else 65_536)
    q_sel = n - f - 2  # Multi-Krum selection size at the grid config

    aggregators = {
        "cw_median": robust.coordinate_median,
        "cw_trimmed_mean": partial(robust.trimmed_mean, f=f),
        "meamed": partial(robust.mean_of_medians, f=f),
        "multi_krum": partial(robust.multi_krum, f=f, q=q_sel),
        "krum": partial(robust.krum, f=f),
        "cge": partial(robust.cge, f=f),
        "monna": partial(robust.monna, f=f),
        "geometric_median": robust.geometric_median,
        "centered_clipping": partial(robust.centered_clipping, c_tau=10.0),
    }
    if args.smoke:
        for name in ("geometric_median", "centered_clipping", "monna"):
            aggregators.pop(name)

    key = jax.random.PRNGKey(0)
    k_clean, k_extra, k_g = jax.random.split(key, 3)
    # heterogeneous-ish honest gradients: shared signal + per-node noise
    signal = jax.random.normal(k_g, (1, d), jnp.float32)
    x_clean = signal + jax.random.normal(k_clean, (n, d), jnp.float32)
    x_clean2 = signal + jax.random.normal(k_extra, (n, d), jnp.float32)

    def attacked(kind):
        honest = x_clean[: n - f]
        if kind == "empire":
            vec = attack_ops.empire(honest, scale=-1.1)
        elif kind == "little":
            vec = attack_ops.little(honest, f=f, n_total=n)
        elif kind == "sign_flip":
            vec = attack_ops.sign_flip(jnp.mean(honest, axis=0), scale=-4.0)
        else:
            raise ValueError(kind)
        return jnp.concatenate(
            [honest, jnp.broadcast_to(vec, (f, d)).astype(honest.dtype)], axis=0
        )

    attacks = ("empire", "little") if args.smoke else (
        "empire", "little", "sign_flip"
    )

    out_path = args.out or os.path.join(
        HERE, "results", f"quant_robustness_{platform}.jsonl"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    provenance = {
        "platform": platform, "n": n, "d": d, "f": f,
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    rows, failures = [], []
    hdr = (f"{'aggregator':18s} {'attack':9s} {'tolerance':>11s} "
           f"{'int8_err':>11s} {'bf16_err':>11s} {'int8/tol':>9s}")
    print(hdr)
    print("-" * len(hdr))
    for agg_name, agg in aggregators.items():
        agg_j = jax.jit(agg)
        base_clean = agg_j(x_clean)
        resample_shift = float(jnp.linalg.norm(agg_j(x_clean2) - base_clean))
        for att in attacks:
            x_att = attacked(att)
            base_att = agg_j(x_att)
            byz_shift = float(jnp.linalg.norm(base_att - base_clean))
            tolerance = max(byz_shift, resample_shift)
            errs = {}
            for mode in ("int8", "bf16", "fp8", "fp8_e5m2", "s4"):
                if mode == "bf16":
                    wire = x_att.astype(jnp.bfloat16).astype(jnp.float32)
                else:
                    wire = qz.dequantize_blockwise(
                        qz.encode_blockwise(x_att, mode)
                    )
                errs[mode] = float(jnp.linalg.norm(agg_j(wire) - base_att))
            ratio = errs["int8"] / tolerance if tolerance else float("inf")
            # the sub-int8 precision floor: the coarsest mode (down the
            # int8 -> fp8 -> fp8_e5m2 -> s4 ladder) reachable without
            # crossing a failed finer rung (boundary err/tol <= 1,
            # same rule as the chaos subint8_floor lane)
            floor = None
            for mode in ("int8", "fp8", "fp8_e5m2", "s4"):
                if not tolerance or errs[mode] / tolerance > 1.0:
                    break
                floor = mode
            rows.append({
                "aggregator": agg_name, "attack": att,
                "byz_shift": byz_shift, "resample_shift": resample_shift,
                "tolerance": tolerance,
                "int8_err": errs["int8"], "bf16_err": errs["bf16"],
                "fp8_err": errs["fp8"], "fp8_e5m2_err": errs["fp8_e5m2"],
                "s4_err": errs["s4"],
                "int8_over_tolerance": ratio,
                "fp8_over_tolerance": (
                    errs["fp8"] / tolerance if tolerance else float("inf")
                ),
                "s4_over_tolerance": (
                    errs["s4"] / tolerance if tolerance else float("inf")
                ),
                "precision_floor": floor, **provenance,
            })
            print(f"{agg_name:18s} {att:9s} {tolerance:11.4f} "
                  f"{errs['int8']:11.4f} {errs['bf16']:11.4f} {ratio:9.4f} "
                  f"fp8={errs['fp8']:.4f} s4={errs['s4']:.4f} "
                  f"floor={floor}")
            if ratio >= 1.0:
                failures.append((agg_name, att, ratio))

    with open(out_path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows -> {out_path}")

    if failures:
        print(f"FAIL: int8 error exceeds Byzantine tolerance for {failures}",
              file=sys.stderr)
        return 1
    print("int8 comm error below every aggregator's Byzantine tolerance: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

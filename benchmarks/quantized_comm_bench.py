"""Quantized communication fabric benchmark: wire bytes + steps/sec.

Three measurements per precision mode (off / bf16 / int8 plus the
sub-int8 tier fp8 / fp8_e5m2 / s4), all from the *compiled artifact*
(`byzpy_tpu.parallel.comms` parses the optimized HLO, so byte counts
are facts about the program XLA runs, not estimates):

1. **collective wire bytes** — ``all_gather_q`` and
   ``reduce_scatter_sum_q`` over an 8-way mesh: per-device interconnect
   bytes per invocation, and the compression ratio vs the f32 fabric
   (acceptance floor for this round: >= 1.5x at int8; blockwise int8
   with 256-wide blocks delivers ~3.9x).
2. **PS round wire bytes** — the fused SPMD parameter-server step with
   ``comm_precision`` threaded through ``build_ps_train_step``: the
   gradient-transpose all-to-all is the round's dominant term and must
   shrink by the same factor.
3. **steps/sec** of that PS step per mode (on CPU the interconnect is
   memcpy so the win is bytes, not time; on ICI both move together —
   the on-chip sweep rides ``rerun_round5.sh``).

A quantize/dequantize round-trip error-bound parity check runs first —
`--smoke` is the CI leg (small shapes, asserts the ratio floor and the
error contract, one quantized-collective step executed end to end).

Appends one provenance-stamped JSON line per (measurement, mode) to
``results/quantized_comm_<platform>.jsonl`` (``--out`` overrides).

Run: ``JAX_PLATFORMS=cpu python benchmarks/quantized_comm_bench.py [--smoke]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))

MODES = ("off", "bf16", "int8", "fp8", "fp8_e5m2", "s4")


def _provenance(platform: str) -> dict:
    return {
        "platform": platform,
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized shapes + hard assertions")
    ap.add_argument("--out", default=None, help="JSONL sink override")
    ap.add_argument("--d", type=int, default=None,
                    help="feature dim for the collective probes")
    ap.add_argument("--repeat", type=int, default=None)
    args = ap.parse_args()

    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from byzpy_tpu.models.bundle import ModelBundle
    from byzpy_tpu.ops import robust
    from byzpy_tpu.parallel import collectives as coll
    from byzpy_tpu.parallel import quantization as qz
    from byzpy_tpu.parallel.comms import collective_traffic
    from byzpy_tpu.parallel.mesh import node_mesh, sharding
    from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step
    from byzpy_tpu.utils.metrics import timed_call_s

    platform = jax.default_backend()
    d = args.d or (8_192 if args.smoke else 262_144)
    repeat = args.repeat or (3 if args.smoke else 10)
    out_path = args.out or os.path.join(
        HERE, "results", f"quantized_comm_{platform}.jsonl"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    rows = []

    # -- 0. round-trip parity gate ------------------------------------
    x = jax.random.normal(jax.random.PRNGKey(0), (64, d), jnp.float32) * 2.0
    q = qz.quantize_blockwise(x)
    err = np.abs(np.asarray(q.dequantize() - x))
    bound = np.asarray(qz.quantization_error_bound(x))
    # the half-step bound holds up to f32 roundoff in x/scale (~1e-5 rel)
    assert (err <= bound * 1.0001 + 1e-7).all(), \
        "int8 round-trip violates absmax/254"
    rows.append({
        "bench": "quant_roundtrip", "d": d, "max_err": float(err.max()),
        "max_bound": float(bound.max()), **_provenance(platform),
    })
    print(f"round-trip parity OK (max err {err.max():.3e} <= bound)")

    # -- 1. collective wire bytes -------------------------------------
    mesh = node_mesh(8)
    xs = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (8, d), jnp.float32),
        sharding(mesh, "nodes"),
    )

    def gather_fn(mode):
        return coll.sharded_fn(
            mesh, "nodes",
            lambda s: coll.all_gather_q(s, "nodes", precision=mode),
            in_spec=P("nodes"), out_spec=P(),
        )

    def scatter_fn(mode):
        return coll.sharded_fn(
            mesh, "nodes",
            lambda s: coll.reduce_scatter_sum_q(s[0], "nodes", precision=mode)[None],
            in_spec=P("nodes"), out_spec=P("nodes"),
        )

    ratios = {}
    for name, build in (("all_gather_q", gather_fn), ("reduce_scatter_sum_q", scatter_fn)):
        base_bytes = None
        for mode in MODES:
            fn = build(mode)
            traffic = collective_traffic(fn, xs)
            wire = traffic["wire_bytes_per_device"]
            ms = timed_call_s(fn, xs, warmup=1, repeat=repeat) * 1e3
            if mode == "off":
                base_bytes = wire
            ratio = base_bytes / wire if wire else float("inf")
            ratios[(name, mode)] = ratio
            rows.append({
                "bench": name, "mode": mode, "d": d,
                "wire_bytes_per_device": wire,
                "bytes_ratio_vs_off": round(ratio, 3),
                "ms": round(ms, 3),
                "per_opcode_bytes": traffic["per_opcode_bytes"],
                **_provenance(platform),
            })
            print(f"{name:22s} {mode:5s}: {wire:>12,} B/device "
                  f"({ratio:.2f}x vs off)  {ms:.2f} ms")

    # -- 2+3. PS round: wire bytes + steps/sec ------------------------
    d_model, d_out = (64, 8) if args.smoke else (512, 32)
    params = {
        "w": jax.random.normal(jax.random.PRNGKey(2), (d_model, d_out)) * 0.1
    }

    def apply_fn(p, xb):
        return xb @ p["w"]

    def loss_fn(p, xb, yb):
        return jnp.mean((apply_fn(p, xb) - yb) ** 2)

    bundle = ModelBundle(apply_fn=apply_fn, params=params, loss_fn=loss_fn)
    cfg = PSStepConfig(n_nodes=8, n_byzantine=1)
    bx = jax.random.normal(jax.random.PRNGKey(3), (8, 32, d_model))
    by = jax.random.normal(jax.random.PRNGKey(4), (8, 32, d_out))
    key = jax.random.PRNGKey(5)

    ps_base = None
    for mode in MODES:
        step, o0 = build_ps_train_step(
            bundle, lambda m: robust.trimmed_mean(m, f=1), cfg,
            mesh=mesh, comm_precision=mode,
        )
        jitted = jax.jit(step)
        traffic = collective_traffic(jitted, params, o0, bx, by, key)
        wire = traffic["wire_bytes_per_device"]
        ms = timed_call_s(
            lambda p, o: jitted(p, o, bx, by, key)[0], params, o0,
            warmup=1, repeat=repeat,
        ) * 1e3
        if mode == "off":
            ps_base = wire
        ratio = ps_base / wire if wire else float("inf")
        rows.append({
            "bench": "ps_round", "mode": mode,
            "d_params": d_model * d_out,
            "wire_bytes_per_device": wire,
            "bytes_ratio_vs_off": round(ratio, 3),
            "ms_per_step": round(ms, 3),
            "steps_per_sec": round(1e3 / ms, 2) if ms else None,
            **_provenance(platform),
        })
        print(f"{'ps_round':22s} {mode:5s}: {wire:>12,} B/device "
              f"({ratio:.2f}x vs off)  {ms:.2f} ms/step")

    with open(out_path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows -> {out_path}")

    # acceptance floors: quantized collectives move >= 1.5x fewer bytes
    # at int8; the sub-int8 tier must clear >= 3.5x at fp8 and >= 7x at
    # s4 vs f32 (fp8 is byte-identical to int8 — 1 B/value — so its win
    # vs f32 matches int8's ~3.9x; s4 halves the payload again)
    floors = {"int8": 1.5, "fp8": 3.5, "s4": 7.0}
    bad = [
        (name, mode, ratios[(name, mode)])
        for name in ("all_gather_q", "reduce_scatter_sum_q")
        for mode, fl in floors.items()
        if ratios[(name, mode)] < fl
    ]
    if bad:
        print(f"FAIL: wire-bytes reduction below floor: {bad}", file=sys.stderr)
        return 1
    print("wire-bytes reduction floors (int8 1.5x, fp8 3.5x, s4 7x): OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Round-3 on-chip re-measurement bundle.

Runs every measurement that changed this round and prints one JSON line
per row (append the relevant ones to ``results/overrides.jsonl`` with a
provenance note):

* SMEA 16x4096 f=5 grid row (device-pure Jacobi path)
* PS + Multi-Krum actor round (host-side node model)
* NNM 196x4096 grid row (fused kernel dispatches only at d >= 256k, so
  this row is unchanged; measured for confirmation) and a 64x1M NNM
  stream comparison (fused vs XLA)
* fused-kernel TPU parity spot-check (selection + NNM, vs the XLA paths)

Usage: python benchmarks/rerun_round3.py
"""

from __future__ import annotations

import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from _timing import timed_ms  # noqa: E402
from byzpy_tpu.ops import preagg, robust  # noqa: E402


def emit(workload: str, ms: float, **extra) -> None:
    print(json.dumps({"workload": workload, "ms": round(ms, 2), **extra}), flush=True)


def grads(n, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), n)
    return [jax.random.normal(k, (d,), jnp.float32) for k in ks]


def parity_checks() -> None:
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 524_288), jnp.float32)
    want = robust.ranked_mean(x, robust.krum_scores(x, f=8), 12)
    got = robust.multi_krum(x, f=8, q=12)  # dispatches to the fused kernel
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"selection kernel parity: {err}"
    xs = x[: 16].reshape(1, 16, 524_288)
    from byzpy_tpu.ops.pallas_kernels import nnm_stream_pallas

    got = nnm_stream_pallas(xs, f=4)[0]
    gram = jnp.einsum("id,jd->ij", xs[0], xs[0], preferred_element_type=jnp.float32)
    nrm = jnp.diagonal(gram)
    d2 = jnp.maximum(nrm[:, None] + nrm[None, :] - 2 * gram, 0.0)
    idx = jnp.argsort(d2, axis=1)[:, :12]
    want = jnp.stack([jnp.mean(xs[0][idx[i]], axis=0) for i in range(16)])
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-4, f"nnm kernel parity: {err}"

    # sorted-reduce (median + trimmed) and MeaMed kernels, real lowering
    y = x[:17]  # odd n exercises padding on chip
    got = robust.coordinate_median(y)  # dispatches at d >= 256k
    want = jnp.median(y, axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err == 0.0, f"sorted-reduce median parity: {err}"
    got = robust.trimmed_mean(y, f=3)
    s = jnp.sort(y, axis=0)
    want = jnp.mean(s[3:-3], axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"sorted-reduce trimmed parity: {err}"
    got = robust.mean_of_medians(y, f=3)
    med = jnp.median(y, axis=0)
    dev = jnp.abs(y - med[None, :])
    order = jnp.argsort(dev, axis=0)[: y.shape[0] - 3]  # (k, d) node indices
    want = jnp.mean(jnp.take_along_axis(y, order, axis=0), axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"meamed kernel parity: {err}"
    print("# on-chip kernel parity OK", flush=True)


def main() -> None:
    print(f"# device={jax.devices()[0]}", file=sys.stderr)
    parity_checks()

    # SMEA grid row (ref best 48.0 ms)
    from byzpy_tpu.aggregators import SMEA

    smea = SMEA(f=5)
    g = grads(16, 4096)
    emit("smea_16x4096_f5", timed_ms(lambda: smea.aggregate(g), repeat=20),
         ref_best_pool_ms=48.0, ref_direct_ms=82)

    # PS actor round (ref best 42 ms) — host-side node model
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import full_grid

    emit("ps_multikrum_round", full_grid.ps_multi_krum_round_ms(rounds=50),
         ref_best_pool_ms=42, ref_byzfl_ms=57, ref_direct_ms=71)

    # NNM grid row confirmation (65k-dim: XLA path, unchanged)
    x196 = jnp.stack(grads(196, 4096, seed=3))
    emit("nnm_196x4096_f32", timed_ms(jax.jit(partial(preagg.nnm, f=32)), x196),
         ref_direct_ms=12)

    # NNM 64x1M: fused kernel vs XLA einsum path
    key = jax.random.PRNGKey(5)
    xs = jax.random.normal(key, (8, 64, 1_048_576), jnp.float32)
    from byzpy_tpu.ops.pallas_kernels import nnm_stream_pallas

    t_fused = timed_ms(jax.jit(partial(nnm_stream_pallas, f=8)), xs, repeat=10) / 8
    os.environ["BYZPY_TPU_PALLAS"] = "0"
    t_xla = timed_ms(
        jax.jit(jax.vmap(partial(preagg.nnm, f=8))), xs, repeat=10
    ) / 8
    os.environ["BYZPY_TPU_PALLAS"] = "auto"
    emit("nnm_64x1M_stream8_fused", t_fused, xla_ms=round(t_xla, 2),
         speedup=round(t_xla / t_fused, 2))

    # headline (same as bench.py, for the overrides record)
    stream = jax.jit(partial(robust.multi_krum_stream, f=8, q=12))
    xs32 = jax.random.normal(key, (32, 64, 1_048_576), jnp.float32)
    t = timed_ms(stream, xs32, repeat=40) / 32
    emit("multi_krum_64x1M_stream32_f32", t, grads_per_sec=round(64 / (t / 1e3), 1))
    t = timed_ms(stream, xs32.astype(jnp.bfloat16), repeat=40) / 32
    emit("multi_krum_64x1M_stream32_bf16", t, grads_per_sec=round(64 / (t / 1e3), 1))


if __name__ == "__main__":
    main()

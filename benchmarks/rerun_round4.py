"""Round-4 on-chip re-measurement bundle (the work queued behind the
mid-session tunnel outage; the first half of the round-4 on-chip work —
kernel parity, SMEA/PS grid rows, headline — landed before it and is
recorded in ``results/overrides.jsonl``).

Runs, printing one JSON line per row:

* 64x1M real-lowering parity for the sort-based kernels under the new
  ``_auto_sort_tile`` budget (the old tile OOM'd Mosaic's scoped VMEM at
  this shape — never reachable before the fix)
* per-kernel roofline cells at 64x1M f32, K=32 stream amortization
  (docs/performance.md pending cells): sorted-reduce median/trimmed,
  MeaMed, NNM, weighted-center (32 fori_loop iterations per dispatch)
* north-star refresh: cw_median single-dispatch + stream (the 6.90 ms
  grid.jsonl row predates the fused kernel)

Usage: python benchmarks/rerun_round4.py
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp

from byzpy_tpu.ops import robust
from byzpy_tpu.ops.pallas_kernels import (
    meamed_stream_pallas,
    nnm_stream_pallas,
    selection_mean_stream_pallas,
    sorted_reduce_stream_pallas,
    weighted_center_step_pallas,
)
from byzpy_tpu.utils.metrics import timed_call_s


def emit(**row) -> None:
    print(json.dumps(row), flush=True)


def parity_64x1m(x) -> None:
    got = sorted_reduce_stream_pallas(x[None], mode="median")[0]
    want = jnp.median(x, axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err == 0.0, f"sorted-reduce median 64x1M: {err}"
    got = sorted_reduce_stream_pallas(x[None], mode="trimmed", f=8)[0]
    s = jnp.sort(x, axis=0)
    want = jnp.mean(s[8:-8], axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"sorted-reduce trimmed 64x1M: {err}"
    got = meamed_stream_pallas(x[None], f=8)[0]
    med = jnp.median(x, axis=0)
    order = jnp.argsort(jnp.abs(x - med[None, :]), axis=0)[:56]
    want = jnp.mean(jnp.take_along_axis(x, order, axis=0), axis=0)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"meamed 64x1M: {err}"
    emit(check="sort_kernels_64x1M_parity", ok=True)


def main() -> None:
    print(f"# device={jax.devices()[0]}", file=sys.stderr)
    K = 32
    xs = jax.random.normal(jax.random.PRNGKey(5), (K, 64, 1 << 20), jnp.float32)
    parity_64x1m(xs[0])

    def run(name, fn, *args, per_round=K, repeat=10):
        t = timed_call_s(jax.jit(fn), *args, warmup=2, repeat=repeat)
        t = t / per_round * 1e3
        emit(kernel=name, ms_per_round=round(t, 3))
        return t

    run("selection_mean_stream_pallas",
        functools.partial(selection_mean_stream_pallas, f=8, q=12), xs)
    run("sorted_reduce_stream_pallas_median",
        functools.partial(sorted_reduce_stream_pallas, mode="median"), xs)
    run("sorted_reduce_stream_pallas_trimmed",
        functools.partial(sorted_reduce_stream_pallas, mode="trimmed", f=8), xs)
    run("meamed_stream_pallas", functools.partial(meamed_stream_pallas, f=8), xs)
    run("nnm_stream_pallas", functools.partial(nnm_stream_pallas, f=8), xs)

    x1, z0 = xs[0], jnp.mean(xs[0], axis=0)

    def iter_center(mode):
        def fn(x, z):
            body = lambda i, zz: weighted_center_step_pallas(  # noqa: E731
                x, zz, mode=mode, c_tau=1.0)
            return jax.lax.fori_loop(0, 32, body, z)
        return fn

    run("weighted_center_step_pallas_weiszfeld", iter_center("weiszfeld"),
        x1, z0, per_round=32, repeat=5)
    run("weighted_center_step_pallas_clip", iter_center("clip"),
        x1, z0, per_round=32, repeat=5)

    # Fused NNM->Multi-Krum pipeline: on-chip parity vs the two-step
    # composition, then per-round cost vs running the two steps
    from byzpy_tpu.ops import preagg
    from byzpy_tpu.ops.pallas_kernels import nnm_selection_mean_stream_pallas

    xpar = xs[0][:16, :524_288]
    got = nnm_selection_mean_stream_pallas(xpar[None], f_nnm=4, f=3, q=5)[0]
    mixed = preagg.nnm(xpar, f=4)
    want = robust.ranked_mean(mixed, robust.krum_scores(mixed, f=3), 5)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-4, f"fused nnm->krum parity: {err}"
    emit(check="nnm_selection_fused_parity", ok=True, max_err=err)

    t_fused = timed_call_s(
        jax.jit(functools.partial(
            robust.nnm_multi_krum_stream, f_nnm=8, f=8, q=12)),
        xs, warmup=2, repeat=10) / K * 1e3

    def two_step(a):
        mixed = jax.vmap(functools.partial(preagg.nnm, f=8))(a)
        return jax.vmap(
            functools.partial(robust.multi_krum, f=8, q=12))(mixed)

    import os as _os
    _os.environ["BYZPY_TPU_PALLAS"] = "0"
    t_two = timed_call_s(jax.jit(two_step), xs, warmup=2, repeat=10) / K * 1e3
    _os.environ["BYZPY_TPU_PALLAS"] = "auto"
    emit(workload="nnm_multi_krum_64x1M_stream32", fused_ms=round(t_fused, 3),
         two_step_xla_ms=round(t_two, 3),
         speedup=round(t_two / t_fused, 2))

    # MeaMed grid row (weakest non-SMEA multiplier at 41.8 ms / 1.4x):
    # measure the XLA path it currently dispatches to at d=65k AND the
    # fused kernel at the same shape — if the kernel wins by more than
    # the dispatch floor, MIN_PALLAS_DIM should drop for meamed
    from byzpy_tpu.ops.pallas_kernels import meamed_stream_pallas as _mm

    x64 = jax.random.normal(jax.random.PRNGKey(7), (64, 65_536), jnp.float32)
    t_xla = timed_call_s(
        jax.jit(functools.partial(robust.mean_of_medians, f=8)), x64,
        warmup=2, repeat=20) * 1e3
    t_fused = timed_call_s(
        jax.jit(lambda a: _mm(a[None], f=8)[0]), x64, warmup=2, repeat=20
    ) * 1e3
    emit(workload="meamed_64x65536_f8", xla_ms=round(t_xla, 2),
         fused_ms=round(t_fused, 2))

    # SMEA grid row under the parallel-order Jacobi (sequential rotation
    # depth 55 -> 11 per sweep at m=11; prior cyclic-order row: 28.0 ms)
    from byzpy_tpu.aggregators import SMEA

    smea = SMEA(f=5)
    ks = jax.random.split(jax.random.PRNGKey(0), 16)
    g16 = [jax.random.normal(k, (4096,), jnp.float32) for k in ks]
    t = timed_call_s(lambda: smea.aggregate(g16), warmup=2, repeat=20) * 1e3
    emit(workload="smea_16x4096_f5", ms=round(t, 2), ref_best_pool_ms=48.0,
         note="parallel-order Jacobi")

    # north-star refresh (grid.jsonl cw_median_64x1M predates the kernel)
    t = timed_call_s(jax.jit(robust.coordinate_median), x1, warmup=2,
                     repeat=20) * 1e3
    emit(workload="cw_median_64x1M", ms=round(t, 3),
         note="fused sorted-reduce kernel (round-4 tile fix)")
    t = timed_call_s(
        jax.jit(functools.partial(robust.coordinate_median_stream)), xs,
        warmup=2, repeat=10,
    ) / K * 1e3
    emit(workload="cw_median_64x1M_stream32", ms_per_round=round(t, 3),
         grads_per_sec=round(64 / (t / 1e3), 1))


if __name__ == "__main__":
    main()

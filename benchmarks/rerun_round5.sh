#!/bin/bash
# On-chip recovery bundle: run EVERYTHING queued behind the tunnel
# outage, each row in a fresh process (tunnel backpressure — see
# ROUND4_NOTES gotchas), results to benchmarks/results/round5_onchip.jsonl.
# Extended for ISSUE 2 (roofline + autotune): steps 4-6 produce the
# on-chip roofline grid, the tuned tile cache, and the refreshed
# benchmark grid the CPU runs of this round stand in for.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round5_onchip.jsonl
mkdir -p benchmarks/results
probe() {
  timeout 60 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu'; print(d)" >/dev/null 2>&1
}
if ! probe; then echo "tunnel down, aborting bundle"; exit 1; fi
echo "# bundle start $(date -u)" >> "$OUT"
# 1. round-4 leftovers: 64x1M sort-kernel parity, roofline cells, cw_median refresh
timeout 3000 python benchmarks/rerun_round4.py >> "$OUT" 2>/tmp/r5_rerun4.err
# 2. MeaMed gate tune (fresh process): prints the measured crossover —
#    commit it to pallas_kernels.MEAMED_MIN_DIM (currently the
#    CPU-derived 64k default)
timeout 1800 python benchmarks/meamed_gate_tune.py >> "$OUT" 2>/tmp/r5_meamed.err
# 3. headline bench (fresh process — exactly what the driver will run)
timeout 1800 python bench.py >> "$OUT" 2>/tmp/r5_bench.err
# 4. Pallas block-shape autotune at the grid + north-star shapes; winners
#    persist to the on-disk tile cache every dispatch consults
#    (tile cache committed for provenance)
timeout 2400 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  python -m byzpy_tpu.profiling --autotune --force \
  >> "$OUT" 2>/tmp/r5_autotune.err
# 5. achieved-vs-roofline grid for every ops.robust aggregator at the
#    BASELINE.md shapes (fresh process so the tuned tiles apply)
timeout 2400 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  python -m byzpy_tpu.profiling --out benchmarks/results/roofline_tpu.jsonl \
  >> "$OUT" 2>/tmp/r5_roofline.err
# 6. full measured grid refresh (fresh process, tuned tiles on)
timeout 3600 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  python benchmarks/full_grid.py > benchmarks/results/grid_tpu.jsonl \
  2>/tmp/r5_grid.err
# 7. ISSUE 3 (quantized comm fabric): on-chip wire-bytes + steps/sec
#    sweep (real ICI — CPU can only certify bytes, not time) and the
#    per-aggregator int8 robustness grid, tuned quant tiles applied
#    (fresh processes; the quant family autotunes in step 4)
timeout 1800 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  python benchmarks/quantized_comm_bench.py \
  --out benchmarks/results/quantized_comm_tpu.jsonl \
  >> "$OUT" 2>/tmp/r5_quantcomm.err
timeout 1800 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  python benchmarks/quant_robustness_study.py \
  --out benchmarks/results/quant_robustness_tpu.jsonl \
  >> "$OUT" 2>/tmp/r5_quantrob.err
# 8. ISSUE 15 (sub-int8 fabric): on-chip fp8/s4 sweep — wire bytes +
#    steps/sec down the whole precision ladder (quantized_comm_bench
#    covers fp8/s4 since round 15), the sub-int8 Pallas kernels'
#    Mosaic bit-parity gate (BYZPY_TPU_SUBINT8_PALLAS=1 flips only
#    with this evidence), the EF convergence study on-chip, and the
#    fp8/s4 autotune families (swept in step 4's --force run)
timeout 1800 env BYZPY_TPU_TUNE_CACHE=benchmarks/results/autotune_tpu.json \
  BYZPY_TPU_SUBINT8_PALLAS=1 \
  python benchmarks/quantized_comm_bench.py \
  --out benchmarks/results/subint8_comm_tpu.jsonl \
  >> "$OUT" 2>/tmp/r5_subint8.err
timeout 1800 python benchmarks/ef_convergence_study.py \
  --out benchmarks/results/round15_subint8_tpu.jsonl \
  >> "$OUT" 2>/tmp/r5_ef.err
echo "# bundle end $(date -u)" >> "$OUT"
echo "bundle complete: $OUT (+ roofline_tpu.jsonl, autotune_tpu.json, grid_tpu.jsonl, quantized_comm_tpu.jsonl, quant_robustness_tpu.jsonl)"

#!/bin/bash
# Round-5 on-chip recovery bundle: run EVERYTHING queued behind the
# tunnel outage, each row in a fresh process (tunnel backpressure — see
# ROUND4_NOTES gotchas), results to benchmarks/results/round5_onchip.jsonl.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round5_onchip.jsonl
mkdir -p benchmarks/results
probe() {
  timeout 60 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu'; print(d)" >/dev/null 2>&1
}
if ! probe; then echo "tunnel down, aborting bundle"; exit 1; fi
echo "# bundle start $(date -u)" >> "$OUT"
# 1. round-4 leftovers: 64x1M sort-kernel parity, roofline cells, cw_median refresh
timeout 3000 python benchmarks/rerun_round4.py >> "$OUT" 2>/tmp/r5_rerun4.err
# 2. MeaMed gate tune (fresh process)
timeout 1800 python benchmarks/meamed_gate_tune.py >> "$OUT" 2>/tmp/r5_meamed.err
# 3. headline bench (fresh process — exactly what the driver will run)
timeout 1800 python bench.py >> "$OUT" 2>/tmp/r5_bench.err
echo "# bundle end $(date -u)" >> "$OUT"
echo "bundle complete: $OUT"

"""Accuracy-under-attack grid on real data (the robust-*learning* study).

Mirrors the reference's ByzFL accuracy sweeps
(``/root/reference/benchmarks/byzfl/*_compare.py``) and the MNIST example's
accuracy eval (``/root/reference/examples/ps/thread/mnist.py:114-119``):
every (aggregator x attack) cell is a full training run on the real
handwritten-digits dataset through the fused SPMD parameter-server step,
scored on held-out data.

Writes ``benchmarks/ROBUST_LEARNING.md`` (accuracy matrix + trajectories)
and appends one JSON row per cell to
``benchmarks/results/robust_learning.jsonl``.

Run on any backend; for the CPU mesh use::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/robust_learning.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_APPENDIX_MARKERS = ("\n## BF16 gradients", "\n## Decentralized (gossip)")


def _replace_section(md_path: str, marker: str, section_text: str) -> None:
    """Idempotently install ``marker``'s appendix section in the study
    doc: replace it in place if present (up to the next appendix marker
    or EOF), append otherwise."""
    existing = open(md_path).read() if os.path.exists(md_path) else ""
    starts = {m: existing.index(m) for m in _APPENDIX_MARKERS if m in existing}
    if marker in starts:
        s = starts[marker]
        later = [i for i in starts.values() if i > s]
        e = min(later) if later else len(existing)
        new = existing[:s] + section_text + existing[e:]
    else:
        new = existing + section_text
    with open(md_path, "w") as fh:
        fh.write(new)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--rounds", type=int, default=240,  # the committed grid/plot provenance
    )
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--byzantine", type=int, default=2)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--eval-every", type=int, default=50)
    parser.add_argument(
        "--aggregators",
        default="mean,median,trimmed_mean,multi_krum,nnm_trimmed_mean",
    )
    parser.add_argument("--attacks", default="none,sign_flip,little,empire")
    parser.add_argument(
        "--write", action="store_true", help="update ROBUST_LEARNING.md + jsonl"
    )
    parser.add_argument(
        "--grad-dtype", default=None, choices=[None, "bfloat16", "float32"],
        help="cast per-node gradients before attack+aggregation; "
             "bfloat16 halves robust-pipeline HBM traffic (params stay f32). "
             "With --write, a bfloat16 run appends the BF16 section to "
             "ROBUST_LEARNING.md instead of rewriting it.",
    )
    parser.add_argument(
        "--mode", default="ps", choices=["ps", "gossip"],
        help="training fabric per cell: fused SPMD parameter-server round "
             "or decentralized gossip (complete topology). With --write, "
             "a gossip run appends the Decentralized section to "
             "ROBUST_LEARNING.md instead of rewriting it.",
    )
    args = parser.parse_args()
    if args.mode == "gossip" and args.grad_dtype is not None:
        parser.error("--grad-dtype is a PS-mode knob (gossip exchanges "
                     "parameters, not gradients)")

    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax

    from byzpy_tpu.utils.robust_study import (
        StudyConfig,
        results_table,
        run_study,
    )

    cfg = StudyConfig(
        n_nodes=args.nodes,
        n_byzantine=args.byzantine,
        rounds=args.rounds,
        batch_size=args.batch,
        eval_every=args.eval_every,
        grad_dtype=args.grad_dtype,
    )
    results = run_study(
        aggregators=tuple(args.aggregators.split(",")),
        attacks=tuple(args.attacks.split(",")),
        cfg=cfg,
        mode=args.mode,
    )
    table = results_table(results)
    print(table)

    if args.write:
        here = os.path.dirname(os.path.abspath(__file__))
        os.makedirs(os.path.join(here, "results"), exist_ok=True)
        with open(os.path.join(here, "results", "robust_learning.jsonl"), "a") as fh:
            for r in results:
                row = r.row()
                row.update(
                    device=str(jax.devices()[0]),
                    rounds=cfg.rounds,
                    n_nodes=cfg.n_nodes,
                    n_byzantine=cfg.n_byzantine,
                    grad_dtype=cfg.grad_dtype or "float32",
                    mode=args.mode,
                )
                fh.write(json.dumps(row) + "\n")
        md_path = os.path.join(here, "ROBUST_LEARNING.md")
        if args.grad_dtype == "bfloat16":
            section = [
                "",
                "## BF16 gradients (robustness survives the cast)",
                "",
                "Same grid with per-node gradients cast to **bfloat16**",
                "before the attack + robust aggregation (the dtype the",
                "150k grads/sec headline kernel runs at; robust ops",
                "accumulate in f32, the aggregated update is applied to",
                "f32 params — the mixed-precision trainer shape).",
                f"{cfg.rounds} rounds, {cfg.n_nodes} nodes, "
                f"{cfg.n_byzantine} byzantine.",
                "",
                table,
                "",
                "Reproduce: `python benchmarks/robust_learning.py "
                "--grad-dtype bfloat16 --write`.",
            ]
            _replace_section(
                md_path, "\n## BF16 gradients", "\n".join(section) + "\n"
            )
            print("updated BF16 section in ROBUST_LEARNING.md")
            return 0
        if args.mode == "gossip":
            section = [
                "",
                "## Decentralized (gossip) cells",
                "",
                "Same grid trained by P2P gossip instead of the PS round:",
                "complete topology, every honest node half-steps on its",
                "shard and robust-aggregates its in-neighborhood; byzantine",
                "nodes broadcast the attack vector. Plain SGD by",
                "construction (parameters themselves gossip — no per-node",
                "momentum state), so absolute accuracies differ slightly",
                "from the PS table; the robust-vs-mean story is the same.",
                f"{cfg.rounds} rounds, {cfg.n_nodes} nodes, "
                f"{cfg.n_byzantine} byzantine. Accuracy is node 0's model.",
                "",
                table,
                "",
                "Reproduce: `python benchmarks/robust_learning.py "
                "--mode gossip --write`.",
            ]
            _replace_section(
                md_path, "\n## Decentralized (gossip)",
                "\n".join(section) + "\n",
            )
            print("updated Decentralized section in ROBUST_LEARNING.md")
            return 0
        md = [
            "# Robust learning on real data (accuracy under attack)",
            "",
            "Real handwritten digits (sklearn's bundled UCI set, 1348 train /",
            "449 held-out, 10 classes), MLP(64), fused SPMD PS round:",
            f"{cfg.n_nodes} nodes, {cfg.n_byzantine} byzantine, "
            f"{cfg.rounds} rounds, batch {cfg.batch_size}/node, "
            f"SGD lr={cfg.learning_rate} m={cfg.momentum}.",
            "Columns are attacks (colluding byzantine rows); cells are",
            "final held-out accuracy.",
            "",
            f"Device: `{jax.devices()[0]}`",
            "",
            table,
            "",
            "Reference analogue: torchvision-MNIST accuracy eval",
            "(`examples/ps/thread/mnist.py:114-119`) and the ByzFL",
            "aggregator-vs-attack sweeps (`benchmarks/byzfl/*_compare.py`).",
            "Reproduce: `python benchmarks/robust_learning.py --write`;",
            "plot: `python benchmarks/plot_robust_learning.py` ->",
            "![trajectories](results/robust_learning.png)",
            "",
            "## Trajectories (round, held-out accuracy)",
            "",
        ]
        for r in results:
            md.append(
                f"- **{r.aggregator}** vs **{r.attack}**: "
                + ", ".join(f"({n}, {a:.3f})" for n, a in r.history)
            )
        # the base (f32 PS) rewrite must not destroy appended variant
        # sections (each documented reproduce command is independent)
        appendix = ""
        if os.path.exists(md_path):
            existing = open(md_path).read()
            starts = [
                existing.index(m) for m in _APPENDIX_MARKERS if m in existing
            ]
            if starts:
                appendix = existing[min(starts):]
        with open(md_path, "w") as fh:
            fh.write("\n".join(md) + "\n" + appendix)
        print("wrote ROBUST_LEARNING.md")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

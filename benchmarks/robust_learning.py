"""Accuracy-under-attack grid on real data (the robust-*learning* study).

Mirrors the reference's ByzFL accuracy sweeps
(``/root/reference/benchmarks/byzfl/*_compare.py``) and the MNIST example's
accuracy eval (``/root/reference/examples/ps/thread/mnist.py:114-119``):
every (aggregator x attack) cell is a full training run on the real
handwritten-digits dataset through the fused SPMD parameter-server step,
scored on held-out data.

Writes ``benchmarks/ROBUST_LEARNING.md`` (accuracy matrix + trajectories)
and appends one JSON row per cell to
``benchmarks/results/robust_learning.jsonl``.

Run on any backend; for the CPU mesh use::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/robust_learning.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--rounds", type=int, default=240,  # the committed grid/plot provenance
    )
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--byzantine", type=int, default=2)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--eval-every", type=int, default=50)
    parser.add_argument(
        "--aggregators",
        default="mean,median,trimmed_mean,multi_krum,nnm_trimmed_mean",
    )
    parser.add_argument("--attacks", default="none,sign_flip,little,empire")
    parser.add_argument(
        "--write", action="store_true", help="update ROBUST_LEARNING.md + jsonl"
    )
    parser.add_argument(
        "--grad-dtype", default=None, choices=[None, "bfloat16", "float32"],
        help="cast per-node gradients before attack+aggregation; "
             "bfloat16 halves robust-pipeline HBM traffic (params stay f32). "
             "With --write, a bfloat16 run appends the BF16 section to "
             "ROBUST_LEARNING.md instead of rewriting it.",
    )
    args = parser.parse_args()

    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax

    from byzpy_tpu.utils.robust_study import (
        StudyConfig,
        results_table,
        run_study,
    )

    cfg = StudyConfig(
        n_nodes=args.nodes,
        n_byzantine=args.byzantine,
        rounds=args.rounds,
        batch_size=args.batch,
        eval_every=args.eval_every,
        grad_dtype=args.grad_dtype,
    )
    results = run_study(
        aggregators=tuple(args.aggregators.split(",")),
        attacks=tuple(args.attacks.split(",")),
        cfg=cfg,
    )
    table = results_table(results)
    print(table)

    if args.write:
        here = os.path.dirname(os.path.abspath(__file__))
        os.makedirs(os.path.join(here, "results"), exist_ok=True)
        with open(os.path.join(here, "results", "robust_learning.jsonl"), "a") as fh:
            for r in results:
                row = r.row()
                row.update(
                    device=str(jax.devices()[0]),
                    rounds=cfg.rounds,
                    n_nodes=cfg.n_nodes,
                    n_byzantine=cfg.n_byzantine,
                    grad_dtype=cfg.grad_dtype or "float32",
                )
                fh.write(json.dumps(row) + "\n")
        if args.grad_dtype == "bfloat16":
            # append the BF16 section to the (f32) study doc, replacing
            # any previous BF16 section (idempotent re-runs)
            md_path = os.path.join(here, "ROBUST_LEARNING.md")
            if os.path.exists(md_path):
                existing = open(md_path).read()
                marker = "\n## BF16 gradients"
                if marker in existing:
                    with open(md_path, "w") as fh:
                        fh.write(existing[: existing.index(marker)])
            section = [
                "",
                "## BF16 gradients (robustness survives the cast)",
                "",
                "Same grid with per-node gradients cast to **bfloat16**",
                "before the attack + robust aggregation (the dtype the",
                "150k grads/sec headline kernel runs at; robust ops",
                "accumulate in f32, the aggregated update is applied to",
                "f32 params — the mixed-precision trainer shape).",
                f"{cfg.rounds} rounds, {cfg.n_nodes} nodes, "
                f"{cfg.n_byzantine} byzantine.",
                "",
                table,
                "",
                "Reproduce: `python benchmarks/robust_learning.py "
                "--grad-dtype bfloat16 --write`.",
            ]
            with open(md_path, "a") as fh:
                fh.write("\n".join(section) + "\n")
            print("appended BF16 section to ROBUST_LEARNING.md")
            return 0
        md = [
            "# Robust learning on real data (accuracy under attack)",
            "",
            "Real handwritten digits (sklearn's bundled UCI set, 1348 train /",
            "449 held-out, 10 classes), MLP(64), fused SPMD PS round:",
            f"{cfg.n_nodes} nodes, {cfg.n_byzantine} byzantine, "
            f"{cfg.rounds} rounds, batch {cfg.batch_size}/node, "
            f"SGD lr={cfg.learning_rate} m={cfg.momentum}.",
            "Columns are attacks (colluding byzantine rows); cells are",
            "final held-out accuracy.",
            "",
            f"Device: `{jax.devices()[0]}`",
            "",
            table,
            "",
            "Reference analogue: torchvision-MNIST accuracy eval",
            "(`examples/ps/thread/mnist.py:114-119`) and the ByzFL",
            "aggregator-vs-attack sweeps (`benchmarks/byzfl/*_compare.py`).",
            "Reproduce: `python benchmarks/robust_learning.py --write`;",
            "plot: `python benchmarks/plot_robust_learning.py` ->",
            "![trajectories](results/robust_learning.png)",
            "",
            "## Trajectories (round, held-out accuracy)",
            "",
        ]
        for r in results:
            md.append(
                f"- **{r.aggregator}** vs **{r.attack}**: "
                + ", ".join(f"({n}, {a:.3f})" for n, a in r.history)
            )
        # the f32 rewrite must not destroy a previously-appended BF16
        # section (the two documented reproduce commands are independent)
        md_path = os.path.join(here, "ROBUST_LEARNING.md")
        bf16_section = ""
        if os.path.exists(md_path):
            existing = open(md_path).read()
            marker = "\n## BF16 gradients"
            if marker in existing:
                bf16_section = existing[existing.index(marker):]
        with open(md_path, "w") as fh:
            fh.write("\n".join(md) + "\n" + bf16_section)
        print("wrote ROBUST_LEARNING.md")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Fused SPMD parameter-server steps/sec vs mesh size.

North-star sweep (BASELINE.json): PS steps/sec scaling 8→128 chips with
≥90% efficiency. Runs over however many devices are visible — on a pod
slice that's real chips over ICI; locally use a virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/scaling_bench.py
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

import time
from functools import partial

import jax
import jax.numpy as jnp

from _timing import report
from byzpy_tpu.models.nets import mnist_mlp
from byzpy_tpu.ops import robust
from byzpy_tpu.parallel.mesh import make_mesh, sharding
from byzpy_tpu.parallel.ps import PSStepConfig, build_ps_train_step

BATCH = 32


def steps_per_sec(n_devices, repeat=20):
    devices = jax.devices()[:n_devices]
    mesh = make_mesh([n_devices], ("nodes",), devices=devices)
    n_nodes = n_devices
    n_byz = n_nodes // 8
    cfg = PSStepConfig(n_nodes=n_nodes, n_byzantine=n_byz, learning_rate=0.05)
    bundle = mnist_mlp(seed=0, hidden=256)
    # trim as many as we can justify while keeping 2f < n
    f = min(max(n_byz, 1), (n_nodes - 1) // 2) if n_nodes > 2 else 0

    step, opt_state = build_ps_train_step(
        bundle, partial(robust.trimmed_mean, f=f), cfg, mesh=mesh
    )
    jit_step = jax.jit(step)
    xs = jax.device_put(
        jnp.zeros((n_nodes, BATCH, 28, 28, 1), jnp.float32), sharding(mesh, "nodes")
    )
    ys = jax.device_put(jnp.zeros((n_nodes, BATCH), jnp.int32), sharding(mesh, "nodes"))
    key = jax.random.PRNGKey(0)
    params = bundle.params

    from byzpy_tpu.utils.metrics import force_result

    params, opt_state, _ = jit_step(params, opt_state, xs, ys, key)  # compile
    force_result(params)  # tunnel block_until_ready returns early; host copy can't
    t0 = time.perf_counter()
    for _ in range(repeat):
        params, opt_state, _ = jit_step(params, opt_state, xs, ys, key)
    force_result(params)
    return repeat / (time.perf_counter() - t0)


def _ensure_virtual_devices(want: int = 8) -> None:
    """With fewer than ``want`` real devices, fall back to a virtual CPU
    mesh. Env vars don't work here — the session's sitecustomize pins and
    initializes the tunnel platform before this script runs — so the
    platform is rebuilt via jax.config + clear_backends (the same dance as
    ``__graft_entry__._ensure_devices``)."""
    if os.environ.get("SCALING_FORCE_CPU") != "1":
        try:
            if len(jax.devices()) >= want:
                return
        except Exception:
            pass  # platform init failed (e.g. tunnel down) -> CPU fallback
    from _timing import force_cpu_platform

    force_cpu_platform(want)
    print(f"# fell back to {len(jax.devices())} virtual CPU devices", file=sys.stderr)


def main():
    _ensure_virtual_devices()
    n = len(jax.devices())
    sizes = [s for s in (1, 2, 4, 8, 16, 32, 64, 128) if s <= n]
    base = None
    for s in sizes:
        sps = steps_per_sec(s)
        if base is None:
            base = sps
        # ideal weak scaling: constant steps/sec as nodes (and total work)
        # grow with the mesh; efficiency = sps / single-device sps
        report(
            f"spmd_ps_steps_per_sec_{s}dev",
            1000.0 / sps,
            steps_per_sec=round(sps, 2),
            weak_scaling_efficiency=round(sps / base, 3),
        )


if __name__ == "__main__":
    main()

"""ParallelScheduler vs NodeScheduler on multi-branch two-stage pipelines.

Reference workload: ``byzpy/benchmarks/scheduler/pipeline_benchmark.py``
(README:65-69 — ParallelScheduler 2.44–2.68× over sequential). Each branch
is ``preprocess (host numpy, GIL-released) -> robust aggregate (pool)``;
the parallel scheduler overlaps branch A's host stage with branch B's pool
stage, which is exactly the overlap that matters on TPU too (host-bound
work vs device-bound work).

Pinned to the CPU platform like the reference's CPU-pool benchmark.
"""

import os

import asyncio
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)                      # for _timing
sys.path.insert(0, os.path.dirname(_here))     # repo root

import jax

from _timing import force_cpu_platform

# CPU-pinned like the reference's CPU-pool benchmark
force_cpu_platform()

import jax.numpy as jnp
import numpy as np

from _timing import report
from byzpy_tpu.aggregators import (
    CenteredClipping,
    CoordinateWiseMedian,
    CoordinateWiseTrimmedMean,
    ComparativeGradientElimination,
)
from byzpy_tpu.engine.graph.graph import ComputationGraph, GraphInput, GraphNode
from byzpy_tpu.engine.graph.operator import OpContext, Operator
from byzpy_tpu.engine.graph.parallel_scheduler import ParallelScheduler
from byzpy_tpu.engine.graph.pool import ActorPool, ActorPoolConfig
from byzpy_tpu.engine.graph.scheduler import NodeScheduler

N, D = 64, 200_000
WORK_ITERS = int(os.environ.get("BENCH_WORK_ITERS", 5))


class PreprocessOp(Operator):
    """Host-side normalize loop (ref: ``_PreprocessingOperator``,
    pipeline_benchmark.py:31-62) — pure numpy in a thread so the loop
    stays free while it grinds."""

    name = "preprocess"
    supports_subtasks = False

    def _work(self, gradients):
        arr = np.asarray(gradients)
        for _ in range(WORK_ITERS):
            arr = arr - arr.mean(axis=1, keepdims=True)
            arr = arr / (arr.std(axis=1, keepdims=True) + 1e-8)
            arr = np.clip(arr, -3, 3)
        return arr

    async def run(self, inputs, *, context: OpContext, pool):
        return await asyncio.to_thread(self._work, inputs["gradients"])

    def compute(self, inputs, *, context: OpContext):
        return self._work(inputs["gradients"])


def build_graph():
    branches = {
        "median": CoordinateWiseMedian(),
        "trimmed": CoordinateWiseTrimmedMean(f=15),
        "cge": ComparativeGradientElimination(f=15),
        "clip": CenteredClipping(c_tau=10.0, M=5),
    }
    nodes = []
    for name, op in branches.items():
        nodes.append(
            GraphNode(name=f"pre_{name}", op=PreprocessOp(),
                      inputs={"gradients": GraphInput("gradients")})
        )
        nodes.append(
            GraphNode(name=name, op=op, inputs={"gradients": f"pre_{name}"})
        )
    return ComputationGraph(nodes, outputs=list(branches))


async def run(scheduler_cls, graph, pool, inputs, repeat=3):
    times = []
    for _ in range(repeat):
        sched = scheduler_cls(graph, pool=pool)
        t0 = time.perf_counter()
        out = await sched.run(inputs)
        jax.block_until_ready({k: jnp.asarray(v) for k, v in out.items()})
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] * 1e3


async def main():
    x = np.random.default_rng(0).normal(size=(N, D)).astype(np.float32)
    inputs = {"gradients": x}
    graph = build_graph()
    async with ActorPool(ActorPoolConfig(backend="thread", count=4)) as pool:
        await run(NodeScheduler, graph, pool, inputs, repeat=1)  # warm compile
        seq = await run(NodeScheduler, graph, pool, inputs)
        par = await run(ParallelScheduler, graph, pool, inputs)
    cpus = len(os.sched_getaffinity(0))
    report("pipeline_4branch_sequential", seq, cpus=cpus)
    # the parallel win requires host cores to overlap on: with 1 visible
    # CPU the schedulers necessarily tie (the reference's 2.44-2.68x was
    # measured on a multicore CI machine)
    report("pipeline_4branch_parallel", par, speedup=round(seq / par, 2),
           ref_speedup="2.44-2.68x", cpus=cpus)


if __name__ == "__main__":
    asyncio.run(main())

"""Serving-tier benchmark: ragged-cohort ingestion at 10k-client scale.

Three lanes, each emitting JSON rows (stdout + ``--out`` JSONL):

* ``swarm`` — a simulated client swarm (default 10,000 distinct client
  identities) streams gradient submissions into one
  :class:`~byzpy_tpu.serving.ServingFrontend` tenant while the cohort
  scheduler closes rounds on the window/size trigger and aggregates
  through the masked bucketed path. Reports sustained accepted
  submissions/sec, p50/p99 round-close latency, rounds, mean cohort,
  the rejection breakdown, and the queue's high-water depth (the
  bounded-backpressure proof: high water never exceeds capacity and
  ends drained).
* ``buckets`` — the jit-cache economics: an identical ragged sequence
  of cohort sizes aggregated (a) through the bucketed masked finalize
  (one compile per ladder rung) and (b) naively at the exact cohort
  size (one compile per DISTINCT size, the recompile-per-cohort-size
  strawman). Wall-clock includes compiles — precisely the cost a
  serving tier pays on fresh shapes — plus warm per-round time and
  per-path compile counts. Asserts bit-parity between both paths every
  round.
* ``wire`` — ingress accounting: measured frame bytes for the actor
  wire transport (off/bf16/int8 × unsigned/HMAC) against the
  ``parallel.comms.serving_ingress_bytes`` law, plus codec round-trip
  throughput (frames/sec) so the swarm lane's in-process numbers can be
  projected onto a TCP deployment.

``--smoke`` shrinks everything for CI and asserts the contracts
(bounded queue, drained shutdown, bucket parity, fewer bucketed than
naive compiles).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU mesh: the serving tier's host-side machinery is what's under test;
# a dead accelerator tunnel must not hang the bench (same policy as the
# other CPU lanes).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from byzpy_tpu.aggregators import (  # noqa: E402
    CoordinateWiseTrimmedMean,
    MultiKrum,
)
from byzpy_tpu.engine.actor import wire  # noqa: E402
from byzpy_tpu.parallel.comms import serving_ingress_bytes  # noqa: E402
from byzpy_tpu.serving import (  # noqa: E402
    ServingFrontend,
    TenantConfig,
)
from byzpy_tpu.serving.cohort import CohortAggregator, build_cohort  # noqa: E402
from byzpy_tpu.serving.credits import CreditPolicy  # noqa: E402
from byzpy_tpu.serving.buckets import BucketLadder  # noqa: E402
from byzpy_tpu.serving.queue import Submission  # noqa: E402
from byzpy_tpu.serving.staleness import StalenessPolicy  # noqa: E402


def _emit(row: dict, out_path: str | None) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    if out_path:
        with open(out_path, "a") as fh:
            fh.write(line + "\n")


# ---------------------------------------------------------------------------
# swarm lane
# ---------------------------------------------------------------------------


def _swarm_tenant(args, agg) -> TenantConfig:
    return TenantConfig(
        name="swarm",
        aggregator=agg,
        dim=args.dim,
        window_s=args.window_ms / 1e3,
        cohort_cap=args.cohort_cap,
        # the aggregator's smallest admissible n (2f+1 for a trimmed
        # mean): without it a tail cohort below the floor is closed,
        # fails validate_n in the crash guard, and silently discards
        # accepted submissions as a failed round
        min_cohort=2 * args.byzantine + 1,
        queue_capacity=args.queue_capacity,
        credit=CreditPolicy(rate_per_s=args.client_rate, burst=args.burst),
        staleness=StalenessPolicy(kind="exponential", gamma=0.5, cutoff=16),
    )


async def _drive_swarm(fe, args, pool, duration_s: float) -> tuple:
    """Flood the frontend from ``args.clients`` simulated identities for
    ``duration_s``; returns ``(offered, accepted, elapsed)``. Offers run
    far above the credit ceiling on purpose — rejection accounting under
    flood is part of what the tier must sustain."""
    rng = np.random.default_rng(0)
    n_clients = args.clients
    accepted = 0
    offered = 0
    t0 = time.monotonic()
    deadline = t0 + duration_s
    burst = 16  # submissions per scheduling slice
    i = 0
    while time.monotonic() < deadline:
        server_round = fe.round_of("swarm")
        for _ in range(burst):
            client = f"c{(i * 2654435761) % n_clients:05d}"
            # clients compute against a recent-but-lagging round
            lag = int(rng.integers(0, 3))
            ok, _reason = fe.submit(
                "swarm", client, server_round - lag, pool[i % len(pool)]
            )
            offered += 1
            accepted += ok
            i += 1
        # yield to the scheduler/aggregation tasks
        await asyncio.sleep(0)
    elapsed = time.monotonic() - t0
    await fe.drain("swarm")
    return offered, accepted, elapsed


async def _run_swarm(args) -> dict:
    agg = CoordinateWiseTrimmedMean(f=args.byzantine)
    rng = np.random.default_rng(0)
    # pre-generated gradient pool: the swarm measures the TIER, not
    # np.random; distinct rows keep aggregation honest
    pool = [
        rng.normal(size=args.dim).astype(np.float32) for _ in range(64)
    ]
    # warmup pass on a throwaway frontend: the masked jit cache lives on
    # the AGGREGATOR, so the measured pass starts with every bucket
    # compiled — steady-state numbers, not compile amortization
    warm = ServingFrontend([_swarm_tenant(args, agg)])
    await warm.start()
    await _drive_swarm(warm, args, pool, min(2.0, args.duration_s))
    await warm.close()

    fe = ServingFrontend([_swarm_tenant(args, agg)])
    await fe.start()
    offered, accepted, elapsed = await _drive_swarm(
        fe, args, pool, args.duration_s
    )
    stats = fe.stats()["swarm"]
    await fe.close()
    row = {
        "lane": "swarm",
        "clients": args.clients,
        "dim": args.dim,
        "aggregator": agg.name,
        "window_ms": args.window_ms,
        "cohort_cap": args.cohort_cap,
        "queue_capacity": args.queue_capacity,
        "duration_s": round(elapsed, 3),
        "offered": offered,
        "accepted": accepted,
        "accepted_per_sec": round(accepted / elapsed, 1),
        "offered_per_sec": round(offered / elapsed, 1),
        "rounds": stats["rounds"],
        "mean_cohort": round(stats["mean_cohort"], 2),
        "p50_round_latency_ms": round(stats["p50_round_latency_s"] * 1e3, 3),
        "p99_round_latency_ms": round(stats["p99_round_latency_s"] * 1e3, 3),
        "queue_high_water": stats["queue_high_water"],
        "queue_depth_final": stats["queue_depth"],
        "outstanding_final": stats["outstanding"],
        "failed_rounds": stats["failed_rounds"],
        "rejected": {
            k: v
            for k, v in stats["ledger"]["totals"].items()
            if k != "accepted"
        },
        "clients_seen": stats["ledger"]["clients_seen"],
    }
    # bounded-queue contract: every accepted submission was aggregated
    # or is part of the (< min_cohort) inadmissible tail the scheduler
    # rightly holds — and no round silently dropped a cohort
    assert stats["queue_high_water"] <= args.queue_capacity, "queue overflow"
    assert stats["failed_rounds"] == 0, "crash-guarded rounds in swarm"
    assert stats["outstanding"] < 2 * args.byzantine + 1, "undrained cohort"
    assert stats["queue_depth"] <= stats["outstanding"], "queue leak"
    return row


# ---------------------------------------------------------------------------
# bucketed-vs-naive lane
# ---------------------------------------------------------------------------


def _ragged_sizes(rounds: int, cap: int, rng, min_m: int = 5) -> list:
    """A serving-shaped cohort-size sequence: mostly mid-size cohorts,
    occasional small stragglers and full windows — many DISTINCT sizes,
    which is exactly what punishes the recompile-per-size strawman.
    ``min_m`` floors every draw at the lane aggregators' smallest
    admissible n (MultiKrum(f=2,q=3) and trimmed-mean f=2 both need
    n >= 5) — a tenant would enforce the same via ``min_cohort``."""
    sizes = []
    for _ in range(rounds):
        r = rng.random()
        if r < 0.15:
            m = int(rng.integers(min_m, max(min_m + 1, cap // 4)))
        elif r < 0.9:
            m = int(rng.integers(max(min_m, cap // 3), cap))
        else:
            m = cap
        sizes.append(m)
    return sizes


def _run_buckets(args) -> dict:
    rng = np.random.default_rng(1)
    cap = args.cohort_cap
    d = args.dim
    agg_m = MultiKrum(f=2, q=3)
    agg_t = CoordinateWiseTrimmedMean(f=2)
    sizes = _ragged_sizes(args.bucket_rounds, cap, rng)
    grads = rng.normal(size=(cap, d)).astype(np.float32)
    ladder = BucketLadder(cap, min_bucket=8)
    staleness = StalenessPolicy()

    def cohort_for(m):
        subs = [
            Submission(client=f"c{j}", round_submitted=0,
                       gradient=grads[j], arrived_s=0.0)
            for j in range(m)
        ]
        return build_cohort(subs, 0, ladder, staleness)

    results = {}
    for name, agg in (("multi-krum", agg_m), ("trimmed-mean", agg_t)):
        # bucketed masked path
        executor = CohortAggregator(agg)
        t0 = time.monotonic()
        bucketed_out = []
        per_round_b = []
        for m in sizes:
            r0 = time.monotonic()
            bucketed_out.append(
                np.asarray(executor.aggregate(cohort_for(m)))
            )
            per_round_b.append(time.monotonic() - r0)
        t_bucketed = time.monotonic() - t0
        bucketed_compiles = agg._masked_jitted()._cache_size()

        # naive path: exact-size aggregate per cohort (recompile per
        # DISTINCT size — what a serving tier without bucketing pays)
        t0 = time.monotonic()
        naive_out = []
        per_round_n = []
        for m in sizes:
            r0 = time.monotonic()
            naive_out.append(
                np.asarray(agg.aggregate([grads[j] for j in range(m)]))
            )
            per_round_n.append(time.monotonic() - r0)
        t_naive = time.monotonic() - t0

        for b, n in zip(bucketed_out, naive_out, strict=True):
            assert np.array_equal(b, n), f"{name}: bucketed != naive"

        warm = max(1, len(sizes) // 2)
        results[name] = {
            "rounds": len(sizes),
            "distinct_sizes": len(set(sizes)),
            "buckets_used": len({ladder.bucket_for(m) for m in sizes}),
            "bucketed_total_s": round(t_bucketed, 3),
            "naive_total_s": round(t_naive, 3),
            "total_speedup": round(t_naive / t_bucketed, 2),
            "bucketed_warm_ms": round(
                1e3 * float(np.mean(per_round_b[warm:])), 3
            ),
            "naive_warm_ms": round(
                1e3 * float(np.mean(per_round_n[warm:])), 3
            ),
            "bucketed_compile_entries": bucketed_compiles,
            "parity": "bit-identical",
        }
    return {
        "lane": "buckets",
        "dim": d,
        "cohort_cap": cap,
        "ladder": list(ladder.sizes),
        "results": results,
    }


# ---------------------------------------------------------------------------
# wire accounting lane
# ---------------------------------------------------------------------------


def _run_wire(args) -> dict:
    # at least 4096 coords: arrays under wire.WIRE_QUANT_MIN_SIZE travel
    # lossless by design, which would make the compressed rows vacuous
    d = max(args.dim, 4096)
    g = np.random.default_rng(2).normal(size=d).astype(np.float32)
    frame = {
        "kind": "submit", "tenant": "swarm", "client": "c01234",
        "round": 7, "gradient": g,
    }
    rows = {}
    for precision in ("off", "bf16", "int8"):
        for signed in (False, True):
            os.environ["BYZPY_TPU_WIRE_PRECISION"] = precision
            if signed:
                os.environ["BYZPY_TPU_WIRE_KEY"] = "bench-key"
            else:
                os.environ.pop("BYZPY_TPU_WIRE_KEY", None)
            encoded = wire.encode(frame)
            measured = len(encoded)
            law = serving_ingress_bytes(
                d, precision=precision, signed=signed
            )
            # codec round-trip throughput (encode + decode, host-side)
            n_iter = 50 if not args.smoke else 10
            t0 = time.monotonic()
            for _ in range(n_iter):
                wire.decode(wire.encode(frame)[4:])
            dt = (time.monotonic() - t0) / n_iter
            rows[f"{precision}{'+hmac' if signed else ''}"] = {
                "measured_bytes": measured,
                "law_bytes": round(law, 1),
                "law_error": round(abs(measured - law) / measured, 4),
                "codec_roundtrips_per_sec": round(1.0 / dt, 1),
            }
    os.environ.pop("BYZPY_TPU_WIRE_PRECISION", None)
    os.environ.pop("BYZPY_TPU_WIRE_KEY", None)
    compressed = rows["int8+hmac"]["measured_bytes"]
    lossless = rows["off+hmac"]["measured_bytes"]
    return {
        "lane": "wire",
        "dim": d,
        "frames": rows,
        "int8_byte_reduction": round(lossless / compressed, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=10_000)
    ap.add_argument("--dim", type=int, default=1024)
    ap.add_argument("--duration-s", type=float, default=6.0)
    ap.add_argument("--window-ms", type=float, default=10.0)
    ap.add_argument("--cohort-cap", type=int, default=256)
    ap.add_argument("--queue-capacity", type=int, default=4096)
    ap.add_argument("--client-rate", type=float, default=50.0)
    ap.add_argument("--burst", type=float, default=40.0)
    ap.add_argument("--byzantine", type=int, default=2)
    ap.add_argument("--bucket-rounds", type=int, default=36)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI run with contract assertions")
    args = ap.parse_args()

    if args.smoke:
        args.clients = 300
        args.dim = 512
        args.duration_s = 2.0
        args.cohort_cap = 32
        args.queue_capacity = 256
        args.bucket_rounds = 10

    meta = {
        "lane": "meta",
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "smoke": bool(args.smoke),
    }
    _emit(meta, args.out)

    swarm = asyncio.run(_run_swarm(args))
    _emit(swarm, args.out)

    buckets = _run_buckets(args)
    _emit(buckets, args.out)

    wire_row = _run_wire(args)
    _emit(wire_row, args.out)

    headline = {
        "lane": "headline",
        "metric": "serving_submissions_per_sec",
        "value": swarm["accepted_per_sec"],
        "unit": "submissions/sec",
        "clients": swarm["clients"],
        "p99_round_latency_ms": swarm["p99_round_latency_ms"],
        "rounds": swarm["rounds"],
        "bucketed_vs_naive_speedup": {
            k: v["total_speedup"] for k, v in buckets["results"].items()
        },
    }
    _emit(headline, args.out)

    if args.smoke:
        assert swarm["rounds"] > 0, "no rounds closed"
        assert swarm["accepted"] > 0, "nothing admitted"
        for res in buckets["results"].values():
            assert res["bucketed_compile_entries"] <= len(buckets["ladder"])
            assert res["bucketed_compile_entries"] < res["distinct_sizes"]
        print("serving smoke OK")


if __name__ == "__main__":
    main()

"""Serving-tier benchmark: ragged-cohort ingestion at 10k-client scale.

Three lanes, each emitting JSON rows (stdout + ``--out`` JSONL):

* ``swarm`` — a simulated client swarm (default 10,000 distinct client
  identities) streams gradient submissions into one
  :class:`~byzpy_tpu.serving.ServingFrontend` tenant while the cohort
  scheduler closes rounds on the window/size trigger and aggregates
  through the masked bucketed path. Reports sustained accepted
  submissions/sec, p50/p99 round-close latency, rounds, mean cohort,
  the rejection breakdown, and the queue's high-water depth (the
  bounded-backpressure proof: high water never exceeds capacity and
  ends drained).
* ``buckets`` — the jit-cache economics: an identical ragged sequence
  of cohort sizes aggregated (a) through the bucketed masked finalize
  (one compile per ladder rung) and (b) naively at the exact cohort
  size (one compile per DISTINCT size, the recompile-per-cohort-size
  strawman). Wall-clock includes compiles — precisely the cost a
  serving tier pays on fresh shapes — plus warm per-round time and
  per-path compile counts. Asserts bit-parity between both paths every
  round.
* ``ragged`` — the PR-11 door: the SAME cohort-size sequence as the
  buckets lane served by the flat-rows ragged executor
  (``serving.ragged``), per-dispatch and greedily batched (several
  cohorts per device call). Reports total wall (incl. the ONE
  compile), warm per-round time per cohort-size tercile, dispatch and
  compile counts, and speedups vs the naive AND bucketed paths from
  the buckets lane; asserts every cohort's aggregate is bit-identical
  to the naive exact path.
* ``wire`` — ingress accounting: measured frame bytes for the actor
  wire transport (off/bf16/int8 × unsigned/HMAC) against the
  ``parallel.comms.serving_ingress_bytes`` law, plus codec round-trip
  throughput (frames/sec) so the swarm lane's in-process numbers can be
  projected onto a TCP deployment.

The swarm lane runs TWICE: the single-tenant bucket-ladder baseline
(``BYZPY_TPU_RAGGED=0``) and a two-tenant swarm through the default
ragged door — the ragged row reports the cross-tenant batch accounting
(``max_batch ≥ 2`` = two tenants' cohorts in one device call).

``--smoke`` shrinks everything for CI and asserts the contracts
(bounded queue, drained shutdown, bucket parity, fewer bucketed than
naive compiles, ragged bit parity + ONE compile per tenant group +
cross-tenant coalescing).
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU mesh: the serving tier's host-side machinery is what's under test;
# a dead accelerator tunnel must not hang the bench (same policy as the
# other CPU lanes).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from byzpy_tpu.aggregators import (  # noqa: E402
    CoordinateWiseTrimmedMean,
    MultiKrum,
)
from byzpy_tpu.engine.actor import wire  # noqa: E402
from byzpy_tpu.parallel.comms import serving_ingress_bytes  # noqa: E402
from byzpy_tpu.serving import (  # noqa: E402
    ServingFrontend,
    TenantConfig,
)
from byzpy_tpu.serving.cohort import CohortAggregator, build_cohort  # noqa: E402
from byzpy_tpu.serving.credits import CreditPolicy  # noqa: E402
from byzpy_tpu.serving.buckets import BucketLadder  # noqa: E402
from byzpy_tpu.serving.queue import Submission  # noqa: E402
from byzpy_tpu.serving.staleness import StalenessPolicy  # noqa: E402


def _emit(row: dict, out_path: str | None) -> None:
    line = json.dumps(row)
    print(line, flush=True)
    if out_path:
        with open(out_path, "a") as fh:
            fh.write(line + "\n")


# ---------------------------------------------------------------------------
# swarm lane
# ---------------------------------------------------------------------------


def _swarm_tenant(args, agg, name="swarm", window_ms=None) -> TenantConfig:
    return TenantConfig(
        name=name,
        aggregator=agg,
        dim=args.dim,
        window_s=(window_ms or args.window_ms) / 1e3,
        cohort_cap=args.cohort_cap,
        # the aggregator's smallest admissible n (2f+1 for a trimmed
        # mean): without it a tail cohort below the floor is closed,
        # fails validate_n in the crash guard, and silently discards
        # accepted submissions as a failed round
        min_cohort=2 * args.byzantine + 1,
        queue_capacity=args.queue_capacity,
        credit=CreditPolicy(rate_per_s=args.client_rate, burst=args.burst),
        staleness=StalenessPolicy(kind="exponential", gamma=0.5, cutoff=16),
    )


async def _drive_swarm(
    fe, args, pool, duration_s: float, tenants, target_rate=None
) -> tuple:
    """Drive the frontend from ``args.clients`` simulated identities for
    ``duration_s``, round-robin across ``tenants``; returns ``(offered,
    accepted, elapsed)``. Default is an unthrottled flood far above the
    credit ceiling (rejection accounting under flood is part of what
    the tier must sustain); ``target_rate`` (total submissions/sec)
    paces the offers instead — the sub-cap-cohort regime the ragged
    coalescing comparison needs."""
    rng = np.random.default_rng(0)
    n_clients = args.clients
    accepted = 0
    offered = 0
    t0 = time.monotonic()
    deadline = t0 + duration_s
    burst = 16  # submissions per scheduling slice
    i = 0
    while time.monotonic() < deadline:
        for _ in range(burst):
            tenant = tenants[i % len(tenants)]
            server_round = fe.round_of(tenant)
            client = f"c{(i * 2654435761) % n_clients:05d}"
            # clients compute against a recent-but-lagging round
            lag = int(rng.integers(0, 3))
            ok, _reason = fe.submit(
                tenant, client, server_round - lag, pool[i % len(pool)]
            )
            offered += 1
            accepted += ok
            i += 1
        if target_rate is not None:
            ahead = offered / target_rate - (time.monotonic() - t0)
            await asyncio.sleep(max(0.0, ahead))
        else:
            # yield to the scheduler/aggregation tasks
            await asyncio.sleep(0)
    elapsed = time.monotonic() - t0
    for tenant in tenants:
        await fe.drain(tenant)
    return offered, accepted, elapsed


async def _run_swarm(
    args, *, lane="swarm", n_tenants=1, ragged=True, agg_factory=None,
    target_rate=None, window_ms=None,
) -> dict:
    """One swarm pass: ``n_tenants`` tenants sharing the aggregator
    signature (one ragged group — their cohorts can coalesce when the
    family supports it) driven by the same client flood;
    ``ragged=False`` pins the bucket-ladder escape hatch for the
    baseline row. The warmup drive runs on the MEASURED frontend so
    both doors start with their programs compiled (the ladder's bucket
    caches and the ragged door's single program alike)."""
    make = agg_factory or (
        lambda: CoordinateWiseTrimmedMean(f=args.byzantine)
    )
    prev = os.environ.get("BYZPY_TPU_RAGGED")
    os.environ["BYZPY_TPU_RAGGED"] = "1" if ragged else "0"
    try:
        names = [f"swarm{i}" for i in range(n_tenants)]
        agg_name = make().name
        fe = ServingFrontend(
            [
                _swarm_tenant(args, make(), name=n, window_ms=window_ms)
                for n in names
            ]
        )

        rng = np.random.default_rng(0)
        # pre-generated gradient pool: the swarm measures the TIER, not
        # np.random; distinct rows keep aggregation honest
        pool = [
            rng.normal(size=args.dim).astype(np.float32) for _ in range(64)
        ]
        await fe.start()
        await _drive_swarm(
            fe, args, pool, min(2.0, args.duration_s), names,
            target_rate=target_rate,
        )
        # warmup→measure boundary: compile-round latencies must not
        # pollute the measured percentile window, and the cumulative
        # accounting (rejections, dispatch counters) is snapshotted so
        # the row reports measured-window DELTAS
        fe.reset_round_stats()
        warm_stats = fe.stats()
        offered, accepted, elapsed = await _drive_swarm(
            fe, args, pool, args.duration_s, names,
            target_rate=target_rate,
        )
        all_stats = fe.stats()
        await fe.close()
    finally:
        if prev is None:
            os.environ.pop("BYZPY_TPU_RAGGED", None)
        else:
            os.environ["BYZPY_TPU_RAGGED"] = prev
    per_tenant = [all_stats[n] for n in names]
    stats = per_tenant[0]
    row = {
        "lane": lane,
        "ragged": ragged,
        "tenants": n_tenants,
        "clients": args.clients,
        "dim": args.dim,
        "aggregator": agg_name,
        "window_ms": args.window_ms,
        "cohort_cap": args.cohort_cap,
        "queue_capacity": args.queue_capacity,
        "duration_s": round(elapsed, 3),
        "offered": offered,
        "accepted": accepted,
        "accepted_per_sec": round(accepted / elapsed, 1),
        "offered_per_sec": round(offered / elapsed, 1),
        "rounds": sum(s["rounds"] for s in per_tenant),
        "mean_cohort": round(
            float(np.mean([s["mean_cohort"] for s in per_tenant])), 2
        ),
        "p50_round_latency_ms": round(
            max(s["p50_round_latency_s"] for s in per_tenant) * 1e3, 3
        ),
        "p99_round_latency_ms": round(
            max(s["p99_round_latency_s"] for s in per_tenant) * 1e3, 3
        ),
        "queue_high_water": max(
            s["queue_high_water"] for s in per_tenant
        ),
        "queue_depth_final": sum(s["queue_depth"] for s in per_tenant),
        "outstanding_final": sum(s["outstanding"] for s in per_tenant),
        "failed_rounds": sum(s["failed_rounds"] for s in per_tenant),
        # measured-window deltas (the warmup drive's accounting is
        # subtracted; see the boundary snapshot above)
        "rejected": {
            k: v - warm_stats[names[0]]["ledger"]["totals"].get(k, 0)
            for k, v in stats["ledger"]["totals"].items()
            if k != "accepted"
        },
        "clients_seen": stats["ledger"]["clients_seen"],
        # ragged dispatch accounting (None on the escape-hatch baseline):
        # device calls, cohorts carried, and the largest cross-tenant
        # batch — max_batch >= 2 is two tenants' cohorts in ONE call;
        # call counters are measured-window deltas
        "ragged_dispatch": (
            None
            if stats["frontend"]["ragged"] is None
            else {
                **stats["frontend"]["ragged"],
                **{
                    k: stats["frontend"]["ragged"][k]
                    - warm_stats[names[0]]["frontend"]["ragged"][k]
                    for k in (
                        "dispatches", "cohorts_dispatched",
                        "batched_calls",
                    )
                },
            }
        ),
    }
    # bounded-queue contract: every accepted submission was aggregated
    # or is part of the (< min_cohort) inadmissible tail the scheduler
    # rightly holds — and no round silently dropped a cohort
    for s in per_tenant:
        assert s["queue_high_water"] <= args.queue_capacity, "queue overflow"
        assert s["failed_rounds"] == 0, "crash-guarded rounds in swarm"
        assert s["outstanding"] < 2 * args.byzantine + 1, "undrained cohort"
        assert s["queue_depth"] <= s["outstanding"], "queue leak"
    return row


# ---------------------------------------------------------------------------
# bucketed-vs-naive lane
# ---------------------------------------------------------------------------


def _ragged_sizes(rounds: int, cap: int, rng, min_m: int = 5) -> list:
    """A serving-shaped cohort-size sequence: mostly mid-size cohorts,
    occasional small stragglers and full windows — many DISTINCT sizes,
    which is exactly what punishes the recompile-per-size strawman.
    ``min_m`` floors every draw at the lane aggregators' smallest
    admissible n (MultiKrum(f=2,q=3) and trimmed-mean f=2 both need
    n >= 5) — a tenant would enforce the same via ``min_cohort``."""
    sizes = []
    for _ in range(rounds):
        r = rng.random()
        if r < 0.15:
            m = int(rng.integers(min_m, max(min_m + 1, cap // 4)))
        elif r < 0.9:
            m = int(rng.integers(max(min_m, cap // 3), cap))
        else:
            m = cap
        sizes.append(m)
    return sizes


def _run_buckets(args) -> tuple:
    """Returns ``(json_row, refs)`` — ``refs`` carries the size
    sequence, gradient pool, per-round naive outputs and timings the
    ragged lane compares against (same workload, different door)."""
    rng = np.random.default_rng(1)
    cap = args.cohort_cap
    d = args.dim
    agg_m = MultiKrum(f=2, q=3)
    agg_t = CoordinateWiseTrimmedMean(f=2)
    sizes = _ragged_sizes(args.bucket_rounds, cap, rng)
    grads = rng.normal(size=(cap, d)).astype(np.float32)
    ladder = BucketLadder(cap, min_bucket=8)
    staleness = StalenessPolicy()

    def cohort_for(m):
        subs = [
            Submission(client=f"c{j}", round_submitted=0,
                       gradient=grads[j], arrived_s=0.0)
            for j in range(m)
        ]
        return build_cohort(subs, 0, ladder, staleness)

    results = {}
    refs = {"sizes": sizes, "grads": grads}
    for name, agg in (("multi-krum", agg_m), ("trimmed-mean", agg_t)):
        # bucketed masked path
        executor = CohortAggregator(agg)
        t0 = time.monotonic()
        bucketed_out = []
        per_round_b = []
        for m in sizes:
            r0 = time.monotonic()
            bucketed_out.append(
                np.asarray(executor.aggregate(cohort_for(m)))
            )
            per_round_b.append(time.monotonic() - r0)
        t_bucketed = time.monotonic() - t0
        bucketed_compiles = agg._masked_jitted()._cache_size()

        # naive path: exact-size aggregate per cohort (recompile per
        # DISTINCT size — what a serving tier without bucketing pays)
        t0 = time.monotonic()
        naive_out = []
        per_round_n = []
        for m in sizes:
            r0 = time.monotonic()
            naive_out.append(
                np.asarray(agg.aggregate([grads[j] for j in range(m)]))
            )
            per_round_n.append(time.monotonic() - r0)
        t_naive = time.monotonic() - t0

        for b, n in zip(bucketed_out, naive_out, strict=True):
            assert np.array_equal(b, n), f"{name}: bucketed != naive"

        warm = max(1, len(sizes) // 2)
        results[name] = {
            "rounds": len(sizes),
            "distinct_sizes": len(set(sizes)),
            "buckets_used": len({ladder.bucket_for(m) for m in sizes}),
            "bucketed_total_s": round(t_bucketed, 3),
            "naive_total_s": round(t_naive, 3),
            "total_speedup": round(t_naive / t_bucketed, 2),
            "bucketed_warm_ms": round(
                1e3 * float(np.mean(per_round_b[warm:])), 3
            ),
            "naive_warm_ms": round(
                1e3 * float(np.mean(per_round_n[warm:])), 3
            ),
            "bucketed_compile_entries": bucketed_compiles,
            "parity": "bit-identical",
        }
        refs[name] = {
            "naive_outs": naive_out,
            "naive_total_s": t_naive,
            "bucketed_total_s": t_bucketed,
            "bucketed_per_round": per_round_b,
            "bucketed_compiles": bucketed_compiles,
        }
    return {
        "lane": "buckets",
        "dim": d,
        "cohort_cap": cap,
        "ladder": list(ladder.sizes),
        "results": results,
    }, refs


# ---------------------------------------------------------------------------
# ragged lane (PR 11: the ladder-free door, same workload)
# ---------------------------------------------------------------------------


def _size_tercile(m: int, cap: int) -> str:
    if m < cap // 3:
        return "small"
    if m < 2 * cap // 3:
        return "mid"
    return "large"


def _run_ragged(args, refs) -> dict:
    """The ragged door on the EXACT workload the buckets lane timed:
    per-dispatch (one cohort per device call, like a lone tenant) and
    greedily batched (consecutive cohorts packed into one call while
    they fit — the cross-tenant coalescing shape). Bit parity vs the
    naive exact outputs is asserted per round; speedups are computed
    against the buckets lane's naive and bucketed totals."""
    from byzpy_tpu.serving.ragged import RaggedExecutor

    cap = args.cohort_cap
    d = args.dim
    sizes = refs["sizes"]
    grads = refs["grads"]
    staleness = StalenessPolicy()

    def cohort_for(m):
        subs = [
            Submission(client=f"c{j}", round_submitted=0,
                       gradient=grads[j], arrived_s=0.0)
            for j in range(m)
        ]
        return build_cohort(subs, 0, None, staleness)

    results = {}
    for name, agg in (
        ("multi-krum", MultiKrum(f=2, q=3)),
        ("trimmed-mean", CoordinateWiseTrimmedMean(f=2)),
    ):
        ref = refs[name]
        # per-dispatch pass: one cohort per device call, ONE compiled
        # program across every distinct size (the compile the whole
        # ladder used to cost). No forensics plane in this lane, so no
        # evidence outputs — matching what the bucketed lane computes
        ex = RaggedExecutor(
            agg, d, row_capacity=cap, max_cohorts=4, with_evidence=False
        )
        t0 = time.monotonic()
        per_round = []
        outs = []
        for m in sizes:
            r0 = time.monotonic()
            (view,) = ex.aggregate([cohort_for(m)], ["t0"])
            outs.append(view.vector)
            per_round.append(time.monotonic() - r0)
        t_ragged = time.monotonic() - t0
        for o, n_ref in zip(outs, ref["naive_outs"], strict=True):
            assert np.array_equal(o, n_ref), f"{name}: ragged != naive"
        compiles = ex.cache_size()

        # batched pass: pack consecutive cohorts into one dispatch
        # while they fit (≤ 4 cohorts, ≤ cap rows) — the multi-tenant
        # coalescing economics on the same size distribution
        ex_b = RaggedExecutor(
            agg, d, row_capacity=cap, max_cohorts=4, with_evidence=False
        )
        batches = []
        cur, rows = [], 0
        for m in sizes:
            if cur and (rows + m > cap or len(cur) == 4):
                batches.append(cur)
                cur, rows = [], 0
            cur.append(m)
            rows += m
        if cur:
            batches.append(cur)
        t0 = time.monotonic()
        outs_b = []
        for batch in batches:
            views = ex_b.aggregate(
                [cohort_for(m) for m in batch],
                [f"t{i}" for i in range(len(batch))],
            )
            outs_b.extend(v.vector for v in views)
        t_batched = time.monotonic() - t0
        for o, n_ref in zip(outs_b, ref["naive_outs"], strict=True):
            assert np.array_equal(o, n_ref), f"{name}: batched != naive"

        warm = max(1, len(sizes) // 2)
        by_size = {}
        for key in ("small", "mid", "large"):
            r_ms = [
                1e3 * t for m, t in zip(sizes[warm:], per_round[warm:],
                                        strict=True)
                if _size_tercile(m, cap) == key
            ]
            b_ms = [
                1e3 * t
                for m, t in zip(
                    sizes[warm:], ref["bucketed_per_round"][warm:],
                    strict=True,
                )
                if _size_tercile(m, cap) == key
            ]
            if r_ms:
                by_size[key] = {
                    "rounds": len(r_ms),
                    "ragged_warm_ms": round(float(np.mean(r_ms)), 3),
                    "bucketed_warm_ms": round(float(np.mean(b_ms)), 3),
                }
        results[name] = {
            "rounds": len(sizes),
            "distinct_sizes": len(set(sizes)),
            "ragged_total_s": round(t_ragged, 3),
            "ragged_batched_total_s": round(t_batched, 3),
            "speedup_vs_naive": round(ref["naive_total_s"] / t_ragged, 2),
            "batched_speedup_vs_naive": round(
                ref["naive_total_s"] / t_batched, 2
            ),
            "speedup_vs_bucketed": round(
                ref["bucketed_total_s"] / t_ragged, 2
            ),
            "batched_speedup_vs_bucketed": round(
                ref["bucketed_total_s"] / t_batched, 2
            ),
            "compile_entries": compiles,
            "bucketed_compile_entries": ref["bucketed_compiles"],
            "batched_dispatches": len(batches),
            "mean_batch": round(len(sizes) / len(batches), 2),
            "warm_ms_by_size": by_size,
            "parity": "bit-identical",
        }
    # forensics-overhead leg: with the score view riding the kernel
    # (RaggedView.precomputed), the plane's prepare stage skips the
    # host O(m²·d) score pass — measure both against a Multi-Krum
    # cohort at the full cap (the shape where the host pass hurts)
    from byzpy_tpu.forensics.plane import ForensicsPlane

    agg = MultiKrum(f=2, q=3)
    ex = RaggedExecutor(agg, d, row_capacity=cap, max_cohorts=1)
    cohort = cohort_for(cap)
    (view,) = ex.aggregate([cohort], ["t0"])
    clients = [f"c{j}" for j in range(cap)]
    plane = ForensicsPlane("bench")
    reps = 3 if args.smoke else 10

    def prep(pre):
        return plane.prepare(
            0, cohort.matrix, cohort.valid, clients, view.vector,
            aggregator=agg, precomputed=pre,
        )

    prep(None)
    t0 = time.monotonic()
    for _ in range(reps):
        prep(None)
    host_ms = (time.monotonic() - t0) / reps * 1e3
    pre = view.precomputed()
    prep(pre)
    t0 = time.monotonic()
    for _ in range(reps):
        prep(pre)
    fused_ms = (time.monotonic() - t0) / reps * 1e3
    forensics = {
        "aggregator": "multi-krum",
        "m": cap,
        "prepare_host_score_pass_ms": round(host_ms, 3),
        "prepare_fused_ms": round(fused_ms, 3),
        "host_pass_skipped_speedup": round(host_ms / max(fused_ms, 1e-9), 1),
    }
    return {
        "lane": "ragged",
        "dim": d,
        "cohort_cap": cap,
        "results": results,
        "forensics_overhead": forensics,
    }


# ---------------------------------------------------------------------------
# scale lane (ISSUE 12: sharded frontend tier toward million-client serving)
# ---------------------------------------------------------------------------


def _scale_tenant(args, agg) -> "TenantConfig":
    from byzpy_tpu.serving.credits import CreditPolicy

    return TenantConfig(
        name="scale",
        aggregator=agg,
        dim=args.scale_dim,
        cohort_cap=args.scale_round_submissions,
        queue_capacity=args.scale_round_submissions + 16,
        # the lane measures the tier, not the rate limiter: rate <= 0
        # disables credit spending; the tracked-client bound must hold
        # the whole identity space so (client, seq) dedup stays exact
        credit=CreditPolicy(
            rate_per_s=0.0,
            burst=1e9,
            max_tracked_clients=max(65536, args.scale_clients + 1),
        ),
        staleness=StalenessPolicy(kind="exponential", gamma=0.5, cutoff=16),
    )


def _drive_shard_partition(
    co, shard_idx, clients, grads, bodies, r
) -> tuple:
    """Drive one shard's client partition through the per-submission
    work a shard ingress pays — ONE wire-frame decode (the PR-6
    frontend's dominant cost and the reason a single process tops out
    near 10k/sec) plus the full admission plane — timed in isolation:
    shards share no state, so the serially-measured leg equals what a
    dedicated shard process would measure."""
    shard_clients = clients[shard_idx]
    t0 = time.monotonic()
    accepted = 0
    for j, c in enumerate(shard_clients):
        req = wire.decode(bodies[j % len(bodies)])
        ok, _reason = co.submit(
            "scale", c, r, req["gradient"], seq=r
        )
        accepted += ok
    return accepted, time.monotonic() - t0


def _scale_round_trace_events(
    n_shards: int, legs_rounds: list, merges: list
) -> list:
    """Render the scale lane's measured per-round numbers as a
    round-causality trace on the lane's parallel-makespan model:
    per round, one ``serving.sharded_round`` root spanning
    ``max(legs) + merge``, each shard's ingress+close leg as a child
    starting at the barrier open (legs overlap on their own lanes —
    dedicated shard processes share nothing until the PartialFold hits
    the root), and the root merge chained after the slowest leg. The
    events carry the same ``span``/``parent``/``shard`` ids the live
    tracer stamps, so ``observability.critical_path`` attributes them
    exactly like a recorded trace — the virtual-clock-trace precedent
    is the chaos ``EventTrace.to_chrome_trace``."""
    events = []
    t = 0.0
    for r, (legs, merge_s) in enumerate(
        zip(legs_rounds, merges, strict=True)
    ):
        makespan = max(legs) + merge_s
        root = f"scale{n_shards}.r{r}"
        events.append(
            {
                "name": "serving.sharded_round", "ph": "X",
                "ts": t * 1e6, "dur": makespan * 1e6, "tid": 0,
                "args": {"span": root, "round": r, "tenant": "scale"},
            }
        )
        for s, leg in enumerate(legs):
            events.append(
                {
                    "name": "serving.shard_ingress", "ph": "X",
                    "ts": t * 1e6, "dur": leg * 1e6, "tid": 1 + s,
                    "args": {
                        "span": f"{root}.s{s}", "parent": root,
                        "shard": s, "round": r,
                    },
                }
            )
        events.append(
            {
                "name": "serving.fold_merge", "ph": "X",
                "ts": (t + max(legs)) * 1e6, "dur": merge_s * 1e6,
                "tid": 0,
                "args": {"span": f"{root}.m", "parent": root, "round": r},
            }
        )
        t += makespan
    return events


def _run_scale(args) -> dict:
    """Sharded-tier scaling: the SAME per-round submission load (drawn
    from ``--scale-clients`` distinct identities) through 1, 2 and 4
    frontend shards. Per-shard admission legs are measured in isolation
    and combined as the parallel makespan ``max(shard legs) + root
    merge`` — on a multi-core host the legs genuinely overlap (each
    shard is its own process with its own queue and ledgers; nothing is
    shared until the PartialFold hits the root), so the makespan is the
    tier's round time; the row carries ``timing_model`` naming the
    measurement honestly, plus the serial wall-clock actually spent.
    Per round, the hierarchical fold's BIT PARITY vs the exact
    unsharded aggregate of the same merged cohort is asserted, and one
    round's PartialFold frames are measured against the
    ``parallel.comms.sharded_round_wire_bytes`` law (< 2%).

    Tracing is ON for the whole lane (ISSUE 13): the per-round parity
    assert therefore doubles as the aggregates-bit-identical-with-
    propagation pin, and the measured legs/merges are rendered as a
    round-causality trace on the lane's own parallel-makespan model
    (each shard's leg overlapping on its own lane, the root merge
    after the barrier — exactly the timing_model, as a span tree) and
    attributed by ``observability.critical_path``: the committed
    ``critical_path_blame`` table replaces the "root merge looks like
    the next bottleneck" folklore with per-stage/per-shard makespan
    shares."""
    from byzpy_tpu import observability as obs
    from byzpy_tpu.observability import critical_path as obs_cp
    from byzpy_tpu.parallel.comms import (
        partial_fold_bytes,
        sharded_round_wire_bytes,
    )
    from byzpy_tpu.serving import ShardedCoordinator
    from byzpy_tpu.serving.sharded import encode_partial_fold, shard_for

    from byzpy_tpu.aggregators import ComparativeGradientElimination

    telemetry_was_on = obs.enabled()
    obs.enable()
    rng = np.random.default_rng(7)
    d = args.scale_dim
    per_round = args.scale_round_submissions
    grads = [rng.normal(size=d).astype(np.float32) for _ in range(64)]
    # pre-encoded representative submit frames: the timed leg decodes
    # one per submission (the ingress cost), encoding is the client's
    bodies = [
        wire.encode(
            {
                "kind": "submit", "tenant": "scale", "client": "c000000",
                "round": 0, "gradient": g, "seq": 0,
            }
        )[4:]
        for g in grads
    ]
    identity = [f"c{i:06d}" for i in range(args.scale_clients)]
    results = {}
    for n_shards in args.scale_shards:
        agg = ComparativeGradientElimination(f=args.byzantine)
        ref_agg = ComparativeGradientElimination(f=args.byzantine)
        co = ShardedCoordinator(
            [_scale_tenant(args, agg)], n_shards, quorum=1
        )
        # rotate a per-round window of the identity space, partitioned
        # by the router's sticky hash (what a deployment's load looks
        # like: every identity exists, a slice is active per round)
        wire_row = None
        per_round_leg = []
        per_round_legs_full = []
        per_round_merge = []
        total_accepted = 0
        wall0 = time.monotonic()
        for r in range(args.scale_rounds + 1):
            warmup = r == 0
            lo = (r * per_round) % max(1, args.scale_clients - per_round + 1)
            window = identity[lo: lo + per_round]
            partition = [
                [c for c in window if shard_for(c, n_shards) == s]
                for s in range(n_shards)
            ]
            legs = []
            partials = []
            # gc hygiene: a collection landing inside ONE serially-
            # measured leg would charge that shard's wall for garbage
            # the whole process produced — real shard processes don't
            # share a collector. Collect between rounds instead.
            gc.collect()
            gc.disable()
            try:
                for s in range(n_shards):
                    # a shard's round work = its ingress leg + its own
                    # close (drain, cohort build, partial extraction,
                    # digest) — all of it runs on the shard process
                    accepted, leg_s = _drive_shard_partition(
                        co, s, partition, grads, bodies, r
                    )
                    t0 = time.monotonic()
                    p = co.shards[s].close_partial("scale")
                    leg_s += time.monotonic() - t0
                    if p is not None:
                        partials.append(p)
                    if not warmup:
                        total_accepted += accepted
                    legs.append(leg_s)
            finally:
                gc.enable()
            if warmup:
                # round 0 is the warmup boundary: the merged masked
                # program compiles here, and the frame-law pin measures
                # one round's shard->root partials against the law
                measured = sum(
                    len(encode_partial_fold(p)) for p in partials
                )
                law = sum(
                    partial_fold_bytes(
                        p.m, d, client_id_bytes=7,
                        extras_bytes=p.m * 4,  # CGE norms
                    )
                    for p in partials
                )
                round_law = sharded_round_wire_bytes(
                    n_shards, sum(p.m for p in partials), d,
                    client_id_bytes=7,
                    extras_bytes_per_shard=(
                        sum(p.m for p in partials) / max(n_shards, 1) * 4
                    ),
                )
                wire_row = {
                    "partial_frames_measured_bytes": measured,
                    "partial_frames_law_bytes": round(law, 1),
                    "partial_law_error": round(
                        abs(measured - law) / measured, 4
                    ),
                    "round_law_bytes": round(round_law, 1),
                }
            # the ROOT's work: verify + hierarchical merge + finalize +
            # confirm/broadcast — merge_partials is the exact door a
            # remote root runs on decoded wire frames
            t_merge0 = time.monotonic()
            res = co.merge_partials("scale", partials)
            merge_s = time.monotonic() - t_merge0
            assert res is not None, (n_shards, r)
            _closed, merged_rows, vec = res
            if warmup:
                continue
            # bit-parity pin: the hierarchical fold vs the exact
            # unsharded aggregate of the same merged cohort, every round
            ref = np.asarray(
                ref_agg.aggregate(
                    [merged_rows[i] for i in range(merged_rows.shape[0])]
                )
            )
            assert np.array_equal(np.asarray(vec), ref), (
                f"hierarchical fold diverged at {n_shards} shards round {r}"
            )
            per_round_merge.append(merge_s)
            per_round_leg.append(max(legs))
            per_round_legs_full.append(list(legs))
        wall = time.monotonic() - wall0
        st = co.stats()["root"]["scale"]
        # steady-state throughput: shard admission (the next window) and
        # the root's merge run in DIFFERENT processes, so a pipelined
        # deployment's round period is max(slowest leg, merge); round
        # LATENCY (p99 below) still pays leg + merge end to end
        per_round_period = [
            max(leg, m)
            for leg, m in zip(per_round_leg, per_round_merge, strict=True)
        ]
        per_round_latency = [
            leg + m
            for leg, m in zip(per_round_leg, per_round_merge, strict=True)
        ]
        # throughput from the MEDIAN round period: a single-core host
        # running every shard's leg serially eats occasional scheduler/
        # GC spikes that a dedicated shard process would not share; the
        # p99 latency below keeps every spike (bounded-p99 evidence)
        period_median = float(np.median(per_round_period))
        accepted_per_round = total_accepted / max(1, len(per_round_period))
        # critical-path blame over the modeled round trace: per-stage/
        # per-shard makespan shares (blame sums to the summed makespan;
        # asserted by the smoke below)
        cp_summary = obs_cp.summarize(
            _scale_round_trace_events(
                n_shards, per_round_legs_full, per_round_merge
            )
        )
        assert cp_summary["max_blame_residual"] < 1e-6, cp_summary[
            "max_blame_residual"
        ]
        results[n_shards] = {
            "accepted": total_accepted,
            "period_median_ms": round(1e3 * period_median, 2),
            "period_total_s": round(float(np.sum(per_round_period)), 3),
            "accepted_per_sec": round(accepted_per_round / period_median, 1),
            "serial_wall_s": round(wall, 3),
            "p99_round_latency_ms": round(
                1e3 * float(np.percentile(per_round_latency, 99)), 2
            ),
            "mean_leg_ms": round(1e3 * float(np.mean(per_round_leg)), 2),
            "mean_merge_ms": round(
                1e3 * float(np.mean(per_round_merge)), 2
            ),
            "rounds": st["rounds"] - 1,  # warmup excluded
            "mean_cohort": st["mean_cohort"],
            "failed_rounds": st["failed_rounds"],
            "forged_partials": st["forged_partials"],
            "wire": wire_row,
            "critical_path_blame": cp_summary["stages"],
            # the headline number the ISSUE-12 bottleneck claim becomes:
            # the fraction of the round makespan the ROOT MERGE owns on
            # the critical path at this shard count
            "root_merge_blame_share": next(
                (
                    r["share"]
                    for r in cp_summary["stages"]
                    if r["stage"] == "serving.fold_merge"
                ),
                0.0,
            ),
        }
    base = results[args.scale_shards[0]]["accepted_per_sec"]
    speedups = {
        n: round(results[n]["accepted_per_sec"] / base, 2)
        for n in args.scale_shards
    }
    row = {
        "lane": "scale",
        "clients": args.scale_clients,
        "dim": d,
        "round_submissions": per_round,
        "rounds": args.scale_rounds,
        "aggregator": f"cge-f{args.byzantine}",
        # machine-readable model tag (ISSUE 14 honesty gap): this lane
        # MODELS the makespan on one core — never compare it silently
        # with the runner lane's timing_model == "measured" rows
        "timing_model": "modeled:max(legs)+merge",
        "timing_model_note": (
            "per-shard ingress legs (frame decode + full admission) "
            "measured in isolation — shards share no state, so the "
            "serial leg equals a dedicated shard process's; round "
            "period = max(slowest leg, root merge) (admission of the "
            "next window pipelines with the root's merge across "
            "processes), round latency = slowest leg + merge; "
            "serial_wall_s is the single-core wall clock actually spent"
        ),
        "shards": results,
        "speedup_vs_1shard": speedups,
        "parity": "bit-identical",
        "telemetry": "on (trace-context propagation active; per-round "
                     "parity assert doubles as the propagation pin)",
        "root_merge_blame_share": {
            n: results[n]["root_merge_blame_share"]
            for n in args.scale_shards
        },
    }
    if not telemetry_was_on:
        obs.disable()
    return row


def _run_streamroot(args) -> dict:
    """Streaming root merge A/B (ISSUE 18): the SAME deterministic
    traffic through two roots — the BARRIER arm (gather all partials,
    then verify-ALL + combine + finalize serially after the barrier:
    the pre-18 door) vs the STREAMING arm (each partial cross-checked
    via :meth:`ShardedCoordinator.check_partial` the moment it exists
    — the arrival-time verify rides the shard's own lane, exactly
    where the runner's proxy reader threads run it — and the close
    consumes the cached verdicts, leaving only dedup + combine +
    finalize on the round's critical path).

    Per round and shard count the two arms' published aggregates are
    asserted BIT-IDENTICAL (array equality, not digest eyeballing).
    Makespans follow the scale lane's parallel model (max(shard legs)
    + root close; legs overlap on their own lanes) and the root-merge
    exclusive blame share is attributed by the same
    ``observability.critical_path`` methodology that produced the PR 13
    baseline table (14.4%/29.9%/37.5% at 1/2/4 shards) — so the two
    tables compare like for like."""
    from byzpy_tpu import observability as obs
    from byzpy_tpu.forensics.evidence import evidence_digest
    from byzpy_tpu.observability import critical_path as obs_cp
    from byzpy_tpu.serving import ShardedCoordinator
    from byzpy_tpu.serving.sharded import shard_for

    from byzpy_tpu.aggregators import ComparativeGradientElimination

    telemetry_was_on = obs.enabled()
    obs.enable()
    rng = np.random.default_rng(7)
    d = args.scale_dim
    per_round = args.scale_round_submissions
    grads = [rng.normal(size=d).astype(np.float32) for _ in range(64)]
    bodies = [
        wire.encode(
            {
                "kind": "submit", "tenant": "scale", "client": "c000000",
                "round": 0, "gradient": g, "seq": 0,
            }
        )[4:]
        for g in grads
    ]
    identity = [f"c{i:06d}" for i in range(args.scale_clients)]
    cells = {}
    for n_shards in args.streamroot_shards:
        co_b = ShardedCoordinator(
            [_scale_tenant(args, ComparativeGradientElimination(
                f=args.byzantine))],
            n_shards, quorum=1,
        )
        co_s = ShardedCoordinator(
            [_scale_tenant(args, ComparativeGradientElimination(
                f=args.byzantine))],
            n_shards, quorum=1,
        )
        legs_b_rounds: list = []
        merges_b: list = []
        legs_s_rounds: list = []
        merges_s: list = []
        digests: list = []
        for r in range(args.scale_rounds + 1):
            warmup = r == 0
            lo = (r * per_round) % max(
                1, args.scale_clients - per_round + 1
            )
            window = identity[lo: lo + per_round]
            partition = [
                [c for c in window if shard_for(c, n_shards) == s]
                for s in range(n_shards)
            ]
            gc.collect()
            gc.disable()
            try:
                # -- barrier arm: verify-ALL lives in the root close --
                legs_b = []
                parts_b = []
                for s in range(n_shards):
                    _acc, leg = _drive_shard_partition(
                        co_b, s, partition, grads, bodies, r
                    )
                    t0 = time.monotonic()
                    p = co_b.shards[s].close_partial("scale")
                    leg += time.monotonic() - t0
                    if p is not None:
                        parts_b.append(p)
                    legs_b.append(leg)
                t0 = time.monotonic()
                res_b = co_b.merge_partials("scale", parts_b)
                merge_b = time.monotonic() - t0
                # -- streaming arm: the arrival-time cross-check rides
                # the shard's own lane (the reader-thread position);
                # the close consumes the cached verdicts -------------
                legs_s = []
                parts_s = []
                prechecked = {}
                for s in range(n_shards):
                    _acc, leg = _drive_shard_partition(
                        co_s, s, partition, grads, bodies, r
                    )
                    t0 = time.monotonic()
                    p = co_s.shards[s].close_partial("scale")
                    if p is not None:
                        prechecked[id(p)] = co_s.check_partial(
                            "scale", p, inflight=True
                        )
                        parts_s.append(p)
                    leg += time.monotonic() - t0
                    legs_s.append(leg)
                t0 = time.monotonic()
                res_s = co_s.merge_partials(
                    "scale", parts_s, prechecked=prechecked
                )
                merge_s = time.monotonic() - t0
            finally:
                gc.enable()
            assert res_b is not None and res_s is not None, (n_shards, r)
            # the bit-identity contract: streaming must not move a bit
            assert np.array_equal(
                np.asarray(res_b[2]), np.asarray(res_s[2])
            ), f"streaming diverged at {n_shards} shards round {r}"
            if warmup:
                continue
            digests.append(evidence_digest(np.asarray(res_s[2])))
            legs_b_rounds.append(legs_b)
            merges_b.append(merge_b)
            legs_s_rounds.append(legs_s)
            merges_s.append(merge_s)
        st = co_s.stats()["root"]["scale"]
        assert st["partials_inflight"] == 0, st
        cp_b = obs_cp.summarize(
            _scale_round_trace_events(n_shards, legs_b_rounds, merges_b)
        )
        cp_s = obs_cp.summarize(
            _scale_round_trace_events(n_shards, legs_s_rounds, merges_s)
        )

        def _share(cp):
            return next(
                (
                    s["share"]
                    for s in cp["stages"]
                    if s["stage"] == "serving.fold_merge"
                ),
                0.0,
            )

        share_b, share_s = _share(cp_b), _share(cp_s)
        mk_b = [
            max(l) + m for l, m in zip(legs_b_rounds, merges_b, strict=True)
        ]
        mk_s = [
            max(l) + m for l, m in zip(legs_s_rounds, merges_s, strict=True)
        ]
        mean_b = float(np.mean(mk_b))
        mean_s = float(np.mean(mk_s))
        cells[n_shards] = {
            "rounds": len(mk_b),
            "barrier": {
                "makespan_mean_ms": round(1e3 * mean_b, 2),
                "root_close_mean_ms": round(
                    1e3 * float(np.mean(merges_b)), 2
                ),
                "root_merge_blame_share": share_b,
            },
            "streaming": {
                "makespan_mean_ms": round(1e3 * mean_s, 2),
                "root_close_mean_ms": round(
                    1e3 * float(np.mean(merges_s)), 2
                ),
                "root_merge_blame_share": share_s,
                "partial_checks": st["partial_checks"],
            },
            "blame_rel_reduction_pct": round(
                100.0 * (1.0 - share_s / max(share_b, 1e-9)), 1
            ),
            "makespan_reduction_pct": round(
                100.0 * (1.0 - mean_s / max(mean_b, 1e-9)), 1
            ),
            "parity": "bit-identical",
            "digest_last": digests[-1],
        }
    host_cores = os.cpu_count() or 1
    row = {
        "lane": "streamroot",
        "clients": args.scale_clients,
        "dim": d,
        "round_submissions": per_round,
        "rounds": args.scale_rounds,
        "aggregator": f"cge-f{args.byzantine}",
        "timing_model": "modeled:max(legs)+merge",
        "timing_model_note": (
            "scale-lane methodology (PR 13 blame table): per-shard legs "
            "measured in isolation and overlapped on their own lanes; "
            "the STREAMING arm's arrival-time verify is charged to the "
            "shard's lane (where the runner's reader threads run it), "
            "the BARRIER arm's verify-all is charged to the root close "
            "— root_merge_blame_share is the serving.fold_merge "
            "exclusive share of the modeled makespan in each arm"
        ),
        "host_cores": host_cores,
        "shards": cells,
        "parity": "bit-identical",
        "root_merge_blame_share": {
            "barrier": {
                n: cells[n]["barrier"]["root_merge_blame_share"]
                for n in args.streamroot_shards
            },
            "streaming": {
                n: cells[n]["streaming"]["root_merge_blame_share"]
                for n in args.streamroot_shards
            },
        },
    }
    top = max(args.streamroot_shards)
    if top >= 4:
        # the acceptance bar, asserted in-run (not eyeballed): at 4
        # shards, >=25% relative reduction in root-merge blame OR >=10%
        # per-round makespan reduction
        c = cells[top]
        assert (
            c["blame_rel_reduction_pct"] >= 25.0
            or c["makespan_reduction_pct"] >= 10.0
        ), c
    if not telemetry_was_on:
        obs.disable()
    return row


def _run_closepath(args) -> dict:
    """Close-path paydown A/B (ISSUE 19): the SAME deterministic
    traffic through two roots — the STREAMING arm (PR 18: arrival-time
    ``check_partial``, but dedup + the whole incremental merge
    accumulator still run inside the close) vs the CLOSE-PATH arm
    (PR 19: ``stage_partial`` at arrival parks the dedup verdict AND
    runs the per-partial merge transform on the shard's own lane; the
    close promotes staged verdicts, runs the cheap shard-order
    placement, and finalizes off-path with the donated masked program,
    computing the merged score view while the device program flies).

    The headline cells run CGE with the scale-lane knobs — EXACTLY the
    PR 18 streamroot construction, so the 4-shard root-merge exclusive
    blame compares like for like against that table's 31.1% streaming
    baseline. A second section runs the Gram family (Multi-Krum) at a
    bounded cohort and pins the cross-Gram arrival-assembly
    accounting: k partials per close cost exactly k·(k−1)/2 cross
    blocks, zero shipped-Gram recomputes (``partial_transforms``), and
    the assembly rides the shard lanes instead of the close. Per round
    and cell the two arms' aggregates are asserted BIT-IDENTICAL."""
    from byzpy_tpu import observability as obs
    from byzpy_tpu.forensics.evidence import evidence_digest
    from byzpy_tpu.observability import critical_path as obs_cp
    from byzpy_tpu.serving import ShardedCoordinator
    from byzpy_tpu.serving.sharded import shard_for

    from byzpy_tpu.aggregators import (
        ComparativeGradientElimination,
        MultiKrum,
    )

    telemetry_was_on = obs.enabled()
    obs.enable()
    rng = np.random.default_rng(7)
    d = args.scale_dim
    per_round = args.scale_round_submissions
    f = args.byzantine
    grads = [rng.normal(size=d).astype(np.float32) for _ in range(64)]
    bodies = [
        wire.encode(
            {
                "kind": "submit", "tenant": "scale", "client": "c000000",
                "round": 0, "gradient": g, "seq": 0,
            }
        )[4:]
        for g in grads
    ]
    identity = [f"c{i:06d}" for i in range(args.scale_clients)]
    cells = {}
    for n_shards in args.closepath_shards:
        co_s = ShardedCoordinator(
            [_scale_tenant(args, ComparativeGradientElimination(f=f))],
            n_shards, quorum=1,
        )
        co_c = ShardedCoordinator(
            [_scale_tenant(args, ComparativeGradientElimination(f=f))],
            n_shards, quorum=1,
        )
        legs_s_rounds: list = []
        merges_s: list = []
        legs_c_rounds: list = []
        merges_c: list = []
        digests: list = []
        for r in range(args.scale_rounds + 1):
            warmup = r == 0
            lo = (r * per_round) % max(
                1, args.scale_clients - per_round + 1
            )
            window = identity[lo: lo + per_round]
            partition = [
                [c for c in window if shard_for(c, n_shards) == s]
                for s in range(n_shards)
            ]
            gc.collect()
            gc.disable()
            try:
                # -- streaming arm (PR 18): arrival check on the shard
                # lane; dedup + full merge accumulator in the close ---
                legs_s = []
                parts_s = []
                prechecked_s = {}
                for s in range(n_shards):
                    _acc, leg = _drive_shard_partition(
                        co_s, s, partition, grads, bodies, r
                    )
                    t0 = time.monotonic()
                    p = co_s.shards[s].close_partial("scale")
                    if p is not None:
                        prechecked_s[id(p)] = co_s.check_partial(
                            "scale", p, inflight=True
                        )
                        parts_s.append(p)
                    leg += time.monotonic() - t0
                    legs_s.append(leg)
                t0 = time.monotonic()
                res_s = co_s.merge_partials(
                    "scale", parts_s, prechecked=prechecked_s
                )
                merge_s = time.monotonic() - t0
                # -- close-path arm (PR 19): check + STAGE on the
                # shard lane (dedup verdict + cross-Gram transform at
                # arrival); the close promotes and finalizes off-path
                legs_c = []
                parts_c = []
                prechecked_c = {}
                for s in range(n_shards):
                    _acc, leg = _drive_shard_partition(
                        co_c, s, partition, grads, bodies, r
                    )
                    t0 = time.monotonic()
                    p = co_c.shards[s].close_partial("scale")
                    if p is not None:
                        chk = co_c.check_partial(
                            "scale", p, inflight=True
                        )
                        prechecked_c[id(p)] = chk
                        if chk[0]:
                            co_c.stage_partial("scale", p, chk)
                        parts_c.append(p)
                    leg += time.monotonic() - t0
                    legs_c.append(leg)
                t0 = time.monotonic()
                res_c = co_c.merge_partials(
                    "scale", parts_c, prechecked=prechecked_c
                )
                merge_c = time.monotonic() - t0
            finally:
                gc.enable()
            assert res_s is not None and res_c is not None, (n_shards, r)
            # the bit-identity contract: staging must not move a bit
            assert np.array_equal(
                np.asarray(res_s[2]), np.asarray(res_c[2])
            ), f"close-path diverged at {n_shards} shards round {r}"
            if warmup:
                continue
            digests.append(evidence_digest(np.asarray(res_c[2])))
            legs_s_rounds.append(legs_s)
            merges_s.append(merge_s)
            legs_c_rounds.append(legs_c)
            merges_c.append(merge_c)
        st = co_c.stats()["root"]["scale"]
        rounds_total = args.scale_rounds + 1
        # the paydown actually ran: every close consumed the arrival-
        # staged accumulator, every staged verdict promoted, none
        # flipped, and no shard's shipped extras were ever recomputed
        assert st["partials_inflight"] == 0, st
        assert st["staged_closes"] == rounds_total, st
        assert st["dedup_restaged"] == 0, st
        assert st["partial_transforms"] == 0, st
        cp_s = obs_cp.summarize(
            _scale_round_trace_events(n_shards, legs_s_rounds, merges_s)
        )
        cp_c = obs_cp.summarize(
            _scale_round_trace_events(n_shards, legs_c_rounds, merges_c)
        )

        def _share(cp):
            return next(
                (
                    s["share"]
                    for s in cp["stages"]
                    if s["stage"] == "serving.fold_merge"
                ),
                0.0,
            )

        share_s, share_c = _share(cp_s), _share(cp_c)
        mk_s = [
            max(l) + m for l, m in zip(legs_s_rounds, merges_s, strict=True)
        ]
        mk_c = [
            max(l) + m for l, m in zip(legs_c_rounds, merges_c, strict=True)
        ]
        mean_s = float(np.mean(mk_s))
        mean_c = float(np.mean(mk_c))
        cells[n_shards] = {
            "rounds": len(mk_s),
            "streaming": {
                "makespan_mean_ms": round(1e3 * mean_s, 2),
                "root_close_mean_ms": round(
                    1e3 * float(np.mean(merges_s)), 2
                ),
                "root_merge_blame_share": share_s,
            },
            "closepath": {
                "makespan_mean_ms": round(1e3 * mean_c, 2),
                "root_close_mean_ms": round(
                    1e3 * float(np.mean(merges_c)), 2
                ),
                "root_merge_blame_share": share_c,
                "staged_closes": st["staged_closes"],
                "dedup_staged": st["dedup_staged"],
                "dedup_promoted": st["dedup_promoted"],
                "dedup_restaged": st["dedup_restaged"],
                "partial_transforms": st["partial_transforms"],
            },
            "blame_rel_reduction_pct": round(
                100.0 * (1.0 - share_c / max(share_s, 1e-9)), 1
            ),
            "makespan_reduction_pct": round(
                100.0 * (1.0 - mean_c / max(mean_s, 1e-9)), 1
            ),
            "parity": "bit-identical",
            "digest_last": digests[-1],
        }
    # -- Gram-family section: Multi-Krum at a bounded cohort (the Gram
    # is O(m²) — unboundable at the scale lane's row counts), arrival
    # assembly vs close assembly, counter-pinned ----------------------
    gram_per_round = min(per_round, 1536)
    gram_rounds = args.scale_rounds
    gram_cells = {}
    for n_shards in args.closepath_shards:
        co_gs = ShardedCoordinator(
            [_scale_tenant(args, MultiKrum(f=f, q=f + 1))],
            n_shards, quorum=1,
        )
        co_gc = ShardedCoordinator(
            [_scale_tenant(args, MultiKrum(f=f, q=f + 1))],
            n_shards, quorum=1,
        )
        stage_s_close: list = []
        stage_c_arrival: list = []
        merges_gs: list = []
        merges_gc: list = []
        for r in range(gram_rounds + 1):
            warmup = r == 0
            lo = (r * gram_per_round) % max(
                1, args.scale_clients - gram_per_round + 1
            )
            window = identity[lo: lo + gram_per_round]
            partition = [
                [c for c in window if shard_for(c, n_shards) == s]
                for s in range(n_shards)
            ]
            gc.collect()
            gc.disable()
            try:
                parts_s, pre_s = [], {}
                for s in range(n_shards):
                    _drive_shard_partition(
                        co_gs, s, partition, grads, bodies, r
                    )
                    p = co_gs.shards[s].close_partial("scale")
                    if p is not None:
                        pre_s[id(p)] = co_gs.check_partial(
                            "scale", p, inflight=True
                        )
                        parts_s.append(p)
                t0 = time.monotonic()
                res_gs = co_gs.merge_partials(
                    "scale", parts_s, prechecked=pre_s
                )
                merge_gs = time.monotonic() - t0
                parts_c, pre_c = [], {}
                arrival_c = 0.0
                for s in range(n_shards):
                    _drive_shard_partition(
                        co_gc, s, partition, grads, bodies, r
                    )
                    p = co_gc.shards[s].close_partial("scale")
                    if p is not None:
                        chk = co_gc.check_partial(
                            "scale", p, inflight=True
                        )
                        pre_c[id(p)] = chk
                        t0 = time.monotonic()
                        if chk[0]:
                            co_gc.stage_partial("scale", p, chk)
                        arrival_c += time.monotonic() - t0
                        parts_c.append(p)
                t0 = time.monotonic()
                res_gc = co_gc.merge_partials(
                    "scale", parts_c, prechecked=pre_c
                )
                merge_gc = time.monotonic() - t0
            finally:
                gc.enable()
            assert res_gs is not None and res_gc is not None
            assert np.array_equal(
                np.asarray(res_gs[2]), np.asarray(res_gc[2])
            ), f"gram close-path diverged at {n_shards} shards round {r}"
            if warmup:
                continue
            merges_gs.append(merge_gs)
            merges_gc.append(merge_gc)
            stage_s_close.append(merge_gs)
            stage_c_arrival.append(arrival_c)
        gst = co_gc.stats()["root"]["scale"]
        rounds_total = gram_rounds + 1
        # the cross-Gram accounting at its combinatorial floor: every
        # close k·(k−1)/2 cross blocks, no shipped-Gram recomputes
        assert gst["staged_closes"] == rounds_total, gst
        assert gst["partial_transforms"] == 0, gst
        assert gst["gram_cross_blocks"] == (
            rounds_total * n_shards * (n_shards - 1) // 2
        ), gst
        assert gst["dedup_restaged"] == 0, gst
        gram_cells[n_shards] = {
            "rounds": gram_rounds,
            "close_arm_root_close_mean_ms": round(
                1e3 * float(np.mean(merges_gs)), 2
            ),
            "arrival_arm_root_close_mean_ms": round(
                1e3 * float(np.mean(merges_gc)), 2
            ),
            "arrival_arm_stage_mean_ms": round(
                1e3 * float(np.mean(stage_c_arrival)), 2
            ),
            "root_close_reduction_pct": round(
                100.0 * (
                    1.0 - float(np.mean(merges_gc))
                    / max(float(np.mean(merges_gs)), 1e-9)
                ), 1
            ),
            "gram_cross_blocks": gst["gram_cross_blocks"],
            "partial_transforms": gst["partial_transforms"],
            "staged_closes": gst["staged_closes"],
            "parity": "bit-identical",
        }
    host_cores = os.cpu_count() or 1
    row = {
        "lane": "closepath",
        "clients": args.scale_clients,
        "dim": d,
        "round_submissions": per_round,
        "rounds": args.scale_rounds,
        "aggregator": f"cge-f{f}",
        "timing_model": "modeled:max(legs)+merge",
        "timing_model_note": (
            "scale-lane methodology (PR 13/18 blame tables): per-shard "
            "legs measured in isolation and overlapped on their own "
            "lanes; BOTH arms charge the arrival-time verify to the "
            "shard's lane, and the CLOSE-PATH arm additionally charges "
            "stage_partial (dedup staging + the per-partial cross-Gram "
            "transform) there — root_merge_blame_share is the "
            "serving.fold_merge exclusive share of the modeled "
            "makespan in each arm"
        ),
        "host_cores": host_cores,
        "shards": cells,
        "gram": {
            "aggregator": f"multi-krum-f{f}-q{f + 1}",
            "round_submissions": gram_per_round,
            "shards": gram_cells,
        },
        "parity": "bit-identical",
        "root_merge_blame_share": {
            "streaming": {
                n: cells[n]["streaming"]["root_merge_blame_share"]
                for n in args.closepath_shards
            },
            "closepath": {
                n: cells[n]["closepath"]["root_merge_blame_share"]
                for n in args.closepath_shards
            },
        },
    }
    top = max(args.closepath_shards)
    if top >= 4:
        # the acceptance bar, asserted in-run: at 4 shards the
        # close-path arm's root-merge exclusive blame must land
        # strictly below the PR 18 streaming baseline (31.1%) AND the
        # per-round makespan must improve on the streaming arm
        c = cells[top]
        assert c["closepath"]["root_merge_blame_share"] < 0.311, c
        assert c["makespan_reduction_pct"] > 0.0, c
    if not telemetry_was_on:
        obs.disable()
    return row


# ---------------------------------------------------------------------------
# process runner lane (ISSUE 14: measured multi-process makespans)
# ---------------------------------------------------------------------------


def _runner_tenant(args, agg) -> "TenantConfig":
    from byzpy_tpu.serving.credits import CreditPolicy

    return TenantConfig(
        name="scale",
        aggregator=agg,
        dim=args.runner_dim,
        cohort_cap=args.runner_round_submissions,
        queue_capacity=args.runner_round_submissions + 16,
        credit=CreditPolicy(
            rate_per_s=0.0,
            burst=1e9,
            max_tracked_clients=max(65536, args.runner_clients + 1),
        ),
        staleness=StalenessPolicy(kind="exponential", gamma=0.5, cutoff=16),
    )


def _drive_runner_rounds(
    args, n_shards: int, fanout, rng, identity
) -> dict:
    """One deployment's measured rounds: spawn the real process fleet
    (N shard processes + merge nodes + root, all over TCP), stream each
    round's pre-encoded frames through windowed-pipelined shard
    connections, close at the root, and assert bit parity vs the
    unsharded aggregate of the same merged cohort — every number here
    is WALL CLOCK across real processes, no makespan model."""
    import gc

    from byzpy_tpu.aggregators import ComparativeGradientElimination
    from byzpy_tpu.serving.runner import Runner, RunnerClient, RunnerSpec

    d = args.runner_dim
    per_round = args.runner_round_submissions
    agg = ComparativeGradientElimination(f=args.byzantine)
    ref_agg = ComparativeGradientElimination(f=args.byzantine)
    spec = RunnerSpec(
        tenants=[_runner_tenant(args, agg)],
        n_shards=n_shards,
        fanout=fanout,
        quorum=1,
        telemetry=True,
        shard_timeout_s=120.0,
    )
    grads = [rng.normal(size=d).astype(np.float32) for _ in range(64)]
    ingest_s: list = []
    close_s: list = []
    total_accepted = 0
    with Runner(spec) as runner:
        client = RunnerClient("127.0.0.1", runner.shard_ports)
        try:
            for r in range(args.runner_rounds + 1):
                warmup = r == 0
                lo = (r * per_round) % max(
                    1, args.runner_clients - per_round + 1
                )
                window = identity[lo: lo + per_round]
                # frame encoding is the CLIENT's cost: build the round's
                # traffic outside the timed region
                frames: dict = {s: [] for s in range(n_shards)}
                for i, c in enumerate(window):
                    s, frame = client.encode_submit(
                        "scale", c, r, grads[i % len(grads)], seq=r
                    )
                    frames[s].append(frame)
                gc.collect()
                t0 = time.monotonic()
                accepted, rejected = client.submit_many(frames)
                t1 = time.monotonic()
                reply = runner.close_round("scale", return_rows=warmup)
                t2 = time.monotonic()
                assert reply["closed"] == r, (n_shards, r, reply)
                assert rejected == 0, (n_shards, r, rejected)
                if warmup:
                    # warmup round compiles the merged masked program
                    # AND pins bit parity: the hierarchical fold vs the
                    # exact unsharded aggregate of the same merged rows
                    rows = np.asarray(reply["rows"])
                    ref = np.asarray(
                        ref_agg.aggregate(
                            [rows[i] for i in range(rows.shape[0])]
                        )
                    )
                    assert np.array_equal(
                        np.asarray(reply["aggregate"]), ref
                    ), f"runner fold diverged at {n_shards} shards"
                    continue
                total_accepted += accepted
                ingest_s.append(t1 - t0)
                close_s.append(t2 - t1)
        finally:
            client.close()
        st = runner.stats()["root"]["scale"]
    makespans = [i + c for i, c in zip(ingest_s, close_s, strict=True)]
    makespan_median = float(np.median(makespans))
    return {
        "accepted": total_accepted,
        "makespan_median_ms": round(1e3 * makespan_median, 2),
        "makespan_p99_ms": round(
            1e3 * float(np.percentile(makespans, 99)), 2
        ),
        "accepted_per_sec": round(
            total_accepted / max(1, len(makespans)) / makespan_median, 1
        ),
        "mean_ingest_ms": round(1e3 * float(np.mean(ingest_s)), 2),
        "mean_close_ms": round(1e3 * float(np.mean(close_s)), 2),
        "rounds": len(makespans),
        "depth": spec.topology.depth,
        "merge_nodes": sum(
            len(level) for level in spec.topology.levels
        ),
        "failed_rounds": st["failed_rounds"],
        "forged_partials": st["forged_partials"],
        "quorum_failures": st["quorum_failures"],
    }


def _run_runner(args) -> dict:
    """MEASURED multi-process scaling (the lane ISSUE 14 adds): the
    same per-round submission load through 1/2/4 REAL shard processes
    — every shard an OS process with its own TCP ingress, the root
    coordinator a process driving the barrier + hierarchical merge
    over sockets — plus a depth-2 vs depth-3 merge-tree A/B at the
    largest shard count. ``timing_model`` is ``"measured"``: the
    numbers are wall clock across the process fleet, never the modeled
    ``max(legs)+merge`` combination, and the row records
    ``host_cores`` so a single-core host's flat scaling reads as what
    it is (the lane measures the tier; the tier needs cores to
    scale)."""
    rng = np.random.default_rng(11)
    identity = [f"c{i:06d}" for i in range(args.runner_clients)]
    results = {}
    for n_shards in args.runner_shards:
        results[n_shards] = _drive_runner_rounds(
            args, n_shards, None, rng, identity
        )
    base = results[args.runner_shards[0]]["accepted_per_sec"]
    speedups = {
        n: round(results[n]["accepted_per_sec"] / base, 2)
        for n in args.runner_shards
    }
    depth_ab = None
    ab_shards = max(args.runner_shards)
    if ab_shards >= 4:
        deep = _drive_runner_rounds(
            args, ab_shards, 2, rng, identity
        )
        flat = results[ab_shards]
        depth_ab = {
            "shards": ab_shards,
            "depth2": {
                "makespan_median_ms": flat["makespan_median_ms"],
                "mean_close_ms": flat["mean_close_ms"],
                "accepted_per_sec": flat["accepted_per_sec"],
            },
            "depth3": {
                "makespan_median_ms": deep["makespan_median_ms"],
                "mean_close_ms": deep["mean_close_ms"],
                "accepted_per_sec": deep["accepted_per_sec"],
                "merge_nodes": deep["merge_nodes"],
            },
            "close_ratio_depth3_vs_depth2": round(
                deep["mean_close_ms"] / max(flat["mean_close_ms"], 1e-9),
                3,
            ),
        }
    host_cores = os.cpu_count() or 1
    row = {
        "lane": "runner",
        "clients": args.runner_clients,
        "dim": args.runner_dim,
        "round_submissions": args.runner_round_submissions,
        "rounds": args.runner_rounds,
        "aggregator": f"cge-f{args.byzantine}",
        "timing_model": "measured",
        "timing_model_note": (
            "real process-per-shard deployment: N shard processes + "
            "merge nodes + root coordinator over TCP; makespan = "
            "pipelined ingest wall + root close wall, measured end to "
            "end — NOT the modeled max(legs)+merge combination the "
            "scale lane uses (never compare the two silently)"
        ),
        "host_cores": host_cores,
        "shards": results,
        "speedup_vs_1shard": speedups,
        "depth_ab": depth_ab,
        "parity": "bit-identical",
        "telemetry": "on (cross-process trace propagation active)",
    }
    if host_cores < max(args.runner_shards):
        row["scaling_caveat"] = (
            f"host has {host_cores} core(s) for "
            f"{max(args.runner_shards)} shard processes — the measured "
            "curve shows process overhead, not the tier's multi-core "
            "scaling; rerun on a host with >= shard-count cores for "
            "the acceptance trend"
        )
    return row


def _drive_runner_pipeline(args, n_shards, identity, *, pipelined) -> dict:
    """One arm of the pipelining A/B: the SAME deterministic traffic
    (rng reseeded per shard count, so both arms replay identical bits)
    through the process fleet, closed either at the classic barrier or
    through :meth:`Runner.close_round_pipelined` — where round N's
    verify/merge/device step runs on the root's finish thread while the
    shards admit round N+1.  Frames are pre-encoded for EVERY round
    before the timed region (encoding is the client's cost in both
    arms), so the measured makespan is ingest wall + close/kick wall
    only.  Returns per-round digests so the caller can pin the
    cross-engine parity contract: pipelining must not change a single
    aggregate bit."""
    import gc

    from byzpy_tpu.serving.runner import Runner, RunnerClient, RunnerSpec

    d = args.runner_dim
    per_round = args.runner_round_submissions
    # the coalescing family: Multi-Krum's root finalize is O(m²·d)
    # (pairwise scores over the MERGED cohort), so the deferred half of
    # a pipelined close carries real compute — the heavy-root regime
    # cross-round pipelining exists for. CGE's cheap-root twin is the
    # runner lane's cell.
    agg = MultiKrum(f=args.byzantine, q=args.byzantine + 1)
    spec = RunnerSpec(
        tenants=[_runner_tenant(args, agg)],
        n_shards=n_shards,
        quorum=1,
        telemetry=True,
        shard_timeout_s=120.0,
        # arm the speculative plane on the pipelined arm: with no
        # stragglers it never fires, but the lane runs the exact
        # configuration the always-on deployment would
        repair_horizon_rounds=1 if pipelined else 0,
    )
    rng = np.random.default_rng(1700 + n_shards)
    grads = [rng.normal(size=d).astype(np.float32) for _ in range(64)]
    digests: list = []
    iter_s: list = []
    overlap: list = []
    total_accepted = 0
    # paced ingest: each round's frames arrive in slices separated by
    # client think-time — the tier's actual regime (rounds close on
    # windows, not on a saturating blast). BOTH arms pay the identical
    # pacing; the pipelined arm's finish thread runs inside the gaps
    # the pacing leaves idle, which is precisely the claim under test.
    slices = max(1, int(args.pipeline_slices))
    pace_s = max(0.0, float(args.pipeline_pace_ms)) / 1e3

    def _paced_submit(client, frames) -> tuple:
        acc = rej = 0
        for k in range(slices):
            chunk = {s: fl[k::slices] for s, fl in frames.items()}
            if any(chunk.values()):
                a, rj = client.submit_many(chunk)
                acc += a
                rej += rj
            if pace_s:
                time.sleep(pace_s / slices)
        return acc, rej

    with Runner(spec) as runner:
        client = RunnerClient("127.0.0.1", runner.shard_ports)
        try:
            all_frames = []
            for r in range(args.runner_rounds + 1):
                lo = (r * per_round) % max(
                    1, args.runner_clients - per_round + 1
                )
                window = identity[lo: lo + per_round]
                frames: dict = {s: [] for s in range(n_shards)}
                for i, c in enumerate(window):
                    s, frame = client.encode_submit(
                        "scale", c, r, grads[i % len(grads)], seq=r
                    )
                    frames[s].append(frame)
                all_frames.append(frames)
            # warmup round 0 compiles the merged masked program in both
            # arms (blocking close, untimed)
            accepted, rejected = client.submit_many(all_frames[0])
            assert rejected == 0, (n_shards, rejected)
            reply = runner.close_round("scale")
            assert reply["closed"] == 0, reply
            gc.collect()
            for r in range(1, args.runner_rounds + 1):
                t0 = time.monotonic()
                accepted, rejected = _paced_submit(client, all_frames[r])
                assert rejected == 0, (n_shards, r, rejected)
                total_accepted += accepted
                if pipelined:
                    reply = runner.close_round_pipelined("scale")
                    assert reply["pending"] == r, (r, reply)
                    prev = reply.get("prev")
                    if prev is not None:
                        digests.append(prev["digest"])
                        if prev.get("overlap_ratio") is not None:
                            overlap.append(prev["overlap_ratio"])
                else:
                    reply = runner.close_round("scale")
                    assert reply["closed"] == r, (r, reply)
                    digests.append(reply["digest"])
                iter_s.append(time.monotonic() - t0)
            if pipelined:
                # the LAST round's finish is still in flight: settling it
                # is part of the pipelined arm's measured cost (no
                # hiding work past the clock)
                t0 = time.monotonic()
                prev = runner.flush_rounds("scale").get("prev")
                iter_s[-1] += time.monotonic() - t0
                assert prev is not None, "flush settled nothing"
                digests.append(prev["digest"])
                if prev.get("overlap_ratio") is not None:
                    overlap.append(prev["overlap_ratio"])
        finally:
            client.close()
        st = runner.stats()["root"]["scale"]
    wall = float(np.sum(iter_s))
    return {
        "accepted": total_accepted,
        "digests": digests,
        "rounds": len(iter_s),
        "wall_s": round(wall, 4),
        "makespan_mean_ms": round(1e3 * wall / max(1, len(iter_s)), 2),
        "makespan_median_ms": round(1e3 * float(np.median(iter_s)), 2),
        "accepted_per_sec": round(total_accepted / max(wall, 1e-9), 1),
        "overlap_ratio_mean": (
            round(float(np.mean(overlap)), 3) if overlap else None
        ),
        "failed_rounds": st["failed_rounds"],
        "speculative_closes": st.get("speculative_closes", 0),
        "repairs": st.get("repairs", 0),
    }


def _run_pipeline(args) -> dict:
    """Pipelined vs barrier close on the SAME fleet and traffic (ISSUE
    17's tentpole cells): per shard count, drive identical rounds
    through both arms, assert the per-round digest streams are
    bit-identical (the chaos wall owns the straggler/repair cases; this
    lane pins the no-late-arrivals contract), and report the makespan
    reduction the overlap buys."""
    identity = [f"c{i:06d}" for i in range(args.runner_clients)]
    cells = {}
    for n_shards in args.runner_shards:
        bar = _drive_runner_pipeline(
            args, n_shards, identity, pipelined=False
        )
        pipe = _drive_runner_pipeline(
            args, n_shards, identity, pipelined=True
        )
        assert bar["digests"] == pipe["digests"], (
            f"pipelined digests diverged at {n_shards} shards: "
            f"{bar['digests']} vs {pipe['digests']}"
        )
        assert bar["accepted"] == pipe["accepted"]
        reduction = 1.0 - (
            pipe["makespan_mean_ms"] / max(bar["makespan_mean_ms"], 1e-9)
        )
        cells[n_shards] = {
            "barrier": {
                k: bar[k]
                for k in (
                    "makespan_mean_ms", "makespan_median_ms",
                    "accepted_per_sec", "rounds", "failed_rounds",
                )
            },
            "pipelined": {
                k: pipe[k]
                for k in (
                    "makespan_mean_ms", "makespan_median_ms",
                    "accepted_per_sec", "rounds", "failed_rounds",
                    "overlap_ratio_mean", "speculative_closes", "repairs",
                )
            },
            "makespan_reduction_pct": round(100.0 * reduction, 1),
            "parity": "bit-identical",
        }
    host_cores = os.cpu_count() or 1
    row = {
        "lane": "pipeline",
        "clients": args.runner_clients,
        "dim": args.runner_dim,
        "round_submissions": args.runner_round_submissions,
        "rounds": args.runner_rounds,
        "aggregator": f"multikrum-f{args.byzantine}-q{args.byzantine + 1}",
        "timing_model": "measured",
        "timing_model_note": (
            "same process fleet, same pre-encoded traffic, two close "
            "disciplines: barrier (submit+close serialized) vs "
            "pipelined (root finish thread overlaps the next round's "
            "ingest); ingest is paced (client think-time, identical in "
            "both arms — the window regime the tier serves); makespan "
            "is wall clock per round including the final flush_rounds "
            "settle"
        ),
        "pace_ms": float(args.pipeline_pace_ms),
        "ingest_slices": int(args.pipeline_slices),
        "host_cores": host_cores,
        "shards": cells,
        "parity": "bit-identical",
    }
    if host_cores < max(args.runner_shards):
        row["scaling_caveat"] = (
            f"host has {host_cores} core(s) for "
            f"{max(args.runner_shards)} shard processes — the overlap "
            "hides the root's finish work inside ingest's IO/scheduling "
            "gaps; a multi-core host overlaps compute too"
        )
    return row


class _DieBeforeConfirm:
    """Failover-drill shard wrapper: ships its partial, then 'dies'
    before the root's confirmation lands — the ambiguous window whose
    exactly-once resolution is the root dedup table's whole job."""

    def __init__(self, shard):
        self._shard = shard

    def __getattr__(self, name):
        return getattr(self._shard, name)

    def confirm(self, *a, **k):
        # the confirmation is lost: no WAL round record is written, so
        # recovery will replay these accepts as pending
        self._shard._inflight.clear()


def _run_failover(args) -> dict:
    """Shard failover drill over ``--failover-seeds`` seeds: (a) kill a
    shard mid-round (in-memory state discarded, WAL kept), assert the
    round still closes as a QUORUM close; (b) recover the shard from
    its WAL alone and fold its replayed pending rows; (c) the ambiguous
    ship-folded-but-unconfirmed window (``_DieBeforeConfirm``): the
    recovered shard re-ships rows the root already folded and the root
    dedup drops them as ``root_duplicate``. Every seed's WALs are then
    audited by ``audit_sharded_exactly_once`` — the acceptance bar is
    ZERO invariant violations across all seeds."""
    import tempfile

    from byzpy_tpu.resilience.durable import DurabilityConfig
    from byzpy_tpu.serving import ShardedCoordinator
    from byzpy_tpu.serving.sharded import (
        audit_sharded_exactly_once,
        shard_for,
    )

    n_shards = 2
    dim = 64
    n_clients = 40
    violations = 0
    quorum_closes = 0
    root_dups = 0
    replayed = 0
    for seed in range(args.failover_seeds):
        rng = np.random.default_rng(1000 + seed)
        clients = [f"c{i:04d}" for i in range(n_clients)]
        grads = {
            c: rng.normal(size=dim).astype(np.float32) for c in clients
        }
        seqs = dict.fromkeys(clients, 0)

        def submit_all(co, r, only_shard=None, expect_down=None):
            count = 0
            for c in clients:
                home = shard_for(c, n_shards)
                if only_shard is not None and home != only_shard:
                    continue
                ok, reason = co.submit(
                    "m0", c, r, grads[c], seq=seqs[c]
                )
                if expect_down is not None and home == expect_down:
                    assert not ok and reason == "rejected_shard_down"
                    continue
                assert ok, (c, reason)
                seqs[c] += 1
                count += 1
            return count

        with tempfile.TemporaryDirectory() as tmp:
            agg = CoordinateWiseTrimmedMean(f=2)
            co = ShardedCoordinator(
                [
                    TenantConfig(
                        name="m0", aggregator=agg, dim=dim,
                        cohort_cap=n_clients,
                        staleness=StalenessPolicy(
                            kind="exponential", gamma=0.5, cutoff=8
                        ),
                    )
                ],
                n_shards,
                quorum=1,
                durability=DurabilityConfig(directory=tmp),
            )
            for r in range(2):
                submit_all(co, r)
                assert co.close_round_nowait("m0") is not None
            # (c) ambiguous window: shard 1 ships + root folds, but the
            # confirmation is lost before the shard records it
            co.shards[1] = _DieBeforeConfirm(co.shards[1])
            submit_all(co, 2)
            assert co.close_round_nowait("m0") is not None
            # (a) the shard is now dead mid-deployment: in-memory state
            # gone, only its WAL survives; the next round must still
            # close (quorum=1) as a degraded quorum close
            co.shards[1] = co.shards[1]._shard
            co.kill_shard(1)
            submit_all(co, 3, expect_down=1)
            res = co.close_round_nowait("m0")
            assert res is not None, "quorum close failed"
            # (b) WAL-only recovery: the unconfirmed round-2 accepts
            # replay as pending; the root dedup must drop every one
            # (they already folded) — exactly once, never twice
            shard = co.recover_shard(1)
            pending = shard.frontend.stats()["m0"]["queue_depth"]
            replayed += pending
            submit_all(co, 4, only_shard=0)
            res = co.close_round_nowait("m0")
            assert res is not None
            st = co.stats()["root"]["m0"]
            quorum_closes += st["quorum_closes"]
            root_dups += st["root_duplicates"]
            audit = audit_sharded_exactly_once(tmp, "m0", n_shards)
            violations += len(audit["violations"])
            assert not audit["violations"], audit["violations"]
    return {
        "lane": "shard_failover",
        "seeds": args.failover_seeds,
        "shards": n_shards,
        "clients": n_clients,
        "quorum_closes": quorum_closes,
        "wal_replayed_pending": replayed,
        "root_duplicates_dropped": root_dups,
        "invariant_violations": violations,
    }


# ---------------------------------------------------------------------------
# wire accounting lane
# ---------------------------------------------------------------------------


def _run_wire(args) -> dict:
    # at least 4096 coords: arrays under wire.WIRE_QUANT_MIN_SIZE travel
    # lossless by design, which would make the compressed rows vacuous
    d = max(args.dim, 4096)
    g = np.random.default_rng(2).normal(size=d).astype(np.float32)
    frame = {
        "kind": "submit", "tenant": "swarm", "client": "c01234",
        "round": 7, "gradient": g,
    }
    rows = {}
    for precision in ("off", "bf16", "int8"):
        for signed in (False, True):
            os.environ["BYZPY_TPU_WIRE_PRECISION"] = precision
            if signed:
                os.environ["BYZPY_TPU_WIRE_KEY"] = "bench-key"
            else:
                os.environ.pop("BYZPY_TPU_WIRE_KEY", None)
            encoded = wire.encode(frame)
            measured = len(encoded)
            law = serving_ingress_bytes(
                d, precision=precision, signed=signed
            )
            # codec round-trip throughput (encode + decode, host-side)
            n_iter = 50 if not args.smoke else 10
            t0 = time.monotonic()
            for _ in range(n_iter):
                wire.decode(wire.encode(frame)[4:])
            dt = (time.monotonic() - t0) / n_iter
            rows[f"{precision}{'+hmac' if signed else ''}"] = {
                "measured_bytes": measured,
                "law_bytes": round(law, 1),
                "law_error": round(abs(measured - law) / measured, 4),
                "codec_roundtrips_per_sec": round(1.0 / dt, 1),
            }
    os.environ.pop("BYZPY_TPU_WIRE_PRECISION", None)
    os.environ.pop("BYZPY_TPU_WIRE_KEY", None)
    compressed = rows["int8+hmac"]["measured_bytes"]
    lossless = rows["off+hmac"]["measured_bytes"]
    return {
        "lane": "wire",
        "dim": d,
        "frames": rows,
        "int8_byte_reduction": round(lossless / compressed, 2),
    }


def _run_batched_door(args) -> dict:
    """The wire-rate batched front door over REAL TCP: one connection
    writes a burst of frames in a single send, so the server's read
    loop drains several complete frames per event-loop wakeup and
    serves them through ONE vectorized decode + admission pass. The row
    proves three contracts: (a) the door actually batches
    (``max_batch > 1``), (b) the acks are identical to serving the same
    bodies through the per-frame door, and (c) telemetry stays exact —
    ``byzpy_wire_frames_total{direction=rx}`` advances by exactly the
    number of frames despite the amortized decode."""
    from byzpy_tpu import observability as obs
    from byzpy_tpu.observability import metrics as obs_metrics
    from byzpy_tpu.serving.frontend import serve_frame

    n = 64 if args.smoke else 256
    d = max(args.dim, 4096)
    os.environ["BYZPY_TPU_WIRE_PRECISION"] = "s4"
    rng = np.random.default_rng(9)
    bodies = [
        wire.encode({
            "kind": "submit", "tenant": "door", "client": f"c{i}",
            "round": 0,
            "gradient": rng.normal(size=d).astype(np.float32),
            "seq": 0,
        })[4:]
        for i in range(n)
    ]
    os.environ.pop("BYZPY_TPU_WIRE_PRECISION", None)

    def mk_fe():
        # window far beyond the burst so no round closes mid-stream and
        # ack round ids are deterministic on both doors
        return ServingFrontend([TenantConfig(
            name="door", dim=d,
            aggregator=CoordinateWiseTrimmedMean(f=1),
            cohort_cap=n, window_s=60.0, queue_capacity=2 * n,
        )])

    obs.enable()
    reg = obs_metrics.registry()
    rx = reg.counter("byzpy_wire_frames_total", labels={"direction": "rx"})
    rx0 = rx.value
    hist = reg.histogram("byzpy_ingress_batch_size")
    hist0 = hist.count

    async def run():
        fe = mk_fe()
        await fe.start()
        host, port = await fe.serve()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(
            b"".join(wire._HEADER.pack(len(b)) + b for b in bodies)
        )
        writer.write_eof()
        await writer.drain()
        data = await reader.read()
        writer.close()
        await fe.close()
        return data, fe

    data, fe = asyncio.run(run())
    rx_delta = rx.value - rx0
    batches_observed = hist.count - hist0
    acks = []
    while data:
        (ln,) = wire._HEADER.unpack(data[:4])
        acks.append(wire.decode(data[4:4 + ln]))
        data = data[4 + ln:]
    obs.disable()

    fe_p = mk_fe()
    acks_p = [wire.decode(serve_frame(fe_p, b)[4:]) for b in bodies]
    return {
        "lane": "batched_door",
        "dim": d,
        "frames": n,
        "batches": fe.ingress_batches,
        "max_batch": fe.ingress_max_batch,
        "frames_per_wakeup": round(
            fe.ingress_frames_batched / max(fe.ingress_batches, 1), 2
        ),
        "batch_size_histogram_count": batches_observed,
        "rx_frames_counted": rx_delta,
        "bad_frames": fe.bad_frames,
        "parity": "acks-identical" if acks == acks_p else "DIVERGED",
    }


def _assert_runner_smoke(args, runner_row: dict) -> None:
    """The runner lane's CI contract: real processes closed every
    round at bit parity, nothing failed/forged, and the lane is
    honestly tagged as measured."""
    assert runner_row["timing_model"] == "measured", runner_row
    assert runner_row["parity"] == "bit-identical"
    for n in args.runner_shards:
        res = runner_row["shards"][n]
        assert res["rounds"] == args.runner_rounds, res
        assert res["failed_rounds"] == 0, res
        assert res["forged_partials"] == 0, res
        assert res["quorum_failures"] == 0, res
        assert res["accepted_per_sec"] > 0, res


def _assert_pipeline_smoke(args, row: dict) -> None:
    """The pipelining A/B's CI contract: both arms closed every round,
    nothing failed, no repair fired (no stragglers in this lane), and
    the digest streams matched bit-for-bit (the assert inside
    :func:`_run_pipeline` already compared them; here we re-check the
    recorded verdict so a refactor cannot drop the comparison
    silently)."""
    assert row["timing_model"] == "measured", row
    assert row["parity"] == "bit-identical"
    for n in args.runner_shards:
        cell = row["shards"][n]
        assert cell["parity"] == "bit-identical", cell
        assert cell["barrier"]["rounds"] == args.runner_rounds, cell
        assert cell["pipelined"]["rounds"] == args.runner_rounds, cell
        assert cell["barrier"]["failed_rounds"] == 0, cell
        assert cell["pipelined"]["failed_rounds"] == 0, cell
        assert cell["pipelined"]["repairs"] == 0, cell


def _assert_streamroot_smoke(args, row: dict) -> None:
    """The streaming root merge A/B's CI contract: every cell's two
    arms published bit-identical aggregates (asserted inside
    :func:`_run_streamroot`; re-checked here so a refactor cannot drop
    the comparison silently), every shard cross-checked at arrival, and
    the inflight gauge drained to zero."""
    assert row["timing_model"].startswith("modeled"), row
    assert row["parity"] == "bit-identical"
    for n in args.streamroot_shards:
        cell = row["shards"][n]
        assert cell["parity"] == "bit-identical", cell
        assert cell["rounds"] == args.scale_rounds, cell
        # every round's every partial was verified at arrival (warmup
        # round included in the counter)
        assert cell["streaming"]["partial_checks"] == (
            (args.scale_rounds + 1) * n
        ), cell


def _assert_closepath_smoke(args, row: dict) -> None:
    """The close-path paydown A/B's CI contract: every cell's two arms
    published bit-identical aggregates, every close consumed the
    arrival-staged accumulator, and the extras-work counters sit at
    the combinatorial floor (zero redundant recomputes)."""
    assert row["timing_model"].startswith("modeled"), row
    assert row["parity"] == "bit-identical"
    rounds_total = args.scale_rounds + 1
    for n in args.closepath_shards:
        cell = row["shards"][n]
        assert cell["parity"] == "bit-identical", cell
        assert cell["rounds"] == args.scale_rounds, cell
        cp = cell["closepath"]
        assert cp["staged_closes"] == rounds_total, cell
        assert cp["partial_transforms"] == 0, cell
        assert cp["dedup_restaged"] == 0, cell
        assert cp["dedup_promoted"] >= rounds_total * n, cell
        g = row["gram"]["shards"][n]
        assert g["parity"] == "bit-identical", g
        assert g["staged_closes"] == rounds_total, g
        assert g["partial_transforms"] == 0, g
        assert g["gram_cross_blocks"] == (
            rounds_total * n * (n - 1) // 2
        ), g


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=10_000)
    ap.add_argument("--dim", type=int, default=1024)
    ap.add_argument("--duration-s", type=float, default=6.0)
    ap.add_argument("--window-ms", type=float, default=10.0)
    ap.add_argument("--cohort-cap", type=int, default=256)
    ap.add_argument("--queue-capacity", type=int, default=4096)
    ap.add_argument("--client-rate", type=float, default=50.0)
    ap.add_argument("--burst", type=float, default=40.0)
    ap.add_argument("--byzantine", type=int, default=2)
    ap.add_argument("--bucket-rounds", type=int, default=36)
    ap.add_argument("--scale-clients", type=int, default=100_000,
                    help="distinct client identities in the scale lane")
    ap.add_argument("--scale-round-submissions", type=int, default=20_000,
                    help="submissions per round (rotating identity window)")
    ap.add_argument("--scale-rounds", type=int, default=6)
    ap.add_argument("--scale-dim", type=int, default=256)
    ap.add_argument("--failover-seeds", type=int, default=10)
    ap.add_argument("--processes", action="store_true",
                    help="run the process-per-shard runner lane "
                         "(real OS processes + sockets; measured, "
                         "not modeled, makespans)")
    ap.add_argument("--processes-only", action="store_true",
                    help="run ONLY the runner lane (implies "
                         "--processes)")
    ap.add_argument("--pipeline-only", action="store_true",
                    help="run ONLY the pipelined-vs-barrier close "
                         "A/B on the process fleet (ISSUE 17 cells)")
    ap.add_argument("--streamroot-only", action="store_true",
                    help="run ONLY the streaming-vs-barrier root merge "
                         "A/B (ISSUE 18 cells; scale-lane knobs apply)")
    ap.add_argument("--closepath-only", action="store_true",
                    help="run ONLY the close-path paydown A/B "
                         "(ISSUE 19 cells: staged dedup + arrival "
                         "cross-Gram + off-path finalize vs the PR-18 "
                         "streaming close; scale-lane knobs apply)")
    ap.add_argument("--pipeline-pace-ms", type=float, default=60.0,
                    help="client think-time per round in the pipeline "
                         "A/B (both arms; 0 = saturating blast)")
    ap.add_argument("--pipeline-slices", type=int, default=2,
                    help="ingest bursts per round in the pipeline A/B "
                         "(think-time splits evenly between them)")
    ap.add_argument("--runner-clients", type=int, default=100_000,
                    help="distinct identities in the runner lane")
    ap.add_argument("--runner-round-submissions", type=int, default=8000)
    ap.add_argument("--runner-rounds", type=int, default=4)
    ap.add_argument("--runner-dim", type=int, default=256)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI run with contract assertions")
    args = ap.parse_args()

    args.scale_shards = (1, 2, 4)
    args.runner_shards = (1, 2, 4)
    args.streamroot_shards = (1, 2, 4)
    args.closepath_shards = (1, 2, 4)
    if args.processes_only:
        args.processes = True
    if args.smoke:
        args.clients = 300
        args.dim = 512
        args.duration_s = 2.0
        args.cohort_cap = 32
        args.queue_capacity = 256
        args.bucket_rounds = 10
        args.scale_clients = 2000
        args.scale_round_submissions = 600
        args.scale_rounds = 5
        args.scale_dim = 64
        args.scale_shards = (1, 2)
        args.failover_seeds = 3
        args.runner_clients = 2000
        args.runner_round_submissions = 400
        args.runner_rounds = 3
        args.runner_dim = 64
        args.runner_shards = (1, 2)
        args.streamroot_shards = (1, 2)
        args.closepath_shards = (1, 2)

    meta = {
        "lane": "meta",
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "host_cores": os.cpu_count() or 1,
        "smoke": bool(args.smoke),
    }
    _emit(meta, args.out)

    if args.streamroot_only:
        streamroot_row = _run_streamroot(args)
        _emit(streamroot_row, args.out)
        if args.smoke:
            _assert_streamroot_smoke(args, streamroot_row)
            print("serving streamroot smoke OK")
        return

    if args.closepath_only:
        closepath_row = _run_closepath(args)
        _emit(closepath_row, args.out)
        if args.smoke:
            _assert_closepath_smoke(args, closepath_row)
            print("serving closepath smoke OK")
        return

    if args.pipeline_only:
        pipeline_row = _run_pipeline(args)
        _emit(pipeline_row, args.out)
        if args.smoke:
            _assert_pipeline_smoke(args, pipeline_row)
            print("serving pipeline smoke OK")
        return

    if args.processes_only:
        runner_row = _run_runner(args)
        _emit(runner_row, args.out)
        pipeline_row = _run_pipeline(args)
        _emit(pipeline_row, args.out)
        if args.smoke:
            _assert_runner_smoke(args, runner_row)
            _assert_pipeline_smoke(args, pipeline_row)
            print("serving runner smoke OK")
        return

    # the classic 10k-client swarm (headline continuity; single tenant,
    # default door), then the cross-tenant batching pair on the
    # COALESCING family (Multi-Krum — one shared Gram scores the whole
    # batch): single-tenant bucket-ladder baseline vs TWO tenants
    # through the ragged dispatcher under the same flood
    swarm = asyncio.run(_run_swarm(args, n_tenants=1, ragged=True))
    _emit(swarm, args.out)

    def mk():
        return MultiKrum(f=args.byzantine, q=args.byzantine + 1)

    # matched TOTAL offered load, paced so the group's per-window rows
    # fill the ragged program's capacity (sub-cap cohorts per tenant —
    # the regime the bucket ladder exists for, and the one where
    # coalescing packs capacity the XLA program pays for regardless)
    mk_rate = args.cohort_cap / (args.window_ms / 1e3)
    baseline = asyncio.run(
        _run_swarm(
            args, lane="swarm_mk_bucketed_baseline", n_tenants=1,
            ragged=False, agg_factory=mk, target_rate=mk_rate,
        )
    )
    _emit(baseline, args.out)
    # the tenancy-matched twin: two tenants through the LADDER at the
    # same load isolates the door's effect from the inherent
    # two-tenants-on-one-device queueing split
    baseline_2t = asyncio.run(
        _run_swarm(
            args, lane="swarm_mk_bucketed_2tenant", n_tenants=2,
            ragged=False, agg_factory=mk, target_rate=mk_rate,
        )
    )
    _emit(baseline_2t, args.out)
    swarm_mk = asyncio.run(
        _run_swarm(
            args, lane="swarm_mk_ragged", n_tenants=2, ragged=True,
            agg_factory=mk, target_rate=mk_rate,
        )
    )
    _emit(swarm_mk, args.out)
    # moderate-load row: at saturation cohorts close FULL and fill the
    # program's capacity alone (nothing to coalesce — correctly); this
    # row paces the load so per-tenant cohorts are sub-cap, the regime
    # the ladder exists for, where two tenants' cohorts genuinely ride
    # ONE device call (max_batch == 2 is the committed demonstration)
    moderate_rate = 0.35 * args.cohort_cap / (50.0 / 1e3)
    swarm_mod = asyncio.run(
        _run_swarm(
            args, lane="swarm_mk_ragged_moderate", n_tenants=2,
            ragged=True, agg_factory=mk, target_rate=moderate_rate,
            window_ms=50.0,
        )
    )
    _emit(swarm_mod, args.out)

    buckets, refs = _run_buckets(args)
    _emit(buckets, args.out)

    ragged_row = _run_ragged(args, refs)
    _emit(ragged_row, args.out)

    wire_row = _run_wire(args)
    _emit(wire_row, args.out)

    door = _run_batched_door(args)
    _emit(door, args.out)

    scale = _run_scale(args)
    _emit(scale, args.out)

    streamroot = _run_streamroot(args)
    _emit(streamroot, args.out)
    closepath = _run_closepath(args)
    _emit(closepath, args.out)

    runner_row = None
    if args.processes:
        runner_row = _run_runner(args)
        _emit(runner_row, args.out)

    failover = _run_failover(args)
    _emit(failover, args.out)

    headline = {
        "lane": "headline",
        "metric": "serving_submissions_per_sec",
        "value": swarm["accepted_per_sec"],
        "unit": "submissions/sec",
        "clients": swarm["clients"],
        "p99_round_latency_ms": swarm["p99_round_latency_ms"],
        "rounds": swarm["rounds"],
        "mk_bucketed_baseline_per_sec": baseline["accepted_per_sec"],
        "mk_bucketed_baseline_p99_ms": baseline["p99_round_latency_ms"],
        "mk_bucketed_2tenant_per_sec": baseline_2t["accepted_per_sec"],
        "mk_bucketed_2tenant_p99_ms": baseline_2t["p99_round_latency_ms"],
        "mk_ragged_2tenant_per_sec": swarm_mk["accepted_per_sec"],
        "mk_ragged_2tenant_p99_ms": swarm_mk["p99_round_latency_ms"],
        "cross_tenant_max_batch": swarm_mod["ragged_dispatch"]["max_batch"],
        "moderate_load_cohorts_per_call": round(
            swarm_mod["ragged_dispatch"]["cohorts_dispatched"]
            / max(swarm_mod["ragged_dispatch"]["dispatches"], 1), 2
        ),
        "bucketed_vs_naive_speedup": {
            k: v["total_speedup"] for k, v in buckets["results"].items()
        },
        "ragged_vs_naive_speedup": {
            k: v["speedup_vs_naive"]
            for k, v in ragged_row["results"].items()
        },
        "ragged_batched_vs_naive_speedup": {
            k: v["batched_speedup_vs_naive"]
            for k, v in ragged_row["results"].items()
        },
        "ragged_compiles": {
            k: v["compile_entries"]
            for k, v in ragged_row["results"].items()
        },
        "sharded_accepted_per_sec": {
            str(n): scale["shards"][n]["accepted_per_sec"]
            for n in args.scale_shards
        },
        "sharded_speedup": {
            str(n): scale["speedup_vs_1shard"][n]
            for n in args.scale_shards
        },
        "sharded_p99_round_latency_ms": {
            str(n): scale["shards"][n]["p99_round_latency_ms"]
            for n in args.scale_shards
        },
        "failover_invariant_violations": failover["invariant_violations"],
        "ingress_frames_per_wakeup": door["frames_per_wakeup"],
        "ingress_max_batch": door["max_batch"],
    }
    _emit(headline, args.out)

    if args.smoke:
        assert swarm["rounds"] > 0, "no rounds closed"
        assert swarm["accepted"] > 0, "nothing admitted"
        for res in buckets["results"].values():
            assert res["bucketed_compile_entries"] <= len(buckets["ladder"])
            assert res["bucketed_compile_entries"] < res["distinct_sizes"]
        for res in ragged_row["results"].values():
            # ONE compiled ragged program per tenant group — strictly
            # fewer than the ladder AND the naive per-size caches
            assert res["compile_entries"] == 1, res
            assert res["compile_entries"] < res["bucketed_compile_entries"]
            assert res["batched_dispatches"] < res["rounds"]
        # two tenants' cohorts rode one device call at least once (the
        # moderate-load row — at saturation full cohorts fill the
        # capacity alone and correctly serialize)
        assert swarm_mod["ragged_dispatch"]["max_batch"] >= 2, (
            swarm_mod["ragged_dispatch"]
        )
        # sharded tier: hierarchical-fold bit parity was asserted per
        # round inside the lane; the 2-shard makespan speedup must be
        # near-linear (full-scale bar: >=1.7x at 2, >=3x at 4) and the
        # partial-fold frame law within tolerance
        assert scale["parity"] == "bit-identical"
        _assert_streamroot_smoke(args, streamroot)
        _assert_closepath_smoke(args, closepath)
        assert scale["speedup_vs_1shard"][2] >= 1.4, scale["speedup_vs_1shard"]
        for n in args.scale_shards:
            w = scale["shards"][n]["wire"]
            assert w["partial_law_error"] < 0.02, w
            assert scale["shards"][n]["failed_rounds"] == 0
        # failover drill: quorum close under a killed shard + WAL
        # replay preserved exactly-once folding on every seed
        assert failover["invariant_violations"] == 0, failover
        assert failover["quorum_closes"] >= args.failover_seeds, failover
        assert failover["root_duplicates_dropped"] > 0, failover
        # batched front door: >1 frame per wakeup over real TCP, acks
        # at parity with the per-frame door, rx frame counter exact
        assert door["max_batch"] > 1, door
        assert door["parity"] == "acks-identical", door
        assert door["rx_frames_counted"] == door["frames"], door
        assert door["batch_size_histogram_count"] == door["batches"], door
        assert door["bad_frames"] == 0, door
        if runner_row is not None:
            _assert_runner_smoke(args, runner_row)
        print("serving smoke OK")


if __name__ == "__main__":
    main()

"""Sharded weight update benchmark: trajectory parity + HLO byte evidence.

Measures, per update-shard variant of the fused PS round (replicated /
sharded × params-gather precision off|bf16|int8), all from the compiled
artifact (`byzpy_tpu.parallel.comms` parses the optimized HLO):

1. **per-round collective wire bytes** — the gradient-transpose
   all-to-all is identical across variants; the update move changes from
   an exact f32 aggregated-gradient all-gather (replicated: it feeds
   every chip's optimizer state) to a params all-gather that compresses
   freely (sharded: each chip's exact shard stays in the carried state).
2. **per-chip carried update state** — replicated keeps every optimizer
   moment whole on every chip; the sharded update splits moments + the
   authoritative flat param shard over the feature grid
   (`comms.opt_state_bytes` law, checked against the leaves' actual
   shard shapes).
3. **fixed-seed trajectory parity** — sharded f32 must match the
   replicated round within f32 fusion-reorder noise (their per-coordinate
   math is identical for coordinate-wise aggregators + elementwise
   optimizers); bf16/int8 gathers must stay inside the blockwise error
   contract per round. The same check runs for the gossip builders
   (feature-sharded exchange) on the general-topology and ring fabrics.

``--smoke`` is the CI leg: a 2-device CPU mesh, hard parity assertions,
and the byte floors (sharded opt state < replicated; int8 params gather
< f32/3). Full runs append provenance-stamped JSON lines to
``results/sharded_update_<platform>.jsonl``.

Run: ``JAX_PLATFORMS=cpu python benchmarks/sharded_update_bench.py [--smoke]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))


def _provenance(platform: str) -> dict:
    return {
        "platform": platform,
        "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI leg: 2-device mesh + hard assertions")
    ap.add_argument("--out", default=None, help="JSONL sink override")
    ap.add_argument("--steps", type=int, default=4,
                    help="fixed-seed parity trajectory length")
    args = ap.parse_args()

    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    from byzpy_tpu.utils.platform import apply_env_platform

    apply_env_platform()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from byzpy_tpu.engine.peer_to_peer.topology import Topology
    from byzpy_tpu.models.bundle import ModelBundle
    from byzpy_tpu.ops import robust
    from byzpy_tpu.parallel.comms import (
        collective_traffic,
        measured_opt_state_bytes,
        opt_state_bytes,
        ps_round_wire_bytes,
    )
    from byzpy_tpu.parallel.gossip import (
        GossipStepConfig,
        build_gossip_train_step,
        build_ring_gossip_train_step,
    )
    from byzpy_tpu.parallel.mesh import node_mesh
    from byzpy_tpu.parallel.ps import (
        PSStepConfig,
        ShardedUpdateConfig,
        build_ps_train_step,
    )
    from byzpy_tpu.utils.metrics import timed_call_s

    platform = jax.default_backend()
    n_dev = 2 if args.smoke else min(8, len(jax.devices()))
    mesh = node_mesh(n_dev, devices=jax.devices()[:n_dev])
    d_model, d_out = (64, 32) if args.smoke else (1024, 1024)
    d = d_model * d_out
    out_path = args.out or os.path.join(
        HERE, "results", f"sharded_update_{platform}.jsonl"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    rows = []

    params = {
        "w": jax.random.normal(jax.random.PRNGKey(2), (d_model, d_out)) * 0.1
    }
    bundle = ModelBundle(
        apply_fn=lambda p, xb: xb @ p["w"],
        params=params,
        loss_fn=lambda p, xb, yb: jnp.mean((xb @ p["w"] - yb) ** 2),
    )
    cfg = PSStepConfig(n_nodes=n_dev, n_byzantine=0 if n_dev < 4 else 1)
    bx = jax.random.normal(jax.random.PRNGKey(3), (n_dev, 16, d_model))
    by = jax.random.normal(jax.random.PRNGKey(4), (n_dev, 16, d_out))
    key = jax.random.PRNGKey(5)
    agg = (lambda m: jnp.mean(m, axis=0)) if n_dev < 4 else (
        lambda m: robust.trimmed_mean(m, f=1)
    )

    # -- 1+2. PS round: wire bytes + carried-state HBM per variant ------
    VARIANTS = (
        ("replicated", "off", "off"),
        ("sharded_f32", "on", "off"),
        ("sharded_bf16",
         ShardedUpdateConfig(mode="on", param_gather_precision="bf16"), "bf16"),
        ("sharded_int8",
         ShardedUpdateConfig(mode="on", param_gather_precision="int8"), "int8"),
    )
    gathers = {}
    states = {}
    trajs = {}
    import optax

    for label, su, pprec in VARIANTS:
        # Adam: 2 moment slots — the carried-state law (slots·n/(slots+1))
        # shows a reduction at every mesh size, incl. the 2-device smoke
        step, o0 = build_ps_train_step(
            bundle, agg, cfg, mesh=mesh, sharded_update=su,
            optimizer=optax.adam(1e-3),
        )
        jitted = jax.jit(step)
        traffic = collective_traffic(jitted, params, o0, bx, by, key)
        state_b = measured_opt_state_bytes(o0)
        law_wire = ps_round_wire_bytes(
            d, n_dev, update_sharded=label != "replicated",
            param_precision=pprec,
        )
        law_state = opt_state_bytes(
            d, slots=2, update_sharded=label != "replicated", n_shards=n_dev,
        )
        ms = timed_call_s(
            lambda p, o: jitted(p, o, bx, by, key)[0], params, o0,
            warmup=1, repeat=3 if args.smoke else 10,
        ) * 1e3
        gathers[label] = traffic["per_opcode_bytes"].get("all-gather", 0)
        states[label] = state_b
        p, o = params, o0
        for _ in range(args.steps):
            p, o, m = jitted(p, o, bx, by, key)
        trajs[label] = np.asarray(p["w"]).ravel()
        rows.append({
            "bench": "ps_update_shard", "variant": label, "d": d,
            "n_dev": n_dev,
            "wire_bytes_per_device": traffic["wire_bytes_per_device"],
            "per_opcode_bytes": traffic["per_opcode_bytes"],
            "carried_state_bytes_per_chip": state_b,
            "law_wire_bytes": round(law_wire, 1),
            "law_state_bytes": law_state,
            "ms_per_step": round(ms, 3),
            **_provenance(platform),
        })
        print(f"ps {label:13s}: wire {traffic['wire_bytes_per_device']:>10,} "
              f"B/dev  gather {gathers[label]:>9,}  state {state_b:>9,} "
              f"B/chip  {ms:.2f} ms/step")

    # -- 3. fixed-seed trajectory parity --------------------------------
    dev_f32 = float(np.abs(trajs["sharded_f32"] - trajs["replicated"]).max())
    scale = float(np.abs(trajs["replicated"]).max())
    print(f"parity sharded_f32 vs replicated: max|Δ| {dev_f32:.3e} "
          f"(|params| max {scale:.3f})")
    rows.append({
        "bench": "ps_parity", "steps": args.steps, "max_abs_dev_f32": dev_f32,
        "max_abs_dev_bf16": float(
            np.abs(trajs["sharded_bf16"] - trajs["replicated"]).max()
        ),
        "max_abs_dev_int8": float(
            np.abs(trajs["sharded_int8"] - trajs["replicated"]).max()
        ),
        "params_scale": scale, **_provenance(platform),
    })

    # -- 4. gossip builders: feature-sharded exchange -------------------
    gcfg = GossipStepConfig(n_nodes=n_dev, n_byzantine=0)
    topo = Topology.ring(n_dev, min(2, n_dev - 1))
    g_traj = {}
    for label, us in (("replicated", "off"), ("sharded", "on")):
        gstep, ginit = build_gossip_train_step(
            bundle, agg, topo, gcfg, mesh=mesh, update_sharding=us,
        )
        gstep = jax.jit(gstep)
        theta = ginit()
        traffic = collective_traffic(gstep, theta, bx, by, key)
        for _ in range(args.steps):
            theta, _ = gstep(theta, bx, by, key)
        g_traj[label] = np.asarray(theta)
        rows.append({
            "bench": "gossip_update_shard", "variant": label, "d": d,
            "n_dev": n_dev,
            "wire_bytes_per_device": traffic["wire_bytes_per_device"],
            "per_opcode_bytes": traffic["per_opcode_bytes"],
            **_provenance(platform),
        })
        print(f"gossip {label:10s}: wire "
              f"{traffic['wire_bytes_per_device']:>10,} B/dev  "
              f"{traffic['per_opcode_bytes']}")
    g_dev = float(np.abs(g_traj["sharded"] - g_traj["replicated"]).max())
    print(f"parity gossip sharded vs replicated: max|Δ| {g_dev:.3e}")

    # ring gossip shard split (coordinate-wise contract; win at k >= 2)
    r_traj = {}
    k = min(2, n_dev - 1)
    for label, us in (("replicated", "off"), ("sharded", "on")):
        rstep, rinit = build_ring_gossip_train_step(
            bundle, robust.coordinate_median, gcfg, mesh, k=k,
            update_sharding=us,
        )
        rstep = jax.jit(rstep)
        theta = rinit()
        traffic = collective_traffic(rstep, theta, bx, by, key)
        for _ in range(args.steps):
            theta, _ = rstep(theta, bx, by, key)
        r_traj[label] = np.asarray(theta)
        rows.append({
            "bench": "ring_gossip_update_shard", "variant": label, "d": d,
            "k": k, "n_dev": n_dev,
            "wire_bytes_per_device": traffic["wire_bytes_per_device"],
            "per_opcode_bytes": traffic["per_opcode_bytes"],
            **_provenance(platform),
        })
        print(f"ring   {label:10s}: wire "
              f"{traffic['wire_bytes_per_device']:>10,} B/dev  "
              f"{traffic['per_opcode_bytes']}")
    r_dev = float(np.abs(r_traj["sharded"] - r_traj["replicated"]).max())
    print(f"parity ring sharded vs replicated: max|Δ| {r_dev:.3e}")
    rows.append({
        "bench": "gossip_parity", "steps": args.steps,
        "max_abs_dev_gossip": g_dev, "max_abs_dev_ring": r_dev,
        **_provenance(platform),
    })

    with open(out_path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows -> {out_path}")

    # -- acceptance floors ---------------------------------------------
    ok = True
    # f32 fusion-reorder noise only: ~ulp-scale, far under any gradient
    tol = 1e-6 * max(scale, 1.0)
    if dev_f32 > tol:
        print(f"FAIL: sharded f32 trajectory deviates {dev_f32:.3e} > {tol:.1e}",
              file=sys.stderr)
        ok = False
    if g_dev > tol or r_dev > tol:
        print(f"FAIL: gossip parity ({g_dev:.3e} / {r_dev:.3e}) > {tol:.1e}",
              file=sys.stderr)
        ok = False
    if states["sharded_f32"] * 2 > states["replicated"] and n_dev >= 4:
        print("FAIL: sharded opt state not reduced >= 2x", file=sys.stderr)
        ok = False
    if states["sharded_f32"] >= states["replicated"]:
        print("FAIL: sharded opt state not below replicated", file=sys.stderr)
        ok = False
    if gathers["sharded_int8"] * 3 > gathers["sharded_f32"]:
        print("FAIL: int8 params gather not >= 3x smaller", file=sys.stderr)
        ok = False
    if not ok:
        return 1
    print("sharded-update parity + byte floors: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

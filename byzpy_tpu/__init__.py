"""byzpy_tpu — TPU-native Byzantine-robust distributed learning framework.

Capability-parity rebuild of the ByzPy reference (see SURVEY.md) designed
for JAX/XLA: aggregation math is jit-compiled and mesh-shardable
(``byzpy_tpu.ops``), operators schedule on an asyncio actor runtime
(``byzpy_tpu.engine``), and training orchestration (parameter-server and
peer-to-peer) lowers gradient movement onto XLA collectives.

Front door (ref: ``byzpy/__init__.py:1-4``)::

    import asyncio
    from byzpy_tpu import run_operator
    from byzpy_tpu.aggregators import CoordinateWiseMedian

    result = asyncio.run(run_operator(CoordinateWiseMedian(), gradients))
"""

from .version import __version__

__all__ = ["__version__", "OperatorExecutor", "run_operator"]


def __getattr__(name: str):
    # lazy: keeps `import byzpy_tpu` (and the CLI, whose doctor must be able
    # to report a broken jax install) from importing jax at package import
    if name in ("OperatorExecutor", "run_operator"):
        from .engine.graph import executor

        return getattr(executor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

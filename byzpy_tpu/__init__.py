"""byzpy_tpu — TPU-native Byzantine-robust distributed learning framework.

Capability-parity rebuild of the ByzPy reference (see SURVEY.md) designed
for JAX/XLA: aggregation math is jit-compiled and mesh-shardable
(``byzpy_tpu.ops``), operators schedule on an asyncio actor runtime
(``byzpy_tpu.engine``), and training orchestration (parameter-server and
peer-to-peer) lowers gradient movement onto XLA collectives.
"""

from .version import __version__

__all__ = ["__version__"]

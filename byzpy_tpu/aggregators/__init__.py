from .base import Aggregator
from .coordinate_wise import CoordinateWiseMedian, CoordinateWiseTrimmedMean, MeanOfMedians
from .geometric_wise import (
    GeometricMedian,
    Krum,
    MinimumDiameterAveraging,
    MoNNA,
    MultiKrum,
    SMEA,
)
from .norm_wise import CAF, CenteredClipping, ComparativeGradientElimination

__all__ = [
    "Aggregator",
    "CoordinateWiseMedian",
    "CoordinateWiseTrimmedMean",
    "MeanOfMedians",
    "MultiKrum",
    "Krum",
    "GeometricMedian",
    "MinimumDiameterAveraging",
    "MoNNA",
    "SMEA",
    "CenteredClipping",
    "CAF",
    "ComparativeGradientElimination",
]

"""Aggregator base class (API parity: ``byzpy/aggregators/base.py:11-103``).

An aggregator reduces a sequence of per-node gradients (pytrees, arrays, or
an already-stacked ``(n, d)`` matrix) to a single aggregated gradient with
the structure of one input. Subclasses implement ``_aggregate_matrix`` — a
pure function on the stacked matrix that jit-compiles and shards over a
device mesh (see ``byzpy_tpu.ops.robust``).

Unlike the reference, parallelism does not require host-side chunking: the
matrix computation is one XLA program. Chunked ``create_subtasks`` paths are
still provided by the mixins in ``chunked.py`` for running on heterogeneous
actor pools (the reference's shm-chunk pattern, minus the shm).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from functools import partial
from typing import Any, Callable, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from ..engine.graph.operator import OpContext, Operator
from ..utils import placement
from ..utils.trees import stack_gradients


@partial(jax.jit, donate_argnums=(0,))
def _slot_insert(buffer: jnp.ndarray, row: jnp.ndarray, index) -> jnp.ndarray:
    """Park one flattened gradient in its canonical slot of the ``(n, d)``
    ingest buffer, IN PLACE: the buffer is donated, so XLA reuses the
    allocation instead of copying the whole matrix per arrival. This is
    what makes finalize's "stack" free — the matrix already exists."""
    return lax.dynamic_update_slice(buffer, row[None, :], (index, 0))


def ravel_gradient(gradient: Any) -> tuple:
    """Flatten one gradient pytree/array to a ``(d,)`` row the way
    :func:`~byzpy_tpu.utils.trees.stack_gradients` would, deciding host/
    device placement from this gradient alone (streaming ingestion sees
    one gradient at a time; the barrier path decides from the full
    list). Returns ``(row, unravel)``."""
    with placement.on(placement.compute_device(gradient)):
        row, unravel = ravel_pytree(gradient)
        if not jnp.issubdtype(row.dtype, jnp.floating):
            row = row.astype(jnp.float32)
    return row, unravel


#: Marker stored in ``SlotFoldState.rows`` for a slot whose gradient
#: lives in the donated ingest buffer (the row reference itself is
#: dropped so fold-state memory stays ~1x the matrix, not 2x).
_STAGED = object()


class SlotFoldState:
    """Default streaming-fold state: an arrival-order ingestion buffer.

    Each gradient is flattened the moment it arrives (``fold``) and
    parked in its canonical node slot; ``fold_finalize`` stacks the
    filled slots *in slot order* and runs the normal matrix aggregate.
    Because the stacked matrix is identical to the barrier path's —
    same per-row flatten, same order — the result is bit-identical for
    every aggregator, regardless of arrival order.

    Ingestion is donated: every arrival lands in a preallocated
    ``(n, d)`` device buffer through an in-place dynamic-update-slice
    (:func:`_slot_insert`) and the per-row reference is dropped
    (``rows`` keeps a :data:`_STAGED` marker), so the per-gradient host
    work (pytree ravel, dtype cast, placement) AND the matrix assembly
    bytes all happen inside the straggler window at ~1x the matrix's
    memory — a full round's finalize reads the already-built matrix
    with zero copies where the barrier path pays an n·d stack after
    the last straggler. A mixed-dtype round (rare) falls back to real
    row references + a finalize stack, rebuilding the already-staged
    rows from the buffer.
    """

    __slots__ = ("n", "rows", "unravel", "dim", "filled", "buffer")

    def __init__(self, n: int) -> None:
        # the one capacity guard for every fold state (the incremental
        # folds all embed a slot buffer)
        if n <= 0:
            raise ValueError(f"fold_init needs n >= 1 (got {n})")
        self.n = n
        self.rows: list = [None] * n
        self.unravel: Optional[Callable[[jnp.ndarray], Any]] = None
        self.dim: Optional[int] = None
        self.filled = 0
        #: donated (n, d) ingest buffer; None until the first row, or
        #: permanently None after a dtype mismatch (stack fallback)
        self.buffer: Optional[jnp.ndarray] = None

    def insert(self, index: int, gradient: Any) -> jnp.ndarray:
        """Flatten ``gradient`` into slot ``index``; returns the row."""
        if not 0 <= index < self.n:
            raise IndexError(f"slot {index} outside [0, {self.n})")
        if self.rows[index] is not None:
            raise ValueError(f"slot {index} folded twice")
        row, unravel = ravel_gradient(gradient)
        if self.dim is None:
            self.dim = int(row.shape[0])
            self.unravel = unravel
        elif int(row.shape[0]) != self.dim:
            raise ValueError(
                f"all gradients must flatten to the same length "
                f"(got {row.shape[0]} != {self.dim})"
            )
        with placement.on(placement.compute_device(row)):
            if self.filled == 0:
                self.buffer = jnp.zeros((self.n, self.dim), row.dtype)
            if self.buffer is not None and row.dtype == self.buffer.dtype:
                self.buffer = _slot_insert(self.buffer, row, index)
                self.rows[index] = _STAGED
            else:
                if self.buffer is not None:
                    # mixed dtypes: rebuild real references for the
                    # already-staged slots (buffer rows ARE the exact
                    # values), then stack at finalize
                    for i, r in enumerate(self.rows):
                        if r is _STAGED:
                            self.rows[i] = self.buffer[i]
                    self.buffer = None
                self.rows[index] = row
        self.filled += 1
        return row

    def placement_source(self) -> Any:
        """The value placement decisions should inspect: the ingest
        buffer when staging is active, else the held rows."""
        return self.buffer if self.buffer is not None else self.rows

    def stacked(self) -> tuple:
        """``(matrix, unravel)`` over the filled slots, in slot order.
        A complete round returns the donated ingest buffer directly
        (bit-identical to the stack — the buffer holds the exact rows);
        partial rounds gather the filled slots from it (same values);
        the mixed-dtype fallback stacks the held rows."""
        if self.filled == 0:
            raise ValueError("fold_finalize before any gradient was folded")
        if self.buffer is not None:
            if self.filled == self.n:
                return self.buffer, self.unravel
            idx = jnp.asarray(
                [i for i, r in enumerate(self.rows) if r is not None],
                jnp.int32,
            )
            return self.buffer[idx], self.unravel
        return (
            jnp.stack([r for r in self.rows if r is not None], axis=0),
            self.unravel,
        )


class Aggregator(Operator, ABC):
    """Robust gradient aggregator ABC: subclasses map an (n, d) stack of per-node gradients to one (d,) vector via ``aggregate`` / ``aggregate_stream``, and schedule on graphs/pools as Operators."""

    name = "aggregator"
    input_key = "gradients"

    #: Arrival-order streaming capability: when True the orchestrators
    #: may feed gradients through ``fold``/``fold_finalize`` as they
    #: land instead of barriering on the full list. The base
    #: implementation (slot buffer + canonical-order stack) is
    #: bit-identical to ``aggregate`` for any subclass; subclasses with
    #: genuinely incremental math (running sums, extreme buffers, Gram
    #: rows) override the hooks. Set False to force the barrier path.
    supports_streaming: bool = True

    def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        if self.input_key not in inputs:
            raise KeyError(f"{self.name} expects input key {self.input_key!r}")
        gradients = inputs[self.input_key]
        if not isinstance(gradients, Sequence) and not hasattr(gradients, "ndim"):
            raise TypeError(f"{self.name} expects a sequence at {self.input_key!r}")
        return self.aggregate(gradients)

    def aggregate(self, gradients: Sequence[Any]) -> Any:
        """Reduce a sequence of gradients to one aggregated gradient.

        Placement: small host-resident inputs (actor-mode nodes hand over
        numpy arrays) run on the CPU backend instead of paying a
        host->accelerator round-trip; see ``utils.placement``.
        """
        with placement.on(placement.compute_device(gradients)):
            matrix, unravel = stack_gradients(gradients)
            self.validate_n(matrix.shape[0])
            return unravel(self._aggregate_matrix(matrix))

    def aggregate_stream(self, rounds: Sequence[Sequence[Any]]) -> list:
        """Aggregate ``K`` buffered rounds in ONE device dispatch.

        ``rounds``: K sequences of per-node gradients (same structure per
        round). Through a remote-tunneled device a dispatch costs
        milliseconds, comparable to an entire 64x1M aggregate, so replay/
        buffered-round aggregation should batch: subclasses whose math has
        a fused stream kernel (Multi-Krum, CW median, ...) override
        ``_aggregate_stream_matrix``; the default runs the per-round
        matrix function under ``lax.scan``
        (``ops.robust.aggregate_stream``)."""
        if not rounds:
            return []
        with placement.on(placement.compute_device(rounds)):
            stacked = []
            unravel = None
            for grads in rounds:
                matrix, unravel = stack_gradients(grads)
                self.validate_n(matrix.shape[0])
                stacked.append(matrix)
            xs = jnp.stack(stacked)
            ys = self._aggregate_stream_matrix(xs)
            return [unravel(ys[i]) for i in range(ys.shape[0])]

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        """Aggregate stacked rounds ``(K, n, d)`` to ``(K, d)``."""
        from ..ops import robust

        return robust.aggregate_stream(self._aggregate_matrix, xs)

    # -- arrival-order streaming (overlapped rounds) ----------------------

    def fold_init(self, n: int) -> Any:
        """Create streaming-fold state for up to ``n`` gradients.

        Slots are canonical node positions (honest nodes first, then
        byzantine, matching the barrier path's list order), NOT arrival
        ranks — finalize reassembles canonical order so selection tie
        rules see the same row indices as ``aggregate``.
        """
        return SlotFoldState(n)

    def fold(self, state: Any, index: int, gradient: Any) -> None:
        """Ingest one gradient the moment it arrives (slot ``index``)."""
        state.insert(index, gradient)

    def fold_finalize(self, state: Any) -> Any:
        """Finish the round: aggregate everything folded so far.

        The default stacks the filled slots in canonical order and runs
        ``_aggregate_matrix`` — bit-identical to ``aggregate`` on the
        same gradients in slot order, for any arrival order.
        """
        with placement.on(placement.compute_device(state.placement_source())):
            matrix, unravel = state.stacked()
            self.validate_n(matrix.shape[0])
            return unravel(self._aggregate_matrix(matrix))

    # -- masked / ragged finalize (serving-tier bucketed cohorts) ---------

    #: True when the subclass ships a masked matrix program
    #: (``_aggregate_matrix_masked``): a fold declared for bucket size
    #: ``n`` can then finalize an actual cohort of ``m <= n`` rows at the
    #: BUCKET's compiled shape via a validity mask — one jit cache entry
    #: per bucket instead of one per distinct cohort size. Subclasses
    #: without one (subset-enumeration aggregators, whose combination
    #: count is a function of ``m``) fall back to the exact-subset
    #: ``fold_finalize`` path.
    supports_masked_finalize: bool = False

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        """Aggregate the VALID rows of the padded ``(n, d)`` matrix to a
        ``(d,)`` vector — exact size-``m`` semantics at the bucket shape
        (``m`` traced; see ``ops.robust`` masked section). Only called
        when :attr:`supports_masked_finalize` is True."""
        raise NotImplementedError(
            f"{type(self).__name__} has no masked matrix program"
        )

    def masked_matrix_fn(self) -> Optional[Callable]:
        """The bare masked ``(matrix, valid) -> vector`` function for
        embedding in jitted bucketed steps (serving parameter server),
        or ``None`` when the aggregator has no masked program."""
        if not self.supports_masked_finalize:
            return None
        return self._aggregate_matrix_masked

    def _masked_view(self, state: Any) -> Optional[tuple]:
        """``(buffer, valid_rows, unravel)`` exposing the fold state's
        padded ingest buffer for a masked finalize, or ``None`` when the
        state cannot provide one (mixed-dtype fallback, custom states).
        ``valid_rows`` is a host-side list/array of booleans per slot."""
        if isinstance(state, SlotFoldState) and state.buffer is not None:
            return (
                state.buffer,
                [r is not None for r in state.rows],
                state.unravel,
            )
        return None

    def _masked_jitted(self) -> Callable:
        fn = getattr(self, "_masked_jit_cache", None)
        if fn is None:
            fn = jax.jit(self._aggregate_matrix_masked)
            self._masked_jit_cache = fn
        return fn

    def _masked_jitted_donated(self) -> Callable:
        """The masked program as a PERSISTENT donated-buffer jit: the
        padded ``(bucket, d)`` matrix argument is donated, so a root
        that finalizes every round at a small set of ladder bucket
        shapes reuses one device allocation per bucket instead of
        paying an alloc + copy per close (jit's shape-keyed cache IS
        the per-bucket program table). Donation is an accelerator
        feature — on the CPU backend XLA ignores donations (with a
        warning), so this resolves to the plain :meth:`_masked_jitted`
        program there: same bits either way, the donated path only
        changes buffer reuse."""
        fn = getattr(self, "_masked_donated_jit_cache", None)
        if fn is None:
            if jax.default_backend() == "cpu":
                fn = self._masked_jitted()
            else:
                fn = jax.jit(
                    self._aggregate_matrix_masked, donate_argnums=(0,)
                )
            self._masked_donated_jit_cache = fn
        return fn

    def aggregate_masked(self, matrix: Any, valid: Any) -> jnp.ndarray:
        """Exact aggregate of the VALID rows of an already-padded
        ``(n, d)`` matrix, at the padded shape — the batch door into the
        same masked program (and per-bucket jit cache) that
        :meth:`fold_finalize_masked` uses, for callers that assembled
        the padded cohort in one pass (the serving front end) instead of
        folding rows as they arrived. Semantics match ``aggregate`` on
        the valid rows bit-for-bit (f32): finite cohorts run the masked
        program; non-finite cohorts — and aggregators without a masked
        program — take the exact compacted-subset path."""
        import numpy as np

        valid_rows = [bool(v) for v in np.asarray(valid)]
        m = sum(valid_rows)
        if m == 0:
            # validate_n is a no-op for f=0 aggregators (e.g. median),
            # and the masked programs' (m-1)//2-style gathers would wrap
            # to a padding row — garbage, not an error — on m=0
            raise ValueError("aggregate_masked requires at least one valid row")
        self.validate_n(m)
        if isinstance(matrix, np.ndarray):
            finite = bool(np.isfinite(matrix).all())
        else:
            finite = bool(jnp.all(jnp.isfinite(matrix)))
        if self.supports_masked_finalize and finite:
            return self._masked_jitted()(
                jnp.asarray(matrix), jnp.asarray(valid_rows, bool)
            )
        rows = [matrix[i] for i, v in enumerate(valid_rows) if v]
        return self.aggregate(rows)

    def fold_finalize_masked(self, state: Any) -> Any:
        """Finish a round at the BUCKET's compiled shape: aggregate the
        ``m`` folded gradients of a fold declared for ``n >= m`` slots
        through the masked matrix program, keeping the ``(n, d)`` jit
        cache entry warm for every cohort size in the bucket. Exact: the
        result is bit-identical (f32) to ``aggregate`` on the same ``m``
        gradients. Falls back to :meth:`fold_finalize` (the exact-subset
        path, which compiles per distinct ``m``) when the subclass has
        no masked program, the state exposes no padded buffer, or the
        cohort contains non-finite values (adversarial NaN/inf rows sort
        differently against the mask padding — the fallback preserves
        the barrier path's exact non-finite semantics)."""
        view = None
        if self.supports_masked_finalize:
            view = self._masked_view(state)
        if view is None:
            return self.fold_finalize(state)
        buffer, valid_rows, unravel = view
        m = sum(bool(v) for v in valid_rows)
        if m == 0:
            raise ValueError("fold_finalize before any gradient was folded")
        self.validate_n(m)
        with placement.on(placement.compute_device(buffer)):
            # invalid rows are zero (finite) in every fold buffer, so one
            # all-reduce answers "is the cohort finite" — the only case
            # the masked programs do not reproduce bit-for-bit
            if not bool(jnp.all(jnp.isfinite(buffer))):
                return self.fold_finalize(state)
            valid = jnp.asarray(valid_rows, bool)
            return unravel(self._masked_jitted()(buffer, valid))

    # -- ragged multi-cohort aggregation (serving-tier flat batches) ------

    #: Score family published by :meth:`ragged_matrix_fn`'s fused
    #: evidence outputs ("" = the ragged program publishes no per-row
    #: scores; the forensics plane then falls back to the host
    #: :meth:`round_evidence` pass).
    ragged_score_kind: str = ""

    #: Whether multiple cohorts should COALESCE into one ragged device
    #: call for this aggregator on the XLA fallback. True only where
    #: the ragged program genuinely shares work across the batch (the
    #: selection families: ONE Gram / norm pass scores every cohort —
    #: measured cheaper than separate dispatches). Sort-based
    #: coordinate-wise programs share nothing on XLA and sorting the
    #: union is superlinear in rows, so they serve one cohort per call
    #: — still through ONE compiled program (the ladder kill is
    #: independent of coalescing). The Pallas path batches everything
    #: with fill-skip; on-chip policy rides the rerun bundle.
    ragged_coalesce: bool = False

    @property
    def supports_ragged(self) -> bool:
        """True when this aggregator can serve the flat-rows ragged
        door (``ops.ragged``): any aggregator with a masked program
        can — the generic per-cohort masked loop is always available —
        while the hot families override :meth:`ragged_matrix_fn` with
        programs that share the segmented sort / Gram / norm pass
        across the whole batch."""
        return self.supports_masked_finalize

    def ragged_group_key(self) -> tuple:
        """Hashable compatibility key for cross-tenant batching: two
        tenants' cohorts may share one ragged device call only when
        their aggregators trace the SAME program (same class, same
        static hyperparameters). The gradient dimension joins the key
        at the dispatcher (it is a property of the arrays, not the
        aggregator)."""
        statics = tuple(
            sorted(
                (k, v)
                for k, v in vars(self).items()
                if isinstance(v, (int, float, str, bool))
            )
        )
        return (type(self).__qualname__, statics)

    def ragged_matrix_fn(self) -> Optional[Callable]:
        """The bare ragged multi-cohort program ``(flat, seg, offsets,
        lengths, *, n_cohorts, segment_sum=None) -> (aggregates,
        score, keep)`` for embedding in one jitted batch dispatch
        (``serving.ragged``), or ``None`` when the aggregator has no
        masked program. Pure and trace-safe — no dispatch reads; the
        caller resolves Pallas/tile pre-trace and passes
        ``segment_sum``. The default reuses the masked program per
        cohort (single compile / single dispatch, no shared work, no
        fused evidence); subclasses with specialized ragged kernels
        override. Results are bit-identical per cohort to the unpadded
        ``aggregate`` under the masked contract's preconditions
        (finite rows, admissible ``m`` — the serving door enforces
        both)."""
        if not self.supports_masked_finalize:
            return None
        masked = self._aggregate_matrix_masked

        def generic(flat, seg, offsets, lengths, *, n_cohorts,
                    segment_sum=None):
            from ..ops import ragged as ragged_ops

            aggs = ragged_ops.ragged_via_masked(
                masked, flat, seg, n_cohorts=n_cohorts
            )
            return aggs, None, None

        return generic

    # -- hierarchical partial folds (sharded serving tier) -----------------

    #: Every aggregator can serve the hierarchical two-level fold
    #: (``serving.sharded``): the default partial carries one shard's
    #: compacted, staleness-discounted rows and the merged finalize runs
    #: the SAME masked door the single frontend uses — bit-identical to
    #: the single-frontend aggregate by the masked-finalize contract.
    #: Streaming families additionally attach their sublinear fold
    #: accumulators (:meth:`_partial_extras`): trimmed-mean running sum
    #: + extreme buffers, Multi-Krum's local Gram block, CGE's squared
    #: norms — merged exactly at the root (order-stat merge, cross-block
    #: Gram assembly, concatenation) and reused for the root's
    #: forensics score view (:meth:`merged_score_view`) and the
    #: compromised-shard consistency cross-check (extras are
    #: deterministic functions of the rows they summarize).
    @property
    def supports_fold_merge(self) -> bool:
        """Whether :meth:`fold_partial`/:meth:`fold_merge`/
        :meth:`fold_merge_finalize` are available (always True: the
        row-carrying default is universal — aggregators without a
        masked program finalize through the exact-subset door)."""
        return True

    def fold_partial(
        self, matrix: Any, valid: Any, weights: Any = None
    ) -> dict:
        """Extract one shard's wire-compact partial fold from its local
        cohort: ``{"rows": (m, d) float32, "m": int[, "extras": ...]}``.

        ``rows`` are the VALID rows of the padded ``matrix`` in
        admission (slot) order, scaled by their staleness ``weights``
        when any differ from 1.0 — elementwise, so scaling per shard is
        bit-identical to scaling the concatenated cohort. ``extras``
        (streaming families) are the sublinear fold accumulators
        computed from those discounted rows."""
        import numpy as np

        valid_arr = np.asarray(valid, bool)
        rows = np.ascontiguousarray(
            np.asarray(matrix, np.float32)[valid_arr]
        )
        if weights is not None and rows.shape[0]:
            w = np.asarray(weights, np.float32)[valid_arr]
            if bool((w != 1.0).any()):
                rows = rows * w[:, None]
        partial: dict = {"rows": rows, "m": int(rows.shape[0])}
        extras = self._partial_extras(rows)
        if extras:
            partial["extras"] = extras
        return partial

    def _partial_extras(self, rows: Any) -> dict:
        """Family-specific sublinear fold accumulators over one shard's
        discounted rows (empty for aggregators whose fold state is the
        rows themselves). Must be a DETERMINISTIC function of ``rows``
        — the sharded tier's root recomputes it to cross-check a
        shard's claimed extras against the rows it shipped."""
        return {}

    def fold_merge(self, partials: Sequence[Mapping[str, Any]]) -> dict:
        """Merge shard partials, IN SHARD ORDER, into one root fold
        state: ``{"rows": (Σm, d), "m": int, "offsets": per-shard row
        starts[, "extras": merged accumulators]}``. Row order is the
        canonical sharded cohort order (shard index, then admission
        order within the shard) — the order the single-frontend parity
        reference uses."""
        import numpy as np

        mats = [np.asarray(p["rows"], np.float32) for p in partials]
        if not mats:
            raise ValueError("fold_merge needs at least one partial")
        dims = {m.shape[1] for m in mats if m.ndim == 2}
        if len(dims) > 1:
            raise ValueError(
                f"partials disagree on gradient dimension: {sorted(dims)}"
            )
        rows = np.concatenate(mats, axis=0)
        offsets = np.cumsum([0] + [m.shape[0] for m in mats])[:-1]
        merged: dict = {
            "rows": rows,
            "m": int(rows.shape[0]),
            "offsets": [int(o) for o in offsets],
        }
        extras_list = [p.get("extras") for p in partials]
        if any(e for e in extras_list):
            merged["extras"] = self._merge_extras(extras_list, partials)
        return merged

    def _merge_extras(
        self,
        extras_list: Sequence[Optional[Mapping[str, Any]]],
        partials: Sequence[Mapping[str, Any]],
    ) -> dict:
        """Merge the shards' sublinear accumulators (family-specific;
        the base class carries none)."""
        return {}

    # -- combined-frame extras (merge-tree internal nodes) -----------------

    def combined_extras(
        self,
        children: Sequence[Tuple[Tuple[Tuple[int, int, int], ...], Any,
                                 Optional[Mapping[str, Any]]]],
    ) -> dict:
        """Extras for a COMBINED partial (a merge-tree internal node)
        from its children's ``(leaf segment spans, rows, extras)``
        triples, in shard order. The default is the full recompute over
        the concatenated rows — exactly what ``combine_partials`` did
        before the incremental assembly landed, and exactly what the
        default :meth:`segmented_extras_reference` recomputes, so the
        parent's ``extras_policy='verify'`` cross-check stays an exact
        bit comparison. Families whose extras admit cheaper blockwise
        assembly (Multi-Krum's Gram) override BOTH methods with the
        same block program (:func:`ops.robust.gram_block`) — the
        block-contraction contract."""
        import numpy as np

        if not any(e for _sp, _r, e in children):
            return {}
        rows = np.concatenate(
            [np.asarray(r, np.float32) for _sp, r, _e in children], axis=0
        )
        return self._partial_extras(rows)

    def segmented_extras_reference(
        self, rows: Any, spans: Sequence[Tuple[int, int, int]]
    ) -> dict:
        """The VERIFIER's recompute program for a segmented (combined)
        frame's extras — the other half of the block-contraction
        contract: whatever block structure :meth:`combined_extras`
        assembled, this method must reproduce from the frame's rows and
        ``(shard, row_lo, row_hi)`` spans with the SAME per-block dot
        program, so ``extras_policy='verify'`` compares exact bits.
        Default: the flat :meth:`_partial_extras` recompute (matches
        the default :meth:`combined_extras`)."""
        import numpy as np

        return self._partial_extras(np.asarray(rows, np.float32))

    # -- incremental (arrival-order) merge accumulator ---------------------

    def fold_merge_begin(self) -> dict:
        """Open an incremental merge accumulator for a STREAMING root:
        verified shard partials are parked as they arrive — in any
        order — and :meth:`fold_merge_finish` concatenates them in
        canonical shard order. The accumulator exists so an
        arrival-driven close can absorb each partial the moment its
        verification lands while keeping the published aggregate
        BIT-IDENTICAL to the barrier ``fold_merge`` of the same
        partials sorted by shard (pinned by
        ``tests/test_streaming_root.py``)."""
        return {"parked": {}}

    def fold_merge_add(
        self, state: dict, shard: int, partial: Mapping[str, Any]
    ) -> None:
        """Park one verified partial under its (unique) shard key.
        Arrival order is deliberately irrelevant — the canonical row
        order is re-established at :meth:`fold_merge_finish`, so an
        out-of-order arrival never has to wait for its predecessor.

        This is also the accumulator's ARRIVAL-TRANSFORM hook: a family
        whose extras merge needs per-partial heavy work (Multi-Krum's
        cross-Gram blocks against the partials already parked) does it
        HERE, on the arrival thread, so :meth:`fold_merge_finish` keeps
        only the cheap sorted-shard-order reduction — the close-path
        paydown. Overrides count their work into the state
        (``cross_blocks``/``transforms``) and surface it as
        ``merged["merge_stats"]`` at finish, which the sharded root
        folds into its ``gram_cross_blocks``/``partial_transforms``
        counters (the zero-redundant-recompute assert reads them)."""
        key = int(shard)
        if key in state["parked"]:
            raise ValueError(f"shard {key} already parked in this merge")
        state["parked"][key] = partial

    def fold_merge_finish(self, state: dict) -> dict:
        """Close the accumulator: merge the parked partials in shard
        order through :meth:`fold_merge` — the exact call the barrier
        close makes, so streaming-then-finish is bit-identical to
        gather-all-then-merge by construction."""
        parked = state["parked"]
        if not parked:
            raise ValueError("fold_merge_finish on an empty accumulator")
        return self.fold_merge([parked[s] for s in sorted(parked)])

    def fold_merge_finalize(
        self,
        merged: Mapping[str, Any],
        *,
        bucket: Optional[int] = None,
        donate: bool = False,
    ) -> jnp.ndarray:
        """Finalize a merged root fold to the ``(d,)`` aggregate —
        BIT-IDENTICAL (f32, finite cohorts) to the single-frontend
        aggregate of the concatenated cohort: the merged rows run
        through the same :meth:`aggregate_masked` door (same masked
        program, same jit cache, same exact-subset and non-finite
        fallbacks) the one-frontend serving path uses. ``bucket``
        (optional, ≥ the merged row count) zero-pads the merged matrix
        to a ladder shape first, so a root serving many distinct merged
        sizes keeps one compiled program per bucket instead of one per
        size — exactness is the masked contract's padding invariance.

        Merged cohorts reach 10⁴–10⁵ rows, so the host-side gates run
        once over the COMPACT rows (the padding is zeros this method
        wrote itself): one f64 sum screens finiteness in a single pass
        (a sum stays finite iff every addend is — an inf never cancels
        without producing NaN first), and the masked program is invoked
        directly — the same per-aggregator jit cache and bit semantics
        as :meth:`aggregate_masked`, minus its full padded-matrix
        ``isfinite`` rescan.

        ``donate=True`` runs the OFF-PATH finalize variant: the same
        masked program through the persistent donated-buffer jit
        (:meth:`_masked_jitted_donated`, keyed by bucket shape), and
        the call returns the UNMATERIALIZED device array the moment the
        program is dispatched — the root kicks the device step the
        instant the last partial settles and overlaps its host-side
        score view with the device work, materializing (``np.asarray``)
        only when the digest needs the bits. Bit-identical to the
        synchronous path: same program, same inputs."""
        import numpy as np

        rows = np.ascontiguousarray(np.asarray(merged["rows"], np.float32))
        m = int(rows.shape[0])
        if m == 0:
            raise ValueError("fold_merge_finalize on an empty merge")
        self.validate_n(m)
        finite = bool(np.isfinite(rows.sum(dtype=np.float64)))
        if not (self.supports_masked_finalize and finite):
            # the exact compacted-subset path aggregate_masked would
            # take for the same inputs (non-finite cohorts, families
            # without a masked program)
            return self.aggregate(list(rows))
        if bucket is not None and bucket > m:
            padded = np.zeros((bucket, rows.shape[1]), np.float32)
            padded[:m] = rows
            valid = np.zeros((bucket,), bool)
            valid[:m] = True
        else:
            padded = rows
            valid = np.ones((m,), bool)
        fn = self._masked_jitted_donated() if donate else self._masked_jitted()
        return fn(jnp.asarray(padded), jnp.asarray(valid))

    #: True when :meth:`merged_score_view` reads ONLY the merged fold
    #: state (rows + published extras) whenever extras are present —
    #: i.e. it never needs the round ``aggregate``. The root's
    #: off-path finalize overlaps the host score pass with the device
    #: program ONLY for such families (the view runs between the
    #: device dispatch and its materialization; a view that wants the
    #: aggregate would force the materialization first and the overlap
    #: would be a lie).
    merged_view_from_extras: bool = False

    def merged_score_view(
        self, merged: Mapping[str, Any], *, aggregate: Any = None
    ) -> Optional[dict]:
        """Per-row ``{"kind", "scores", "keep"}`` view of the MERGED
        cohort for the root's forensics fan-out (sliced per shard and
        fed to each shard plane as ``precomputed``), reusing the merged
        extras where the family published them (Gram blocks, norms)
        instead of paying the host score pass again. Falls back to
        :meth:`round_evidence` on the merged rows. ``None`` when the
        aggregator publishes no per-row scores."""
        import numpy as np

        rows = np.asarray(merged["rows"], np.float32)
        if rows.shape[0] == 0:
            return None
        return self.round_evidence(
            rows, np.ones((rows.shape[0],), bool), aggregate=aggregate
        )

    # -- forensics evidence (per-row score view) ---------------------------

    #: True when :meth:`round_evidence` publishes a binary keep set
    #: (selection aggregators: Krum families, CGE, MoNNA). Lets
    #: selection-only consumers (``chaos.influence.selection_mask``)
    #: skip the score computation entirely for aggregators whose view
    #: carries scores but no selection (e.g. trimmed-mean clip
    #: fractions — an O(m·d·log m) host pass that would be discarded).
    evidence_selects: bool = False

    def round_evidence(
        self, matrix: Any, valid: Any, *, aggregate: Any = None
    ) -> Optional[dict]:
        """Per-row score/selection view of one (padded) cohort for the
        forensics plane (``byzpy_tpu.forensics``), or ``None`` when the
        aggregator publishes no per-row scores (or the valid cohort is
        empty/inadmissible — no defined selection).

        Returns ``{"kind": str, "scores": (n,) float array, "keep":
        (n,) bool array or None}`` aligned to PADDED slot positions
        (invalid rows carry NaN scores / False keeps). Computed
        HOST-SIDE from the same published score programs the aggregate
        uses (``ops.robust.krum_scores``, per-row norms, …) — never
        inside the aggregation program, so round aggregates stay
        digest-identical with forensics on or off. ``aggregate`` (the
        round's broadcast) is only needed by center-seeking aggregators
        (geomed/clipping) whose scores are distances to the output."""
        return None

    def _evidence_rows(self, matrix: Any, valid: Any) -> Optional[tuple]:
        """Shared preamble for ``round_evidence`` overrides: the
        compacted valid rows as float32 numpy, their padded indices,
        and the padded shape — or ``None`` when the valid cohort is
        empty or inadmissible (``validate_n`` rejects ``m``)."""
        import numpy as np

        valid = np.asarray(valid, bool)
        idx = np.flatnonzero(valid)
        m = int(idx.size)
        if m == 0:
            return None
        try:
            self.validate_n(m)
        except ValueError:
            return None
        rows = np.asarray(matrix, np.float32)[idx]
        return rows, idx, valid.shape[0]

    @staticmethod
    def _evidence_view(
        kind: str, n: int, idx, scores, keep_local=None
    ) -> dict:
        """Scatter compacted per-row ``scores`` (and an optional local
        keep index set) back to padded positions."""
        import numpy as np

        full = np.full((n,), np.nan, np.float32)
        full[idx] = np.asarray(scores, np.float32)
        keep = None
        if keep_local is not None:
            keep = np.zeros((n,), bool)
            keep[idx[np.asarray(keep_local)]] = True
        return {"kind": kind, "scores": full, "keep": keep}

    def validate_n(self, n: int) -> None:
        """Hook for subclasses to validate hyperparameters against n."""

    @abstractmethod
    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        """Aggregate the stacked ``(n, d)`` matrix to a ``(d,)`` vector."""

    def matrix_fn(self) -> Callable[[jnp.ndarray], jnp.ndarray]:
        """The bare matrix->vector function, for embedding in jitted
        training steps (SPMD parameter server, gossip loops)."""
        return self._aggregate_matrix


__all__ = ["Aggregator", "SlotFoldState", "ravel_gradient"]

"""Aggregator base class (API parity: ``byzpy/aggregators/base.py:11-103``).

An aggregator reduces a sequence of per-node gradients (pytrees, arrays, or
an already-stacked ``(n, d)`` matrix) to a single aggregated gradient with
the structure of one input. Subclasses implement ``_aggregate_matrix`` — a
pure function on the stacked matrix that jit-compiles and shards over a
device mesh (see ``byzpy_tpu.ops.robust``).

Unlike the reference, parallelism does not require host-side chunking: the
matrix computation is one XLA program. Chunked ``create_subtasks`` paths are
still provided by the mixins in ``chunked.py`` for running on heterogeneous
actor pools (the reference's shm-chunk pattern, minus the shm).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Mapping, Sequence

import jax.numpy as jnp

from ..engine.graph.operator import OpContext, Operator
from ..utils import placement
from ..utils.trees import stack_gradients


class Aggregator(Operator, ABC):
    """Robust gradient aggregator ABC: subclasses map an (n, d) stack of per-node gradients to one (d,) vector via ``aggregate`` / ``aggregate_stream``, and schedule on graphs/pools as Operators."""

    name = "aggregator"
    input_key = "gradients"

    def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        if self.input_key not in inputs:
            raise KeyError(f"{self.name} expects input key {self.input_key!r}")
        gradients = inputs[self.input_key]
        if not isinstance(gradients, Sequence) and not hasattr(gradients, "ndim"):
            raise TypeError(f"{self.name} expects a sequence at {self.input_key!r}")
        return self.aggregate(gradients)

    def aggregate(self, gradients: Sequence[Any]) -> Any:
        """Reduce a sequence of gradients to one aggregated gradient.

        Placement: small host-resident inputs (actor-mode nodes hand over
        numpy arrays) run on the CPU backend instead of paying a
        host->accelerator round-trip; see ``utils.placement``.
        """
        with placement.on(placement.compute_device(gradients)):
            matrix, unravel = stack_gradients(gradients)
            self.validate_n(matrix.shape[0])
            return unravel(self._aggregate_matrix(matrix))

    def aggregate_stream(self, rounds: Sequence[Sequence[Any]]) -> list:
        """Aggregate ``K`` buffered rounds in ONE device dispatch.

        ``rounds``: K sequences of per-node gradients (same structure per
        round). Through a remote-tunneled device a dispatch costs
        milliseconds, comparable to an entire 64x1M aggregate, so replay/
        buffered-round aggregation should batch: subclasses whose math has
        a fused stream kernel (Multi-Krum, CW median, ...) override
        ``_aggregate_stream_matrix``; the default runs the per-round
        matrix function under ``lax.scan``
        (``ops.robust.aggregate_stream``)."""
        if not rounds:
            return []
        with placement.on(placement.compute_device(rounds)):
            stacked = []
            unravel = None
            for grads in rounds:
                matrix, unravel = stack_gradients(grads)
                self.validate_n(matrix.shape[0])
                stacked.append(matrix)
            xs = jnp.stack(stacked)
            ys = self._aggregate_stream_matrix(xs)
            return [unravel(ys[i]) for i in range(ys.shape[0])]

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        """Aggregate stacked rounds ``(K, n, d)`` to ``(K, d)``."""
        from ..ops import robust

        return robust.aggregate_stream(self._aggregate_matrix, xs)

    def validate_n(self, n: int) -> None:
        """Hook for subclasses to validate hyperparameters against n."""

    @abstractmethod
    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        """Aggregate the stacked ``(n, d)`` matrix to a ``(d,)`` vector."""

    def matrix_fn(self) -> Callable[[jnp.ndarray], jnp.ndarray]:
        """The bare matrix->vector function, for embedding in jitted
        training steps (SPMD parameter server, gossip loops)."""
        return self._aggregate_matrix


__all__ = ["Aggregator"]

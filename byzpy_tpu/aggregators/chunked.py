"""Subtask-chunking mixins for aggregators on actor pools.

The reference parallelizes aggregators by slicing the stacked gradient
matrix into shared-memory chunks fanned out to pool workers (feature chunks
for coordinate-wise ops, ``median.py:108-134``; row/score chunks for
geometric ops, ``krum.py:371-475``). On TPU the preferred path is a single
jitted (optionally mesh-sharded) program, but the chunked path is kept for
heterogeneous pools (e.g. CPU process workers assisting a host) and for
behavioral parity with the reference's scheduler integration.

Chunk functions are module-level so process/remote workers can unpickle
them; they use jax.numpy, which runs on whatever platform the worker has.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

import numpy as np
import jax.numpy as jnp

from ..engine.graph.chunking import select_adaptive_chunk_size
from ..engine.graph.operator import OpContext
from ..engine.graph.subtask import SubTask
from ..utils.trees import stack_gradients


def _pool_size(context: OpContext) -> int:
    metadata = getattr(context, "metadata", None) or {}
    return int(metadata.get("pool_size") or 0)


class FeatureChunkedAggregator:
    """Mixin: fan out column (feature) chunks; concatenate partial vectors.

    Subclasses set ``_chunk_fn`` to a module-level ``fn(chunk, **params)``
    returning the aggregated vector for those coordinates, and
    ``_chunk_params()`` for its kwargs.
    """

    supports_subtasks = True
    chunk_size = 8192
    _chunk_fn: Any = None

    def _chunk_params(self) -> Mapping[str, Any]:
        return {}

    def create_subtasks(self, inputs, *, context: OpContext) -> Iterable[SubTask]:
        # Stateless across create/reduce: reduce re-derives the unravel from
        # `inputs`, so one instance can run at multiple concurrent graph nodes.
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        host = np.asarray(matrix)
        d = host.shape[1]
        chunk = select_adaptive_chunk_size(
            d, self.chunk_size, pool_size=_pool_size(context)
        )
        params = dict(self._chunk_params())
        fn = type(self)._chunk_fn

        def gen():
            for start in range(0, d, chunk):
                end = min(d, start + chunk)
                yield SubTask(
                    fn=fn,
                    args=(host[:, start:end],),
                    kwargs=params,
                    name=f"{self.name}-feat[{start}:{end}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext) -> Any:
        vec = jnp.concatenate([jnp.asarray(p) for p in partials])
        _, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(vec)


class RowScoredAggregator:
    """Mixin: fan out row-range scoring against the full matrix, then select
    rows centrally (the Krum/MoNNA/CGE pattern)."""

    supports_subtasks = True
    chunk_size = 32
    _score_fn: Any = None

    def _score_params(self) -> Mapping[str, Any]:
        return {}

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def create_subtasks(self, inputs, *, context: OpContext) -> Iterable[SubTask]:
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        host = np.asarray(matrix)
        n = host.shape[0]
        chunk = select_adaptive_chunk_size(
            n, self.chunk_size, pool_size=_pool_size(context)
        )
        params = dict(self._score_params())
        fn = type(self)._score_fn

        def gen():
            for start in range(0, n, chunk):
                end = min(n, start + chunk)
                yield SubTask(
                    fn=fn,
                    args=(host, start, end),
                    kwargs=params,
                    name=f"{self.name}-rows[{start}:{end}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext) -> Any:
        scores = jnp.concatenate([jnp.asarray(p) for p in partials])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(self._select_from_scores(scores, matrix))


__all__ = ["FeatureChunkedAggregator", "RowScoredAggregator"]

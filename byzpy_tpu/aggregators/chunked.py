"""Subtask-chunking mixins for aggregators on actor pools.

The reference parallelizes aggregators by slicing the stacked gradient
matrix into shared-memory chunks fanned out to pool workers (feature chunks
for coordinate-wise ops, ``median.py:108-134``; row/score chunks for
geometric ops, ``krum.py:371-475``). On TPU the preferred path is a single
jitted (optionally mesh-sharded) program, but the chunked path is kept for
heterogeneous pools (e.g. CPU process workers assisting a host) and for
behavioral parity with the reference's scheduler integration.

Chunk functions are module-level so process/remote workers can unpickle
them; they use jax.numpy, which runs on whatever platform the worker has.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

import numpy as np
import jax.numpy as jnp

from ..engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ..engine.graph.operator import OpContext
from ..engine.graph.subtask import SubTask
from ..utils.trees import stack_gradients



class FeatureChunkedAggregator:
    """Mixin: fan out column (feature) chunks; concatenate partial vectors.

    Subclasses set ``_chunk_fn`` to a module-level ``fn(chunk, **params)``
    returning the aggregated vector for those coordinates, and
    ``_chunk_params()`` for its kwargs.
    """

    supports_subtasks = True
    chunk_size = 8192
    _chunk_fn: Any = None

    def _chunk_params(self) -> Mapping[str, Any]:
        return {}

    def create_subtasks(self, inputs, *, context: OpContext) -> Iterable[SubTask]:
        # Stateless across create/reduce: reduce re-derives the unravel from
        # `inputs`, so one instance can run at multiple concurrent graph nodes.
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        host = np.asarray(matrix)
        d = host.shape[1]
        chunk = select_adaptive_chunk_size(
            d, self.chunk_size, pool_size=pool_size_from_context(context)
        )
        params = dict(self._chunk_params())
        fn = type(self)._chunk_fn

        def gen():
            for start in range(0, d, chunk):
                end = min(d, start + chunk)
                yield SubTask(
                    fn=fn,
                    args=(host[:, start:end],),
                    kwargs=params,
                    name=f"{self.name}-feat[{start}:{end}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext) -> Any:
        vec = jnp.concatenate([jnp.asarray(p) for p in partials])
        _, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(vec)


class RowScoredAggregator:
    """Mixin: fan out row-range scoring against the full matrix, then select
    rows centrally (the Krum/MoNNA/CGE pattern)."""

    supports_subtasks = True
    chunk_size = 32
    _score_fn: Any = None

    def _score_params(self) -> Mapping[str, Any]:
        return {}

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def create_subtasks(self, inputs, *, context: OpContext) -> Iterable[SubTask]:
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        host = np.asarray(matrix)
        n = host.shape[0]
        chunk = select_adaptive_chunk_size(
            n, self.chunk_size, pool_size=pool_size_from_context(context)
        )
        params = dict(self._score_params())
        fn = type(self)._score_fn

        def gen():
            for start in range(0, n, chunk):
                end = min(n, start + chunk)
                yield SubTask(
                    fn=fn,
                    args=(host, start, end),
                    kwargs=params,
                    name=f"{self.name}-rows[{start}:{end}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext) -> Any:
        scores = jnp.concatenate([jnp.asarray(p) for p in partials])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(self._select_from_scores(scores, matrix))


# ---------------------------------------------------------------------------
# Barriered iterative fan-out (the reference's third execution mode:
# ``byzpy/engine/graph/operator.py:50-60`` dispatching to per-iteration
# chunk fan-outs like ``geometric_median.py:106-158`` and
# ``center_clipping.py:158-257``)
# ---------------------------------------------------------------------------

def _resolve_rows(block: Any) -> np.ndarray:
    """Materialize a row chunk shipped as a shared-store handle.

    Copy-then-close on every call: caching mapped views across calls would
    leave dangling pointers once the coordinator's cleanup unmaps/unlinks
    the segment (thread backends share the process) and would pin dead
    row-blocks across training rounds. One memcpy per chunk per iteration
    is the price of a strict no-view-outlives-the-call discipline."""
    from ..engine.storage.native_store import (
        SharedTensorHandle, close_tensor, open_tensor,
    )

    if isinstance(block, SharedTensorHandle):
        view = open_tensor(block)
        try:
            return np.array(view, copy=True)
        finally:
            del view
            close_tensor(block)
    return np.asarray(block)


def _weiszfeld_chunk(block: Any, center: np.ndarray, *, eps: float):
    """One Weiszfeld term over a row chunk: (sum_i w_i x_i, sum_i w_i) with
    w_i = 1 / max(||x_i - z||, eps)."""
    x = jnp.asarray(_resolve_rows(block))
    z = jnp.asarray(center)
    diff = x - z[None, :]
    dist = jnp.sqrt(jnp.sum(diff * diff, axis=1))
    w = 1.0 / jnp.maximum(dist, eps)
    return np.asarray(jnp.sum(w[:, None] * x, axis=0)), float(jnp.sum(w))


def _centered_clip_chunk(block: Any, center: np.ndarray, *, c_tau: float, eps: float):
    """One centered-clipping contribution over a row chunk:
    (sum_i clip(x_i - v, c_tau), rows)."""
    x = jnp.asarray(_resolve_rows(block))
    v = jnp.asarray(center)
    diff = x - v[None, :]
    dist = jnp.sqrt(jnp.sum(diff * diff, axis=1))
    scale = jnp.minimum(1.0, c_tau / jnp.maximum(dist, eps))
    return np.asarray(jnp.sum(diff * scale[:, None], axis=0)), int(x.shape[0])


class BarrieredIterativeAggregator:
    """Mixin: per-iteration fan-out of row-chunk contributions with a
    barrier and a coordinator-side state update.

    Subclasses set the module-level ``_barrier_chunk_fn`` plus the hooks
    below. Row blocks are registered in the shared store once and shipped
    as handles; only the small ``center`` vector travels per iteration.
    With no pool (or one worker) the fused ``lax``-loop ``compute`` path
    runs instead — it is strictly better on a single device.
    """

    supports_barriered_subtasks = True
    row_chunk_size = 16
    _barrier_chunk_fn: Any = None

    def _barrier_params(self) -> Mapping[str, Any]:
        return {}

    def _barrier_init(self, host: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _barrier_update(self, partials: Any, center: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _barrier_max_iters(self) -> int:
        raise NotImplementedError

    def _barrier_converged(self, old: np.ndarray, new: np.ndarray) -> bool:
        return False

    async def run_barriered_subtasks(self, inputs, *, context: OpContext, pool) -> Any:
        from ..engine.graph.operator import _maybe_await
        from ..engine.storage.native_store import cleanup_tensor, register_tensor

        if pool is None or pool.size <= 1:
            return await _maybe_await(self.compute(inputs, context=context))
        gradients = inputs.get(self.input_key)
        matrix, unravel = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        host = np.asarray(matrix)
        n = host.shape[0]
        chunk = select_adaptive_chunk_size(
            n, self.row_chunk_size, pool_size=pool.size
        )
        params = dict(self._barrier_params())
        fn = type(self)._barrier_chunk_fn
        handles = []
        spans = []
        try:
            # registration inside the try: a partial failure (e.g. ENOSPC on
            # /dev/shm) must still unlink the segments already registered
            for start in range(0, n, chunk):
                end = min(n, start + chunk)
                handles.append(register_tensor(np.ascontiguousarray(host[start:end])))
                spans.append((start, end))
            center = self._barrier_init(host)
            for _ in range(self._barrier_max_iters()):
                tasks = [
                    SubTask(
                        fn=fn,
                        args=(h, center),
                        kwargs=params,
                        name=f"{self.name}-iter-rows[{s}:{e}]",
                    )
                    for h, (s, e) in zip(handles, spans, strict=True)
                ]
                partials = await self._run_subtasks(pool, tasks, context)
                new_center = self._barrier_update(partials, center)
                done = self._barrier_converged(center, new_center)
                center = new_center
                if done:
                    break
        finally:
            for h in handles:
                cleanup_tensor(h)
        return unravel(jnp.asarray(center, matrix.dtype))


__all__ = [
    "FeatureChunkedAggregator",
    "RowScoredAggregator",
    "BarrieredIterativeAggregator",
]

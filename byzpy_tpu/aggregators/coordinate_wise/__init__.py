from .mean_of_medians import MeanOfMedians
from .median import CoordinateWiseMedian
from .trimmed_mean import CoordinateWiseTrimmedMean

__all__ = ["CoordinateWiseMedian", "CoordinateWiseTrimmedMean", "MeanOfMedians"]

"""MeaMed: per-coordinate mean of the ``n - f`` values nearest the median
(behavioral parity: ``byzpy/aggregators/coordinate_wise/mean_of_medians.py:28-162``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import FeatureChunkedAggregator


def _meamed_chunk(chunk: np.ndarray, *, f: int) -> jnp.ndarray:
    return robust.mean_of_medians(jnp.asarray(chunk), f=f)


class MeanOfMedians(FeatureChunkedAggregator, Aggregator):
    """MeaMed: per coordinate, average the n - f values closest to the median."""
    name = "mean-of-medians"
    _chunk_fn = staticmethod(_meamed_chunk)

    def __init__(self, f: int, *, chunk_size: int = 8192) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n:
            raise ValueError(f"f must satisfy 0 <= f < n (got n={n}, f={self.f})")

    def _chunk_params(self):
        return {"f": self.f}

    supports_masked_finalize = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.mean_of_medians(x, f=self.f)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_mean_of_medians(x, valid, f=self.f)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.mean_of_medians_stream(xs, f=self.f)


__all__ = ["MeanOfMedians"]

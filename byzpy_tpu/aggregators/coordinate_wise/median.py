"""Coordinate-wise median aggregator
(behavioral parity: ``byzpy/aggregators/coordinate_wise/median.py:28-178``).

TPU execution: one ``jnp.median`` over the node axis — fully local per chip
when the matrix is feature-sharded, no communication. The pool-chunked path
fans out column blocks instead of the reference's shm chunks.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import FeatureChunkedAggregator


def _median_chunk(chunk: np.ndarray) -> jnp.ndarray:
    return jnp.median(jnp.asarray(chunk), axis=0)


class CoordinateWiseMedian(FeatureChunkedAggregator, Aggregator):
    """Per-coordinate median over the node axis."""
    name = "coordinate-wise-median"
    _chunk_fn = staticmethod(_median_chunk)

    def __init__(self, *, chunk_size: int = 8192) -> None:
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.chunk_size = int(chunk_size)

    supports_masked_finalize = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.coordinate_median(x)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_coordinate_median(x, valid)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.coordinate_median_stream(xs)

    def ragged_matrix_fn(self):
        """Ragged program, sort strategy resolved pre-trace (see
        ``CoordinateWiseTrimmedMean.ragged_matrix_fn``): segmented
        program on TPU (finite rows only — the serving ragged door
        routes non-finite cohorts to the exact fallback, and on finite
        data the masked program's NaN rewrite is a no-op, so parity
        stays bit-for-bit), per-cohort masked program on the XLA
        fallback."""
        from ...ops import ragged as ragged_ops
        from ...ops.pallas_kernels import _on_tpu

        if not _on_tpu():
            return super().ragged_matrix_fn()

        def fn(flat, seg, offsets, lengths, *, n_cohorts, segment_sum=None):
            aggs = ragged_ops.ragged_median(
                flat, seg, offsets, lengths, n_cohorts=n_cohorts
            )
            return aggs, None, None

        return fn


__all__ = ["CoordinateWiseMedian"]

"""Coordinate-wise trimmed mean (Yin et al. 2018)
(behavioral parity: ``byzpy/aggregators/coordinate_wise/trimmed_mean.py:27-211``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import FeatureChunkedAggregator


def _trimmed_mean_chunk(chunk: np.ndarray, *, f: int) -> jnp.ndarray:
    return robust.trimmed_mean(jnp.asarray(chunk), f=f)


class CoordinateWiseTrimmedMean(FeatureChunkedAggregator, Aggregator):
    """Drop the f largest and f smallest values per coordinate, average the rest."""
    name = "coordinate-wise-trimmed-mean"
    _chunk_fn = staticmethod(_trimmed_mean_chunk)

    def __init__(self, f: int, *, chunk_size: int = 8192) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(
                f"trim parameter f must satisfy 0 <= 2f < n (got n={n}, f={self.f})"
            )

    def _chunk_params(self):
        return {"f": self.f}

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.trimmed_mean(x, f=self.f)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.trimmed_mean_stream(xs, f=self.f)


__all__ = ["CoordinateWiseTrimmedMean"]

"""Coordinate-wise trimmed mean (Yin et al. 2018)
(behavioral parity: ``byzpy/aggregators/coordinate_wise/trimmed_mean.py:27-211``).
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ...utils import placement
from ..base import Aggregator, SlotFoldState
from ..chunked import FeatureChunkedAggregator


def _trimmed_mean_chunk(chunk: np.ndarray, *, f: int) -> jnp.ndarray:
    return robust.trimmed_mean(jnp.asarray(chunk), f=f)


class _TrimmedMeanFoldState:
    """Incremental trimmed-mean state: running coordinate sum + folded
    ``f``-smallest/``f``-largest buffers (``ops.robust
    .extremes_fold_update``), so per-arrival work is O(f·d) and finalize
    is O(f·d) — the sort cost streams over the round. Raw rows are kept
    in a slot buffer as the exact fallback: a non-finite gradient (an
    adversary's NaN/inf) would corrupt the extreme buffers, so finalize
    detects it (one flag, no per-arrival host sync) and reruns the
    barrier-identical sorted path on the kept rows."""

    __slots__ = ("slots", "total", "low", "high", "nonfinite")

    def __init__(self, n: int) -> None:
        self.slots = SlotFoldState(n)
        self.total = None
        self.low = None
        self.high = None
        self.nonfinite = None


class CoordinateWiseTrimmedMean(FeatureChunkedAggregator, Aggregator):
    """Drop the f largest and f smallest values per coordinate, average the rest."""
    name = "coordinate-wise-trimmed-mean"
    _chunk_fn = staticmethod(_trimmed_mean_chunk)

    def __init__(self, f: int, *, chunk_size: int = 8192) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(
                f"trim parameter f must satisfy 0 <= 2f < n (got n={n}, f={self.f})"
            )

    def _chunk_params(self):
        return {"f": self.f}

    supports_masked_finalize = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.trimmed_mean(x, f=self.f)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_trimmed_mean(x, valid, f=self.f)

    def _masked_view(self, state):
        # the incremental fold keeps raw rows in a slot buffer precisely
        # for exact fallbacks; the masked finalize reads the same buffer
        # (the base class's finite check then routes a NaN/inf round to
        # the exact sorted path, like the extremes fold does)
        return Aggregator._masked_view(self, state.slots)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.trimmed_mean_stream(xs, f=self.f)

    def ragged_matrix_fn(self):
        """Ragged program with the sort strategy resolved HERE, before
        any trace (the PR-2 wrapper pattern): on TPU the specialized
        segmented program (ONE two-key sort serves every cohort in the
        batch, ``ops.ragged.ragged_trimmed_mean``); on the XLA
        fallback the per-cohort masked program — XLA:CPU's
        multi-operand ``lax.sort`` measured 3.4× the single-key sort
        at the same shape, so the shared sort loses there (and
        ``ragged_coalesce`` is False: one cohort per call, still ONE
        compiled program across every cohort size)."""
        from ...ops import ragged as ragged_ops
        from ...ops.pallas_kernels import _on_tpu

        f = self.f
        if not _on_tpu():
            return super().ragged_matrix_fn()

        def fn(flat, seg, offsets, lengths, *, n_cohorts, segment_sum=None):
            aggs = ragged_ops.ragged_trimmed_mean(
                flat, seg, offsets, lengths, f=f, n_cohorts=n_cohorts,
                segment_sum=segment_sum,
            )
            return aggs, None, None

        return fn

    #: Coordinate cap for the host-side clip-fraction evidence: past
    #: this, the per-coordinate rank pass samples an evenly-strided
    #: subset (evidence is a screening signal, not the aggregate).
    _EVIDENCE_MAX_COORDS = 65536

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Per-row clip counts: the fraction of a row's coordinates
        that fell in the trimmed ``f``-smallest/``f``-largest window
        (host-side ranks; stable order matches the sort the aggregate
        trims with). An honest row is clipped on ~``2f/m`` of
        coordinates by symmetry; a directional attacker concentrates
        near 1.0. No binary selection (``keep`` is None) — trimming is
        per-coordinate."""
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        m, d = rows.shape
        if self.f == 0:
            return self._evidence_view(
                "trim_fraction", n, idx, np.zeros((m,), np.float32)
            )
        cols = rows
        if d > self._EVIDENCE_MAX_COORDS:
            sample = np.linspace(
                0, d - 1, self._EVIDENCE_MAX_COORDS, dtype=np.int64
            )
            cols = rows[:, sample]
        order = np.argsort(cols, axis=0, kind="stable")
        ranks = np.empty_like(order)
        np.put_along_axis(
            ranks, order, np.arange(m, dtype=order.dtype)[:, None], axis=0
        )
        trimmed = (ranks < self.f) | (ranks >= m - self.f)
        frac = trimmed.mean(axis=1).astype(np.float32)
        return self._evidence_view("trim_fraction", n, idx, frac)

    # -- hierarchical partial fold (sharded serving tier) -----------------

    def _partial_extras(self, rows) -> dict:
        """Sublinear streaming summary of one shard's discounted rows:
        the running coordinate sum plus the ``f``-smallest/``f``-largest
        extreme buffers (±inf-padded below ``f`` rows, exactly like the
        streaming fold's init), and a finite flag. Extreme buffers merge
        EXACTLY across shards (order statistics of a multiset compose),
        so the root can maintain the same O(f·d) streaming state the
        overlapped fold keeps — and cross-check a shard's claim against
        the rows it shipped (deterministic recompute)."""
        d = rows.shape[1] if rows.ndim == 2 else 0
        extras: dict = {
            "total": rows.sum(axis=0, dtype=np.float32),
            "finite": bool(np.isfinite(rows).all()),
        }
        if self.f > 0:
            lo_pad = np.full((self.f, d), np.inf, np.float32)
            hi_pad = np.full((self.f, d), -np.inf, np.float32)
            extras["low"] = np.sort(
                np.concatenate([rows, lo_pad], axis=0), axis=0
            )[: self.f]
            extras["high"] = np.sort(
                np.concatenate([rows, hi_pad], axis=0), axis=0
            )[-self.f:]
        return extras

    def _merge_extras(self, extras_list, partials) -> dict:
        """Exact root merge: totals left-fold in shard order; the
        merged extreme buffers are the per-coordinate ``f`` smallest/
        largest of the concatenated shard buffers — bit-equal to the
        extremes of the full concatenated cohort (multiset order
        statistics). A shard that shipped no extras has them recomputed
        from its rows (extras are deterministic summaries)."""
        import functools

        fixed = [
            e if e else self._partial_extras(
                np.asarray(p["rows"], np.float32)
            )
            for e, p in zip(extras_list, partials, strict=True)
        ]
        merged: dict = {
            "total": functools.reduce(
                np.add, [np.asarray(e["total"], np.float32) for e in fixed]
            ),
            "finite": all(bool(e.get("finite", True)) for e in fixed),
        }
        if self.f > 0:
            merged["low"] = np.sort(
                np.concatenate([e["low"] for e in fixed], axis=0), axis=0
            )[: self.f]
            merged["high"] = np.sort(
                np.concatenate([e["high"] for e in fixed], axis=0), axis=0
            )[-self.f:]
        return merged

    # -- arrival-order streaming fold ------------------------------------

    def fold_init(self, n: int) -> Any:
        return _TrimmedMeanFoldState(n)

    def fold(self, state: Any, index: int, gradient: Any) -> None:
        row = state.slots.insert(index, gradient)
        f = self.f
        with placement.on(placement.compute_device(row)):
            if state.total is None:
                # a COPY, not `row` itself: the donated add below deletes
                # its first argument, and `row` is shared with the slot
                # buffer the exact fallback reads
                state.total = jnp.array(row, copy=True)
            else:
                state.total = robust.fold_add_donated(state.total, row)
            bad = ~jnp.all(jnp.isfinite(row))
            state.nonfinite = (
                bad if state.nonfinite is None else state.nonfinite | bad
            )
            if f > 0:
                if state.low is None:
                    d = row.shape[0]
                    state.low = jnp.full((f, d), jnp.inf, row.dtype)
                    state.high = jnp.full((f, d), -jnp.inf, row.dtype)
                state.low = robust.extremes_fold_update_donated(
                    state.low, row, largest=False
                )
                state.high = robust.extremes_fold_update_donated(
                    state.high, row, largest=True
                )

    def fold_finalize(self, state: Any) -> Any:
        n = state.slots.filled
        self.validate_n(n)
        if state.nonfinite is None or bool(state.nonfinite):
            # exact sorted path on the kept rows (matches the barrier's
            # NaN-propagation / inf-trimming semantics bit for bit)
            return Aggregator.fold_finalize(self, state.slots)
        with placement.on(
            placement.compute_device(state.slots.placement_source())
        ):
            vec = robust.trimmed_mean_from_extremes(
                state.total, state.low, state.high, n, f=self.f
            )
            return state.slots.unravel(vec)


__all__ = ["CoordinateWiseTrimmedMean"]

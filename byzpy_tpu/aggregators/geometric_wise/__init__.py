from .geometric_median import GeometricMedian
from .krum import Krum, MultiKrum
from .minimum_diameter_average import MinimumDiameterAveraging
from .monna import MoNNA
from .smea import SMEA

__all__ = ["MultiKrum", "Krum", "GeometricMedian", "MinimumDiameterAveraging", "MoNNA", "SMEA"]

"""Geometric median via Weiszfeld iterations
(behavioral parity: ``byzpy/aggregators/geometric_wise/geometric_median.py:33-158``).

The reference implements the iteration as *barriered subtasks*: every
Weiszfeld step fans partial weighted sums over shm chunks and reduces on the
coordinator. On TPU the whole iteration is a single ``lax.while_loop`` —
with a feature-sharded matrix the per-step distance reduction becomes a
psum and there are zero host round-trips, so no barriered machinery exists
here by design.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator


class GeometricMedian(Aggregator):
    name = "geometric-median"

    def __init__(
        self,
        *,
        tol: float = 1e-6,
        max_iter: int = 256,
        eps: float = 1e-12,
        init: str = "median",
    ) -> None:
        if tol <= 0:
            raise ValueError("tol must be > 0")
        if max_iter <= 0:
            raise ValueError("max_iter must be > 0")
        if eps <= 0:
            raise ValueError("eps must be > 0")
        if init not in {"median", "mean"}:
            raise ValueError("init must be 'median' or 'mean'")
        self.tol = float(tol)
        self.max_iter = int(max_iter)
        self.eps = float(eps)
        self.init = init

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.geometric_median(
            x, tol=self.tol, max_iter=self.max_iter, eps=self.eps, init=self.init
        )


__all__ = ["GeometricMedian"]

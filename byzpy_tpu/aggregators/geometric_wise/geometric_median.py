"""Geometric median via Weiszfeld iterations
(behavioral parity: ``byzpy/aggregators/geometric_wise/geometric_median.py:33-158``).

Two execution paths:

* single device (no pool / one worker): the whole iteration is one
  ``lax.while_loop`` — with a feature-sharded matrix the per-step distance
  reduction becomes a psum and there are zero host round-trips;
* actor pool: the reference's *barriered* mode — every Weiszfeld step fans
  per-row-chunk weighted partial sums over the pool (chunks live in the
  shared store, only the center travels per iteration) and reduces on the
  coordinator (ref: ``geometric_median.py:106-158``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import BarrieredIterativeAggregator, _weiszfeld_chunk


class GeometricMedian(BarrieredIterativeAggregator, Aggregator):
    """Weiszfeld-iterated geometric median of the gradient rows."""
    name = "geometric-median"
    _barrier_chunk_fn = staticmethod(_weiszfeld_chunk)

    def __init__(
        self,
        *,
        tol: float = 1e-6,
        max_iter: int = 256,
        eps: float = 1e-12,
        init: str = "median",
    ) -> None:
        if tol <= 0:
            raise ValueError("tol must be > 0")
        if max_iter <= 0:
            raise ValueError("max_iter must be > 0")
        if eps <= 0:
            raise ValueError("eps must be > 0")
        if init not in {"median", "mean"}:
            raise ValueError("init must be 'median' or 'mean'")
        self.tol = float(tol)
        self.max_iter = int(max_iter)
        self.eps = float(eps)
        self.init = init

    supports_masked_finalize = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.geometric_median(
            x, tol=self.tol, max_iter=self.max_iter, eps=self.eps, init=self.init
        )

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_geometric_median(
            x, valid, tol=self.tol, max_iter=self.max_iter,
            eps=self.eps, init=self.init,
        )

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Weiszfeld-weight view: each row's distance to the published
        geometric median (its implicit weight is ``∝ 1/distance`` at
        the fixed point, so a large score = a down-weighted row).
        Needs the round's ``aggregate``; returns None without it."""
        if aggregate is None:
            return None
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        center = np.asarray(aggregate, np.float32).reshape(-1)
        dists = np.linalg.norm(rows - center[None, :], axis=1)
        return self._evidence_view("geomed_distance", n, idx, dists)

    # -- barriered hooks (pool mode) -----------------------------------------

    def _barrier_params(self):
        return {"eps": self.eps}

    def _barrier_init(self, host: np.ndarray) -> np.ndarray:
        if self.init == "median":
            return np.median(host, axis=0)
        return host.mean(axis=0)

    def _barrier_update(self, partials, center):
        num = np.sum([p[0] for p in partials], axis=0)
        den = sum(p[1] for p in partials)
        return num / max(den, 1e-30)

    def _barrier_max_iters(self) -> int:
        return self.max_iter

    def _barrier_converged(self, old, new) -> bool:
        return float(np.linalg.norm(new - old)) <= self.tol


__all__ = ["GeometricMedian"]

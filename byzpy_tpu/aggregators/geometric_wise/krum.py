"""Krum / Multi-Krum (Blanchard et al. 2017)
(behavioral parity: ``byzpy/aggregators/geometric_wise/krum.py:82-475``).

TPU execution: the pairwise squared distances come from one Gram matmul
(MXU work); with the matrix feature-sharded, each chip computes a partial
Gram and XLA psums the tiny ``(n, n)`` block — O(n^2) bytes over ICI
instead of the reference's O(n*d) shm traffic per chunk. Selection is a
replicated top-q over an ``(n,)`` score vector.

The pool-chunked path scores row ranges against the full matrix, the
reference's subtask layout (``krum.py:371-475``) without the shm handles.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ...utils import placement
from ..base import Aggregator, ravel_gradient
from ..chunked import RowScoredAggregator


class _GramFoldState:
    """Incremental Gram state for streaming Multi-Krum: each arriving
    gradient lands in a donated ``(n, d)`` staging buffer and
    contributes its Gram row/column through ONE donated matvec dispatch
    (``ops.robust.gram_fold_update`` — the old design paid k separate
    einsum dispatches on arrival k, O(n²) host dispatches per round,
    plus a full-matrix copy per insert and an O(n)-step Gram assembly
    at the barrier). The O(n²·d) Gram — the dominant cost of Krum
    scoring — is complete the moment the last straggler lands, indexed
    in canonical slot order (selection tie rules see the same row
    indices as the barrier path). Finalize runs score + selection
    straight from the staged matrix and Gram — on TPU at large ``d``
    as ONE fused Pallas pass
    (``pallas_kernels.selection_mean_from_gram_pallas``)."""

    __slots__ = ("n", "buffer", "gram", "present", "unravel", "dim", "filled")

    def __init__(self, n: int) -> None:
        if n <= 0:
            raise ValueError(f"fold_init needs n >= 1 (got {n})")
        self.n = n
        self.buffer: Optional[jnp.ndarray] = None  # (n, d) staged rows
        self.gram: Optional[jnp.ndarray] = None  # (n, n) accumulator
        self.present = [False] * n
        self.unravel = None
        self.dim: Optional[int] = None
        self.filled = 0


def _krum_score_rows(host: np.ndarray, start: int, end: int, *, f: int) -> jnp.ndarray:
    """Scores for rows [start, end): sum of the n-f-1 smallest squared
    distances to other rows."""
    x = jnp.asarray(host)
    block = x[start:end]
    n = x.shape[0]
    d2 = (
        jnp.sum(block * block, axis=1, keepdims=True)
        + jnp.sum(x * x, axis=1)[None, :]
        - 2.0 * block @ x.T
    )
    d2 = jnp.maximum(d2, 0.0)
    # mask self-distance per row, then sum the n-f-1 smallest
    rows = jnp.arange(start, end)
    d2 = d2.at[jnp.arange(end - start), rows].set(jnp.inf)
    sortd = jnp.sort(d2, axis=1)
    return jnp.sum(sortd[:, : n - f - 1], axis=1)


class MultiKrum(RowScoredAggregator, Aggregator):
    """Average the q rows with the best Krum scores (sum of distances to each row's n - f - 1 nearest neighbors)."""
    name = "multi-krum"
    _score_fn = staticmethod(_krum_score_rows)

    def __init__(self, f: int, q: int, *, chunk_size: int = 32) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if q < 1:
            raise ValueError("q must be >= 1")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.q = int(q)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n - 1:
            raise ValueError(f"f must satisfy 0 <= f < n-1 (got n={n}, f={self.f})")
        if self.q > n - self.f:
            raise ValueError(
                f"q must satisfy 1 <= q <= n - f (got n={n}, f={self.f}, q={self.q})"
            )

    def _score_params(self):
        return {"f": self.f}

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        return robust.ranked_mean(matrix, scores, self.q)

    supports_masked_finalize = True
    evidence_selects = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.multi_krum(x, f=self.f, q=self.q)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_multi_krum(x, valid, f=self.f, q=self.q)

    def _masked_view(self, state):
        # the Gram fold's staging buffer is exactly a padded matrix
        # (zero rows for absent slots); the masked program recomputes
        # the Gram from it the way the barrier path would, so parity is
        # bit-for-bit rather than the incremental fold's tolerance-level
        if state.buffer is None:
            return None
        return state.buffer, list(state.present), state.unravel

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.multi_krum_stream(xs, f=self.f, q=self.q)

    ragged_score_kind = "krum_distance"
    #: one shared Gram scores the whole batch — coalescing wins
    ragged_coalesce = True

    def ragged_matrix_fn(self):
        """Specialized ragged program: ONE shared Gram scores every
        cohort in the batch (``ops.ragged.ragged_multi_krum``); the
        Krum-distance scores + lowest-``q`` keep set ride along as the
        fused forensics view."""
        from ...ops import ragged as ragged_ops

        f, q = self.f, self.q

        def fn(flat, seg, offsets, lengths, *, n_cohorts, segment_sum=None):
            return ragged_ops.ragged_multi_krum(
                flat, seg, lengths, f=f, q=q, n_cohorts=n_cohorts,
                segment_sum=segment_sum,
            )

        return fn

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Krum-distance scores + the lowest-``q`` selection, scattered
        to padded positions (host-side; tie rule = the aggregation
        program's stable lowest-``q`` pick)."""
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        scores = np.asarray(robust.krum_scores(jnp.asarray(rows), f=self.f))
        keep_local = np.argsort(scores, kind="stable")[: int(self.q)]
        return self._evidence_view("krum_distance", n, idx, scores, keep_local)

    # -- hierarchical partial fold (sharded serving tier) -----------------

    #: the merged score view reads the assembled Gram, never the round
    #: aggregate — the root's off-path finalize overlaps it with the
    #: device program
    merged_view_from_extras = True

    def _partial_extras(self, rows) -> dict:
        """One shard's local Gram block over its discounted rows — the
        streaming Gram accumulation's sharded form, through the
        CANONICAL block contraction (:func:`ops.robust.gram_block`: the
        block-contraction contract's one dot program, so every
        downstream verifier compares exact bits). The root reuses it
        as the diagonal block of the merged cohort's full Gram; only
        the cross-shard blocks remain to compute at merge. An
        adversarial NaN/inf row yields NaN Gram entries — advisory
        only: the merged finalize reads rows, not extras, and routes
        non-finite cohorts to the exact path."""
        return {"gram": robust.gram_block(rows, rows)}

    def _merge_extras(self, extras_list, partials) -> dict:
        """Assemble the merged cohort's ``(m, m)`` Gram: shard-local
        blocks dropped onto the diagonal (recomputed when a shard
        shipped none — the summary is deterministic), cross-shard
        blocks via one :func:`ops.robust.gram_block` per shard pair
        (the irreducible remainder: cross inner products need both
        shards' rows). The incremental accumulator
        (:meth:`fold_merge_add`) computes the SAME blocks at arrival —
        same function, same operands, so streaming-then-finish and
        this barrier path publish bit-identical Grams."""
        mats = [np.asarray(p["rows"], np.float32) for p in partials]
        sizes = [m.shape[0] for m in mats]
        offs = np.cumsum([0] + sizes)
        total = int(offs[-1])
        gram = np.zeros((total, total), np.float32)
        for i, mi in enumerate(mats):
            e = extras_list[i]
            block = (
                np.asarray(e["gram"], np.float32)
                if e and "gram" in e
                else robust.gram_block(mi, mi)
            )
            gram[offs[i]:offs[i + 1], offs[i]:offs[i + 1]] = block
            for j in range(i + 1, len(mats)):
                cross = robust.gram_block(mi, mats[j])
                gram[offs[i]:offs[i + 1], offs[j]:offs[j + 1]] = cross
                gram[offs[j]:offs[j + 1], offs[i]:offs[i + 1]] = cross.T
        return {"gram": gram}

    def combined_extras(self, children) -> dict:
        """Blockwise extras for a merge-tree COMBINED frame: each
        child's shipped Gram drops onto its diagonal region verbatim
        (it is itself the leaf-blockwise assembly, by induction down
        the tree), and only the CROSS blocks between children are
        computed — one :func:`ops.robust.gram_block` per LEAF-segment
        pair, O(m_i·m_j·d), replacing the old full O(m²·d) recompute
        at every tree level. Leaf granularity is load-bearing: the
        parent's ``extras_policy='verify'`` check recomputes per leaf
        pair (:meth:`segmented_extras_reference`), and a single big
        cross matmul would only match those blocks to matmul
        tolerance."""
        if not any(e for _sp, _r, e in children):
            return {}
        prepared = []  # (rows_f32, local spans, shipped gram or None)
        total = 0
        for spans, rows, extras in children:
            rows = np.asarray(rows, np.float32)
            shipped = None
            if extras and "gram" in extras:
                shipped = np.asarray(extras["gram"], np.float32)
            prepared.append((rows, tuple(spans), shipped))
            total += int(rows.shape[0])
        gram = np.zeros((total, total), np.float32)
        off = 0
        offsets = []
        for rows, spans, shipped in prepared:
            m = int(rows.shape[0])
            offsets.append(off)
            if shipped is not None:
                gram[off:off + m, off:off + m] = shipped
            else:
                # child shipped no Gram: recompute its diagonal region
                # leaf-blockwise — the verifier's granularity
                for i, (_sa, la, ha) in enumerate(spans):
                    for _sb, lb, hb in spans[i:]:
                        blk = robust.gram_block(rows[la:ha], rows[lb:hb])
                        gram[off + la:off + ha, off + lb:off + hb] = blk
                        if lb != la:
                            gram[off + lb:off + hb, off + la:off + ha] = (
                                blk.T
                            )
            off += m
        for i, (rows_i, spans_i, _si) in enumerate(prepared):
            for j in range(i + 1, len(prepared)):
                rows_j, spans_j, _sj = prepared[j]
                for _sa, la, ha in spans_i:
                    for _sb, lb, hb in spans_j:
                        blk = robust.gram_block(
                            rows_i[la:ha], rows_j[lb:hb]
                        )
                        gram[
                            offsets[i] + la:offsets[i] + ha,
                            offsets[j] + lb:offsets[j] + hb,
                        ] = blk
                        gram[
                            offsets[j] + lb:offsets[j] + hb,
                            offsets[i] + la:offsets[i] + ha,
                        ] = blk.T
        return {"gram": gram}

    def segmented_extras_reference(self, rows, spans) -> dict:
        """The verifier's half of the block-contraction contract: the
        Gram of a segmented frame recomputed PER LEAF-SEGMENT PAIR with
        the same :func:`ops.robust.gram_block` the assembly used — an
        honest combined frame matches to the exact bit (pinned by
        ``tests/test_closepath.py``); >0 ulp of drift is a forged
        frame, not tolerance."""
        rows = np.asarray(rows, np.float32)
        spans = tuple(spans)
        if len(spans) <= 1:
            return self._partial_extras(rows)
        total = int(rows.shape[0])
        gram = np.zeros((total, total), np.float32)
        for i, (_sa, la, ha) in enumerate(spans):
            for _sb, lb, hb in spans[i:]:
                blk = robust.gram_block(rows[la:ha], rows[lb:hb])
                gram[la:ha, lb:hb] = blk
                if lb != la:
                    gram[lb:hb, la:ha] = blk.T
        return {"gram": gram}

    # -- incremental merge accumulator: cross blocks at arrival -----------

    def fold_merge_begin(self) -> dict:
        state = super().fold_merge_begin()
        state.update(
            diag={}, cross={}, any_extras=False,
            cross_blocks=0, transforms=0,
        )
        return state

    def fold_merge_add(self, state, shard, partial) -> None:
        """Park the partial AND do its heavy merge work now, on the
        arrival thread: its diagonal block (shipped, or recomputed —
        counted as a ``transform``) and the cross-Gram blocks against
        every partial already parked (O(m_i·m_j·d) each, counted as
        ``cross_blocks``). By the time the LAST partial lands the full
        Gram exists in blocks; :meth:`fold_merge_finish` only places
        them — the close's critical path keeps the concat and the
        placement, not the matmuls."""
        super().fold_merge_add(state, shard, partial)
        if partial.get("extras") and "gram" in partial["extras"]:
            state["diag"][int(shard)] = np.asarray(
                partial["extras"]["gram"], np.float32
            )
            state["any_extras"] = True
        # Gram blocks only exist when the merged fold will carry extras
        # at all (the base fold_merge gate: any partial shipped some);
        # once that is known, keep the block set complete on every add
        if state["any_extras"]:
            self._complete_blocks(state)

    def _complete_blocks(self, state) -> None:
        """Compute every missing diagonal/cross block for the parked
        set, in canonical (ascending-shard) orientation. Incremental in
        steady state — after partial k arrives only its k-1 new cross
        blocks are missing; idempotent at finish."""
        parked = state["parked"]
        for key, inp in parked.items():
            if key not in state["diag"]:
                rows = np.asarray(inp["rows"], np.float32)
                state["diag"][key] = robust.gram_block(rows, rows)
                state["transforms"] += 1
        keys = sorted(parked)
        for i, a in enumerate(keys):
            for b in keys[i + 1:]:
                if (a, b) not in state["cross"]:
                    state["cross"][(a, b)] = robust.gram_block(
                        np.asarray(parked[a]["rows"], np.float32),
                        np.asarray(parked[b]["rows"], np.float32),
                    )
                    state["cross_blocks"] += 1

    def fold_merge_finish(self, state) -> dict:
        """Close the accumulator: sorted-shard-order row concat (the
        exact barrier concat) plus pure PLACEMENT of the blocks
        computed at arrival — zero matmuls here. Bit-identical to
        ``fold_merge`` → :meth:`_merge_extras` of the same partials by
        construction: same :func:`ops.robust.gram_block` calls on the
        same operands, same orientation. ``merged["merge_stats"]``
        carries the accumulated block counts for the root's
        zero-redundant-recompute accounting."""
        parked = state["parked"]
        if not parked:
            raise ValueError("fold_merge_finish on an empty accumulator")
        if not state["any_extras"]:
            merged = self.fold_merge([parked[s] for s in sorted(parked)])
            merged["merge_stats"] = {
                "cross_blocks": state["cross_blocks"],
                "transforms": state["transforms"],
            }
            return merged
        self._complete_blocks(state)
        keys = sorted(parked)
        mats = [np.asarray(parked[s]["rows"], np.float32) for s in keys]
        dims = {m.shape[1] for m in mats if m.ndim == 2}
        if len(dims) > 1:
            raise ValueError(
                f"partials disagree on gradient dimension: {sorted(dims)}"
            )
        rows = np.concatenate(mats, axis=0)
        offs = np.cumsum([0] + [m.shape[0] for m in mats])
        total = int(offs[-1])
        gram = np.zeros((total, total), np.float32)
        for i, a in enumerate(keys):
            gram[offs[i]:offs[i + 1], offs[i]:offs[i + 1]] = (
                state["diag"][a]
            )
            for j in range(i + 1, len(keys)):
                blk = state["cross"][(a, keys[j])]
                gram[offs[i]:offs[i + 1], offs[j]:offs[j + 1]] = blk
                gram[offs[j]:offs[j + 1], offs[i]:offs[i + 1]] = blk.T
        return {
            "rows": rows,
            "m": total,
            "offsets": [int(o) for o in offs[:-1]],
            "extras": {"gram": gram},
            "merge_stats": {
                "cross_blocks": state["cross_blocks"],
                "transforms": state["transforms"],
            },
        }

    def merged_score_view(self, merged, *, aggregate=None):
        """Krum-distance scores straight from the merged Gram (pairwise
        squared distances are a Gram read: ``g_ii + g_jj − 2 g_ij``) —
        the root's forensics view without a second O(m²·d) row pass.
        Tie rule matches :meth:`round_evidence` (stable lowest-``q``)."""
        extras = merged.get("extras") or {}
        gram = extras.get("gram")
        m = int(merged["m"])
        if gram is None or m == 0:
            return super().merged_score_view(merged, aggregate=aggregate)
        try:
            self.validate_n(m)
        except ValueError:
            return None
        g = np.asarray(gram, np.float32)
        diag = np.diag(g)
        d2 = np.maximum(diag[:, None] + diag[None, :] - 2.0 * g, 0.0)
        np.fill_diagonal(d2, np.inf)
        d2.sort(axis=1)
        scores = d2[:, : m - self.f - 1].sum(axis=1).astype(np.float32)
        keep = np.zeros((m,), bool)
        keep[np.argsort(scores, kind="stable")[: self.q]] = True
        return {"kind": "krum_distance", "scores": scores, "keep": keep}

    # -- arrival-order streaming fold ------------------------------------

    def fold_init(self, n: int) -> Any:
        return _GramFoldState(n)

    def fold(self, state: Any, index: int, gradient: Any) -> None:
        if not 0 <= index < state.n:
            raise IndexError(f"slot {index} outside [0, {state.n})")
        if state.present[index]:
            raise ValueError(f"slot {index} folded twice")
        row, unravel = ravel_gradient(gradient)
        if state.dim is None:
            state.dim = int(row.shape[0])
            state.unravel = unravel
        elif int(row.shape[0]) != state.dim:
            raise ValueError(
                f"all gradients must flatten to the same length "
                f"(got {row.shape[0]} != {state.dim})"
            )
        with placement.on(placement.compute_device(row)):
            if state.buffer is None:
                acc = (
                    jnp.float32
                    if row.dtype in (jnp.bfloat16, jnp.float16)
                    else row.dtype
                )
                state.buffer = jnp.zeros((state.n, state.dim), row.dtype)
                state.gram = jnp.zeros((state.n, state.n), acc)
            elif row.dtype != state.buffer.dtype:
                # mixed dtypes in one round: promote the staged state the
                # way jnp.stack would promote the barrier matrix (an
                # exact upcast of everything staged so far; the donated
                # update below would otherwise silently DOWNCAST this
                # row to the first arrival's dtype)
                promo = jnp.promote_types(state.buffer.dtype, row.dtype)
                acc = (
                    jnp.float32
                    if promo in (jnp.bfloat16, jnp.float16)
                    else promo
                )
                state.buffer = state.buffer.astype(promo)
                state.gram = state.gram.astype(acc)
            state.buffer, state.gram = robust.gram_fold_update(
                state.buffer, state.gram, row, index
            )
        state.present[index] = True
        state.filled += 1

    def fold_finalize(self, state: Any) -> Any:
        m = state.filled
        self.validate_n(m)
        if state.buffer is None:
            raise ValueError("fold_finalize before any gradient was folded")
        with placement.on(placement.compute_device(state.buffer)):
            if m == state.n:
                matrix, gram = state.buffer, state.gram
            else:
                # elastic partial round: gather the arrived subset (the
                # Gram's absent rows/columns were never written past
                # their zero init)
                idx = jnp.asarray(
                    np.flatnonzero(np.asarray(state.present)), jnp.int32
                )
                matrix = state.buffer[idx]
                gram = state.gram[idx][:, idx]
            return state.unravel(
                robust.multi_krum_from_gram(matrix, gram, f=self.f, q=self.q)
            )


class Krum(MultiKrum):
    """Classic Krum: the single lowest-score gradient (Multi-Krum q=1;
    ref: ``krum.py:302-368``)."""

    name = "krum"

    def __init__(self, f: int, *, chunk_size: int = 32) -> None:
        super().__init__(f, 1, chunk_size=chunk_size)


__all__ = ["MultiKrum", "Krum"]

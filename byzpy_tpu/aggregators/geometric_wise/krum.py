"""Krum / Multi-Krum (Blanchard et al. 2017)
(behavioral parity: ``byzpy/aggregators/geometric_wise/krum.py:82-475``).

TPU execution: the pairwise squared distances come from one Gram matmul
(MXU work); with the matrix feature-sharded, each chip computes a partial
Gram and XLA psums the tiny ``(n, n)`` block — O(n^2) bytes over ICI
instead of the reference's O(n*d) shm traffic per chunk. Selection is a
replicated top-q over an ``(n,)`` score vector.

The pool-chunked path scores row ranges against the full matrix, the
reference's subtask layout (``krum.py:371-475``) without the shm handles.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ...utils import placement
from ..base import Aggregator, SlotFoldState
from ..chunked import RowScoredAggregator


class _GramFoldState:
    """Incremental Gram state for streaming Multi-Krum: each arriving
    gradient contributes its dot products against the rows already in
    hand (O(k·d) work on arrival ``k``), so the O(n²·d) Gram — the
    dominant cost of Krum scoring — is complete the moment the last
    straggler lands. Finalize assembles the ``(n, n)`` Gram in canonical
    slot order (selection tie rules see the same row indices as the
    barrier path) and runs score + masked-mean selection
    (``ops.robust.multi_krum_from_gram``)."""

    __slots__ = ("slots", "arrival", "dots")

    def __init__(self, n: int) -> None:
        self.slots = SlotFoldState(n)
        self.arrival: list = []  # slot indices in arrival order
        self.dots: list = []  # k-th entry: (k+1,) dots vs arrivals 0..k


def _krum_score_rows(host: np.ndarray, start: int, end: int, *, f: int) -> jnp.ndarray:
    """Scores for rows [start, end): sum of the n-f-1 smallest squared
    distances to other rows."""
    x = jnp.asarray(host)
    block = x[start:end]
    n = x.shape[0]
    d2 = (
        jnp.sum(block * block, axis=1, keepdims=True)
        + jnp.sum(x * x, axis=1)[None, :]
        - 2.0 * block @ x.T
    )
    d2 = jnp.maximum(d2, 0.0)
    # mask self-distance per row, then sum the n-f-1 smallest
    rows = jnp.arange(start, end)
    d2 = d2.at[jnp.arange(end - start), rows].set(jnp.inf)
    sortd = jnp.sort(d2, axis=1)
    return jnp.sum(sortd[:, : n - f - 1], axis=1)


class MultiKrum(RowScoredAggregator, Aggregator):
    """Average the q rows with the best Krum scores (sum of distances to each row's n - f - 1 nearest neighbors)."""
    name = "multi-krum"
    _score_fn = staticmethod(_krum_score_rows)

    def __init__(self, f: int, q: int, *, chunk_size: int = 32) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if q < 1:
            raise ValueError("q must be >= 1")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.q = int(q)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n - 1:
            raise ValueError(f"f must satisfy 0 <= f < n-1 (got n={n}, f={self.f})")
        if self.q > n - self.f:
            raise ValueError(
                f"q must satisfy 1 <= q <= n - f (got n={n}, f={self.f}, q={self.q})"
            )

    def _score_params(self):
        return {"f": self.f}

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        return robust.ranked_mean(matrix, scores, self.q)

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.multi_krum(x, f=self.f, q=self.q)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.multi_krum_stream(xs, f=self.f, q=self.q)

    # -- arrival-order streaming fold ------------------------------------

    def fold_init(self, n: int) -> Any:
        return _GramFoldState(n)

    def fold(self, state: Any, index: int, gradient: Any) -> None:
        row = state.slots.insert(index, gradient)
        with placement.on(placement.compute_device(row)):
            acc = (
                jnp.float32
                if row.dtype in (jnp.bfloat16, jnp.float16)
                else row.dtype
            )
            dots = [
                jnp.einsum(
                    "d,d->", state.slots.rows[j], row,
                    preferred_element_type=acc,
                )
                for j in state.arrival
            ]
            dots.append(
                jnp.einsum("d,d->", row, row, preferred_element_type=acc)
            )
            state.dots.append(jnp.stack(dots).astype(acc))
        state.arrival.append(index)

    def fold_finalize(self, state: Any) -> Any:
        m = len(state.arrival)
        self.validate_n(m)
        # arrival rank of each canonical (slot-sorted) row
        rank = {slot: k for k, slot in enumerate(state.arrival)}
        perm = np.asarray(
            [rank[s] for s in sorted(state.arrival)], dtype=np.int32
        )
        with placement.on(placement.compute_device(state.slots.rows)):
            matrix, unravel = state.slots.stacked()
            acc = state.dots[0].dtype if state.dots else matrix.dtype
            gram = jnp.zeros((m, m), acc)
            for k, dvec in enumerate(state.dots):
                gram = gram.at[k, : k + 1].set(dvec)
            # mirror the lower triangle (diagonal already in place)
            gram = gram + jnp.tril(gram, -1).T
            gram = gram[perm][:, perm]
            return unravel(
                robust.multi_krum_from_gram(matrix, gram, f=self.f, q=self.q)
            )


class Krum(MultiKrum):
    """Classic Krum: the single lowest-score gradient (Multi-Krum q=1;
    ref: ``krum.py:302-368``)."""

    name = "krum"

    def __init__(self, f: int, *, chunk_size: int = 32) -> None:
        super().__init__(f, 1, chunk_size=chunk_size)


__all__ = ["MultiKrum", "Krum"]

"""Minimum Diameter Averaging: exact search over ``(n - f)``-subsets
(behavioral parity: ``byzpy/aggregators/geometric_wise/minimum_diameter_average.py:80-444``).

Subset enumeration is combinatorial and stays on the host (as in the
reference); scoring is batched on device: the ``(n, n)`` distance matrix is
computed once, then ``vmap``-gathered diameters for combination batches.
The pool path fans combination ranges out to workers.
"""

from __future__ import annotations

import math
from itertools import islice
from typing import Iterable

import numpy as np
import jax.numpy as jnp

from ...engine.graph.chunking import select_adaptive_chunk_size
from ...engine.graph.operator import OpContext
from ...engine.graph.subtask import SubTask
from ...ops import robust
from ...utils.combinatorics import iter_combinations
from ...utils.trees import stack_gradients
from ..base import Aggregator

_DEVICE_BATCH = 4096


def _combo_batches(n: int, m: int, batch: int) -> Iterable[np.ndarray]:
    it = iter_combinations(n, m)
    while True:
        block = list(islice(it, batch))
        if not block:
            return
        yield np.asarray(block, dtype=np.int32)


def _score_combo_range(
    host_d2: np.ndarray, n: int, m: int, start: int, count: int
) -> tuple[float, np.ndarray]:
    """Best (min-diameter) combo among combinations [start, start+count)."""
    d2 = jnp.asarray(host_d2)
    it = islice(iter_combinations(n, m, start), count)
    best_score = math.inf
    best_combo: np.ndarray | None = None
    while True:
        block = list(islice(it, _DEVICE_BATCH))
        if not block:
            break
        combos = jnp.asarray(np.asarray(block, dtype=np.int32))
        scores = robust.subset_diameters(d2, combos)
        i = int(jnp.argmin(scores))
        score = float(scores[i])
        if score < best_score:
            best_score = score
            best_combo = np.asarray(combos[i])
    assert best_combo is not None
    return best_score, best_combo


class MinimumDiameterAveraging(Aggregator):
    name = "minimum-diameter-averaging"
    supports_subtasks = True

    def __init__(self, f: int, *, chunk_size: int = 20000) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n:
            raise ValueError(f"f must satisfy 0 <= f < n (got n={n}, f={self.f})")

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        m = n - self.f
        d2 = robust.pairwise_sq_dists(x)
        best_score = math.inf
        best_combo: jnp.ndarray | None = None
        for combos in _combo_batches(n, m, _DEVICE_BATCH):
            scores = robust.subset_diameters(d2, jnp.asarray(combos))
            i = int(jnp.argmin(scores))
            score = float(scores[i])
            if score < best_score:
                best_score = score
                best_combo = jnp.asarray(combos[i])
        assert best_combo is not None
        return robust.subset_mean(x, best_combo)

    # -- pool path ----------------------------------------------------------

    def create_subtasks(self, inputs, *, context: OpContext):
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        n = matrix.shape[0]
        m = n - self.f
        total = math.comb(n, m)
        host_d2 = np.asarray(robust.pairwise_sq_dists(matrix))
        metadata = getattr(context, "metadata", None) or {}
        chunk = select_adaptive_chunk_size(
            total, self.chunk_size, pool_size=int(metadata.get("pool_size") or 0)
        )

        def gen():
            for start in range(0, total, chunk):
                count = min(chunk, total - start)
                yield SubTask(
                    fn=_score_combo_range,
                    args=(host_d2, n, m, start, count),
                    name=f"mda-combos[{start}:{start + count}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext):
        best_score, best_combo = min(partials, key=lambda p: p[0])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(robust.subset_mean(matrix, jnp.asarray(best_combo)))


__all__ = ["MinimumDiameterAveraging"]

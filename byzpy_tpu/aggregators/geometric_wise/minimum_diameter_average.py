"""Minimum Diameter Averaging: exact search over ``(n - f)``-subsets
(behavioral parity: ``byzpy/aggregators/geometric_wise/minimum_diameter_average.py:80-444``).

The search is exact branch-and-bound on the host — the reference prunes a
DFS with a per-seed incumbent (``_search_seed``, minimum_diameter_average.py:359-380);
here the incumbent is **global** and pre-seeded with a greedy-peeling upper
bound, which prunes strictly harder. The ``(n, n)`` distance matrix comes
off the device once (``ops.robust.pairwise_sq_dists``); the subset search
itself is tiny host data, combinatorial by nature (SURVEY §7 hard part b).

A batched device scorer (``subset_diameters`` over combo index arrays) is
kept for the pool fan-out path and for validating the B&B result.
"""

from __future__ import annotations

import math
from itertools import combinations, islice
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ...engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ...engine.graph.operator import OpContext
from ...engine.graph.subtask import SubTask
from ...ops import robust
from ...utils.combinatorics import iter_combinations
from ...utils.trees import stack_gradients
from ..base import Aggregator

_DEVICE_BATCH = 4096

# below this many elements the host matmul beats a device round-trip (the
# search itself is host-side, so a device d2 must come back anyway)
_HOST_D2_ELEMENTS = 1 << 22


def _dists_for_search(x: jnp.ndarray) -> np.ndarray:
    if x.size <= _HOST_D2_ELEMENTS:
        arr = np.asarray(x, dtype=np.float64 if x.dtype == jnp.float64 else np.float32)
        norms = np.sum(arr * arr, axis=1, keepdims=True)
        d2 = norms + norms.T - 2.0 * (arr @ arr.T)
        return np.maximum(d2, 0.0)
    return np.asarray(robust.pairwise_sq_dists(x))


# ---------------------------------------------------------------------------
# Exact search: greedy bound + branch-and-bound DFS
# ---------------------------------------------------------------------------


def greedy_peel_bound(d2: np.ndarray, m: int) -> Tuple[float, List[int]]:
    """Upper bound: repeatedly drop the point with the largest max-distance
    to the survivors until ``m`` remain. O(n^2) and usually near-optimal —
    a strong incumbent for the B&B."""
    alive = list(range(d2.shape[0]))
    while len(alive) > m:
        sub = d2[np.ix_(alive, alive)]
        worst = int(np.argmax(sub.max(axis=1)))
        alive.pop(worst)
    diam = float(d2[np.ix_(alive, alive)].max()) if len(alive) > 1 else 0.0
    return diam, alive


def branch_and_bound_min_diameter(
    d2: np.ndarray,
    m: int,
    *,
    prefixes: Optional[Iterable[Sequence[int]]] = None,
    initial_bound: float = math.inf,
    initial_combo: Optional[Sequence[int]] = None,
) -> Tuple[float, List[int]]:
    """Exact minimum-diameter ``m``-subset by DFS over increasing indices.

    A branch extends the current set with index ``idx``; its diameter so
    far is the running max distance, and any branch whose max already
    reaches the incumbent is cut. ``initial_bound`` prunes from the very
    first branch even without ``initial_combo`` — a fully pruned search
    returns ``(initial_bound, [])``, meaning nothing beat the bound. With
    ``prefixes``, only subsets starting with one of the given index
    prefixes are explored (the pool-partitioned search; the incumbent
    still tightens across prefixes within one call).
    """
    n = d2.shape[0]
    best = [float(initial_bound), list(initial_combo or [])]

    def dfs(indices: List[int], current: float, start: int, remain: int) -> None:
        if remain == 0:
            if current < best[0]:
                best[0], best[1] = current, list(indices)
            return
        for idx in range(start, n - remain + 1):
            new_max = current
            if indices:
                row = d2[idx, indices]
                new_max = max(current, float(row.max()))
            if new_max >= best[0]:
                continue  # bound: cannot beat the incumbent
            indices.append(idx)
            dfs(indices, new_max, idx + 1, remain - 1)
            indices.pop()

    if prefixes is None:
        prefixes = [()]
    for prefix in prefixes:
        prefix = list(prefix)
        if len(prefix) > m:
            continue
        current = (
            float(d2[np.ix_(prefix, prefix)].max()) if len(prefix) > 1 else 0.0
        )
        if current >= best[0]:
            continue
        start = (prefix[-1] + 1) if prefix else 0
        dfs(prefix, current, start, m - len(prefix))
    return best[0], best[1]


def _exact_min_diameter(d2: np.ndarray, m: int) -> List[int]:
    bound, combo = greedy_peel_bound(d2, m)
    # strict-improvement DFS keeps the greedy combo unless something beats it
    _, best = branch_and_bound_min_diameter(
        d2, m, initial_bound=bound, initial_combo=combo
    )
    return best


# ---------------------------------------------------------------------------
# Device-batched scorer (pool path + validation)
# ---------------------------------------------------------------------------


def _combo_batches(
    n: int, m: int, batch: int, *, start: int = 0, count: int | None = None
) -> Iterable[np.ndarray]:
    """Fixed-size ``(batch, m)`` blocks; the tail is padded by repeating its
    first combo so every device call shares one compiled shape (padding
    can't win the min — it duplicates a real candidate)."""
    it = iter_combinations(n, m, start)
    if count is not None:
        it = islice(it, count)
    while True:
        block = list(islice(it, batch))
        if not block:
            return
        arr = np.asarray(block, dtype=np.int32)
        if arr.shape[0] < batch:
            pad = np.repeat(arr[:1], batch - arr.shape[0], axis=0)
            arr = np.concatenate([arr, pad], axis=0)
        yield arr


def _device_best(
    matrix: jnp.ndarray,
    batches: Iterable[np.ndarray],
    score_fn=robust.subset_diameters,
) -> tuple[float, np.ndarray]:
    """Scan batches keeping the per-batch best ON DEVICE; a single host
    sync at the end picks the global winner (each intermediate force would
    cost a device round-trip per batch — the dominant cost over a TPU
    tunnel). ``score_fn(matrix, combos) -> (c,) scores``; minimum wins."""
    best_scores = []
    best_combos = []
    for combos in batches:
        combos = jnp.asarray(combos)
        scores = score_fn(matrix, combos)
        i = jnp.argmin(scores)
        best_scores.append(scores[i])
        best_combos.append(combos[i])
    stacked = jnp.stack(best_scores)
    k = int(jnp.argmin(stacked))  # the one host sync
    return float(stacked[k]), np.asarray(best_combos[k])


def _score_combo_range(
    host_d2: np.ndarray, n: int, m: int, start: int, count: int
) -> tuple[float, np.ndarray]:
    """Best (min-diameter) combo among combinations [start, start+count)
    — brute-force device scoring for explicit-range pool subtasks."""
    d2 = jnp.asarray(host_d2)
    batch = min(_DEVICE_BATCH, count)
    return _device_best(
        d2, _combo_batches(n, m, batch, start=start, count=count)
    )


def _search_seed_group(
    host_d2: np.ndarray, seeds: Tuple[Tuple[int, ...], ...], m: int, bound: float
) -> tuple[float, np.ndarray]:
    """Pool subtask: B&B restricted to the given seed prefixes (ref:
    ``_mda_best_subset_seeded``, minimum_diameter_average.py:297-325)."""
    score, combo = branch_and_bound_min_diameter(
        np.asarray(host_d2), m, prefixes=seeds, initial_bound=bound
    )
    return score, np.asarray(combo if combo else [], dtype=np.int32)


class MinimumDiameterAveraging(Aggregator):
    """Average of the (n - f)-subset with the smallest pairwise diameter, found by branch-and-bound over the device-computed distance matrix."""
    name = "minimum-diameter-averaging"
    supports_subtasks = True

    def __init__(
        self,
        f: int,
        *,
        chunk_size: int = 20000,
        seed_prefix: int = 2,
        seeds_per_task: int = 4,
    ) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)
        self.seed_prefix = int(seed_prefix)
        self.seeds_per_task = int(seeds_per_task)

    def validate_n(self, n: int) -> None:
        if self.f >= n:
            raise ValueError(f"f must satisfy 0 <= f < n (got n={n}, f={self.f})")

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        m = n - self.f
        d2 = _dists_for_search(x)
        combo = _exact_min_diameter(d2, m)
        return robust.subset_mean(x, jnp.asarray(combo, dtype=jnp.int32))

    # -- pool path ----------------------------------------------------------

    def create_subtasks(self, inputs, *, context: OpContext):
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        n = matrix.shape[0]
        m = n - self.f
        host_d2 = _dists_for_search(matrix)

        if 0 < self.seed_prefix < m:
            # partition the space by index prefixes; every task gets the
            # greedy incumbent so pruning starts tight everywhere. Tasks
            # where nothing beats it return an empty combo; if ALL do, the
            # greedy subset itself was optimal (reduce falls back to it).
            bound, _ = greedy_peel_bound(host_d2, m)
            depth = self.seed_prefix
            max_last = n - (m - depth) - 1

            def gen_seeded():
                group: List[Tuple[int, ...]] = []
                for seed in combinations(range(n), depth):
                    if seed[-1] > max_last:
                        continue
                    group.append(seed)
                    if len(group) >= self.seeds_per_task:
                        yield SubTask(
                            fn=_search_seed_group,
                            args=(host_d2, tuple(group), m, bound),
                            name=f"mda-seeds-{group[0]}",
                        )
                        group = []
                if group:
                    yield SubTask(
                        fn=_search_seed_group,
                        args=(host_d2, tuple(group), m, bound),
                        name=f"mda-seeds-{group[0]}",
                    )

            return gen_seeded()

        total = math.comb(n, m)
        chunk = select_adaptive_chunk_size(
            total, self.chunk_size, pool_size=pool_size_from_context(context)
        )

        def gen():
            for start in range(0, total, chunk):
                count = min(chunk, total - start)
                yield SubTask(
                    fn=_score_combo_range,
                    args=(host_d2, n, m, start, count),
                    name=f"mda-combos[{start}:{start + count}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext):
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        viable = [p for p in partials if len(np.atleast_1d(p[1]))]
        if not viable:
            # every seeded task was fully pruned by the shared bound: the
            # greedy incumbent is optimal (same d2 source as create_subtasks
            # so the recomputed combo matches the bound's derivation)
            d2 = _dists_for_search(matrix)
            _, combo = greedy_peel_bound(d2, matrix.shape[0] - self.f)
            return unravel(robust.subset_mean(matrix, jnp.asarray(combo, dtype=jnp.int32)))
        best_score, best_combo = min(viable, key=lambda p: p[0])
        return unravel(robust.subset_mean(matrix, jnp.asarray(best_combo)))


__all__ = [
    "MinimumDiameterAveraging",
    "branch_and_bound_min_diameter",
    "greedy_peel_bound",
]

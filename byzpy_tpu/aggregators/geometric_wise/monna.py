"""MoNNA: mean of the ``n - f`` nearest neighbors of a trusted reference
(behavioral parity: ``byzpy/aggregators/geometric_wise/monna.py:36-178``)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import RowScoredAggregator


def _monna_dist_rows(host: np.ndarray, start: int, end: int, *, reference_index: int) -> jnp.ndarray:
    x = jnp.asarray(host)
    diff = x[start:end] - x[reference_index][None, :]
    return jnp.sum(diff * diff, axis=1)


class MoNNA(RowScoredAggregator, Aggregator):
    """Mean of the n - f nearest neighbors of a trusted pivot row."""
    name = "monna"
    _score_fn = staticmethod(_monna_dist_rows)

    def __init__(self, f: int, *, reference_index: int = 0, chunk_size: int = 32) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if reference_index < 0:
            raise ValueError("reference_index must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.reference_index = int(reference_index)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(f"Cannot tolerate 2f >= n (got n={n}, f={self.f})")
        if not 0 <= self.reference_index < n:
            raise ValueError(
                f"reference_index must be between 0 and {n - 1} (got {self.reference_index})"
            )

    def _score_params(self):
        return {"reference_index": self.reference_index}

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        return robust.ranked_mean(matrix, scores, matrix.shape[0] - self.f)

    supports_masked_finalize = True
    evidence_selects = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.monna(x, f=self.f, reference_index=self.reference_index)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_monna(
            x, valid, f=self.f, reference_index=self.reference_index
        )

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.monna_stream(xs, f=self.f, reference_index=self.reference_index)

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Squared-distance-to-the-trusted-pivot scores + the
        nearest-``m − f`` selection (host-side, stable tie rule)."""
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        m = rows.shape[0]
        jrows = jnp.asarray(rows)
        ref = jrows[int(self.reference_index) % m]
        d2 = np.asarray(jnp.sum((jrows - ref[None, :]) ** 2, axis=1))
        keep_local = np.argsort(d2, kind="stable")[: m - int(self.f)]
        return self._evidence_view("reference_distance", n, idx, d2, keep_local)


__all__ = ["MoNNA"]

"""SMEA: Smallest Maximum Eigenvalue Averaging
(behavioral parity: ``byzpy/aggregators/geometric_wise/smea.py:110-228``).

The ``(n, n)`` Gram runs on the MXU; subset enumeration AND eigenvalue
scoring run on the host — each subset's score is the top eigenvalue of
its centered ``m x m`` Gram block via stacked LAPACK ``eigvalsh`` (TPUs
have no native eigensolver; see ``_score_combo_range_smea``). The winner's
rows are averaged on device. ``byzpy_tpu.ops.robust.subset_max_eigvals``
is the same score as a jitted device function (for mesh users); a parity
test pins the two together.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ...engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ...engine.graph.operator import OpContext
from ...engine.graph.subtask import SubTask
from ...ops import robust
from ...utils.trees import stack_gradients
from ..base import Aggregator

_DEVICE_BATCH = 2048


def _score_combo_range_smea(
    host_gram: np.ndarray, n: int, m: int, start: int, count: int
) -> tuple[float, np.ndarray]:
    """Best (min top-eigenvalue) combo in [start, start+count).

    Scores on the HOST: the expensive O(n^2 d) Gram already ran on the MXU;
    what remains is thousands of m x m symmetric eigenproblems, and TPUs
    have no native eigensolver (XLA lowers eigh to a serialized QR
    iteration — measured 380 ms for C(16,11) subsets where stacked LAPACK
    eigvalsh needs ~15 ms). Same split as MDA: enumeration + small-matrix
    work on host, bulk linear algebra on device."""
    from .minimum_diameter_average import _combo_batches

    h = np.eye(m) - np.full((m, m), 1.0 / m)
    batch = min(_DEVICE_BATCH, count)
    # A node whose gradient contains NaN/inf poisons its Gram row; LAPACK
    # eigvalsh raises on non-finite input, so subsets containing such a
    # node are scored +inf without ever entering the eigensolver (an
    # adversary must not be able to crash — or win — the selection).
    bad_row = ~np.isfinite(host_gram).all(axis=1)
    best_score, best_combo = np.inf, None
    for combos in _combo_batches(n, m, batch, start=start, count=count):
        sub = host_gram[combos[:, :, None], combos[:, None, :]]  # (c, m, m)
        centered = h @ sub @ h
        combo_bad = bad_row[combos].any(axis=1)
        if combo_bad.any():
            centered[combo_bad] = np.eye(m)
        top = np.linalg.eigvalsh(centered)[:, -1]
        scores = np.where(combo_bad, np.inf, np.maximum(top, 0.0) / m)
        i = int(np.argmin(scores))
        if best_combo is None or scores[i] < best_score:
            best_score, best_combo = float(scores[i]), combos[i]
    return best_score, np.asarray(best_combo)


class SMEA(Aggregator):
    name = "smea"
    supports_subtasks = True

    def __init__(self, f: int, *, chunk_size: int = 4096) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(f"2f must be < n (got n={n}, f={self.f})")

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        m = n - self.f
        gram = robust.gram_matrix(x)
        best_score, best_combo = _score_combo_range_smea(
            np.asarray(gram), n, m, 0, math.comb(n, m)
        )
        return robust.subset_mean(x, jnp.asarray(best_combo))

    def create_subtasks(self, inputs, *, context: OpContext):
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        n = matrix.shape[0]
        m = n - self.f
        total = math.comb(n, m)
        host_gram = np.asarray(robust.gram_matrix(matrix))
        chunk = select_adaptive_chunk_size(
            total, self.chunk_size, pool_size=pool_size_from_context(context)
        )

        def gen():
            for start in range(0, total, chunk):
                count = min(chunk, total - start)
                yield SubTask(
                    fn=_score_combo_range_smea,
                    args=(host_gram, n, m, start, count),
                    name=f"smea-combos[{start}:{start + count}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext):
        best_score, best_combo = min(partials, key=lambda p: p[0])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(robust.subset_mean(matrix, jnp.asarray(best_combo)))


__all__ = ["SMEA"]

"""SMEA: Smallest Maximum Eigenvalue Averaging
(behavioral parity: ``byzpy/aggregators/geometric_wise/smea.py:110-228``).

Two scoring paths, same score:

* **Device-pure** (default for combo spaces up to ``_DEVICE_COMBO_CAP``):
  Gram on the MXU, every subset's top eigenvalue via batched cyclic
  Jacobi (``ops.robust.subset_max_eigvals_jacobi``), argmin + winner mean
  on device. ONE dispatch, no host synchronization anywhere — on a
  remote-tunneled chip a mid-call host sync serializes every round on the
  full network round-trip (the round-2 host-LAPACK path measured 141 ms
  for the reference's 16x4096 workload; this path is RTT + ~2 ms).
* **Host LAPACK** (pool subtasks / huge combo spaces): stacked
  ``eigvalsh`` over chunked combo ranges, fanned out over the actor pool
  (``create_subtasks``), exactly like MDA.
"""

from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ...engine.graph.operator import OpContext
from ...engine.graph.subtask import SubTask
from ...ops import robust
from ...utils.trees import stack_gradients
from ..base import Aggregator

_DEVICE_BATCH = 2048
# Device-pure scoring materializes the (n_combos, m, m) centered blocks in
# HBM: 32768 x 32 x 32 f32 = 134 MB, a comfortable cap.
_DEVICE_COMBO_CAP = 32768
# The fixed-8-sweep Jacobi scorer is precision-validated for m <= 32
# (tests pin m=11 against LAPACK; convergence degrades slowly with m) --
# larger subsets take the exact host-LAPACK path.
_DEVICE_JACOBI_MAX_M = 32


@functools.lru_cache(maxsize=32)
def _device_combos(n: int, m: int) -> jnp.ndarray:
    from .minimum_diameter_average import _combo_batches

    parts = [np.asarray(c) for c in _combo_batches(n, m, _DEVICE_COMBO_CAP)]
    # _combo_batches pads its tail block by repeating the first combo;
    # slice back to the exact count (a duplicate can never win argmin's
    # first-occurrence tie-break, but don't score it twice either).
    return jnp.asarray(np.concatenate(parts, axis=0)[: math.comb(n, m)].astype(np.int32))


@jax.jit
def _smea_select_mean(x: jnp.ndarray, combos: jnp.ndarray) -> jnp.ndarray:
    """Gram -> Jacobi subset scores -> argmin -> winner mean, all on
    device (ties: first combo in enumeration order, like the host loop)."""
    gram = robust.gram_matrix(x)
    scores = robust.subset_max_eigvals_jacobi(gram, combos)
    best = jnp.argmin(scores)
    return jnp.mean(x[combos[best]], axis=0)


def _score_combo_range_smea(
    host_gram: np.ndarray, n: int, m: int, start: int, count: int
) -> tuple[float, np.ndarray]:
    """Best (min top-eigenvalue) combo in [start, start+count).

    Scores on the HOST: the expensive O(n^2 d) Gram already ran on the MXU;
    what remains is thousands of m x m symmetric eigenproblems, and TPUs
    have no native eigensolver (XLA lowers eigh to a serialized QR
    iteration — measured 380 ms for C(16,11) subsets where stacked LAPACK
    eigvalsh needs ~15 ms). Same split as MDA: enumeration + small-matrix
    work on host, bulk linear algebra on device."""
    from .minimum_diameter_average import _combo_batches

    h = np.eye(m) - np.full((m, m), 1.0 / m)
    batch = min(_DEVICE_BATCH, count)
    # A node whose gradient contains NaN/inf poisons its Gram row; LAPACK
    # eigvalsh raises on non-finite input, so subsets containing such a
    # node are scored +inf without ever entering the eigensolver (an
    # adversary must not be able to crash — or win — the selection).
    bad_row = ~np.isfinite(host_gram).all(axis=1)
    best_score, best_combo = np.inf, None
    for combos in _combo_batches(n, m, batch, start=start, count=count):
        sub = host_gram[combos[:, :, None], combos[:, None, :]]  # (c, m, m)
        centered = h @ sub @ h
        combo_bad = bad_row[combos].any(axis=1)
        if combo_bad.any():
            centered[combo_bad] = np.eye(m)
        top = np.linalg.eigvalsh(centered)[:, -1]
        scores = np.where(combo_bad, np.inf, np.maximum(top, 0.0) / m)
        i = int(np.argmin(scores))
        if best_combo is None or scores[i] < best_score:
            best_score, best_combo = float(scores[i]), combos[i]
    return best_score, np.asarray(best_combo)


class SMEA(Aggregator):
    """Smallest-Maximum-Eigenvalue Averaging: average the (n - f)-subset whose centered Gram has the smallest top eigenvalue (batched-Jacobi scoring on device)."""
    name = "smea"
    supports_subtasks = True

    def __init__(self, f: int, *, chunk_size: int = 4096) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(f"2f must be < n (got n={n}, f={self.f})")

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        m = n - self.f
        if math.comb(n, m) <= _DEVICE_COMBO_CAP and m <= _DEVICE_JACOBI_MAX_M:
            return _smea_select_mean(x, _device_combos(n, m))
        gram = robust.gram_matrix(x)
        best_score, best_combo = _score_combo_range_smea(
            np.asarray(gram), n, m, 0, math.comb(n, m)
        )
        return robust.subset_mean(x, jnp.asarray(best_combo))

    def create_subtasks(self, inputs, *, context: OpContext):
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        n = matrix.shape[0]
        m = n - self.f
        total = math.comb(n, m)
        host_gram = np.asarray(robust.gram_matrix(matrix))
        chunk = select_adaptive_chunk_size(
            total, self.chunk_size, pool_size=pool_size_from_context(context)
        )

        def gen():
            for start in range(0, total, chunk):
                count = min(chunk, total - start)
                yield SubTask(
                    fn=_score_combo_range_smea,
                    args=(host_gram, n, m, start, count),
                    name=f"smea-combos[{start}:{start + count}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext):
        best_score, best_combo = min(partials, key=lambda p: p[0])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(robust.subset_mean(matrix, jnp.asarray(best_combo)))


__all__ = ["SMEA"]

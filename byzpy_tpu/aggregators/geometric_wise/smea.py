"""SMEA: Smallest Maximum Eigenvalue Averaging
(behavioral parity: ``byzpy/aggregators/geometric_wise/smea.py:110-228``).

Enumerates ``(n - f)``-subsets on the host, scores batches on device: each
subset's score is the top eigenvalue of its centered ``m x m`` Gram block
(``jnp.linalg.eigvalsh`` vmapped over the batch), the winner's rows are
averaged.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ...engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ...engine.graph.operator import OpContext
from ...engine.graph.subtask import SubTask
from ...ops import robust
from ...utils.trees import stack_gradients
from ..base import Aggregator

_DEVICE_BATCH = 2048


def _score_combo_range_smea(
    host_gram: np.ndarray, n: int, m: int, start: int, count: int
) -> tuple[float, np.ndarray]:
    from .minimum_diameter_average import _combo_batches, _device_best

    gram = jnp.asarray(host_gram)
    batch = min(_DEVICE_BATCH, count)
    return _device_best(
        gram,
        _combo_batches(n, m, batch, start=start, count=count),
        score_fn=robust.subset_max_eigvals,
    )


class SMEA(Aggregator):
    name = "smea"
    supports_subtasks = True

    def __init__(self, f: int, *, chunk_size: int = 4096) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(f"2f must be < n (got n={n}, f={self.f})")

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        m = n - self.f
        gram = robust.gram_matrix(x)
        best_score, best_combo = _score_combo_range_smea(
            np.asarray(gram), n, m, 0, math.comb(n, m)
        )
        return robust.subset_mean(x, jnp.asarray(best_combo))

    def create_subtasks(self, inputs, *, context: OpContext):
        gradients = inputs.get(self.input_key)
        matrix, _ = stack_gradients(gradients)
        self.validate_n(matrix.shape[0])
        n = matrix.shape[0]
        m = n - self.f
        total = math.comb(n, m)
        host_gram = np.asarray(robust.gram_matrix(matrix))
        chunk = select_adaptive_chunk_size(
            total, self.chunk_size, pool_size=pool_size_from_context(context)
        )

        def gen():
            for start in range(0, total, chunk):
                count = min(chunk, total - start)
                yield SubTask(
                    fn=_score_combo_range_smea,
                    args=(host_gram, n, m, start, count),
                    name=f"smea-combos[{start}:{start + count}]",
                )

        return gen()

    def reduce_subtasks(self, partials, inputs, *, context: OpContext):
        best_score, best_combo = min(partials, key=lambda p: p[0])
        matrix, unravel = stack_gradients(inputs.get(self.input_key))
        return unravel(robust.subset_mean(matrix, jnp.asarray(best_combo)))


__all__ = ["SMEA"]

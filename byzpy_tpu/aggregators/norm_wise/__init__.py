from .caf import CAF
from .center_clipping import CenteredClipping
from .comparative_gradient_elimination import ComparativeGradientElimination

__all__ = ["CenteredClipping", "CAF", "ComparativeGradientElimination"]

"""CAF: Covariance-bound Agnostic Filter
(behavioral parity: ``byzpy/aggregators/norm_wise/caf.py:36-185``).

The data-dependent filtering loop (down-weight along the dominant residual
direction until <= n - 2f weight remains) runs as a ``lax.while_loop`` with
the power iteration inside — one compiled program instead of the
reference's host loop over shm chunk fetches.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator


class CAF(Aggregator):
    """Covariance-bound Adaptive Filter: iteratively downweights rows along the top covariance eigendirection until the spectral bound holds."""
    name = "caf"

    def __init__(self, f: int, *, power_iters: int = 3, seed: int = 0) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if power_iters <= 0:
            raise ValueError("power_iters must be > 0")
        self.f = int(f)
        self.power_iters = int(power_iters)
        self.seed = int(seed)

    def validate_n(self, n: int) -> None:
        if 2 * self.f >= n:
            raise ValueError(f"Cannot tolerate 2f >= n (got n={n}, f={self.f})")

    # no masked matrix program: the filter's spectral reductions are
    # shape-sensitive at the bit level (a padded power iteration drifts
    # ~1e-6 from the compacted one), so ragged cohorts take the exact
    # subset fallback of ``fold_finalize_masked`` instead
    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.caf(x, f=self.f, power_iters=self.power_iters, seed=self.seed)


__all__ = ["CAF"]

"""Centered Clipping (Karimireddy et al. 2021, ICML)
(behavioral parity: ``byzpy/aggregators/norm_wise/center_clipping.py:29-269``).

Single-device path: the M clipping iterations are a ``lax.fori_loop``
inside one compiled program (per-iteration distance reductions shard over
the mesh as psums). Pool path: the reference's *barriered* mode — each of
the M iterations fans per-row-chunk clip sums over the pool and the
coordinator applies ``v += mean`` (ref: ``center_clipping.py:158-257``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import BarrieredIterativeAggregator, _centered_clip_chunk


class CenteredClipping(BarrieredIterativeAggregator, Aggregator):
    """Iterative momentum-centered clipping: clip each row to a radius around the running center, then re-center."""
    name = "centered-clipping"
    _barrier_chunk_fn = staticmethod(_centered_clip_chunk)

    def __init__(
        self,
        *,
        c_tau: float,
        M: int = 10,
        eps: float = 1e-12,
        init: str = "mean",
    ) -> None:
        if c_tau < 0:
            raise ValueError("c_tau must be >= 0")
        if M <= 0:
            raise ValueError("M must be >= 1")
        if eps <= 0:
            raise ValueError("eps must be > 0")
        if init not in {"mean", "median", "zero"}:
            raise ValueError("init must be one of {'mean','median','zero'}")
        self.c_tau = float(c_tau)
        self.M = int(M)
        self.eps = float(eps)
        self.init = init

    supports_masked_finalize = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.centered_clipping(
            x, c_tau=self.c_tau, M=self.M, eps=self.eps, init=self.init
        )

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_centered_clipping(
            x, valid, c_tau=self.c_tau, M=self.M, eps=self.eps, init=self.init
        )

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Clip-ratio view: each row's distance to the published center
        over ``c_tau`` (ratio > 1 = the row was clipped to the radius;
        the excess is the magnitude the clip discarded). Needs the
        round's ``aggregate``; returns None without it."""
        if aggregate is None:
            return None
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        center = np.asarray(aggregate, np.float32).reshape(-1)
        dists = np.linalg.norm(rows - center[None, :], axis=1)
        if self.c_tau > 0:
            return self._evidence_view("clip_ratio", n, idx, dists / self.c_tau)
        return self._evidence_view("center_distance", n, idx, dists)

    # -- barriered hooks (pool mode) -----------------------------------------

    def _barrier_params(self):
        return {"c_tau": self.c_tau, "eps": self.eps}

    def _barrier_init(self, host: np.ndarray) -> np.ndarray:
        if self.init == "mean":
            return host.mean(axis=0)
        if self.init == "median":
            return np.median(host, axis=0)
        return np.zeros(host.shape[1], host.dtype)

    def _barrier_update(self, partials, center):
        # denominator from the partials themselves: one source of truth for
        # the row count, matching the 1/n mean in the fused path
        total = np.sum([p[0] for p in partials], axis=0)
        rows = sum(p[1] for p in partials)
        return center + total / rows

    def _barrier_max_iters(self) -> int:
        return self.M


__all__ = ["CenteredClipping"]

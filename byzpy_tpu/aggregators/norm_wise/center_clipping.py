"""Centered Clipping (Karimireddy et al. 2021, ICML)
(behavioral parity: ``byzpy/aggregators/norm_wise/center_clipping.py:29-269``).

The reference iterates with barriered subtasks writing per-chunk
contribution slots into shm; here the M clipping iterations are a
``lax.fori_loop`` inside one compiled program (per-iteration distance
reductions shard over the mesh as psums).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator


class CenteredClipping(Aggregator):
    name = "centered-clipping"

    def __init__(
        self,
        *,
        c_tau: float,
        M: int = 10,
        eps: float = 1e-12,
        init: str = "mean",
    ) -> None:
        if c_tau < 0:
            raise ValueError("c_tau must be >= 0")
        if M <= 0:
            raise ValueError("M must be >= 1")
        if eps <= 0:
            raise ValueError("eps must be > 0")
        if init not in {"mean", "median", "zero"}:
            raise ValueError("init must be one of {'mean','median','zero'}")
        self.c_tau = float(c_tau)
        self.M = int(M)
        self.eps = float(eps)
        self.init = init

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.centered_clipping(
            x, c_tau=self.c_tau, M=self.M, eps=self.eps, init=self.init
        )


__all__ = ["CenteredClipping"]

"""CGE: drop the ``f`` largest-L2-norm gradients, average the rest
(behavioral parity:
``byzpy/aggregators/norm_wise/comparative_gradient_elimination.py:28-154``).
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ...utils import placement
from ..base import Aggregator, SlotFoldState
from ..chunked import RowScoredAggregator


def _sq_norm_rows(host: np.ndarray, start: int, end: int) -> jnp.ndarray:
    block = jnp.asarray(host[start:end])
    return jnp.sum(block * block, axis=1)


class _NormFoldState:
    """Incremental CGE state: each node's squared norm is computed the
    moment its gradient arrives. Per-node norms are arrival-order
    independent (one reduction over that row alone), so streaming CGE is
    deterministic for any arrival order; parity with the barrier path is
    to float tolerance (the barrier runs norms + selection as one jitted
    program whose fused codegen rounds ~1 ulp differently from the eager
    finalize here)."""

    __slots__ = ("slots", "norms")

    def __init__(self, n: int) -> None:
        self.slots = SlotFoldState(n)
        self.norms: dict = {}


class ComparativeGradientElimination(RowScoredAggregator, Aggregator):
    """CGE: drop the f largest-norm rows and average the rest."""
    name = "comparative-gradient-elimination"
    _score_fn = staticmethod(_sq_norm_rows)

    def __init__(self, f: int, *, chunk_size: int = 32) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n:
            raise ValueError(f"f must satisfy 0 <= f < n (got n={n}, f={self.f})")

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        return robust.ranked_mean(matrix, scores, matrix.shape[0] - self.f)

    supports_masked_finalize = True
    evidence_selects = True

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.cge(x, f=self.f)

    def _aggregate_matrix_masked(
        self, x: jnp.ndarray, valid: jnp.ndarray
    ) -> jnp.ndarray:
        return robust.masked_cge(x, valid, f=self.f)

    def _masked_view(self, state):
        return Aggregator._masked_view(self, state.slots)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.cge_stream(xs, f=self.f)

    ragged_score_kind = "norm"
    #: one shared norm pass scores the whole batch — coalescing wins
    ragged_coalesce = True

    def ragged_matrix_fn(self):
        """Specialized ragged program: ONE squared-norm pass scores
        every cohort in the batch (``ops.ragged.ragged_cge``); the
        published L2 norms + keep set are the fused forensics view."""
        from ...ops import ragged as ragged_ops

        f = self.f

        def fn(flat, seg, offsets, lengths, *, n_cohorts, segment_sum=None):
            return ragged_ops.ragged_cge(
                flat, seg, lengths, f=f, n_cohorts=n_cohorts,
                segment_sum=segment_sum,
            )

        return fn

    def round_evidence(self, matrix, valid, *, aggregate=None):
        """Per-row L2-norm scores + the lowest-``m − f`` selection
        (host-side; stable tie rule matching the selection program)."""
        pre = self._evidence_rows(matrix, valid)
        if pre is None:
            return None
        rows, idx, n = pre
        norms = np.asarray(jnp.linalg.norm(jnp.asarray(rows), axis=1))
        keep_local = np.argsort(norms, kind="stable")[: rows.shape[0] - int(self.f)]
        return self._evidence_view("norm", n, idx, norms, keep_local)

    # -- hierarchical partial fold (sharded serving tier) -----------------

    #: the merged score view reads the merged norm vector, never the
    #: round aggregate — eligible for the root's off-path finalize
    #: overlap (score pass during the device program's flight)
    merged_view_from_extras = True

    def _partial_extras(self, rows) -> dict:
        """Per-row squared norms of one shard's discounted rows — CGE's
        whole streaming state; norms are row-local, so the sharded fold
        summary is exactly the per-arrival norm fold, batched."""
        return {
            "sqnorms": np.einsum("ij,ij->i", rows, rows).astype(np.float32)
        }

    def _merge_extras(self, extras_list, partials) -> dict:
        """Concatenate shard norm vectors in shard order (recomputed
        for shards that shipped none — the summary is deterministic)."""
        parts = [
            np.asarray(e["sqnorms"], np.float32)
            if e and "sqnorms" in e
            else self._partial_extras(
                np.asarray(p["rows"], np.float32)
            )["sqnorms"]
            for e, p in zip(extras_list, partials, strict=True)
        ]
        return {"sqnorms": np.concatenate(parts)}

    def merged_score_view(self, merged, *, aggregate=None):
        """L2-norm scores + the lowest-``m − f`` keep set from the
        merged norm vector alone (no row pass at the root); tie rule
        matches :meth:`round_evidence` (stable ascending norms)."""
        extras = merged.get("extras") or {}
        sq = extras.get("sqnorms")
        m = int(merged["m"])
        if sq is None or m == 0:
            return super().merged_score_view(merged, aggregate=aggregate)
        try:
            self.validate_n(m)
        except ValueError:
            return None
        norms = np.sqrt(np.asarray(sq, np.float32))
        keep = np.zeros((m,), bool)
        keep[np.argsort(norms, kind="stable")[: m - self.f]] = True
        return {"kind": "norm", "scores": norms, "keep": keep}

    # -- arrival-order streaming fold ------------------------------------

    def fold_init(self, n: int) -> Any:
        return _NormFoldState(n)

    def fold(self, state: Any, index: int, gradient: Any) -> None:
        row = state.slots.insert(index, gradient)
        with placement.on(placement.compute_device(row)):
            state.norms[index] = jnp.sum(row * row)

    def fold_finalize(self, state: Any) -> Any:
        m = state.slots.filled
        self.validate_n(m)
        with placement.on(
            placement.compute_device(state.slots.placement_source())
        ):
            matrix, unravel = state.slots.stacked()
            scores = jnp.stack(
                [state.norms[s] for s in sorted(state.norms)]
            )
            return unravel(robust.ranked_mean(matrix, scores, m - self.f))


__all__ = ["ComparativeGradientElimination"]

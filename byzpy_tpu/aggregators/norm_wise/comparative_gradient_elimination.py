"""CGE: drop the ``f`` largest-L2-norm gradients, average the rest
(behavioral parity:
``byzpy/aggregators/norm_wise/comparative_gradient_elimination.py:28-154``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops import robust
from ..base import Aggregator
from ..chunked import RowScoredAggregator


def _sq_norm_rows(host: np.ndarray, start: int, end: int) -> jnp.ndarray:
    block = jnp.asarray(host[start:end])
    return jnp.sum(block * block, axis=1)


class ComparativeGradientElimination(RowScoredAggregator, Aggregator):
    """CGE: drop the f largest-norm rows and average the rest."""
    name = "comparative-gradient-elimination"
    _score_fn = staticmethod(_sq_norm_rows)

    def __init__(self, f: int, *, chunk_size: int = 32) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        if chunk_size <= 0:
            raise ValueError("chunk_size must be > 0")
        self.f = int(f)
        self.chunk_size = int(chunk_size)

    def validate_n(self, n: int) -> None:
        if self.f >= n:
            raise ValueError(f"f must satisfy 0 <= f < n (got n={n}, f={self.f})")

    def _select_from_scores(self, scores: jnp.ndarray, matrix: jnp.ndarray) -> jnp.ndarray:
        return robust.ranked_mean(matrix, scores, matrix.shape[0] - self.f)

    def _aggregate_matrix(self, x: jnp.ndarray) -> jnp.ndarray:
        return robust.cge(x, f=self.f)

    def _aggregate_stream_matrix(self, xs: jnp.ndarray) -> jnp.ndarray:
        return robust.cge_stream(xs, f=self.f)


__all__ = ["ComparativeGradientElimination"]

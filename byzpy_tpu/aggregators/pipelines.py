"""Pipeline fusion: recognize (pre-aggregator, aggregator) combinations
with a Gram-collapse kernel.

Reference-style training code spells the robust pipeline as two objects
(``ParameterServer(pre_aggregator=NearestNeighborMixing(f),
aggregator=MultiKrum(f, q))`` — ref:
``byzpy/engine/parameter_server/ps.py:127-137``). For combinations where
the pre-aggregation is a linear row operator with Gram-derivable
coefficients, the composition runs as ONE fused two-sweep kernel instead
of two materialized steps (see ``docs/performance.md`` "pipeline rows"):
the orchestrators consult :func:`fused_pipeline_matrix_fn` and fall back
to the two-step path whenever it returns ``None`` — semantics are
identical either way (documented deviations: non-finite corner rules,
``PARITY.md``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax.numpy as jnp


def fused_pipeline_matrix_fn(
    pre: Any, agg: Any
) -> Optional[Callable[[jnp.ndarray], jnp.ndarray]]:
    """A fused ``(n, d) -> (d,)`` function for the (pre, agg) pair, or
    ``None`` when no fused kernel exists (callers then run the ordinary
    two-step path)."""
    from ..ops import robust
    from ..pre_aggregators.arc import ARC
    from ..pre_aggregators.clipping import Clipping
    from ..pre_aggregators.nnm import NearestNeighborMixing
    from .geometric_wise.krum import Krum, MultiKrum

    # EXACT-type matching on purpose: a subclass may override the
    # documented extension hooks (_aggregate_matrix / _transform_matrix)
    # and the fused kernel would silently bypass the override. Krum is
    # admitted explicitly (it only pins q=1).
    if type(agg) not in (MultiKrum, Krum):
        return None
    if type(pre) is NearestNeighborMixing:
        return partial(
            robust.nnm_multi_krum, f_nnm=pre.f, f=agg.f, q=agg.q
        )
    if type(pre) is Clipping and pre.threshold > 0:
        # threshold == 0 is degenerate (every row clips to zero); keep it
        # on the materialized path, whose semantics are the contract
        return partial(
            robust.clipped_multi_krum, tau=pre.threshold, f=agg.f, q=agg.q
        )
    if type(pre) is ARC:
        return partial(
            robust.arc_multi_krum, f_arc=pre.f, f=agg.f, q=agg.q
        )
    return None


__all__ = ["fused_pipeline_matrix_fn"]

"""byzlint: JAX-aware static analysis for the byzpy_tpu codebase.

Generic linters cannot see the hazards that actually cost this repo
debugging rounds — stale closure capture of env/config inside jitted
kernels, use-after-donate on donated fold buffers, unbound collective
axis names inside ``shard_map``, host-sync stalls in the overlap round
loops, and blocking calls inside the async actor fabric. byzlint turns
each of those hard-won conventions into a machine-checked invariant.

Usage::

    python -m byzpy_tpu.analysis byzpy_tpu benchmarks examples
    byzpy-tpu lint                       # same gate via the CLI
    python -m byzpy_tpu.analysis --format json --select DONATION paths...

Suppress a deliberate violation with a trailing or preceding comment —
``# byzlint: ignore[RULE-ID]`` — plus a justification; stale suppressions
are themselves reported (``UNUSED-IGNORE``). Rule catalog and the real
incident behind each rule: ``docs/static_analysis.md``.
"""

from __future__ import annotations

from .core import (
    Finding,
    ModuleInfo,
    ScanResult,
    Suppression,
    UNUSED_IGNORE,
    render_json,
    render_text,
    scan_paths,
)
from .rules import ALL_RULES, Rule, ScanContext

__all__ = [
    "ALL_RULES",
    "Finding",
    "ModuleInfo",
    "Rule",
    "ScanContext",
    "ScanResult",
    "Suppression",
    "UNUSED_IGNORE",
    "main",
    "render_json",
    "render_text",
    "scan_paths",
]


def main(argv=None) -> int:
    """Entry point for ``python -m byzpy_tpu.analysis`` / ``byzpy-tpu
    lint`` (see :func:`byzpy_tpu.analysis.__main__.run`)."""
    from .__main__ import run

    return run(argv)

"""Command-line front end for byzlint.

``python -m byzpy_tpu.analysis [paths...]`` scans the given files or
directories (default: ``byzpy_tpu`` ``benchmarks`` ``examples`` relative
to the current directory, whichever exist) and exits 0 when clean, 1
when findings survive suppression, 2 on usage errors — the exit-code
contract the CI gate relies on.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .core import render_json, render_text, scan_paths
from .rules import ALL_RULES

DEFAULT_PATHS = ("byzpy_tpu", "benchmarks", "examples")


def build_parser() -> argparse.ArgumentParser:
    """Assemble the byzlint argument parser."""
    parser = argparse.ArgumentParser(
        prog="python -m byzpy_tpu.analysis",
        description=(
            "byzlint: JAX-aware static analysis (trace-safety, donation, "
            "collective-axis, async-actor hazards)"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: byzpy_tpu benchmarks "
        "examples, whichever exist under the current directory)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select",
        default=None,
        metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    return parser


def run(argv: Optional[List[str]] = None) -> int:
    """Parse args, scan, report; returns the process exit code."""
    args = build_parser().parse_args(argv)
    if args.list_rules:
        try:
            for rule in ALL_RULES:
                print(f"{rule.id}\t{rule.summary}")
            print("UNUSED-IGNORE\tsuppression comment that suppresses nothing")
        except BrokenPipeError:  # piped into head — fine
            pass
        return 0
    paths = args.paths
    if not paths:
        from pathlib import Path

        paths = [p for p in DEFAULT_PATHS if Path(p).exists()]
        if not paths:
            print(
                "byzlint: no paths given and none of "
                f"{'/'.join(DEFAULT_PATHS)} exist here",
                file=sys.stderr,
            )
            return 2
    select = args.select.split(",") if args.select else None
    try:
        result = scan_paths(paths, select=select)
    except (FileNotFoundError, ValueError, SyntaxError) as exc:
        print(f"byzlint: error: {exc}", file=sys.stderr)
        return 2
    render = render_json if args.format == "json" else render_text
    try:
        print(render(result))
    except BrokenPipeError:  # e.g. piped into head — not a lint failure
        pass
    return 0 if result.clean else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(run())

"""Shared AST machinery for the byzlint rule engine.

Everything here is pure stdlib-``ast`` analysis: import-alias resolution
(so ``lax.psum`` and ``from jax.lax import psum`` both resolve to the
same qualified name), discovery of *traced contexts* (functions whose
bodies execute under ``jax.jit`` / ``shard_map`` / ``pmap`` tracing or as
``pallas_call`` kernels), string constant propagation for axis-name
resolution, and extraction of donation signatures from ``jax.jit``
calls. Rules in :mod:`byzpy_tpu.analysis.rules` are thin walks over
these primitives.

No jax import happens here — the linter must run in seconds on a machine
with no accelerator runtime.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

#: Qualified names that mean "this function body is traced by XLA".
JIT_QUALNAMES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}

#: Last-component names of SPMD wrappers that trace their mapped function.
SPMD_WRAPPERS = {"shard_map", "pmap", "xmap"}

#: Known mesh-constructor helpers → the axis names they bind. The jax
#: constructors are resolved structurally (tuple-of-string argument); the
#: repo helpers carry their axis defaults so in-repo call sites resolve.
MESH_HELPER_AXES = {
    "node_mesh": ("nodes",),
    "feature_mesh": ("feat",),
    "grid_mesh": ("nodes", "data"),
}


def build_import_map(tree: ast.Module) -> Dict[str, str]:
    """Map local names to dotted import paths for one module.

    ``import jax.numpy as jnp`` → ``{"jnp": "jax.numpy"}``; ``from jax
    import lax`` → ``{"lax": "jax.lax"}``. Relative imports are stored
    with the leading dots stripped (``from ..profiling import tilecache``
    → ``{"tilecache": "profiling.tilecache"}``) — matching is therefore
    done on name suffixes, not full paths, where relative imports occur.
    """
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    head = alias.name.split(".")[0]
                    imports[head] = head
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                imports[local] = f"{base}.{alias.name}" if base else alias.name
    return imports


def qualname(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Resolve an attribute chain to a dotted qualified name, or ``None``.

    ``lax.psum`` with ``from jax import lax`` resolves to
    ``"jax.lax.psum"``; a chain rooted in anything other than a plain
    name (a call result, a subscript) resolves to ``None``.
    """
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(imports.get(cur.id, cur.id))
        return ".".join(reversed(parts))
    return None


def last_component(qual: Optional[str]) -> str:
    """Final dotted component of a qualified name (``""`` for ``None``)."""
    return qual.rsplit(".", 1)[-1] if qual else ""


def string_consts(scopes: Sequence[ast.AST]) -> Dict[str, Optional[str]]:
    """Best-effort constant propagation for string variables.

    Scans simple ``name = "literal"`` assignments in the given scopes
    (innermost last). A name assigned exactly one string literal maps to
    that literal; a name assigned twice with different values (or any
    non-literal) maps to ``None`` (ambiguous — callers must stay silent).
    """
    out: Dict[str, Optional[str]] = {}
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Name):
                    continue
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    prev = out.get(tgt.id, node.value.value)
                    out[tgt.id] = (
                        node.value.value if prev == node.value.value else None
                    )
                else:
                    out[tgt.id] = None
    return out


def resolve_str(
    node: ast.AST, consts: Dict[str, Optional[str]]
) -> Optional[str]:
    """A string literal, or a name that constant-propagates to one."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _callable_qual(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Qualified name of a decorator/callable expression, unwrapping
    ``functools.partial(f, ...)`` to ``f``."""
    if isinstance(node, ast.Call):
        fq = qualname(node.func, imports)
        if last_component(fq) == "partial" and node.args:
            return _callable_qual(node.args[0], imports)
        return fq
    return qualname(node, imports)


def traced_kind(dec: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Classify a decorator: ``"jit"``, ``"shard_map"``, ``"pmap"``, or
    ``None`` when the decorator does not put the body under a trace."""
    qual = _callable_qual(dec, imports)
    if qual in JIT_QUALNAMES:
        return "jit"
    last = last_component(qual)
    if last in ("shard_map", "xmap"):
        return "shard_map"
    if last == "pmap":
        return "pmap"
    return None


FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass
class TracedFn:
    """One function whose body runs under a JAX trace."""

    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    kind: str  # "jit" | "shard_map" | "pmap" | "pallas"
    #: the shard_map/pmap wrapping Call when one exists (for axis specs)
    binding: Optional[ast.Call] = None
    #: parameters that are *static* under the trace (jit
    #: static_argnums/static_argnames, kwargs pre-bound via
    #: ``functools.partial`` at a pallas_call/wrap site) — host-side
    #: Python values, exempt from traced-value rules
    static_params: Set[str] = field(default_factory=set)


def _positional_params(fn: ast.AST) -> Tuple[str, ...]:
    args = getattr(fn, "args", None)
    if args is None:
        return ()
    return tuple(a.arg for a in args.posonlyargs + args.args)


def static_param_names(call: ast.Call, fn: ast.AST) -> Set[str]:
    """Static parameter names declared by a ``jax.jit`` call/decorator
    (``static_argnames`` literals; ``static_argnums`` mapped through the
    wrapped def's positional parameters)."""
    names: Set[str] = set()
    params = _positional_params(fn)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            lits = _str_literals(kw.value)
            if lits:
                names |= lits
        elif kw.arg == "static_argnums":
            nums = _int_literals(kw.value)
            if nums:
                names |= {params[i] for i in nums if i < len(params)}
    return names


def _local_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """Every function definition in the module by name (first definition
    wins on shadowing — good enough to resolve wrap-call targets)."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, FunctionNode):
            defs.setdefault(node.name, node)
    return defs


def traced_functions(
    tree: ast.Module, imports: Dict[str, str]
) -> List[TracedFn]:
    """Every function in the module whose body executes under a trace.

    Four discovery paths: (1) decorators — ``@jax.jit``,
    ``@partial(jax.jit, ...)``, ``@partial(shard_map, ...)``; (2) wrap
    call sites — ``jax.jit(fn)``, ``shard_map(fn, ...)``, ``pmap(fn)``
    where ``fn`` names a local def or is an inline lambda; (3) kernels —
    the first argument of any ``pallas_call``; (4) nested defs inside any
    of the above are implicitly traced (callers should walk the returned
    nodes recursively, which covers them).
    """
    defs = _local_defs(tree)
    found: List[TracedFn] = []
    by_id: Dict[int, TracedFn] = {}

    def add(
        node: ast.AST,
        kind: str,
        binding: Optional[ast.Call],
        statics: Set[str],
    ) -> None:
        if id(node) in by_id:
            by_id[id(node)].static_params |= statics
        else:
            traced = TracedFn(node, kind, binding, statics)
            by_id[id(node)] = traced
            found.append(traced)

    # decorators
    for node in ast.walk(tree):
        if isinstance(node, FunctionNode):
            for dec in node.decorator_list:
                kind = traced_kind(dec, imports)
                if kind is not None:
                    binding = dec if isinstance(dec, ast.Call) else None
                    statics = (
                        static_param_names(dec, node)
                        if isinstance(dec, ast.Call)
                        else set()
                    )
                    add(node, kind, binding, statics)

    # wrap call sites + pallas kernels
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fq = qualname(node.func, imports)
        last = last_component(fq)
        kind: Optional[str] = None
        if fq in JIT_QUALNAMES:
            kind = "jit"
        elif last in ("shard_map", "xmap"):
            kind = "shard_map"
        elif last == "pmap":
            kind = "pmap"
        elif last == "pallas_call":
            kind = "pallas"
        if kind is None or not node.args:
            continue
        target = node.args[0]
        prebound: Set[str] = set()
        if isinstance(target, ast.Call):  # partial(kernel, k=3, ...)
            tq = qualname(target.func, imports)
            if last_component(tq) == "partial" and target.args:
                prebound = {kw.arg for kw in target.keywords if kw.arg}
                target = target.args[0]
        binding = node if kind in ("shard_map", "pmap") else None
        resolved: Optional[ast.AST] = None
        if isinstance(target, ast.Lambda):
            resolved = target
        elif isinstance(target, ast.Name) and target.id in defs:
            resolved = defs[target.id]
        if resolved is not None:
            statics = prebound | static_param_names(node, resolved)
            add(resolved, kind, binding, statics)
    return found


def enclosing_param_names(fn: ast.AST) -> Set[str]:
    """Parameter names of one function/lambda node."""
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


# ---------------------------------------------------------------------------
# Donation signatures
# ---------------------------------------------------------------------------


@dataclass
class DonationSig:
    """Donated-argument positions/names of one jitted callable."""

    argnums: Set[int] = field(default_factory=set)
    argnames: Set[str] = field(default_factory=set)
    #: positional parameter names of the wrapped fn when statically known
    params: Tuple[str, ...] = ()

    def donated_args(self, call: ast.Call) -> List[Tuple[str, ast.AST]]:
        """``(variable-name, arg-node)`` pairs donated at this call site
        (only plain-name arguments are tracked)."""
        out: List[Tuple[str, ast.AST]] = []
        names = set(self.argnames)
        nums = set(self.argnums)
        for name in self.argnames:
            if name in self.params:
                nums.add(self.params.index(name))
        for i, arg in enumerate(call.args):
            donated = i in nums or (
                i < len(self.params) and self.params[i] in names
            )
            if donated and isinstance(arg, ast.Name):
                out.append((arg.id, arg))
        for kw in call.keywords:
            if kw.arg is None:
                continue
            donated = kw.arg in names or (
                kw.arg in self.params and self.params.index(kw.arg) in nums
            )
            if donated and isinstance(kw.value, ast.Name):
                out.append((kw.value.id, kw.value))
        return out


def _int_literals(node: ast.AST) -> Optional[Set[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            sub = _int_literals(elt)
            if sub is None:
                return None
            out |= sub
        return out
    return None


def _str_literals(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            sub = _str_literals(elt)
            if sub is None:
                return None
            out |= sub
        return out
    return None


def donation_from_call(
    call: ast.Call, imports: Dict[str, str], defs: Dict[str, ast.AST]
) -> Optional[DonationSig]:
    """Donation signature of a ``jax.jit(fn, donate_arg...=...)`` call
    (or ``partial(jax.jit, donate_arg...=...)`` decorator), ``None`` when
    the call does not donate or the donation spec is not literal."""
    fq = _callable_qual(call, imports)
    if fq not in JIT_QUALNAMES:
        return None
    sig = DonationSig()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _int_literals(kw.value)
            if nums is None:
                return None
            sig.argnums |= nums
        elif kw.arg == "donate_argnames":
            names = _str_literals(kw.value)
            if names is None:
                return None
            sig.argnames |= names
    if not sig.argnums and not sig.argnames:
        return None
    # recover the wrapped fn's positional params when it is a local def
    target = call.args[0] if call.args else None
    if isinstance(target, ast.Name) and target.id in defs:
        fn = defs[target.id]
        args = getattr(fn, "args", None)
        if args is not None:
            sig.params = tuple(a.arg for a in args.posonlyargs + args.args)
    return sig


__all__ = [
    "JIT_QUALNAMES",
    "SPMD_WRAPPERS",
    "MESH_HELPER_AXES",
    "DonationSig",
    "TracedFn",
    "build_import_map",
    "donation_from_call",
    "enclosing_param_names",
    "last_component",
    "qualname",
    "resolve_str",
    "static_param_names",
    "string_consts",
    "traced_functions",
    "traced_kind",
]

"""Execution-context classification for byzlint's concurrency rules.

PR 19's staging race survived review because nothing *named* the fact
that ``_finish`` settles the fold table on the event loop while proxy
reader threads write it concurrently. This module recovers that fact
statically: a per-module call graph labels every function with the
execution contexts its body can run under —

* ``event-loop`` — ``async def`` bodies and loop callbacks registered
  via ``add_reader`` / ``call_soon`` / ``call_later``: everything here
  shares one asyncio loop.
* ``thread`` — ``threading.Thread(target=...)`` targets: a dedicated
  OS thread.
* ``executor`` — ``loop.run_in_executor`` / ``pool.submit`` targets:
  some worker thread from a pool.
* ``traced`` — jit / shard_map / pmap / pallas bodies (reusing the
  discovery in :mod:`.astutils`).

Labels propagate transitively to *sync* callees resolvable within the
module (bare names to unique local defs, ``self.method`` within the
enclosing class) — a helper called from both an async method and a
reader-thread target carries both labels, which is exactly the fact
``THREAD-SHARED`` needs. Resolution is deliberately conservative:
ambiguous names get no edge, unresolved targets get no seed, and an
unlabeled function produces no findings — precision over completeness,
like every other byzlint pass.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astutils import FunctionNode, last_component, qualname, traced_functions
from .core import ModuleInfo

EVENT_LOOP = "event-loop"
THREAD = "thread"
EXECUTOR = "executor"
TRACED = "traced"

#: the labels that mean "concurrent with the others" — two of these on
#: one attribute's writers is a data race unless a common guard exists
CONCURRENT_LABELS = frozenset({EVENT_LOOP, THREAD, EXECUTOR})

#: loop-callback registrars → positional index of the callback argument
LOOP_CALLBACK_ARG: Dict[str, int] = {
    "add_reader": 1,
    "add_writer": 1,
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "call_later": 1,
    "call_at": 1,
}

#: receiver-name hints for ``.submit()`` worker pools (kept narrow so an
#: unrelated ``.submit`` method never seeds a context)
SUBMIT_RECEIVER_HINTS = ("pool", "executor", "exec", "workers")


@dataclass
class FnInfo:
    """One function definition plus its classification."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    #: nearest enclosing class through the def-nesting chain (what
    #: ``self`` binds to inside this body), or ``None`` at module level
    class_name: Optional[str]
    labels: Set[str] = field(default_factory=set)
    #: id(FnInfo.node) of statically-resolved same-module callees
    callees: Set[int] = field(default_factory=set)


@dataclass
class ContextMap:
    """Per-module function→context classification (pass-0 artifact)."""

    #: id(function node) → its info record
    fns: Dict[int, FnInfo] = field(default_factory=dict)
    #: id(any AST node) → the FnInfo owning it (nearest enclosing def,
    #: nested-def subtrees belong to the nested def)
    owner: Dict[int, FnInfo] = field(default_factory=dict)

    def labels_of(self, node: ast.AST) -> Set[str]:
        """Context labels of a function node (empty when unknown)."""
        info = self.fns.get(id(node))
        return set(info.labels) if info is not None else set()

    def owner_of(self, node: ast.AST) -> Optional[FnInfo]:
        """The function whose body directly contains ``node``."""
        return self.owner.get(id(node))


def receiver_text(expr: ast.AST) -> str:
    """Lower-cased dotted text of an attribute-chain receiver — good
    enough for hint matching (``self._finish_pool`` → ``self._finish_pool``,
    a call link contributes its callee text)."""
    parts: List[str] = []
    cur = expr
    while True:
        if isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Call):
            cur = cur.func
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Name):
            parts.append(cur.id)
            break
        else:
            break
    return ".".join(reversed(parts)).lower()


def _unwrap_callable(expr: ast.AST) -> ast.AST:
    """Strip ``partial(f, ...)`` / ``carry_context(f)``-style wrappers
    down to the wrapped callable expression."""
    while isinstance(expr, ast.Call) and expr.args:
        expr = expr.args[0]
    return expr


def build_context_map(mod: ModuleInfo) -> ContextMap:
    """Classify every function in ``mod`` (see module docstring)."""
    cmap = ContextMap()
    by_name: Dict[str, List[FnInfo]] = {}
    by_method: Dict[Tuple[str, str], List[FnInfo]] = {}

    def collect(body, class_name: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, FunctionNode):
                info = FnInfo(stmt, stmt.name, class_name)
                cmap.fns[id(stmt)] = info
                if class_name is None:
                    by_name.setdefault(stmt.name, []).append(info)
                else:
                    by_method.setdefault(
                        (class_name, stmt.name), []
                    ).append(info)
                # a nested def's `self` still binds to the method's class
                collect(stmt.body, class_name)
            elif isinstance(stmt, ast.ClassDef):
                collect(stmt.body, stmt.name)
            else:
                for node in ast.walk(stmt):
                    if isinstance(node, FunctionNode):
                        # defs hiding inside compound statements (an
                        # `if:` guard, a `with:` block) — same scoping
                        info = FnInfo(node, node.name, class_name)
                        if id(node) not in cmap.fns:
                            cmap.fns[id(node)] = info
                            collect(node.body, class_name)

    collect(mod.tree.body, None)

    # ownership: every node belongs to its nearest enclosing def. A
    # nested def's lineno is strictly greater than its encloser's, so
    # walking defs in source order and overwriting lets the innermost
    # claim on each subtree win.
    ordered = sorted(
        cmap.fns.values(), key=lambda i: getattr(i.node, "lineno", 0)
    )
    for info in ordered:
        for node in ast.walk(info.node):
            if node is not info.node and id(node) not in cmap.fns:
                cmap.owner[id(node)] = info

    def resolve(expr: ast.AST, site: Optional[FnInfo]) -> Optional[FnInfo]:
        """Unique in-module resolution of a callable expression."""
        expr = _unwrap_callable(expr)
        if isinstance(expr, ast.Name):
            cands = by_name.get(expr.id, [])
            return cands[0] if len(cands) == 1 else None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and site is not None
            and site.class_name is not None
        ):
            cands = by_method.get((site.class_name, expr.attr), [])
            return cands[0] if len(cands) == 1 else None
        return None

    # --- seeds -----------------------------------------------------------
    for info in cmap.fns.values():
        if isinstance(info.node, ast.AsyncFunctionDef):
            info.labels.add(EVENT_LOOP)
    for traced in traced_functions(mod.tree, mod.imports):
        info = cmap.fns.get(id(traced.node))
        if info is not None:
            info.labels.add(TRACED)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        site = cmap.owner.get(id(node))
        func = node.func
        if last_component(qualname(func, mod.imports)) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = resolve(kw.value, site)
                    if target is not None:
                        target.labels.add(THREAD)
        if not isinstance(func, ast.Attribute):
            continue
        attr = func.attr
        if attr == "run_in_executor" and len(node.args) >= 2:
            target = resolve(node.args[1], site)
            if target is not None:
                target.labels.add(EXECUTOR)
        elif attr == "submit" and node.args:
            recv = receiver_text(func.value)
            if any(h in recv for h in SUBMIT_RECEIVER_HINTS):
                target = resolve(node.args[0], site)
                if target is not None:
                    target.labels.add(EXECUTOR)
        elif attr in LOOP_CALLBACK_ARG:
            pos = LOOP_CALLBACK_ARG[attr]
            if len(node.args) > pos:
                target = resolve(node.args[pos], site)
                if target is not None:
                    target.labels.add(EVENT_LOOP)

    # --- call-graph edges -------------------------------------------------
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        site = cmap.owner.get(id(node))
        if site is None:
            continue
        callee = resolve(node.func, site)
        if callee is not None and callee is not site:
            site.callees.add(id(callee.node))

    # --- transitive propagation (sync callees inherit concurrency) -------
    changed = True
    while changed:
        changed = False
        for info in cmap.fns.values():
            carry = info.labels & CONCURRENT_LABELS
            if not carry:
                continue
            for cid in info.callees:
                callee = cmap.fns[cid]
                if isinstance(callee.node, ast.AsyncFunctionDef):
                    continue  # scheduling, not a sync call-through
                before = len(callee.labels)
                callee.labels |= carry
                if len(callee.labels) != before:
                    changed = True
    return cmap


__all__ = [
    "CONCURRENT_LABELS",
    "ContextMap",
    "EVENT_LOOP",
    "EXECUTOR",
    "FnInfo",
    "LOOP_CALLBACK_ARG",
    "SUBMIT_RECEIVER_HINTS",
    "THREAD",
    "TRACED",
    "build_context_map",
    "receiver_text",
]

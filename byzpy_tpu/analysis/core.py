"""byzlint core: file walking, suppressions, rule driving, reporting.

The engine parses each file once, hands the module to every selected
rule, filters the raw findings through ``# byzlint: ignore[RULE]``
suppressions, and reports any suppression that suppressed nothing as a
finding of its own (``UNUSED-IGNORE``) — a stale ignore is how a real
hazard sneaks back in behind an old waiver.

Suppression syntax (mirrors ``# noqa`` placement rules):

* trailing, on the flagged line::

      x = os.environ.get("FLAG")  # byzlint: ignore[TRACE-DISPATCH]

* own-line, directly above the flagged line::

      # byzlint: ignore[DONATION, HOST-SYNC]
      out = step(state)
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .astutils import build_import_map

UNUSED_IGNORE = "UNUSED-IGNORE"

_SUPPRESS_RE = re.compile(r"#\s*byzlint:\s*ignore\[([A-Za-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        """``path:line:col: RULE: message`` (the human/CI line format)."""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        """JSON-serializable dict form (stable key order)."""
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class Suppression:
    """One parsed ``# byzlint: ignore[...]`` comment."""

    line: int
    rules: Set[str]
    own_line: bool
    #: inclusive line range the comment covers — the full span of the
    #: statement it annotates, so a trailing comment on the last line of
    #: a wrapped call still reaches a finding anchored on its first line
    span: Tuple[int, int] = (0, 0)
    used: bool = False

    def covers(self, finding: Finding) -> bool:
        """Whether this comment's placement+rules reach the finding."""
        if finding.rule not in self.rules:
            return False
        return self.span[0] <= finding.line <= self.span[1]


@dataclass
class ModuleInfo:
    """One parsed source file plus the per-module lookup tables rules use."""

    path: Path
    relpath: str
    source: str
    tree: ast.Module
    imports: Dict[str, str]
    suppressions: List[Suppression] = field(default_factory=list)


@dataclass
class ScanResult:
    """Outcome of one engine run over a set of paths."""

    findings: List[Finding]
    files_scanned: int
    suppressed: int

    @property
    def clean(self) -> bool:
        """True when no finding survived suppression filtering."""
        return not self.findings


def _statement_spans(tree: ast.Module) -> List[Tuple[int, int]]:
    """(lineno, end_lineno) of every statement, innermost-last ordering
    not required — lookups pick the narrowest containing span."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt) and node.end_lineno is not None:
            spans.append((node.lineno, node.end_lineno))
    return spans


def _covering_span(
    spans: List[Tuple[int, int]], line: int, *, starts_at: bool = False
) -> Tuple[int, int]:
    """Narrowest statement span containing ``line`` (or, with
    ``starts_at``, starting exactly there); falls back to the line itself."""
    if starts_at:
        candidates = [s for s in spans if s[0] == line]
    else:
        candidates = [s for s in spans if s[0] <= line <= s[1]]
    if not candidates:
        return (line, line)
    return min(candidates, key=lambda s: s[1] - s[0])


def parse_suppressions(
    source: str, tree: Optional[ast.Module] = None
) -> List[Suppression]:
    """Extract every ``# byzlint: ignore[...]`` comment from a source
    string. A trailing comment covers the full line span of the statement
    it sits on (so wrapped calls still suppress); an own-line comment
    covers the statement starting on the next line. Tokenized, not
    grepped — the syntax *quoted inside a docstring* (as in this
    package's own docs) is not a suppression."""
    import io
    import tokenize

    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return out
    if tree is None:
        try:
            tree = ast.parse(source)
        except SyntaxError:  # pragma: no cover — callers pre-parse
            tree = ast.Module(body=[], type_ignores=[])
    spans = _statement_spans(tree)
    lines = source.splitlines()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        text = lines[lineno - 1] if lineno <= len(lines) else ""
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        own_line = text.lstrip().startswith("#")
        if own_line:
            span = _covering_span(spans, lineno + 1, starts_at=True)
        else:
            span = _covering_span(spans, lineno)
        out.append(Suppression(lineno, rules, own_line, span))
    return out


def load_module(path: Path, relpath: str) -> ModuleInfo:
    """Parse one file into a :class:`ModuleInfo` (raises ``SyntaxError``
    on unparsable source — the ruff gate runs first, so scanned trees are
    syntactically valid by construction)."""
    source = path.read_text(encoding="utf-8")
    tree = ast.parse(source, filename=str(path))
    return ModuleInfo(
        path=path,
        relpath=relpath,
        source=source,
        tree=tree,
        imports=build_import_map(tree),
        suppressions=parse_suppressions(source, tree),
    )


def iter_python_files(paths: Sequence[str]) -> Iterable[Path]:
    """Yield every ``.py`` file under the given files/directories, in
    sorted order, skipping ``__pycache__`` and hidden directories."""
    for raw in paths:
        p = Path(raw)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                parts = f.relative_to(p).parts
                if any(
                    part == "__pycache__" or part.startswith(".")
                    for part in parts
                ):
                    continue
                yield f
        else:
            raise FileNotFoundError(f"no such file or directory: {raw}")


def _display_path(p: Path) -> str:
    try:
        return str(p.resolve().relative_to(Path.cwd()))
    except ValueError:
        return str(p)


def scan_paths(
    paths: Sequence[str],
    *,
    select: Optional[Sequence[str]] = None,
) -> ScanResult:
    """Run the (optionally ``select``-filtered) rule set over ``paths``.

    Returns the suppression-filtered findings plus counters; see
    :func:`byzpy_tpu.analysis.main` for the CLI wrapper. Unknown rule ids
    in ``select`` raise ``ValueError`` so CI typos fail loudly.
    """
    from .rules import ALL_RULES, ScanContext

    rules = list(ALL_RULES)
    check_unused = True
    if select is not None:
        wanted = {s.strip() for s in select if s.strip()}
        known = {r.id for r in rules} | {UNUSED_IGNORE}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule id(s): {', '.join(sorted(unknown))} "
                f"(known: {', '.join(sorted(known))})"
            )
        rules = [r for r in rules if r.id in wanted]
        check_unused = UNUSED_IGNORE in wanted
    selected_ids = {r.id for r in rules}

    modules: List[ModuleInfo] = []
    for path in iter_python_files(paths):
        modules.append(load_module(path, _display_path(path)))

    ctx = ScanContext.build(modules)

    findings: List[Finding] = []
    suppressed = 0
    for mod in modules:
        raw: List[Finding] = []
        for rule in rules:
            raw.extend(rule.check(mod, ctx))
        for finding in raw:
            hit = False
            for sup in mod.suppressions:
                if sup.covers(finding):
                    sup.used = True
                    hit = True
            if hit:
                suppressed += 1
            else:
                findings.append(finding)
        if check_unused:
            for sup in mod.suppressions:
                # a suppression naming only non-selected rules is not
                # provably stale in a filtered run — skip it then
                if sup.used or (select is not None and not (sup.rules & selected_ids)):
                    continue
                findings.append(
                    Finding(
                        UNUSED_IGNORE,
                        mod.relpath,
                        sup.line,
                        0,
                        "suppression matches no finding — remove it (or "
                        f"re-justify): ignore[{', '.join(sorted(sup.rules))}]",
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return ScanResult(findings, len(modules), suppressed)


def render_text(result: ScanResult) -> str:
    """Human-readable report: one line per finding plus a summary."""
    lines = [f.render() for f in result.findings]
    status = "clean" if result.clean else f"{len(result.findings)} finding(s)"
    lines.append(
        f"byzlint: {status} — {result.files_scanned} file(s) scanned, "
        f"{result.suppressed} suppressed"
    )
    return "\n".join(lines)


def render_json(result: ScanResult) -> str:
    """Machine-readable report (stable ordering, for CI artifacts)."""
    return json.dumps(
        {
            "findings": [f.to_json() for f in result.findings],
            "files_scanned": result.files_scanned,
            "suppressed": result.suppressed,
            "clean": result.clean,
        },
        indent=2,
        sort_keys=True,
    )


__all__ = [
    "Finding",
    "ModuleInfo",
    "ScanResult",
    "Suppression",
    "UNUSED_IGNORE",
    "iter_python_files",
    "load_module",
    "parse_suppressions",
    "render_json",
    "render_text",
    "scan_paths",
]

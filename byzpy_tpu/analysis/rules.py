"""The byzlint rule catalog.

Each rule encodes one *silent-until-runtime* JAX hazard this repo has
actually shipped and debugged (see ``docs/static_analysis.md`` for the
incident behind each one):

* ``TRACE-DISPATCH`` — env/tile-cache/dispatch-config reads inside a
  traced body (jit / shard_map / pmap / pallas kernel). Dispatch must
  resolve in the Python wrapper *before* trace, or the first-trace value
  is baked into the compiled executable forever.
* ``DONATION`` — a buffer donated via ``donate_argnums``/``argnames`` is
  read again after the jitted call (or re-passed on the next loop
  iteration without rebinding): XLA has already reused its memory.
* ``AXIS-BINDING`` — a collective inside ``shard_map``/``pmap`` names an
  axis the enclosing mesh/spec does not bind (an unbound-axis NameError
  at best, silent wrong-mesh reduction at worst).
* ``HOST-SYNC`` — ``.item()`` / ``np.asarray`` / ``float(param)`` on
  traced values inside traced bodies (TracerConversionError), or forced
  device syncs inside the PS/gossip round loops (kills the overlap
  pipeline).
* ``ASYNC-BLOCKING`` — blocking calls (``time.sleep``, sync process
  joins, raw-socket ops, ``open``) directly in an ``async def``: one
  stalled coroutine freezes every actor sharing the event loop.
* ``PYTREE-REG`` — an instance of a scanned-tree class passed into a
  collective without pytree registration (jax would treat it as a leaf
  and fail — or silently close over it as a constant).
* ``THREAD-SHARED`` — a ``self.*`` attribute written from two distinct
  execution contexts (event loop / reader thread / executor, per the
  :mod:`.contexts` classifier) with no common lock guard: the PR 19
  arrival-time staging race, as a rule.
* ``ACK-ORDER`` — in a function that both appends to a durability/WAL
  object and sends on a writer, every path must append *before* it
  sends: an ack is a durable promise (the PR 9 double-fold replay).
* ``PARITY-PURITY`` — functions reachable from the digest-parity set
  (``_agg_digest``, ``fold_merge_*``, ``combine_partials``,
  ``gram_block``, trace digests) must not call clocks/RNG or iterate
  bare sets into folded bytes (the PR 7 np.mean digest drift, class of).
* ``METRIC-CONTRACT`` — every metric registration and span label must
  appear, with a matching type, in ``byzpy_tpu/observability/catalog.py``
  (single source of truth; the docs tables are checked against it).

Rules are deliberately *precise over complete*: each stays silent when
static resolution fails rather than guessing, so a finding is worth
reading. The self-scan gate (``tests/test_analysis_selfclean.py``) keeps
the shipped tree clean.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .astutils import (
    FunctionNode,
    MESH_HELPER_AXES,
    donation_from_call,
    enclosing_param_names,
    last_component,
    qualname,
    resolve_str,
    string_consts,
    traced_functions,
    _local_defs,
)
from .contexts import (
    CONCURRENT_LABELS,
    ContextMap,
    FnInfo,
    build_context_map,
    receiver_text,
)
from .core import Finding, ModuleInfo
from ..observability import catalog

TRACE_DISPATCH = "TRACE-DISPATCH"
DONATION = "DONATION"
AXIS_BINDING = "AXIS-BINDING"
HOST_SYNC = "HOST-SYNC"
ASYNC_BLOCKING = "ASYNC-BLOCKING"
PYTREE_REG = "PYTREE-REG"
THREAD_SHARED = "THREAD-SHARED"
ACK_ORDER = "ACK-ORDER"
PARITY_PURITY = "PARITY-PURITY"
METRIC_CONTRACT = "METRIC-CONTRACT"

#: collective name → positional index of the axis-name argument
COLLECTIVE_AXIS_ARG: Dict[str, int] = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "all_to_all": 1,
    "axis_index": 0,
    "axis_size": 0,
    # byzpy_tpu.parallel.collectives wrappers (same calling convention)
    "all_reduce_sum": 1,
    "all_reduce_mean": 1,
    "reduce_scatter_sum": 1,
    "neighbor_shift": 1,
    "ring_all_reduce_sum": 1,
    "all_gather_q": 1,
    "reduce_scatter_sum_q": 1,
    "all_to_all_q": 1,
}

#: in-repo pre-trace dispatch helpers (reading them mid-trace bakes the
#: first-call answer into the compiled executable — the PR-2 incident)
DISPATCH_HELPERS = {"_tuned_tile", "matmul_input_dtype"}

#: blocking callables by resolved qualified name
BLOCKING_QUALNAMES = {
    "time.sleep",
    "select.select",
    "socket.create_connection",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "os.system",
    "os.waitpid",
    "urllib.request.urlopen",
}

#: sync-socket method names (never awaitable; asyncio code uses streams)
BLOCKING_SOCKET_ATTRS = {"recv", "recv_into", "recvfrom", "accept"}

#: receiver-name hints for blocking ``.join()`` (process/thread handles —
#: kept narrow so ``", ".join(...)`` never matches)
JOIN_RECEIVER_HINTS = ("proc", "thread", "worker", "child")


@dataclass
class ScanContext:
    """Cross-module facts collected before rules run (pass 0).

    ``PYTREE-REG`` needs the whole scanned tree: a class is defined in
    one module (``QuantizedBlocks`` in ``parallel/quantization.py``) and
    flowed through a collective in another (``parallel/collectives.py``).
    The concurrency rules (``THREAD-SHARED`` / ``PARITY-PURITY``) share
    one execution-context classification per module, built here so the
    per-module call graph is computed once, not once per rule.
    """

    #: every class name defined anywhere in the scanned tree
    class_names: Set[str] = field(default_factory=set)
    #: subset registered as pytrees (decorator, registration call,
    #: NamedTuple base, or flax.struct dataclass)
    registered_pytrees: Set[str] = field(default_factory=set)
    #: module relpath → execution-context classification (contexts.py)
    contexts: Dict[str, ContextMap] = field(default_factory=dict)

    @staticmethod
    def build(modules: Sequence[ModuleInfo]) -> "ScanContext":
        """Collect class definitions and pytree registrations tree-wide."""
        ctx = ScanContext()
        reg_decorators = {
            "register_pytree_node_class",
            "register_pytree_with_keys_class",
        }
        reg_calls = {
            "register_pytree_node",
            "register_pytree_with_keys",
            "register_dataclass",
            "register_static",
        }
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    ctx.class_names.add(node.name)
                    for dec in node.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) else dec
                        dq = qualname(target, mod.imports)
                        if last_component(dq) in reg_decorators or (
                            dq is not None and dq.endswith("struct.dataclass")
                        ):
                            ctx.registered_pytrees.add(node.name)
                    for base in node.bases:
                        if last_component(qualname(base, mod.imports)) in (
                            "NamedTuple",
                        ):
                            ctx.registered_pytrees.add(node.name)
                elif isinstance(node, ast.Call):
                    if (
                        last_component(qualname(node.func, mod.imports))
                        in reg_calls
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                    ):
                        ctx.registered_pytrees.add(node.args[0].id)
        for mod in modules:
            ctx.contexts[mod.relpath] = build_context_map(mod)
        return ctx


class Rule:
    """Base class: one hazard, one ``check`` over a parsed module."""

    id: str = ""
    summary: str = ""

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Yield findings for ``mod`` (pure; no I/O)."""
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str) -> Finding:
        """Build a finding anchored at ``node``'s source location."""
        return Finding(
            self.id,
            mod.relpath,
            getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0),
            message,
        )


# ---------------------------------------------------------------------------
# TRACE-DISPATCH
# ---------------------------------------------------------------------------


class TraceDispatchRule(Rule):
    """No env/tile-cache/dispatch-config reads inside traced bodies."""

    id = TRACE_DISPATCH
    summary = (
        "os.environ / tile-cache / dispatch-config reads must resolve in "
        "the Python wrapper before trace, never inside a jitted body"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Flag env and dispatch-cache reads lexically inside any traced
        function (jit/shard_map/pmap decorated, wrapped, or a pallas
        kernel), including nested defs."""
        seen: Set[Tuple[int, int]] = set()
        for traced in traced_functions(mod.tree, mod.imports):
            for node in ast.walk(traced.node):
                key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
                if isinstance(node, ast.Attribute):
                    if qualname(node, mod.imports) == "os.environ" and key not in seen:
                        seen.add(key)
                        yield self.finding(
                            mod,
                            node,
                            "os.environ read inside a traced body — the "
                            "first-trace value is baked into the compiled "
                            "executable; resolve it in the Python wrapper "
                            "pre-trace (PR-2 wrapper pattern)",
                        )
                elif isinstance(node, ast.Call):
                    fq = qualname(node.func, mod.imports)
                    if fq == "os.getenv" and key not in seen:
                        seen.add(key)
                        yield self.finding(
                            mod,
                            node,
                            "os.getenv inside a traced body — resolve env "
                            "config in the wrapper pre-trace",
                        )
                    elif (
                        fq is not None
                        and (
                            fq.endswith("tilecache.lookup")
                            or last_component(fq) in DISPATCH_HELPERS
                        )
                        and key not in seen
                    ):
                        seen.add(key)
                        yield self.finding(
                            mod,
                            node,
                            f"dispatch helper {last_component(fq)!r} called "
                            "inside a traced body — tile/dtype dispatch is a "
                            "static jit argument and must be read pre-trace",
                        )


# ---------------------------------------------------------------------------
# DONATION
# ---------------------------------------------------------------------------


def _store_names(stmt: ast.stmt) -> Set[str]:
    """Names (re)bound by one statement, including tuple unpacking and
    loop targets."""
    out: Set[str] = set()
    for node in ast.walk(stmt):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            out.add(node.id)
    return out


class DonationRule(Rule):
    """No reads of a donated buffer after the donating jitted call."""

    id = DONATION
    summary = (
        "an argument donated via donate_argnums/donate_argnames must not "
        "be referenced after the jitted call in the same scope"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Track ``jax.jit(..., donate_arg*)`` callables (decorators and
        local assignments), then scan each call site's scope for
        use-after-donate — straight-line reads after the call, sibling
        reads in the same statement, and loop re-entry without rebinding."""
        defs = _local_defs(mod.tree)
        donating: Dict[str, object] = {}
        # decorated defs
        for name, fn in defs.items():
            for dec in getattr(fn, "decorator_list", []):
                if isinstance(dec, ast.Call):
                    # @partial(jax.jit, donate_...) — reuse the extractor
                    # by treating the decorator like a jit call wrapping fn
                    sig = donation_from_call(dec, mod.imports, defs)
                    if sig is not None:
                        args = getattr(fn, "args", None)
                        if args is not None:
                            sig.params = tuple(
                                a.arg for a in args.posonlyargs + args.args
                            )
                        donating[name] = sig
        # local `jitted = jax.jit(f, donate_...)` assignments bind the
        # donating callable to ONE scope — a same-named, non-donating
        # `step` in a sibling function must not inherit the signature
        def scope_assigns(scope: ast.AST) -> Dict[str, object]:
            out: Dict[str, object] = {}
            for node in _scope_nodes_ordered(scope):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    sig = donation_from_call(node.value, mod.imports, defs)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            if sig is not None:
                                out[tgt.id] = sig
                            else:
                                out.pop(tgt.id, None)
            return out

        module_assigns = scope_assigns(mod.tree)
        scopes: List[ast.AST] = [mod.tree]
        for node in ast.walk(mod.tree):
            if isinstance(node, FunctionNode):
                scopes.append(node)
        for scope in scopes:
            scoped = dict(donating)
            scoped.update(module_assigns)
            if scope is not mod.tree:
                local = scope_assigns(scope)
                # a local assignment SHADOWS any same-named outer binding,
                # donating or not
                for name in {
                    t.id
                    for n in _scope_nodes_ordered(scope)
                    if isinstance(n, ast.Assign)
                    for t in n.targets
                    if isinstance(t, ast.Name)
                }:
                    scoped.pop(name, None)
                scoped.update(local)
            if not scoped:
                continue
            yield from self._scan_block(mod, scope.body, scoped, loops=())

    def _scan_block(
        self,
        mod: ModuleInfo,
        block: Sequence[ast.stmt],
        donating: Dict[str, object],
        loops: Tuple[ast.stmt, ...],
    ) -> Iterator[Finding]:
        for idx, stmt in enumerate(block):
            if isinstance(stmt, FunctionNode):
                continue  # nested function bodies are their own scopes
            for call in self._donated_calls(stmt, donating):
                sig = donating[call.func.id]  # type: ignore[union-attr]
                for var, arg_node in sig.donated_args(call):  # type: ignore[attr-defined]
                    yield from self._check_use_after(
                        mod, block, idx, stmt, call, var, arg_node, loops
                    )
            # recurse into compound statements (their bodies are part of
            # this scope's control flow)
            for sub_block, is_loop in _sub_blocks(stmt):
                yield from self._scan_block(
                    mod,
                    sub_block,
                    donating,
                    loops + ((stmt,) if is_loop else ()),
                )

    @staticmethod
    def _donated_calls(
        stmt: ast.stmt, donating: Dict[str, object]
    ) -> List[ast.Call]:
        out = []
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                if node.func.id in donating:
                    out.append(node)
        return out

    def _check_use_after(
        self,
        mod: ModuleInfo,
        block: Sequence[ast.stmt],
        idx: int,
        stmt: ast.stmt,
        call: ast.Call,
        var: str,
        arg_node: ast.AST,
        loops: Tuple[ast.stmt, ...],
    ) -> Iterator[Finding]:
        call_arg_ids = {id(n) for n in ast.walk(call)}
        rebound_here = var in _store_names(stmt)
        # sibling read in the same statement, outside the call itself
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Name)
                and node.id == var
                and isinstance(node.ctx, ast.Load)
                and id(node) not in call_arg_ids
            ):
                yield self.finding(
                    mod,
                    node,
                    f"{var!r} is donated to {call.func.id!r} in this same "  # type: ignore[union-attr]
                    "statement — its buffer may already be reused",
                )
                return
        if not rebound_here:
            # straight-line reads after the call until a rebind. Loads are
            # checked per-statement BEFORE the rebind stops the scan:
            # `state = state + 1` rebinds, but its RHS still reads the
            # donated buffer first
            for later in block[idx + 1 :]:
                load = next(
                    (
                        node
                        for node in ast.walk(later)
                        if isinstance(node, ast.Name)
                        and node.id == var
                        and isinstance(node.ctx, ast.Load)
                    ),
                    None,
                )
                if load is not None:
                    yield self.finding(
                        mod,
                        load,
                        f"{var!r} read after being donated to "
                        f"{call.func.id!r} (line {call.lineno}) — "  # type: ignore[union-attr]
                        "use the call's result, or drop it from "
                        "donate_argnums",
                    )
                    return
                if var in _store_names(later):
                    return  # rebound (without a read) — safe from here on
            # loop re-entry: donated var never rebound inside the loop
            if loops:
                loop = loops[-1]
                if var not in _store_names(loop):
                    yield self.finding(
                        mod,
                        arg_node,
                        f"{var!r} is donated to {call.func.id!r} inside a "  # type: ignore[union-attr]
                        "loop but never rebound — the second iteration "
                        "passes an already-donated buffer",
                    )


def _sub_blocks(stmt: ast.stmt) -> List[Tuple[Sequence[ast.stmt], bool]]:
    """(block, is_loop_body) pairs for a compound statement's bodies."""
    out: List[Tuple[Sequence[ast.stmt], bool]] = []
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        out.append((stmt.body, True))
        out.append((stmt.orelse, False))
    elif isinstance(stmt, ast.If):
        out.append((stmt.body, False))
        out.append((stmt.orelse, False))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        out.append((stmt.body, False))
    elif isinstance(stmt, ast.Try):
        out.append((stmt.body, False))
        for handler in stmt.handlers:
            out.append((handler.body, False))
        out.append((stmt.orelse, False))
        out.append((stmt.finalbody, False))
    elif isinstance(stmt, ast.Match):
        for case in stmt.cases:
            out.append((case.body, False))
    return [(b, l) for b, l in out if b]


# ---------------------------------------------------------------------------
# AXIS-BINDING
# ---------------------------------------------------------------------------


class AxisBindingRule(Rule):
    """Collective axis names inside shard_map/pmap must be bound."""

    id = AXIS_BINDING
    summary = (
        "lax collective axis names inside shard_map/pmap bodies must be "
        "bound by the enclosing mesh/axis spec"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """For every shard_map/pmap-wrapped body whose binding fully
        resolves to literal axis names, flag collectives naming an axis
        outside that set. Unresolvable bindings (mesh built elsewhere,
        non-literal axis variables) stay silent — precision over recall."""
        module_consts = string_consts([mod.tree])
        for traced in traced_functions(mod.tree, mod.imports):
            if traced.kind not in ("shard_map", "pmap") or traced.binding is None:
                continue
            bound, complete = self._bound_axes(
                traced.binding, mod, module_consts, kind=traced.kind
            )
            if not complete:
                continue
            consts = dict(module_consts)
            consts.update(string_consts([traced.node]))
            for node in ast.walk(traced.node):
                if not isinstance(node, ast.Call):
                    continue
                name = last_component(qualname(node.func, mod.imports))
                if name not in COLLECTIVE_AXIS_ARG:
                    continue
                axis_expr = self._axis_expr(node, COLLECTIVE_AXIS_ARG[name])
                if axis_expr is None:
                    continue
                axis = resolve_str(axis_expr, consts)
                if axis is not None and axis not in bound:
                    bound_desc = ", ".join(sorted(bound)) or "<none>"
                    yield self.finding(
                        mod,
                        axis_expr,
                        f"collective {name!r} uses axis {axis!r} but the "
                        f"enclosing {traced.kind} binds only [{bound_desc}]",
                    )

    @staticmethod
    def _axis_expr(call: ast.Call, pos: int) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return kw.value
        if len(call.args) > pos:
            return call.args[pos]
        return None

    def _bound_axes(
        self,
        binding: ast.Call,
        mod: ModuleInfo,
        consts: Dict[str, Optional[str]],
        *,
        kind: str,
    ) -> Tuple[Set[str], bool]:
        """Literal axis names bound by a shard_map/pmap wrapping call,
        plus whether the binding resolved completely."""
        bound: Set[str] = set()
        complete = True
        if kind == "pmap":
            for kw in binding.keywords:
                if kw.arg == "axis_name":
                    axis = resolve_str(kw.value, consts)
                    if axis is None:
                        return set(), False
                    bound.add(axis)
            return bound, True
        # shard_map: the bound axes are the MESH's axis names (specs name
        # a subset — a collective may legally reduce over a mesh axis the
        # specs never mention). Enforcement therefore requires the mesh to
        # resolve statically; spec tokens only ever add to the bound set.
        mesh_axes = None
        for kw in binding.keywords:
            if kw.arg == "mesh":
                mesh_axes = self._mesh_axes(kw.value, mod, consts)
        if mesh_axes is None:
            for arg in list(binding.args)[1:]:
                mesh_axes = self._mesh_axes(arg, mod, consts)
                if mesh_axes is not None:
                    break
        if mesh_axes is None:
            return set(), False
        bound |= mesh_axes
        for arg in list(binding.args)[1:] + [
            kw.value for kw in binding.keywords if kw.arg != "mesh"
        ]:
            self._spec_tokens(arg, mod, consts, bound)
        return bound, complete

    @staticmethod
    def _spec_tokens(
        expr: ast.AST,
        mod: ModuleInfo,
        consts: Dict[str, Optional[str]],
        bound: Set[str],
    ) -> bool:
        """Collect literal axis tokens from P(...)/PartitionSpec(...)
        expressions; returns False when any token fails to resolve."""
        ok = True
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = last_component(qualname(node.func, mod.imports))
                if name not in ("P", "PartitionSpec"):
                    continue
                for sub in list(node.args) + [k.value for k in node.keywords]:
                    for leaf in ast.walk(sub):
                        if isinstance(leaf, ast.Constant):
                            if isinstance(leaf.value, str):
                                bound.add(leaf.value)
                            # None literals are fine (replicated dims)
                        elif isinstance(leaf, ast.Name):
                            lit = consts.get(leaf.id)
                            if lit is None:
                                ok = False
                            else:
                                bound.add(lit)
        return ok

    @staticmethod
    def _mesh_axes(
        expr: ast.AST, mod: ModuleInfo, consts: Dict[str, Optional[str]]
    ) -> Optional[Set[str]]:
        """Axis names of the mesh expression when statically derivable."""

        def from_call(call: ast.Call) -> Optional[Set[str]]:
            name = last_component(qualname(call.func, mod.imports))
            if name in MESH_HELPER_AXES:
                return set(MESH_HELPER_AXES[name])
            if name in ("Mesh", "make_mesh", "create_device_mesh"):
                for sub in list(call.args) + [
                    k.value for k in call.keywords
                ]:
                    if isinstance(sub, (ast.Tuple, ast.List)) and sub.elts:
                        axes: Set[str] = set()
                        for elt in sub.elts:
                            lit = resolve_str(elt, consts)
                            if lit is None:
                                break
                            axes.add(lit)
                        else:
                            return axes
            return None

        if isinstance(expr, ast.Call):
            return from_call(expr)
        if isinstance(expr, ast.Name):
            # one-hop resolution: mesh = Mesh(..., ("nodes",)) earlier
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                    and isinstance(node.value, ast.Call)
                ):
                    return from_call(node.value)
        return None


# ---------------------------------------------------------------------------
# HOST-SYNC
# ---------------------------------------------------------------------------

ROUND_LOOP_DIRS = ("engine/parameter_server/", "engine/peer_to_peer/")


class HostSyncRule(Rule):
    """No host-sync forcing (``.item()``, ``np.asarray``) on traced values."""

    id = HOST_SYNC
    summary = (
        "no .item()/float()/np.asarray on traced values inside jitted "
        "bodies, and no forced device syncs in the PS/gossip round loops"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Two contexts: (a) traced bodies — any ``.item()`` /
        ``block_until_ready`` / ``jax.device_get`` / numpy materialization
        / ``float(param)``; (b) loop bodies of async round drivers under
        ``engine/parameter_server`` and ``engine/peer_to_peer`` — sync
        forcers that stall the overlap pipeline."""
        emitted: Set[Tuple[int, int]] = set()
        for traced in traced_functions(mod.tree, mod.imports):
            params = enclosing_param_names(traced.node)
            for inner in ast.walk(traced.node):
                if isinstance(inner, (*FunctionNode, ast.Lambda)):
                    params = params | enclosing_param_names(inner)
            params -= traced.static_params
            for node in ast.walk(traced.node):
                f = self._sync_finding(mod, node, params, "a traced body")
                if f is not None:
                    key = (f.line, f.col)
                    if key not in emitted:
                        emitted.add(key)
                        yield f
        rel = mod.relpath.replace("\\", "/")
        if any(d in rel for d in ROUND_LOOP_DIRS):
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.AsyncFunctionDef):
                    continue
                for loop in ast.walk(node):
                    if not isinstance(
                        loop, (ast.For, ast.While, ast.AsyncFor)
                    ):
                        continue
                    for sub in ast.walk(loop):
                        f = self._sync_finding(
                            mod, sub, set(), "the async round loop"
                        )
                        if f is not None:
                            key = (f.line, f.col)
                            if key not in emitted:
                                emitted.add(key)
                                yield f

    def _sync_finding(
        self,
        mod: ModuleInfo,
        node: ast.AST,
        params: Set[str],
        where: str,
    ) -> Optional[Finding]:
        if not isinstance(node, ast.Call):
            return None
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                return self.finding(
                    mod,
                    node,
                    f".item() in {where} forces a host sync "
                    "(TracerConversionError under jit; a pipeline stall in "
                    "the round loop) — keep values on device or hoist to "
                    "the host boundary",
                )
            if node.func.attr == "block_until_ready":
                return self.finding(
                    mod,
                    node,
                    f"block_until_ready() in {where} forces a device sync",
                )
        fq = qualname(node.func, mod.imports)
        if fq == "jax.device_get":
            return self.finding(
                mod, node, f"jax.device_get in {where} forces a host transfer"
            )
        if (
            fq is not None
            and fq.startswith("numpy.")
            and last_component(fq) in ("asarray", "array")
        ):
            return self.finding(
                mod,
                node,
                f"{last_component(fq)} (numpy) in {where} materializes a "
                "traced value on host — use jnp, or move this out of the "
                "traced/round-loop region",
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and params
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in params
        ):
            return self.finding(
                mod,
                node,
                f"{node.func.id}() on traced argument "
                f"{node.args[0].id!r} in {where} — python scalar "
                "conversion fails under trace",
            )
        return None


# ---------------------------------------------------------------------------
# ASYNC-BLOCKING
# ---------------------------------------------------------------------------


class AsyncBlockingRule(Rule):
    """No blocking calls directly inside ``async def`` bodies."""

    id = ASYNC_BLOCKING
    summary = (
        "no time.sleep / sync socket ops / blocking file-process I/O "
        "directly inside async def (actor/node fabric shares one loop)"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Walk each ``async def`` whose *nearest* function scope is that
        async def (nested sync defs are executor targets and exempt),
        flagging known blocking callables that are not awaited."""
        yield from self._visit(mod, mod.tree.body)

    def _visit(
        self, mod: ModuleInfo, body: Sequence[ast.stmt]
    ) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, ast.AsyncFunctionDef):
                yield from self._scan_async_body(mod, stmt)
                yield from self._visit(mod, stmt.body)
            elif isinstance(stmt, (ast.FunctionDef, ast.ClassDef)):
                yield from self._visit(mod, stmt.body)
            else:
                for sub_block, _ in _sub_blocks(stmt):
                    yield from self._visit(mod, sub_block)

    def _scan_async_body(
        self, mod: ModuleInfo, fn: ast.AsyncFunctionDef
    ) -> Iterator[Finding]:
        awaited: Set[int] = set()
        skip: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Await):
                for sub in ast.walk(node.value):
                    awaited.add(id(sub))
            # nested function bodies (sync defs = executor targets,
            # nested async defs are scanned on their own) are exempt
            if isinstance(node, (*FunctionNode, ast.Lambda)) and node is not fn:
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(fn):
            if (
                not isinstance(node, ast.Call)
                or id(node) in skip
                or id(node) in awaited
            ):
                continue
            msg = self._blocking_reason(node, mod)
            if msg is not None:
                yield self.finding(
                    mod,
                    node,
                    f"{msg} inside async def {fn.name!r} stalls the shared "
                    "event loop — use the asyncio equivalent or "
                    "loop.run_in_executor",
                )

    @staticmethod
    def _blocking_reason(node: ast.Call, mod: ModuleInfo) -> Optional[str]:
        fq = qualname(node.func, mod.imports)
        if fq in BLOCKING_QUALNAMES:
            return f"blocking call {fq}"
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            if "open" not in mod.imports:
                return "blocking file open()"
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in BLOCKING_SOCKET_ATTRS:
                return f"sync socket .{attr}()"
            if attr == "join":
                recv = node.func.value
                tail = ""
                if isinstance(recv, ast.Attribute):
                    tail = recv.attr
                elif isinstance(recv, ast.Name):
                    tail = recv.id
                if any(h in tail.lower() for h in JOIN_RECEIVER_HINTS):
                    return f"blocking {tail}.join()"
        return None


# ---------------------------------------------------------------------------
# PYTREE-REG
# ---------------------------------------------------------------------------


def _scope_nodes_ordered(scope: ast.AST) -> List[ast.AST]:
    """Nodes belonging to one scope (nested function/lambda subtrees
    excluded), sorted by source position so assignment→use order holds."""
    skip: Set[int] = set()
    for node in ast.walk(scope):
        if node is not scope and isinstance(node, (*FunctionNode, ast.Lambda)):
            for sub in ast.walk(node):
                skip.add(id(sub))
    nodes = [
        n
        for n in ast.walk(scope)
        if id(n) not in skip and hasattr(n, "lineno")
    ]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    return nodes


class PytreeRegRule(Rule):
    """Classes flowed through collectives must be registered pytrees."""

    id = PYTREE_REG
    summary = (
        "an instance of a scanned-tree class passed to a collective must "
        "be a registered pytree (register_pytree_node[_class], "
        "flax.struct, or NamedTuple)"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Flag collective payloads that are (or resolve one assignment
        back to) constructor calls of scanned-tree classes lacking pytree
        registration."""
        emitted: Set[Tuple[int, int]] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (*FunctionNode, ast.Module)):
                continue
            scope = node
            # latest constructor assignment per name, in textual order,
            # over this scope's OWN nodes (nested defs are their own
            # scopes — mixing their locals in would invent dataflow)
            ctor_of: Dict[str, str] = {}
            for sub in _scope_nodes_ordered(scope):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Name):
                        cls = self._ctor_class(sub.value, mod, ctx)
                        if cls is not None:
                            ctor_of[tgt.id] = cls
                        elif tgt.id in ctor_of:
                            del ctor_of[tgt.id]
                if not isinstance(sub, ast.Call):
                    continue
                name = last_component(qualname(sub.func, mod.imports))
                if name not in COLLECTIVE_AXIS_ARG or not sub.args:
                    continue
                payload = sub.args[0]
                cls = self._ctor_class(payload, mod, ctx)
                if cls is None and isinstance(payload, ast.Name):
                    cls = ctor_of.get(payload.id)
                key = (payload.lineno, payload.col_offset)
                if (
                    cls is not None
                    and cls not in ctx.registered_pytrees
                    and key not in emitted
                ):
                    emitted.add(key)
                    yield self.finding(
                        mod,
                        payload,
                        f"{cls!r} flows through collective {name!r} but is "
                        "not a registered pytree — decorate it with "
                        "@jax.tree_util.register_pytree_node_class (see "
                        "QuantizedBlocks) or register it explicitly",
                    )

    @staticmethod
    def _ctor_class(
        expr: ast.AST, mod: ModuleInfo, ctx: ScanContext
    ) -> Optional[str]:
        if isinstance(expr, ast.Call):
            name = last_component(qualname(expr.func, mod.imports))
            if name in ctx.class_names:
                return name
        return None


# ---------------------------------------------------------------------------
# THREAD-SHARED
# ---------------------------------------------------------------------------

#: receiver-name hints that make a ``with`` context manager count as a
#: lock guard (identity = the full dotted receiver text)
LOCK_NAME_HINTS = ("lock", "mutex", "sem")

#: methods that run before the object is published to other contexts
CONSTRUCTOR_METHODS = {"__init__", "__new__", "__post_init__"}


def _self_root_attr(expr: ast.AST) -> Optional[str]:
    """The attribute directly on ``self`` at the root of a store target
    (``self.a`` / ``self.a[k]`` / ``self.a.b`` all root at ``a``) —
    container/field mutation counts as writing the root attribute."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    if isinstance(expr, (ast.Attribute, ast.Subscript)):
        return _self_root_attr(expr.value)
    return None


def _lock_guard_name(expr: ast.AST) -> Optional[str]:
    """Guard identity of a ``with`` item when it looks like a lock."""
    text = receiver_text(expr)
    if any(h in text for h in LOCK_NAME_HINTS):
        return text
    return None


class ThreadSharedRule(Rule):
    """Cross-context ``self.*`` writes need a common lock guard."""

    id = THREAD_SHARED
    summary = (
        "a self.* attribute written from two execution contexts (event "
        "loop / reader thread / executor) needs a common lock guard"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Group ``self.*`` stores per class/attribute with the writing
        method's context labels (per the :mod:`.contexts` classifier) and
        the lock guards lexically held at the store. Flag attributes
        written from ≥2 distinct concurrent contexts when no single lock
        covers every write."""
        cmap = ctx.contexts.get(mod.relpath)
        if cmap is None:
            return
        # class → attr → [(anchor, labels, guards)]
        writes: Dict[str, Dict[str, List[Tuple[ast.AST, Set[str], Set[str]]]]]
        writes = {}
        for info in cmap.fns.values():
            if info.class_name is None or info.name in CONSTRUCTOR_METHODS:
                continue
            labels = info.labels & CONCURRENT_LABELS
            if not labels:
                continue
            for attr, anchor, guards in self._stores(info.node):
                writes.setdefault(info.class_name, {}).setdefault(
                    attr, []
                ).append((anchor, labels, guards))
        for cls in sorted(writes):
            for attr, sites in sorted(writes[cls].items()):
                contexts: Set[str] = set()
                for _, labels, _ in sites:
                    contexts |= labels
                if len(contexts) < 2:
                    continue
                common = set(sites[0][2])
                for _, _, guards in sites[1:]:
                    common &= guards
                if common:
                    continue
                anchor = min(
                    (a for a, _, _ in sites),
                    key=lambda n: (n.lineno, n.col_offset),
                )
                ctx_desc = "/".join(sorted(contexts))
                yield self.finding(
                    mod,
                    anchor,
                    f"{cls}.{attr} is written from {ctx_desc} contexts "
                    "with no common lock — serialize every write under "
                    "one `with self.<lock>:`, or confine mutation to a "
                    "single context via an epoch-stamped handoff (the "
                    "PR 19 staging split)",
                )

    @staticmethod
    def _stores(
        fn: ast.AST,
    ) -> Iterator[Tuple[str, ast.AST, Set[str]]]:
        """``(attr, anchor, lock-guards-held)`` for every ``self.*``
        store lexically in ``fn``'s own body (nested defs are their own
        functions and classified separately)."""

        def targets_of(stmt: ast.stmt) -> List[ast.AST]:
            if isinstance(stmt, ast.Assign):
                return list(stmt.targets)
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                return [stmt.target]
            if isinstance(stmt, ast.Delete):
                return list(stmt.targets)
            return []

        def scan(
            stmts: Sequence[ast.stmt], guards: Set[str]
        ) -> Iterator[Tuple[str, ast.AST, Set[str]]]:
            for stmt in stmts:
                if isinstance(stmt, FunctionNode):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    held = set(guards)
                    for item in stmt.items:
                        g = _lock_guard_name(item.context_expr)
                        if g is not None:
                            held.add(g)
                    yield from scan(stmt.body, held)
                    continue
                for tgt in targets_of(stmt):
                    attr = _self_root_attr(tgt)
                    if attr is not None:
                        yield attr, tgt, set(guards)
                for sub, _ in _sub_blocks(stmt):
                    yield from scan(sub, guards)

        yield from scan(getattr(fn, "body", []), set())


# ---------------------------------------------------------------------------
# ACK-ORDER
# ---------------------------------------------------------------------------

#: writer-ish method names that emit an ack/reply toward a client
SEND_ATTRS = {"write", "sendall", "send", "send_bytes"}
SEND_RECEIVER_HINTS = (
    "writer", "sock", "conn", "transport", "stream", "wfile", "chan",
)
#: durability-object hints: appends on these are WAL records
WAL_RECEIVER_HINTS = ("durability", "wal", "journal")


def _ackish_name(name: str) -> bool:
    """Callable names that mean "emit the ack" (kept to word matches so
    ``pack``/``callback``/``track`` never count)."""
    low = name.lower()
    return (
        low == "ack"
        or low.endswith("_ack")
        or low.startswith("ack_")
        or "send_ack" in low
    )


class AckOrderRule(Rule):
    """The WAL append must dominate the ack on every path."""

    id = ACK_ORDER
    summary = (
        "in a function that both appends to a durability/WAL object and "
        "sends on a writer, the append must come before the send on "
        "every path — an ack is a durable promise"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Flow-sensitive single pass per function: track "a send has
        happened on this path" through branches (union on merge, return/
        raise kills the path) and flag any WAL append reached with a
        send already behind it. Runs only on functions containing both
        event kinds — everything else is out of contract."""
        for node in ast.walk(mod.tree):
            if isinstance(node, FunctionNode):
                yield from self._check_fn(mod, node)

    def _check_fn(
        self, mod: ModuleInfo, fn: ast.AST
    ) -> Iterator[Finding]:
        kinds = {
            self._event_kind(n, mod)
            for n in self._own_nodes(fn)
            if isinstance(n, ast.Call)
        }
        if not ({"send", "append"} <= kinds):
            return
        out: List[Finding] = []
        self._flow(mod, fn.body, False, out)
        yield from out

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
        """Nodes in ``fn``'s own scope (nested def subtrees excluded)."""
        skip: Set[int] = set()
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (*FunctionNode, ast.Lambda)):
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(fn):
            if id(node) not in skip:
                yield node

    def _event_kind(self, call: ast.Call, mod: ModuleInfo) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute):
            recv = receiver_text(func.value)
            if func.attr in SEND_ATTRS and any(
                h in recv for h in SEND_RECEIVER_HINTS
            ):
                return "send"
            if (
                func.attr.startswith("record_") or func.attr == "append"
            ) and any(h in recv for h in WAL_RECEIVER_HINTS):
                return "append"
            if _ackish_name(func.attr):
                return "send"
        elif isinstance(func, ast.Name) and _ackish_name(func.id):
            return "send"
        return None

    def _header_events(
        self,
        mod: ModuleInfo,
        stmt: ast.stmt,
        sent: bool,
        out: List[Finding],
    ) -> bool:
        """Process the events of one statement's own expressions (its
        sub-blocks and nested defs excluded), in source order."""
        skip: Set[int] = set()
        for blk, _ in _sub_blocks(stmt):
            for s in blk:
                for n in ast.walk(s):
                    skip.add(id(n))
        for n in ast.walk(stmt):
            if isinstance(n, (*FunctionNode, ast.Lambda)):
                for sub in ast.walk(n):
                    skip.add(id(sub))
        events: List[Tuple[int, int, str, ast.Call]] = []
        for n in ast.walk(stmt):
            if id(n) in skip or not isinstance(n, ast.Call):
                continue
            kind = self._event_kind(n, mod)
            if kind is not None:
                events.append((n.lineno, n.col_offset, kind, n))
        for _, _, kind, n in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == "send":
                sent = True
            elif sent:
                out.append(
                    self.finding(
                        mod,
                        n,
                        "durable append reached with an ack/send already "
                        "emitted on this path — the WAL append must "
                        "dominate the ack (a crash between them replays "
                        "an un-promised submission: the PR 9 double-fold "
                        "incident)",
                    )
                )
        return sent

    def _flow(
        self,
        mod: ModuleInfo,
        stmts: Sequence[ast.stmt],
        sent: bool,
        out: List[Finding],
    ) -> Tuple[bool, bool]:
        """Returns ``(sent_at_exit, path_alive)``."""
        alive = True
        for stmt in stmts:
            if isinstance(stmt, FunctionNode):
                continue
            sent = self._header_events(mod, stmt, sent, out)
            if isinstance(
                stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue)
            ):
                return sent, False
            if isinstance(stmt, ast.If):
                s_a, a_a = self._flow(mod, stmt.body, sent, out)
                s_b, a_b = self._flow(mod, stmt.orelse, sent, out)
                alive = a_a or a_b
                sent = (a_a and s_a) or (a_b and s_b)
                if not alive:
                    return sent, False
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                s_body, _ = self._flow(mod, stmt.body, sent, out)
                # zero-iteration exit is always possible; break/return
                # subtleties are deliberately ignored (one pass, no
                # loop-carry — precision over completeness)
                sent = sent or s_body
                s_else, a_else = self._flow(mod, stmt.orelse, sent, out)
                if stmt.orelse and a_else:
                    sent = s_else
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                sent, alive = self._flow(mod, stmt.body, sent, out)
                if not alive:
                    return sent, False
            elif isinstance(stmt, ast.Try):
                s_body, a_body = self._flow(mod, stmt.body, sent, out)
                exits: List[bool] = []
                if a_body:
                    if stmt.orelse:
                        s_else, a_else = self._flow(
                            mod, stmt.orelse, s_body, out
                        )
                        if a_else:
                            exits.append(s_else)
                    else:
                        exits.append(s_body)
                for handler in stmt.handlers:
                    # an exception can fire before any send in the body:
                    # handlers start from the entry state
                    s_h, a_h = self._flow(mod, handler.body, sent, out)
                    if a_h:
                        exits.append(s_h)
                alive = bool(exits)
                sent = any(exits)
                s_fin, a_fin = self._flow(mod, stmt.finalbody, sent, out)
                if stmt.finalbody:
                    sent, alive = s_fin, alive and a_fin
                if not alive:
                    return sent, False
            elif isinstance(stmt, ast.Match):
                exits = []
                for case in stmt.cases:
                    s_c, a_c = self._flow(mod, case.body, sent, out)
                    if a_c:
                        exits.append(s_c)
                # no exhaustiveness check: fall-through keeps entry state
                sent = sent or any(exits)
        return sent, alive


# ---------------------------------------------------------------------------
# PARITY-PURITY
# ---------------------------------------------------------------------------

#: functions on the digest-parity contract by exact name
PARITY_ROOT_NAMES = {"combine_partials", "gram_block"}

#: nondeterminism sources by qualified-name prefix
IMPURE_CALL_PREFIXES = (
    "time.",
    "random.",
    "numpy.random.",
    "secrets.",
    "uuid.",
)
IMPURE_CALL_EXACT = {"os.urandom"}


def _is_parity_root(name: str) -> bool:
    """Whether a function name puts it on the digest-parity contract."""
    return (
        name in PARITY_ROOT_NAMES
        or "digest" in name
        or name.startswith("fold_merge")
    )


class ParityPurityRule(Rule):
    """No clocks/RNG/set-iteration in digest-parity code."""

    id = PARITY_PURITY
    summary = (
        "functions reachable from the digest-parity set (fold_merge_*, "
        "combine_partials, gram_block, *digest*) must not call clocks/"
        "RNG or iterate bare sets into folded bytes"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Close the module-local call graph over the parity roots, then
        flag nondeterminism inside every reachable function: clock/RNG
        calls by qualified name, and ``for``/comprehension iteration
        over bare set expressions (``sorted(...)`` launders the order)."""
        cmap = ctx.contexts.get(mod.relpath)
        if cmap is None:
            return
        reach: Dict[int, str] = {}
        queue: List[FnInfo] = []
        for info in cmap.fns.values():
            if _is_parity_root(info.name):
                reach[id(info.node)] = info.name
                queue.append(info)
        while queue:
            info = queue.pop()
            for cid in info.callees:
                if cid not in reach:
                    reach[cid] = reach[id(info.node)]
                    queue.append(cmap.fns[cid])
        for info in sorted(
            cmap.fns.values(), key=lambda i: getattr(i.node, "lineno", 0)
        ):
            root = reach.get(id(info.node))
            if root is None:
                continue
            yield from self._scan_fn(mod, cmap, info, root)

    def _scan_fn(
        self, mod: ModuleInfo, cmap: ContextMap, info: FnInfo, root: str
    ) -> Iterator[Finding]:
        via = "" if root == info.name else f" (parity-reachable from {root!r})"
        for node in ast.walk(info.node):
            if node is not info.node and cmap.owner.get(id(node)) is not info:
                continue  # nested defs are classified on their own
            if isinstance(node, ast.Call):
                fq = qualname(node.func, mod.imports)
                if fq is not None and (
                    fq in IMPURE_CALL_EXACT
                    or fq.startswith(IMPURE_CALL_PREFIXES)
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"{fq} inside {info.name!r}{via} — digest-parity "
                        "code must be bit-deterministic; hoist clocks/RNG "
                        "to the caller (the PR 7 digest-drift class)",
                    )
            iters: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(
                node,
                (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp),
            ):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if self._bare_set(it, mod):
                    yield self.finding(
                        mod,
                        it,
                        f"iterating a bare set inside {info.name!r}{via} — "
                        "set order is nondeterministic across processes; "
                        "wrap it in sorted(...) before it reaches folded "
                        "bytes",
                    )

    @staticmethod
    def _bare_set(expr: ast.AST, mod: ModuleInfo) -> bool:
        if isinstance(expr, ast.Set) or isinstance(expr, ast.SetComp):
            return True
        if isinstance(expr, ast.Call):
            return last_component(qualname(expr.func, mod.imports)) in (
                "set",
                "frozenset",
            )
        return False


# ---------------------------------------------------------------------------
# METRIC-CONTRACT
# ---------------------------------------------------------------------------

#: MetricsRegistry factory method names → instrument type
METRIC_FACTORY_ATTRS = {"counter", "gauge", "histogram"}
#: receiver hints for registry objects (``reg``, ``registry()``,
#: ``self._metrics``) — an unrelated ``.counter()`` never matches
METRIC_RECEIVER_HINTS = ("reg", "metric")
#: tracing entry points that take a span/instant label
SPAN_CALL_NAMES = {"span", "device_span", "begin_span", "instant"}
SPAN_RECEIVER_HINTS = ("tracing", "tracer", "trace")


class MetricContractRule(Rule):
    """Metric and span names must match the observability catalog."""

    id = METRIC_CONTRACT
    summary = (
        "every Counter/Gauge/Histogram registration and span() label "
        "must appear, with a matching type, in "
        "byzpy_tpu/observability/catalog.py (and the docs tables)"
    )

    def check(self, mod: ModuleInfo, ctx: ScanContext) -> Iterator[Finding]:
        """Check the literal first argument of registry factory calls
        and tracing span/instant calls against the catalog. Computed
        names stay silent unless a declared dynamic prefix covers them —
        a new dynamic family must be catalogued as a prefix."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._literal_name(node)
            if name is None:
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in METRIC_FACTORY_ATTRS
            ):
                recv = receiver_text(func.value)
                if any(h in recv for h in METRIC_RECEIVER_HINTS):
                    yield from self._check_metric(mod, node, func.attr, name)
                continue
            fq = qualname(func, mod.imports) or ""
            last = last_component(fq) or (
                func.attr if isinstance(func, ast.Attribute) else ""
            )
            if last not in SPAN_CALL_NAMES:
                continue
            is_tracing = any(
                fq.endswith("tracing." + s) for s in SPAN_CALL_NAMES
            ) or (
                isinstance(func, ast.Attribute)
                and any(
                    h in receiver_text(func.value)
                    for h in SPAN_RECEIVER_HINTS
                )
            )
            if is_tracing:
                yield from self._check_span(mod, node, name)

    @staticmethod
    def _literal_name(call: ast.Call) -> Optional[str]:
        expr: Optional[ast.AST] = call.args[0] if call.args else None
        if expr is None:
            for kw in call.keywords:
                if kw.arg == "name":
                    expr = kw.value
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    def _check_metric(
        self, mod: ModuleInfo, node: ast.Call, kind: str, name: str
    ) -> Iterator[Finding]:
        want = catalog.METRICS.get(name)
        if want is None:
            if name.startswith(catalog.METRIC_PREFIXES):
                return
            yield self.finding(
                mod,
                node,
                f"metric {name!r} is not in the observability catalog — "
                "add it to byzpy_tpu/observability/catalog.py and the "
                "docs/observability.md table",
            )
        elif want != kind:
            yield self.finding(
                mod,
                node,
                f"metric {name!r} registered as a {kind} but catalogued "
                f"as a {want} — one name, one type",
            )

    def _check_span(
        self, mod: ModuleInfo, node: ast.Call, name: str
    ) -> Iterator[Finding]:
        if name in catalog.SPANS or name.startswith(catalog.SPAN_PREFIXES):
            return
        yield self.finding(
            mod,
            node,
            f"span label {name!r} is not in the observability catalog — "
            "add it to byzpy_tpu/observability/catalog.py and the "
            "docs/observability.md span taxonomy",
        )


#: the shipped rule set, in reporting order
ALL_RULES: Tuple[Rule, ...] = (
    TraceDispatchRule(),
    DonationRule(),
    AxisBindingRule(),
    HostSyncRule(),
    AsyncBlockingRule(),
    PytreeRegRule(),
    ThreadSharedRule(),
    AckOrderRule(),
    ParityPurityRule(),
    MetricContractRule(),
)

__all__ = [
    "ACK_ORDER",
    "ALL_RULES",
    "ASYNC_BLOCKING",
    "AXIS_BINDING",
    "AckOrderRule",
    "AsyncBlockingRule",
    "AxisBindingRule",
    "COLLECTIVE_AXIS_ARG",
    "DONATION",
    "DonationRule",
    "HOST_SYNC",
    "HostSyncRule",
    "METRIC_CONTRACT",
    "MetricContractRule",
    "PARITY_PURITY",
    "PYTREE_REG",
    "ParityPurityRule",
    "PytreeRegRule",
    "Rule",
    "ScanContext",
    "THREAD_SHARED",
    "TRACE_DISPATCH",
    "ThreadSharedRule",
    "TraceDispatchRule",
]

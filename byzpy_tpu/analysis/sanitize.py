"""Runtime invariant sanitizer — the dynamic half of byzlint.

The static rules (``byzpy_tpu/analysis/rules.py``) close what a scan
can prove; this module asserts the invariants that only exist at
runtime, as cheap opt-in hooks compiled into the serving tier:

* ``loop_tick(name, threshold_s)`` — event-loop stall watchdog: each
  scheduler-loop iteration ticks; a monotonic gap above the threshold
  means something blocked the loop (the ASYNC-BLOCKING rule's dynamic
  twin — it catches the blocking call the classifier couldn't see).
* ``audit_fold(tenant, round_id, keys)`` — exactly-once fold audit on
  every round close: a tenant's round ids must be strictly increasing
  (a repeated id is the double-fold shape the PR 9 incident shipped),
  and an idempotency-keyed submission must fold at most once.
* ``check_drained(name, value)`` — quiescence drain: at coordinator
  close, ``byzpy_root_partials_inflight`` must read 0; a leaked
  partial means a verify/merge path lost a decrement.

The sanitizer NEVER raises on the hot path and touches no RNG or
virtual clock — violations are recorded and surfaced later via
:func:`assert_clean`, so a sanitized run's event-trace digest is
bit-identical to the unsanitized twin *by construction* (the chaos
bench's ``sanitize`` leg pins exactly that). Enable with
``BYZPY_TPU_SANITIZE=1`` in the environment or :func:`enable` in
code; disabled, every hook is one predicate check.

Stdlib only — importable from the serving hot path without jax.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

_TRUTHY = ("1", "true", "yes", "on")


class _Sanitizer:
    """Process-wide sanitizer state (thread-safe; hooks fire from the
    event loop, the fold executor, and reader threads alike)."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "BYZPY_TPU_SANITIZE", ""
        ).lower() in _TRUTHY
        self._lock = threading.Lock()
        self.violations: List[str] = []
        self._last_tick: Dict[str, float] = {}
        self._last_round: Dict[str, int] = {}
        self._folded_keys: set = set()
        self.counters: Dict[str, int] = {
            "loop_ticks": 0,
            "folds_audited": 0,
            "drain_checks": 0,
        }

    def _violate(self, message: str) -> None:
        with self._lock:
            self.violations.append(message)


_STATE = _Sanitizer()


def enabled() -> bool:
    """Whether hooks are live (env ``BYZPY_TPU_SANITIZE`` or
    :func:`enable`)."""
    return _STATE.enabled


def enable() -> None:
    """Turn the hooks on for this process (tests, bench legs)."""
    _STATE.enabled = True


def disable() -> None:
    """Turn the hooks back off (state is kept; :func:`reset` drops it)."""
    _STATE.enabled = False


def reset() -> None:
    """Drop recorded violations, watchdog marks, audit state and
    counters — call between independent runs (the enable flag is
    preserved)."""
    with _STATE._lock:
        _STATE.violations.clear()
        _STATE._last_tick.clear()
        _STATE._last_round.clear()
        _STATE._folded_keys.clear()
        for k in _STATE.counters:
            _STATE.counters[k] = 0


def loop_tick(name: str, threshold_s: float = 1.0) -> None:
    """One scheduler-loop heartbeat. A monotonic gap since the previous
    tick above ``threshold_s`` records a stall violation — something
    blocked the loop between iterations. Thresholds are the CALLER's
    job to set generously (a window-length sleep is not a stall)."""
    if not _STATE.enabled:
        return
    now = time.monotonic()
    with _STATE._lock:
        _STATE.counters["loop_ticks"] += 1
        prev = _STATE._last_tick.get(name)
        _STATE._last_tick[name] = now
    if prev is not None and now - prev > threshold_s:
        _STATE._violate(
            f"loop-stall[{name}]: {now - prev:.3f}s between ticks "
            f"(threshold {threshold_s:.3f}s) — a blocking call is "
            f"riding the loop"
        )


def audit_fold(
    tenant: str,
    round_id: int,
    keys: Iterable[Tuple[str, Optional[Any]]] = (),
) -> None:
    """Exactly-once audit for one round close. ``keys`` is the folded
    cohort's ``(client, seq)`` pairs; pairs with ``seq=None`` (legacy
    clients — no idempotency key) are skipped, the round-monotonicity
    check still runs."""
    if not _STATE.enabled:
        return
    with _STATE._lock:
        _STATE.counters["folds_audited"] += 1
        last = _STATE._last_round.get(tenant)
        _STATE._last_round[tenant] = round_id
        dup_rounds = last is not None and round_id <= last
        dup_keys = []
        for client, seq in keys:
            if seq is None:
                continue
            key = (tenant, client, seq)
            if key in _STATE._folded_keys:
                dup_keys.append((client, seq))
            else:
                _STATE._folded_keys.add(key)
    if dup_rounds:
        _STATE._violate(
            f"double-fold[{tenant}]: round {round_id} closed after "
            f"round {last} — round ids must strictly increase "
            f"(exactly-once close)"
        )
    for client, seq in dup_keys:
        _STATE._violate(
            f"double-fold[{tenant}]: submission ({client}, seq={seq}) "
            f"folded twice"
        )


def check_drained(name: str, value: int) -> None:
    """Quiescence check: ``value`` must be 0 (e.g. the
    ``byzpy_root_partials_inflight`` gauge at coordinator close)."""
    if not _STATE.enabled:
        return
    with _STATE._lock:
        _STATE.counters["drain_checks"] += 1
    if value != 0:
        _STATE._violate(
            f"leak[{name}]: {value} still in flight at quiescence — "
            f"a decrement was lost on some verify/merge path"
        )


def violations() -> List[str]:
    """Snapshot of recorded violations (copy; safe to mutate)."""
    with _STATE._lock:
        return list(_STATE.violations)


def counters() -> Dict[str, int]:
    """Snapshot of hook-fire counters — a sanitized run with zero
    ``folds_audited`` proves nothing; assert these are nonzero."""
    with _STATE._lock:
        return dict(_STATE.counters)


def assert_clean() -> None:
    """Raise ``AssertionError`` listing every recorded violation (the
    bench/test-side teeth — never called on the hot path)."""
    found = violations()
    if found:
        raise AssertionError(
            "sanitizer recorded %d violation(s):\n  %s"
            % (len(found), "\n  ".join(found))
        )


__all__ = [
    "enabled",
    "enable",
    "disable",
    "reset",
    "loop_tick",
    "audit_fold",
    "check_drained",
    "violations",
    "counters",
    "assert_clean",
]

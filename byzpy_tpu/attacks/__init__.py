from .adaptive import (
    AdaptiveAttack,
    InfluenceAscentAttack,
    KrumEvasionAttack,
    PublicRoundState,
    ResidualShapingAttack,
    StalenessAbuseAttack,
)
from .base import Attack
from .empire import EmpireAttack
from .gaussian import GaussianAttack
from .inf import InfAttack
from .label_flip import LabelFlipAttack
from .little import LittleAttack
from .mimic import MimicAttack
from .sign_flip import SignFlipAttack

__all__ = [
    "Attack",
    "SignFlipAttack",
    "EmpireAttack",
    "LittleAttack",
    "GaussianAttack",
    "InfAttack",
    "MimicAttack",
    "LabelFlipAttack",
    "AdaptiveAttack",
    "InfluenceAscentAttack",
    "KrumEvasionAttack",
    "PublicRoundState",
    "ResidualShapingAttack",
    "StalenessAbuseAttack",
]

"""Adaptive Byzantine attacks: stateful adversaries on the public round feed.

The reference attacks (and this repo's, until round 7) are *stateless*
functions applied blind each round — a sign flip does not know whether the
aggregator trimmed it away. A realistic adversary participates in the
protocol: it pulls the broadcast model like every client, sees which of its
submissions were accepted, and optimizes the next one. This module is that
adversary, built on the :meth:`~byzpy_tpu.attacks.base.Attack.observe_round`
observation channel:

* :class:`PublicRoundState` — what a client legitimately learns per round:
  the broadcast aggregate, the server round counter, per-client
  acceptance/selection decisions (a Krum-style aggregator's published
  cohort, or simply "my update was reflected"), and the admission-layer
  ack verdicts of the serving tier (credit/staleness reason strings).
* :class:`AdaptiveAttack` — stateful base: records observations, exposes
  deterministic per-instance randomness (same seed + same observation
  sequence ⇒ bit-identical submission sequence, the chaos harness's
  replay contract).
* :class:`InfluenceAscentAttack` — gradient-ascent on aggregator
  influence: a multiplicative line search on the attack magnitude that
  grows while the aggregate keeps moving along the malicious direction
  and backs off the moment the aggregator clips/trims the push away —
  converging to the just-inside-tolerance magnitude a static attack can
  only find by luck.
* :class:`KrumEvasionAttack` — mimicry of accepted rows: submits the
  publicly observable consensus (the broadcast aggregate — for Krum
  families, literally a mean of accepted rows) plus an adaptive bias,
  shrinking the bias whenever it loses selection, so it stays *inside*
  the accepted set for many rounds while steadily steering it.
* :class:`StalenessAbuseAttack` — serving-tier staleness-window abuse:
  stamps each submission at the oldest admissible round (``δ = cutoff``)
  and pre-inflates it by ``1 / discount(δ)`` so the tier's staleness
  discount cancels exactly, while pacing submissions under the published
  credit policy. The inflated raw row rides the *stale* path through any
  admission-side magnitude screening that only looks at fresh-equivalent
  norms — the threat model note in ``docs/serving.md`` — and lands in
  the cohort at full intended magnitude.

Every attacker here uses ONLY public information (its observations and
its own parameters) — none requests ``honest_grads``. That is what makes
them deployable against the serving tier, where honest rows are never
revealed, and what makes actor-mode vs fused-SPMD parity exact: same
observation sequence in, same submission sequence out
(``tests/test_chaos_adaptive.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional

import numpy as np

from .base import Attack


@dataclass(frozen=True)
class PublicRoundState:
    """One closed round's public outcome, as an adaptive adversary sees it.

    ``aggregate`` is the broadcast update/model delta every client pulls
    (host ``(d,)`` array or pytree); ``accepted`` maps client ids to the
    round's acceptance/selection verdict where the fabric publishes one
    (empty when it doesn't); ``verdicts`` maps client ids to
    admission-layer ack reason strings (``accepted``/``rejected_rate``/
    ``rejected_too_stale``/… — each client at least knows its own acks);
    ``server_round`` is the server's round counter at broadcast time
    (what a submission's staleness δ is measured against)."""

    round_id: int
    aggregate: Any
    accepted: Mapping[str, bool] = field(default_factory=dict)
    verdicts: Mapping[str, str] = field(default_factory=dict)
    server_round: int = 0


class AdaptiveAttack(Attack):
    """Stateful attack base over the :meth:`observe_round` feed.

    Subclasses implement ``_update(state)`` (digest one observation) and
    ``apply`` (emit the next submission from current state). Determinism
    contract: all state transitions are pure functions of the
    constructor arguments and the observation sequence — float32 numpy
    arithmetic, per-instance ``np.random.Generator`` seeded from
    ``seed`` — so identical observations replay identical submissions
    (pinned by ``tests/test_chaos_adaptive.py``)."""

    is_adaptive = True
    name = "adaptive"

    def __init__(self, dim: int, *, seed: int = 0, client_id: str = "byz") -> None:
        if dim <= 0:
            raise ValueError(f"dim must be >= 1 (got {dim})")
        self.dim = int(dim)
        self.client_id = str(client_id)
        self.seed = int(seed)
        self.rng = np.random.default_rng(seed)
        self.observations: List[PublicRoundState] = []
        self.submissions = 0

    # -- observation channel ------------------------------------------------

    def observe_round(self, public_state: PublicRoundState) -> None:
        """Digest one round's public outcome (appends to ``observations``
        then delegates to the subclass's ``_update``)."""
        self.observations.append(public_state)
        self._update(public_state)

    def _update(self, state: PublicRoundState) -> None:
        """Subclass hook: fold one observation into attack state."""

    # -- convenience --------------------------------------------------------

    def _aggregate_estimate(self) -> np.ndarray:
        """The attacker's best public estimate of the honest consensus:
        the last broadcast aggregate (zeros before any observation)."""
        if not self.observations:
            return np.zeros((self.dim,), np.float32)
        agg = np.asarray(self.observations[-1].aggregate, np.float32)
        return agg.reshape(-1)[: self.dim]

    def _was_accepted(self, state: PublicRoundState) -> Optional[bool]:
        """This attacker's acceptance verdict in ``state`` (None when the
        round published no per-client decision for it)."""
        if self.client_id in state.accepted:
            return bool(state.accepted[self.client_id])
        return None


def _unit(direction: Any, dim: int) -> np.ndarray:
    """Normalized float32 direction vector (default: all-ones)."""
    if direction is None:
        vec = np.ones((dim,), np.float32)
    else:
        vec = np.asarray(direction, np.float32).reshape(-1)
        if vec.shape[0] != dim:
            raise ValueError(f"direction has {vec.shape[0]} coords, expected {dim}")
    norm = float(np.linalg.norm(vec))
    if norm == 0.0:
        raise ValueError("direction must be non-zero")
    return (vec / np.float32(norm)).astype(np.float32)


class InfluenceAscentAttack(AdaptiveAttack):
    """Gradient-ascent on aggregator influence.

    Goal: drag the broadcast aggregate along ``direction``. Each round
    the attacker measures its *realized influence* — the component of
    the broadcast aggregate along the malicious direction — and runs a
    multiplicative line search on its attack magnitude ``scale``:

    * influence improved (the aggregator passed the push through) →
      ``scale *= grow``: push harder next round;
    * influence regressed (trimmed/clipped/excluded — the push
      backfired or vanished) → ``scale *= shrink``: retreat back inside
      the aggregator's tolerance.

    The submission is ``estimate + scale · direction`` where
    ``estimate`` is the last broadcast aggregate — so the row sits near
    the honest consensus and the whole budget goes into the directional
    push. Against a trimmed mean this converges from either side onto
    the largest per-coordinate offset that still survives the trim
    window (the 'a little is enough' magnitude, *learned online* instead
    of assumed from known honest variance); a static attack at a fixed
    large scale is trimmed to zero influence every round
    (``benchmarks/chaos_bench.py`` 'adaptive' lane measures the gap)."""

    name = "influence-ascent"

    def __init__(
        self,
        dim: int,
        *,
        direction: Any = None,
        scale0: float = 0.05,
        grow: float = 1.6,
        shrink: float = 0.5,
        max_scale: float = 1e3,
        seed: int = 0,
        client_id: str = "byz",
    ) -> None:
        super().__init__(dim, seed=seed, client_id=client_id)
        if not (0.0 < shrink < 1.0 < grow):
            raise ValueError("need 0 < shrink < 1 < grow")
        self.direction = _unit(direction, dim)
        self.scale = np.float32(scale0)
        self.grow = np.float32(grow)
        self.shrink = np.float32(shrink)
        self.max_scale = np.float32(max_scale)
        self._last_influence: Optional[np.float32] = None

    def _update(self, state: PublicRoundState) -> None:
        influence = np.float32(
            np.dot(
                np.asarray(state.aggregate, np.float32).reshape(-1)[: self.dim],
                self.direction,
            )
        )
        if self._last_influence is None or influence > self._last_influence:
            self.scale = min(self.scale * self.grow, self.max_scale)
        else:
            self.scale = self.scale * self.shrink
        self._last_influence = influence

    def apply(self, *, model=None, x=None, y=None,
              honest_grads=None, base_grad=None) -> np.ndarray:
        """Next submission: consensus estimate + current push."""
        self.submissions += 1
        return (
            self._aggregate_estimate() + self.scale * self.direction
        ).astype(np.float32)


class KrumEvasionAttack(AdaptiveAttack):
    """Krum evasion via mimicry of accepted rows.

    Selection aggregators (Krum, Multi-Krum, CGE, MoNNA) publish — via
    the broadcast itself — a consensus of the *accepted* rows. The
    evader submits exactly that public consensus plus an adaptive bias
    ``eps · direction``:

    * while it keeps being selected (its id in the published accepted
      set, or no exclusion signal) → ``eps *= grow``: steer harder;
    * the round it loses selection → ``eps *= shrink``: snap back to
      near-perfect mimicry and re-enter the accepted set.

    A static outlier is excluded by Krum in round 0 and never scores
    again; the mimic stays inside the selection for many rounds
    (``exclusion_round`` metric in the chaos grid) while biasing every
    round's output it participates in."""

    name = "krum-evasion"

    def __init__(
        self,
        dim: int,
        *,
        direction: Any = None,
        eps0: float = 0.01,
        grow: float = 1.5,
        shrink: float = 0.25,
        max_eps: float = 1e3,
        seed: int = 0,
        client_id: str = "byz",
    ) -> None:
        super().__init__(dim, seed=seed, client_id=client_id)
        if not (0.0 < shrink < 1.0 < grow):
            raise ValueError("need 0 < shrink < 1 < grow")
        self.direction = _unit(direction, dim)
        self.eps = np.float32(eps0)
        self.grow = np.float32(grow)
        self.shrink = np.float32(shrink)
        self.max_eps = np.float32(max_eps)

    def _update(self, state: PublicRoundState) -> None:
        # exclusion = an explicit accepted=False, OR an admission-layer
        # rejection ack (a serving feed encodes cohort membership only
        # as presence, so the attacker's own non-accepted ack is the
        # other public signal that its row did not score)
        accepted = self._was_accepted(state)
        verdict = state.verdicts.get(self.client_id)
        rejected = verdict is not None and verdict != "accepted"
        if accepted is False or rejected:
            self.eps = self.eps * self.shrink
        else:
            self.eps = min(self.eps * self.grow, self.max_eps)

    def apply(self, *, model=None, x=None, y=None,
              honest_grads=None, base_grad=None) -> np.ndarray:
        """Next submission: mimic the published consensus, plus bias."""
        self.submissions += 1
        return (
            self._aggregate_estimate() + self.eps * self.direction
        ).astype(np.float32)


class StalenessAbuseAttack(AdaptiveAttack):
    """Staleness-window abuse against the serving tier.

    The serving frontend admits a round-``k`` submission up to
    ``cutoff`` rounds late and folds it discounted by ``discount(δ)``
    (:class:`~byzpy_tpu.serving.staleness.StalenessPolicy`) — both
    policy parameters are public (clients must know them to participate).
    The abuser therefore:

    * stamps every submission at the OLDEST admissible round
      (``δ = cutoff``), maximizing the window between computing its
      payload and the geometry the aggregator judges it against;
    * pre-inflates the payload by ``1 / discount(δ)`` so the tier's
      discount cancels exactly — the row enters the cohort at full
      intended magnitude even though it was "discounted";
    * paces itself under the published credit policy (one submission per
      admission opportunity — the token bucket never rejects it, so it
      never burns reputation with ``rejected_rate`` acks), retreating
      for ``backoff_rounds`` after any rejection verdict.

    ``next_round_stamp(server_round)`` is the round id to put on the
    wire; ``apply`` returns the pre-inflated gradient row. Outcome
    against each aggregator (contained or breached) is measured by the
    ``serving`` lane of ``benchmarks/chaos_bench.py`` and reported in
    ``benchmarks/RESULTS.md``; the defensive moral — magnitude screens
    must run post-discount — is documented in ``docs/serving.md``."""

    name = "staleness-abuse"

    def __init__(
        self,
        dim: int,
        *,
        staleness: Any = None,
        direction: Any = None,
        scale: float = 1.0,
        backoff_rounds: int = 1,
        seed: int = 0,
        client_id: str = "byz",
    ) -> None:
        super().__init__(dim, seed=seed, client_id=client_id)
        from ..serving.staleness import StalenessPolicy

        self.staleness = (
            staleness if staleness is not None else StalenessPolicy()
        )
        if not isinstance(self.staleness, StalenessPolicy):
            raise TypeError("staleness must be a StalenessPolicy")
        self.direction = _unit(direction, dim)
        self.scale = np.float32(scale)
        self.backoff_rounds = int(backoff_rounds)
        self._cooldown = 0

    @property
    def delta(self) -> int:
        """The staleness the attack CLAIMS right now: the policy's
        cutoff, clamped to the last observed server round (a round-2
        server cannot be handed a round −2 gradient; before the cutoff
        is reachable the attack claims what it can). 0 when the policy
        has no cutoff — nothing to abuse, submissions go out fresh."""
        cutoff = int(self.staleness.cutoff or 0)
        server = (
            int(self.observations[-1].server_round)
            if self.observations
            else 0
        )
        return min(cutoff, server)

    @property
    def inflation(self) -> np.float32:
        """``1 / discount(δ)`` for the CLAIMED δ — the pre-inflation
        that cancels the tier's staleness discount bit-for-bit at fold
        time (grows with the server round until the cutoff caps it)."""
        return np.float32(1.0 / self.staleness.discount(self.delta))

    def next_round_stamp(self, server_round: int) -> int:
        """The round id to stamp on the next submission: the oldest the
        cutoff admits (clamped at round 0)."""
        return max(0, int(server_round) - int(self.staleness.cutoff or 0))

    def should_submit(self) -> bool:
        """Credit pacing: False while backing off after a rejection."""
        return self._cooldown <= 0

    def _update(self, state: PublicRoundState) -> None:
        verdict = state.verdicts.get(self.client_id)
        if verdict is not None and verdict != "accepted":
            self._cooldown = self.backoff_rounds
        elif self._cooldown > 0:
            self._cooldown -= 1

    def apply(self, *, model=None, x=None, y=None,
              honest_grads=None, base_grad=None) -> np.ndarray:
        """Next submission: the consensus estimate plus the malicious
        push, pre-inflated to cancel the staleness discount."""
        self.submissions += 1
        payload = self._aggregate_estimate() + self.scale * self.direction
        return (self.inflation * payload).astype(np.float32)


#: The integer-grid wire modes an encoder-controlling client can shape
#: (fp8 shaping is the same scale-inflation signature on the format
#: grid; the code maxima themselves come from the wire codec's own
#: table at call time so the two can never drift).
_SHAPE_MODES = ("int8", "s4")


def _shaped_wire_roundtrip(
    payload: np.ndarray, mode: str, block: int, kappa: float
) -> tuple:
    """What a residual-shaping client's self-controlled encoder emits:
    blockwise codes on a ``kappa``-inflated scale grid (each block's
    scale is ``kappa * absmax / qmax`` instead of the honest
    ``absmax / qmax``), plus the resulting decode and the PRE-decode
    inflation ratio an ingress would measure. The grid constant comes
    from ``engine.actor.wire._WIRE_QMAX`` and the ratio is computed by
    the REAL ``wire.frame_inflation`` over the shaped frame's actual
    code layout — the attack and the countermeasure read one rulebook.
    Returns ``(decoded, inflation)``."""
    from ..engine.actor import wire as _wire

    qmax = _wire._WIRE_QMAX[mode]
    flat = np.ascontiguousarray(payload, np.float32).ravel()
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    xb = flat.reshape(nb, block)
    absmax = np.max(np.abs(xb), axis=1)
    scales = np.where(
        absmax > 0, absmax / qmax * np.float32(kappa), 1.0
    ).astype(np.float32)
    codes = np.clip(np.rint(xb / scales[:, None]), -qmax, qmax)
    decoded = (codes * scales[:, None]).ravel()[:n].astype(np.float32)
    if mode == "s4":
        nib = (codes + 8.0).astype(np.uint8).ravel()
        wire_codes = nib[0::2] | (nib[1::2] << 4)  # packed, block-padded
    else:
        wire_codes = codes.astype(np.int8).ravel()[:n]
    inflation = _wire.frame_inflation(
        _wire.QuantizedWireArray(
            mode, wire_codes, scales, block, payload.shape, "float32"
        )
    )
    return decoded.reshape(payload.shape), float(inflation)


class ResidualShapingAttack(InfluenceAscentAttack):
    """Error-feedback residual shaping on the sub-int8 wire fabric.

    Error feedback makes the compressed uplink *stateful*: an honest
    client carries the residual its encoder lost and folds it into the
    next frame. A Byzantine client CONTROLS its encoder, so it can
    shape both halves of that loop:

    * it inflates its per-block scales by ``kappa`` (> 1) — a grid
      ``kappa``x coarser than its content needs. Post-decode the row
      still lands near the honest consensus (the coarse rounding is
      absorbed exactly like quantization noise), so magnitude/z-score
      screens see nothing;
    * the rounding error of that self-chosen coarse grid — up to
      ``kappa/2`` code steps per coordinate — is not noise to the
      attacker: it is *budget*. The attack carries it as its EF
      residual and re-injects it every round, so directional pushes
      far below one honest grid step accumulate across rounds and
      eventually cross the grid — influence a single shaped frame
      could never deliver, riding the same line search as
      :class:`InfluenceAscentAttack` (which this class extends: the
      magnitude knob stays adaptive).

    The countermeasure is PRE-decode: an honest blockwise encoder maps
    each block's absmax to exactly the code maximum, so its per-block
    inflation ratio ``qmax / max|code|`` is 1.0; this attack's frames
    sit at ~``kappa``. The serving ingress measures that ratio on the
    still-compressed frame (``wire.decode_with_stats``) and the
    forensics ``residual_shaping`` detector flags it — measured (recall
    + honest FP) by the ``subint8`` lane of
    ``benchmarks/chaos_bench.py``. ``apply`` returns the DECODED row
    (the wire view the frontend folds); ``wire_inflation`` exposes the
    pre-decode tell the in-process engines thread into
    ``ServingFrontend.submit(wire_inflation=...)``, exactly what the
    TCP ingress would have measured from the frame."""

    name = "residual-shaping"

    def __init__(
        self,
        dim: int,
        *,
        mode: str = "s4",
        block: int = 256,
        kappa: float = 4.0,
        direction: Any = None,
        scale0: float = 0.05,
        grow: float = 1.6,
        shrink: float = 0.5,
        max_scale: float = 1e3,
        seed: int = 0,
        client_id: str = "byz",
    ) -> None:
        super().__init__(
            dim, direction=direction, scale0=scale0, grow=grow,
            shrink=shrink, max_scale=max_scale, seed=seed,
            client_id=client_id,
        )
        if mode not in _SHAPE_MODES:
            raise ValueError(
                f"mode must be one of {sorted(_SHAPE_MODES)}, got {mode!r}"
            )
        if kappa < 1.0:
            raise ValueError(f"kappa must be >= 1 (got {kappa})")
        if block <= 0:
            raise ValueError(f"block must be positive, got {block}")
        self.mode = mode
        self.block = int(block)
        self.kappa = float(kappa)
        #: the attacker's EF residual: everything its shaped grid has
        #: "lost" so far and will re-inject (attacker-controlled state —
        #: the reason sub-int8 EF needs its own detector)
        self.residual = np.zeros((dim,), np.float32)
        #: pre-decode inflation ratio of the LAST emitted frame (what
        #: the ingress would measure; ~kappa while shaping)
        self.wire_inflation: float = 1.0

    def apply(self, *, model=None, x=None, y=None,
              honest_grads=None, base_grad=None) -> np.ndarray:
        """Next submission: consensus estimate + line-searched push +
        carried residual, round-tripped through the attacker's own
        kappa-shaped encoder. The decoded row is what lands in the
        cohort; the residual update is exactly EF's."""
        self.submissions += 1
        target = (
            self._aggregate_estimate()
            + self.scale * self.direction
            + self.residual
        ).astype(np.float32)
        decoded, self.wire_inflation = _shaped_wire_roundtrip(
            target, self.mode, self.block, self.kappa
        )
        self.residual = target - decoded
        return decoded


__all__ = [
    "AdaptiveAttack",
    "InfluenceAscentAttack",
    "KrumEvasionAttack",
    "PublicRoundState",
    "ResidualShapingAttack",
    "StalenessAbuseAttack",
]

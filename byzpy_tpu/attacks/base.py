"""Attack base class (API parity: ``byzpy/attacks/base.py:12-119``).

Attacks simulate Byzantine behavior by generating adversarial gradients.
Subclasses declare needs via flags — ``uses_base_grad`` (own honest
gradient), ``uses_model_batch`` (model + batch for gradient computation),
``uses_honest_grads`` (other nodes' gradients) — and implement ``apply``.

TPU note: an attack is a pure function of its inputs, so inside an SPMD
training step byzantine nodes are a ``jnp.where`` on a byzantine mask over
vmapped per-node gradients (see ``byzpy_tpu.parallel``) rather than a
separate code path; this class layer serves the actor/graph orchestration
mode, matching the reference's scheduling semantics.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, List, Mapping, Optional

from ..engine.graph.operator import OpContext, Operator


class Attack(Operator, ABC):
    """Byzantine attack ABC: ``apply`` builds the malicious gradient from whatever the needs-flags request (model/batch, base grad, honest grads)."""

    uses_base_grad: bool = False
    uses_model_batch: bool = False
    uses_honest_grads: bool = False

    #: True for stateful attacks that consume the public round feed
    #: (:meth:`observe_round`) to optimize their next submission — see
    #: ``attacks/adaptive.py``. Static attacks stay pure functions.
    is_adaptive: bool = False

    name = "attack"

    def observe_round(self, public_state: Any) -> None:
        """Receive one closed round's PUBLIC outcome.

        ``public_state`` is a
        :class:`~byzpy_tpu.attacks.adaptive.PublicRoundState`: the
        broadcast aggregate every client pulls, the round counter, and
        whatever acceptance/admission verdicts the fabric publishes
        (selection decisions, credit/staleness ack reasons). This is the
        observation channel of the adaptive-adversary API — orchestrators
        (actor-mode PS, the chaos harness, the serving tier) feed it after
        every round. The base attack is stateless, so the default is a
        no-op; adaptive subclasses override it to update their strategy.
        """

    def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        return self.apply_placed(**self._collect_inputs(inputs))

    def apply_placed(self, **kwargs: Any) -> Any:
        """``apply`` under the latency-aware placement policy: small
        host-resident inputs compute on the CPU backend instead of paying
        a host->accelerator round-trip (see ``utils.placement``; the
        scheduled/graph path routes through here automatically)."""
        from ..utils import placement

        with placement.on(placement.compute_device(kwargs)):
            return self.apply(**kwargs)

    @abstractmethod
    def apply(
        self,
        *,
        model: Any = None,
        x: Any = None,
        y: Any = None,
        honest_grads: Optional[List[Any]] = None,
        base_grad: Any = None,
    ) -> Any:
        """Return one malicious gradient shaped like the honest ones.

        ``model`` is a :class:`byzpy_tpu.models.ModelBundle` (or anything
        with ``loss_fn(params, x, y)`` and ``params``) for
        ``uses_model_batch`` attacks — the JAX-native stand-in for the
        reference's ``nn.Module``.
        """

    def _collect_inputs(self, inputs: Mapping[str, Any]) -> Dict[str, Any]:
        kwargs: Dict[str, Any] = {}
        if self.uses_model_batch:
            for key in ("model", "x", "y"):
                if key not in inputs:
                    raise KeyError(f"Attack requires input {key!r}")
            kwargs["model"] = inputs["model"]
            kwargs["x"] = inputs["x"]
            kwargs["y"] = inputs["y"]
        if self.uses_honest_grads:
            if "honest_grads" not in inputs:
                raise KeyError("Attack requires 'honest_grads'")
            kwargs["honest_grads"] = inputs["honest_grads"]
        if self.uses_base_grad:
            if "base_grad" not in inputs:
                raise KeyError("Attack requires 'base_grad'")
            kwargs["base_grad"] = inputs["base_grad"]
        return kwargs


__all__ = ["Attack"]

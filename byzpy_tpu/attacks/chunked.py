"""Subtask fan-out for attacks on actor pools.

The reference parallelizes every attack except LabelFlip by slicing the
work across pool workers (``byzpy/attacks/base.py:47-119`` + per-attack
``create_subtasks``). Here the analogous split is over the feature
dimension of the stacked honest matrix (or the raveled base gradient):
each subtask emits the malicious coordinates for one column span and the
reduce concatenates them back into the gradient pytree.

On a single device the plain ``apply`` path (one jitted call) is faster;
this mode exists for heterogeneous pools and scheduler-integration parity.
Chunk functions are module-level so process/remote workers can unpickle
them.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

import numpy as np
import jax
import jax.numpy as jnp

from ..engine.graph.chunking import pool_size_from_context, select_adaptive_chunk_size
from ..engine.graph.operator import OpContext
from ..engine.graph.subtask import SubTask
from ..ops import attack_ops
from ..utils.trees import stack_gradients



# -- module-level chunk kernels (picklable by name) --------------------------


def _empire_chunk(cols: np.ndarray, *, scale: float) -> np.ndarray:
    return np.asarray(attack_ops.empire(jnp.asarray(cols), scale=scale))


def _little_chunk(cols: np.ndarray, *, f: int, n_total: int) -> np.ndarray:
    return np.asarray(attack_ops.little(jnp.asarray(cols), f=f, n_total=n_total))


def _mimic_chunk(cols: np.ndarray, *, epsilon: int) -> np.ndarray:
    return np.asarray(cols[epsilon])


def _inf_chunk(width: int, *, dtype_descr: str) -> np.ndarray:
    return np.full((width,), np.inf, dtype=np.dtype(dtype_descr))


def _sign_flip_chunk(cols: np.ndarray, *, scale: float) -> np.ndarray:
    # base_grad stacks to a (1, w) block
    return np.asarray(attack_ops.sign_flip(jnp.asarray(cols[0]), scale=scale))


def _gaussian_chunk(
    width: int, key_data: np.ndarray, idx: int, *, mu: float, sigma: float,
    dtype_descr: str,
) -> np.ndarray:
    key = jax.random.fold_in(jnp.asarray(key_data, jnp.uint32), idx)
    out = attack_ops.gaussian(
        key, (width,), dtype=np.dtype(dtype_descr), mu=mu, sigma=sigma
    )
    return np.asarray(out)


# -- mixin -------------------------------------------------------------------


class FeatureChunkedAttack:
    """Mixin: fan malicious-coordinate spans across the pool and
    concatenate (the reference's attack subtask mode, feature-sharded the
    way the TPU data plane shards coordinates)."""

    supports_subtasks = True
    chunk_size = 65536
    _chunk_fn: Any = None

    def _chunk_params(self, host: np.ndarray) -> Mapping[str, Any]:
        return {}

    def _chunk_host(self, inputs: Mapping[str, Any]) -> np.ndarray:
        """The (n, d) stacked honest matrix (or (1, d) base-grad block)."""
        grads = inputs.get("honest_grads")
        if not grads:
            raise ValueError(f"{self.name} attack requires honest_grads")
        matrix, _ = stack_gradients(grads)
        return np.asarray(matrix)

    def _unravel_like(self, inputs: Mapping[str, Any]):
        grads = inputs.get("honest_grads")
        _, unravel = stack_gradients(grads)
        return unravel

    def _chunk_args(
        self, host: np.ndarray, start: int, end: int, idx: int
    ) -> tuple:
        return (host[:, start:end],)

    def create_subtasks(
        self, inputs: Mapping[str, Any], *, context: OpContext
    ) -> Iterable[SubTask]:
        host = self._chunk_host(inputs)
        d = host.shape[-1]
        chunk = select_adaptive_chunk_size(
            d, self.chunk_size, pool_size=pool_size_from_context(context)
        )
        params = dict(self._chunk_params(host))
        fn = type(self)._chunk_fn
        # eager list (spans are few and args are views of `host`): instance
        # state read by _chunk_args (e.g. a split PRNG key) must be captured
        # before a concurrent create_subtasks call advances it
        tasks = []
        for idx, start in enumerate(range(0, d, chunk)):
            end = min(d, start + chunk)
            tasks.append(
                SubTask(
                    fn=fn,
                    args=self._chunk_args(host, start, end, idx),
                    kwargs=params,
                    name=f"{self.name}-feat[{start}:{end}]",
                )
            )
        return tasks

    def reduce_subtasks(
        self, partials, inputs: Mapping[str, Any], *, context: OpContext
    ) -> Any:
        vec = jnp.concatenate([jnp.asarray(p) for p in partials])
        return self._unravel_like(inputs)(vec)


class BaseGradChunkedAttack(FeatureChunkedAttack):
    """Variant for ``uses_base_grad`` attacks: spans come from the node's
    own gradient instead of the honest matrix."""

    def _chunk_host(self, inputs: Mapping[str, Any]) -> np.ndarray:
        base = inputs.get("base_grad")
        if base is None:
            raise ValueError(f"{self.name} attack requires base_grad")
        matrix, _ = stack_gradients([base])
        return np.asarray(matrix)

    def _unravel_like(self, inputs: Mapping[str, Any]):
        _, unravel = stack_gradients([inputs.get("base_grad")])
        return unravel


__all__ = [
    "FeatureChunkedAttack",
    "BaseGradChunkedAttack",
    "_empire_chunk",
    "_little_chunk",
    "_mimic_chunk",
    "_inf_chunk",
    "_sign_flip_chunk",
    "_gaussian_chunk",
]

"""Empire attack: ``scale * mean(honest_grads)``, default scale -1
(behavioral parity: ``byzpy/attacks/empire.py:23-187``)."""

from __future__ import annotations

from typing import Any, List, Optional

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack
from .chunked import FeatureChunkedAttack, _empire_chunk


class EmpireAttack(FeatureChunkedAttack, Attack):
    """Send ``scale * mean(honest)`` — inner-product manipulation of the average."""
    name = "empire"
    uses_honest_grads = True
    _chunk_fn = staticmethod(_empire_chunk)

    def __init__(self, *, scale: float = -1.0) -> None:
        self.scale = float(scale)

    def _chunk_params(self, host):
        return {"scale": self.scale}

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("EmpireAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        return unravel(attack_ops.empire(matrix, scale=self.scale))


__all__ = ["EmpireAttack"]

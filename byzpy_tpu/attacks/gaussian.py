"""Gaussian attack: iid ``N(mu, sigma^2)`` coordinates, seedable
(behavioral parity: ``byzpy/attacks/gaussian.py:38-139``). Randomness uses
an explicit jax.random key chain so repeated applies draw fresh noise
reproducibly."""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import numpy as np

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack
from .chunked import FeatureChunkedAttack, _gaussian_chunk


class GaussianAttack(FeatureChunkedAttack, Attack):
    """Send IID Gaussian noise in place of a gradient."""
    name = "gaussian"
    uses_honest_grads = True
    _chunk_fn = staticmethod(_gaussian_chunk)

    def __init__(self, *, mu: float = 0.0, sigma: float = 1.0, seed: int = 0,
                 key: Optional[jax.Array] = None) -> None:
        if sigma < 0:
            raise ValueError("sigma must be >= 0")
        self.mu = float(mu)
        self.sigma = float(sigma)
        self._key = key if key is not None else jax.random.PRNGKey(seed)

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("GaussianAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        self._key, sub = jax.random.split(self._key)
        noise = attack_ops.gaussian(
            sub, (matrix.shape[1],), dtype=matrix.dtype, mu=self.mu, sigma=self.sigma
        )
        return unravel(noise)

    # -- fan-out: per-chunk noise from a fold_in'd subkey (the draw differs
    # from the single-dispatch path but is the same distribution; the
    # reference's chunked RNG likewise draws per chunk) -----------------------

    def create_subtasks(self, inputs, *, context):
        self._key, self._fanout_key = jax.random.split(self._key)
        return super().create_subtasks(inputs, context=context)

    def _chunk_params(self, host):
        return {
            "mu": self.mu,
            "sigma": self.sigma,
            "dtype_descr": host.dtype.str,
        }

    def _chunk_args(self, host, start, end, idx):
        return (end - start, np.asarray(self._fanout_key), idx)


__all__ = ["GaussianAttack"]

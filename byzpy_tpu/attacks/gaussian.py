"""Gaussian attack: iid ``N(mu, sigma^2)`` coordinates, seedable
(behavioral parity: ``byzpy/attacks/gaussian.py:38-139``). Randomness uses
an explicit jax.random key chain so repeated applies draw fresh noise
reproducibly."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack


class GaussianAttack(Attack):
    name = "gaussian"
    uses_honest_grads = True

    def __init__(self, *, mu: float = 0.0, sigma: float = 1.0, seed: int = 0,
                 key: Optional[jax.Array] = None) -> None:
        if sigma < 0:
            raise ValueError("sigma must be >= 0")
        self.mu = float(mu)
        self.sigma = float(sigma)
        self._key = key if key is not None else jax.random.PRNGKey(seed)

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("GaussianAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        self._key, sub = jax.random.split(self._key)
        noise = attack_ops.gaussian(
            sub, (matrix.shape[1],), dtype=matrix.dtype, mu=self.mu, sigma=self.sigma
        )
        return unravel(noise)


__all__ = ["GaussianAttack"]

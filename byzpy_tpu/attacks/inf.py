"""Inf attack: ``+inf``-filled vector shaped like the gradients
(behavioral parity: ``byzpy/attacks/inf.py:35-119``)."""

from __future__ import annotations

from typing import Any, List, Optional

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack
from .chunked import FeatureChunkedAttack, _inf_chunk


class InfAttack(FeatureChunkedAttack, Attack):
    """Send a ``+inf``-filled vector (crash-the-mean probe)."""
    name = "inf"
    uses_honest_grads = True
    _chunk_fn = staticmethod(_inf_chunk)

    def _chunk_params(self, host):
        return {"dtype_descr": host.dtype.str}

    def _chunk_args(self, host, start, end, idx):
        return (end - start,)

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("InfAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        return unravel(attack_ops.inf_vector((matrix.shape[1],), dtype=matrix.dtype))


__all__ = ["InfAttack"]

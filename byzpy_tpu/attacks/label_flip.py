"""Label-flip attack: gradient of the loss on flipped labels
(behavioral parity: ``byzpy/attacks/label_flip.py:35-91``): labels map
through an explicit lookup table or the default ``num_classes - 1 - y``.

``model`` is a :class:`byzpy_tpu.models.ModelBundle` (pure ``loss_fn`` +
params) instead of the reference's torch module.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import Attack


class LabelFlipAttack(Attack):
    """Train on flipped labels and send the resulting (poisoned) gradient."""
    name = "label-flip"
    uses_model_batch = True

    def __init__(
        self,
        *,
        num_classes: Optional[int] = None,
        mapping: Optional[Sequence[int]] = None,
    ) -> None:
        if num_classes is None and mapping is None:
            raise ValueError("LabelFlipAttack requires num_classes or mapping")
        self.num_classes = num_classes
        self.mapping = None if mapping is None else jnp.asarray(mapping)

    def apply(self, *, model: Any = None, x: Any = None, y: Any = None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if model is None or x is None or y is None:
            raise ValueError("LabelFlipAttack requires model, x, and y")
        if self.mapping is not None:
            flipped = self.mapping[y]
        else:
            flipped = self.num_classes - 1 - y
        return jax.grad(model.loss_fn)(model.params, x, flipped)


__all__ = ["LabelFlipAttack"]

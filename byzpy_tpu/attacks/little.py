"""'A Little Is Enough' attack (Baruch et al. 2019)
(behavioral parity: ``byzpy/attacks/little.py:81-150``):
``mu + z_max * sigma`` with ``s = floor(N/2) + 1 - f``,
``z_max = ndtri((N - s) / N)``. ``N`` defaults to
``len(honest_grads) + f`` as in the reference."""

from __future__ import annotations

from typing import Any, List, Optional

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack


class LittleAttack(Attack):
    name = "little"
    uses_honest_grads = True

    def __init__(self, f: int, N: Optional[int] = None) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        self.f = int(f)
        self.N = None if N is None else int(N)

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("LittleAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        total = self.N if self.N is not None else matrix.shape[0] + self.f
        if total < self.f:
            raise ValueError(f"N must be >= f (got N={total}, f={self.f})")
        return unravel(attack_ops.little(matrix, f=self.f, n_total=total))


__all__ = ["LittleAttack"]

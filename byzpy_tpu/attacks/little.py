"""'A Little Is Enough' attack (Baruch et al. 2019)
(behavioral parity: ``byzpy/attacks/little.py:81-150``):
``mu + z_max * sigma`` with ``s = floor(N/2) + 1 - f``,
``z_max = ndtri((N - s) / N)``. ``N`` defaults to
``len(honest_grads) + f`` as in the reference."""

from __future__ import annotations

from typing import Any, List, Optional

from ..ops import attack_ops
from ..utils.trees import stack_gradients
from .base import Attack
from .chunked import FeatureChunkedAttack, _little_chunk


class LittleAttack(FeatureChunkedAttack, Attack):
    """'A Little Is Enough' (Baruch et al. 2019): shift the mean by z_max standard deviations per coordinate, staying inside the honest spread."""
    name = "little"
    uses_honest_grads = True
    _chunk_fn = staticmethod(_little_chunk)

    def __init__(self, f: int, N: Optional[int] = None) -> None:
        if f < 0:
            raise ValueError("f must be >= 0")
        self.f = int(f)
        self.N = None if N is None else int(N)

    def _chunk_params(self, host):
        return {"f": self.f, "n_total": self._resolve_total(host.shape[0])}

    def _resolve_total(self, n_honest: int) -> int:
        """``N`` defaults to honest count + f (ref little.py:81-139); one
        resolver serves both the direct and the pooled path."""
        total = self.N if self.N is not None else n_honest + self.f
        if total < self.f:
            raise ValueError(f"N must be >= f (got N={total}, f={self.f})")
        return total

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("LittleAttack requires honest_grads")
        matrix, unravel = stack_gradients(honest_grads)
        total = self._resolve_total(matrix.shape[0])
        return unravel(attack_ops.little(matrix, f=self.f, n_total=total))


__all__ = ["LittleAttack"]

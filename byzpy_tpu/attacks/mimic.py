"""Mimic attack: replay honest worker ``epsilon``'s gradient
(behavioral parity: ``byzpy/attacks/mimic.py:35-142``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from .base import Attack
from .chunked import FeatureChunkedAttack, _mimic_chunk


class MimicAttack(FeatureChunkedAttack, Attack):
    """Copy one honest worker's gradient (breaks uniqueness assumptions without being an outlier)."""
    name = "mimic"
    uses_honest_grads = True
    _chunk_fn = staticmethod(_mimic_chunk)

    def __init__(self, *, epsilon: int = 0) -> None:
        if epsilon < 0:
            raise ValueError("epsilon must be >= 0")
        self.epsilon = int(epsilon)

    def _chunk_params(self, host):
        if self.epsilon >= host.shape[0]:
            raise ValueError(
                f"epsilon must index an honest worker in [0, {host.shape[0]}) "
                f"(got {self.epsilon})"
            )
        return {"epsilon": self.epsilon}

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if not honest_grads:
            raise ValueError("MimicAttack requires honest_grads")
        if self.epsilon >= len(honest_grads):
            raise ValueError(
                f"epsilon must index an honest worker in [0, {len(honest_grads)}) "
                f"(got {self.epsilon})"
            )
        # copy so downstream mutation of the attack output can't alias the
        # honest gradient (reference copies too)
        return jax.tree_util.tree_map(lambda a: a + 0, honest_grads[self.epsilon])


__all__ = ["MimicAttack"]

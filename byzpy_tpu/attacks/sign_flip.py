"""Sign-flip attack: ``scale * base_grad``, default scale -1
(behavioral parity: ``byzpy/attacks/sign_flip.py:22-145``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from ..ops import attack_ops
from .base import Attack
from .chunked import BaseGradChunkedAttack, _sign_flip_chunk


class SignFlipAttack(BaseGradChunkedAttack, Attack):
    """Send ``scale * base_grad`` — the scaled-negated true gradient."""
    name = "sign-flip"
    uses_base_grad = True
    _chunk_fn = staticmethod(_sign_flip_chunk)

    def __init__(self, *, scale: float = -1.0) -> None:
        self.scale = float(scale)

    def _chunk_params(self, host):
        return {"scale": self.scale}

    def apply(self, *, model=None, x=None, y=None,
              honest_grads: Optional[List[Any]] = None, base_grad: Any = None) -> Any:
        if base_grad is None:
            raise ValueError("SignFlipAttack requires base_grad")
        return jax.tree_util.tree_map(
            lambda leaf: attack_ops.sign_flip(leaf, scale=self.scale), base_grad
        )


__all__ = ["SignFlipAttack"]

"""Chaos fabric: trace-driven fault injection + adaptive adversaries.

The scenario-diversity subsystem (ROADMAP "pod-scale chaos"): thousands
of simulated clients run against the round fabric — the direct masked
aggregation path, a fused-SPMD-style jitted step, the actor-mode
parameter server, or the PR-6 serving frontend — under configurable
chaos (arrival/straggler/failure distributions, partition and rejoin
events, crash/restart mid-round), every run replayable from a single
seed via a declarative :class:`Scenario` and audited by an
:class:`EventTrace` whose digest is the determinism contract.

On top of the harness rides the adaptive-adversary API
(``byzpy_tpu.attacks.adaptive``): attackers observe each round's public
state through :meth:`~byzpy_tpu.attacks.base.Attack.observe_round` and
optimize their next submission. ``benchmarks/chaos_bench.py`` runs the
standing (attack × fault × aggregator × precision) grid over this
package; its committed ``benchmarks/results/chaos_cpu.jsonl`` is the
regression wall scaling PRs must hold. See ``docs/chaos.md``.
"""

from .drills import DRILL_SCENARIOS, run_drill
from .events import ChaosEvent, EventTrace
from .harness import ChaosHarness, ChaosReport
from .influence import attacker_influence, selection_mask
from .shards import FORGE_MODES, CompromisedShard
from .scenario import (
    ArrivalModel,
    AttackSpec,
    CrashModel,
    FaultPlan,
    PartitionEvent,
    Scenario,
    SLOSpec,
    StragglerModel,
    build_aggregator,
    build_attack,
)

__all__ = [
    "ArrivalModel",
    "AttackSpec",
    "ChaosEvent",
    "DRILL_SCENARIOS",
    "run_drill",
    "ChaosHarness",
    "ChaosReport",
    "CompromisedShard",
    "CrashModel",
    "FORGE_MODES",
    "EventTrace",
    "FaultPlan",
    "PartitionEvent",
    "SLOSpec",
    "Scenario",
    "StragglerModel",
    "attacker_influence",
    "build_aggregator",
    "build_attack",
    "selection_mask",
]

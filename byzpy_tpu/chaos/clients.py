"""Simulated clients: the cheap thousands-of-workers population.

Podracer's lesson (arXiv:2104.06272) applied to robust aggregation: the
interesting scale questions — cohort raggedness, straggler skew, crash
churn, adaptive drag — do not need real model replicas. One simulated
client is a quadratic task (a per-client target vector) plus a seeded
noise stream; a byzantine client swaps the honest gradient for its
attack's output. The harness owns all *timing* randomness (arrivals,
delays, crashes) so the population stays embarrassingly cheap and the
event schedule replays from the scenario seed alone.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..attacks.base import Attack


class SimClient:
    """One simulated client of the chaos harness.

    Honest behavior: ``gradient(w) = 2 (w - target) + noise`` — the
    gradient of ``||w - target||²`` with per-client observation noise
    (seeded ``np.random.Generator``; the noise stream is part of the
    replay contract). Byzantine behavior (``attack`` set): the attack's
    ``apply`` output, with honest context provided for static attacks
    that request it; adaptive attacks run on their public-feed state
    alone."""

    def __init__(
        self,
        cid: str,
        dim: int,
        target: np.ndarray,
        *,
        seed: int,
        noise: float = 0.05,
        attack: Optional[Attack] = None,
    ) -> None:
        self.cid = str(cid)
        self.dim = int(dim)
        self.target = np.asarray(target, np.float32)
        self.noise = np.float32(noise)
        self.rng = np.random.default_rng(seed)
        self.attack = attack
        # fault-state flags owned by the harness schedule
        self.alive = True
        self.partitioned = False
        self.down_since_round = -1

    @property
    def byzantine(self) -> bool:
        """Whether this client runs an attack."""
        return self.attack is not None

    def honest_gradient(self, w: np.ndarray) -> np.ndarray:
        """The quadratic-task gradient at the broadcast params ``w``."""
        g = 2.0 * (np.asarray(w, np.float32) - self.target)
        if self.noise > 0:
            g = g + self.noise * self.rng.standard_normal(
                self.dim
            ).astype(np.float32)
        return g.astype(np.float32)

    def submission(
        self, w: np.ndarray, honest_rows: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """This round's submission: honest gradient, or the attack's
        output (static attacks that request honest context receive this
        round's honest rows — the classic omniscient-attacker model;
        adaptive attacks see only their observation feed)."""
        if self.attack is None:
            return self.honest_gradient(w)
        kwargs: dict = {}
        if getattr(self.attack, "uses_honest_grads", False):
            if honest_rows is None:
                raise ValueError(
                    f"{self.attack.name} needs honest rows, none provided"
                )
            kwargs["honest_grads"] = [row for row in honest_rows]
        if getattr(self.attack, "uses_base_grad", False):
            kwargs["base_grad"] = self.honest_gradient(w)
        out = np.asarray(self.attack.apply(**kwargs), np.float32)
        return out.reshape(self.dim)


class StaticVectorAttack(Attack):
    """The grid's static attacks that have NO class in
    ``byzpy_tpu.attacks`` (sign-flip and empire reuse the real
    :class:`~byzpy_tpu.attacks.SignFlipAttack` /
    :class:`~byzpy_tpu.attacks.EmpireAttack` — see the
    ``chaos.scenario.ATTACKS`` registry):

    * ``little`` — mean + ``scale`` honest standard deviations ('a
      little is enough' with an assumed-known sigma; the
      :class:`~byzpy_tpu.attacks.LittleAttack` class parametrizes the
      shift by ``(f, N)`` instead, which a dim-only registry builder
      cannot supply);
    * ``outlier`` — a constant ``scale``-magnitude vector, the crude
      drill attack (``tests/test_multihost.py``'s 1e3 outlier).

    These are the static counterparts the adaptive lane of
    ``benchmarks/chaos_bench.py`` compares against."""

    name = "static-vector"

    _MODES = ("little", "outlier")

    def __init__(self, dim: int, *, mode: str, scale: float) -> None:
        if mode not in self._MODES:
            raise ValueError(f"mode must be one of {self._MODES}")
        self.dim = int(dim)
        self.mode = mode
        self.scale = np.float32(scale)
        self.name = mode
        # needs-flags are per-mode, so they live on the instance
        self.uses_honest_grads = mode == "little"

    def apply(self, *, model: Any = None, x: Any = None, y: Any = None,
              honest_grads: Any = None, base_grad: Any = None) -> np.ndarray:
        """One malicious row from this round's honest context."""
        if self.mode == "outlier":
            return np.full((self.dim,), self.scale, np.float32)
        if not honest_grads:
            raise ValueError(f"{self.mode} requires honest_grads")
        honest = np.stack([np.asarray(g, np.float32) for g in honest_grads])
        mu = honest.mean(axis=0)
        sigma = honest.std(axis=0)
        return (mu + self.scale * sigma).astype(np.float32)


__all__ = ["SimClient", "StaticVectorAttack"]

"""The four hand-written fault drills, promoted to declarative scenarios.

``tests/test_multihost.py`` exercises real OS-process faults (a
SIGKILLed actor host mid-round, a byzantine subprocess peer, a
heartbeat excision, the two-process multihost bring-up). Those drills
stay in place as regression pins — nothing simulates a real SIGKILL —
but their *fault semantics* now also exist as :class:`Scenario` configs
the chaos harness executes in milliseconds, which is what lets the same
shapes run at every point of the chaos grid instead of only at n=3/4
with one aggregator. ``run_drill`` executes one by name and checks its
invariant (``tests/test_chaos_drills.py`` runs all four).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .harness import ChaosHarness, ChaosReport
from .scenario import (
    AttackSpec,
    CrashModel,
    FaultPlan,
    PartitionEvent,
    Scenario,
)

#: The promoted drill configs, keyed by the original test's short name.
DRILL_SCENARIOS: Dict[str, Scenario] = {
    # test_two_process_psum_over_distributed_runtime: the multihost
    # bring-up — every worker's contribution lands in every round's
    # aggregate. Simulated shape: 2 clients, no faults, mean-family
    # aggregate; invariant: all rounds close with full cohorts.
    "two_host_psum": Scenario(
        name="drill-two-host-psum",
        seed=11,
        n_clients=2,
        dim=8,
        rounds=3,
        aggregator="trimmed_mean",
        aggregator_params={"f": 0},
        noise=0.0,
        client_values=(1.0, 2.0),
    ),
    # test_elastic_ps_survives_sigkilled_host_process_midround: a worker
    # dies with its gradient IN FLIGHT and never returns; the rounds
    # keep closing on the survivors. Simulated shape: the third client
    # crashes mid-round at round 0 (prob 1 while alive, no restart) and
    # the trimmed mean converges on the survivors' consensus (1.5).
    "sigkill_midround": Scenario(
        name="drill-sigkill-midround",
        seed=12,
        n_clients=3,
        dim=8,
        rounds=30,
        aggregator="trimmed_mean",
        aggregator_params={"f": 0},
        noise=0.0,
        client_values=(1.0, 2.0, 9.0),
        faults=FaultPlan(
            crash=CrashModel(at_round=0, victim_indices=(2,))
        ),
        learning_rate=0.2,
    ),
    # test_gossip_with_byzantine_process: a byzantine peer floods a 1e3
    # outlier; median consensus among the honest peers must hold.
    # Simulated shape: 3 honest + 1 outlier attacker under a median —
    # invariant: final params within the honest spread, outlier
    # influence bounded.
    "byzantine_process": Scenario(
        name="drill-byzantine-process",
        seed=13,
        n_clients=4,
        n_byzantine=1,
        dim=8,
        rounds=40,
        aggregator="median",
        noise=0.0,
        client_values=(0.0, 1.0, 2.0, 0.0),
        attack=AttackSpec(name="outlier", params={"scale": 1e3}),
        learning_rate=0.2,
    ),
    # test_heartbeat_policy_excises_sigkilled_process_peer: a peer goes
    # silent mid-training and is excised; the survivors keep training.
    # Simulated shape: a partition takes out one client from round 3 on
    # (the detector's view of a dead peer IS a permanent partition);
    # invariant: later cohorts are survivor-only and training converges
    # on the survivors' consensus.
    "heartbeat_excision": Scenario(
        name="drill-heartbeat-excision",
        seed=14,
        n_clients=4,
        dim=8,
        rounds=40,
        aggregator="median",
        noise=0.0,
        client_values=(0.0, 1.0, 2.0, 9.0),
        faults=FaultPlan(
            partitions=(
                PartitionEvent(start_round=3, end_round=40, members=(3,)),
            )
        ),
        learning_rate=0.2,
    ),
}


def run_drill(name: str) -> Tuple[ChaosReport, bool]:
    """Execute one promoted drill; returns ``(report, invariant_held)``.

    The invariant mirrors the original subprocess drill's assertion —
    rounds keep closing and the final parameters sit at the survivors'
    (or honest) consensus, undragged by the fault/attack."""
    scenario = DRILL_SCENARIOS[name]
    report = ChaosHarness(scenario).run()
    ok = report.rounds_completed > 0
    w = report.final_params
    if name == "two_host_psum":
        ok &= report.rounds_completed == scenario.rounds
        ok &= len(report.trace.of_kind("arrive")) == 2 * scenario.rounds
    elif name == "sigkill_midround":
        # survivors' trimmed-mean consensus: targets 1.0/2.0 -> 1.5
        ok &= len(report.trace.of_kind("crash")) == 1
        ok &= bool(np.allclose(w, 1.5, atol=0.05))
    elif name == "byzantine_process":
        # median holds within the honest targets' hull despite the 1e3
        # outlier (mean aggregation would sit near 250)
        ok &= float(np.max(np.abs(w))) < 3.0
        ok &= report.influence_max < 10.0
    else:  # heartbeat_excision
        # the partitioned peer is out of every cohort after round 3 and
        # the survivors converge among their own targets
        ok &= len(report.trace.of_kind("partition")) == 1
        ok &= bool(np.all(w <= 2.5)) and bool(np.all(w >= -0.5))
    return report, bool(ok)


__all__ = ["DRILL_SCENARIOS", "run_drill"]

"""Chaos event trace: the replay/determinism contract of the harness.

Every observable thing that happens in a simulated run — an arrival, a
straggle past the window, a crash, a restart, a partition, an admission
verdict, a round close — is appended to one :class:`EventTrace` in
virtual-time order. The trace's :meth:`~EventTrace.digest` is a SHA-256
over the canonical rendering of every event, so "same seed ⇒ identical
run" is testable as a single string equality (and a grid cell's digest,
committed in ``benchmarks/results/chaos_cpu.jsonl``, pins the cell
against silent behavioral drift in later PRs).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Dict, Iterator, List

from ..observability import runtime as _obs_runtime
from ..observability import tracing as _obs_tracing

#: Canonical event kinds emitted by the harness (other layers may add
#: their own — the trace is an open vocabulary, the digest covers all).
KINDS = (
    "arrive",
    "straggle",
    "crash",
    "restart",
    "partition",
    "rejoin",
    "submit",
    "reject",
    "exclude",
    "round_close",
)


def array_digest(arr) -> str:
    """8-hex-char fingerprint of an array's exact bits — round_close
    events carry the aggregate's digest so the trace pins numeric
    outcomes, not just the schedule."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(arr))
    return hashlib.sha256(a.tobytes()).hexdigest()[:8]


@dataclass(frozen=True)
class ChaosEvent:
    """One simulated occurrence.

    ``t`` is virtual seconds (the harness clock, not wall time);
    ``round_id`` the server round it happened in; ``kind`` one of
    :data:`KINDS` (or a layer-specific extension); ``who`` the client or
    worker id (empty for round-level events); ``detail`` a short
    canonical string (rejection reason, cohort size, …)."""

    t: float
    round_id: int
    kind: str
    who: str = ""
    detail: str = ""

    def canonical(self) -> str:
        """The digest-stable rendering (time rounded to ns so replays
        hash identically regardless of float repr churn)."""
        return f"{self.t:.9f}|{self.round_id}|{self.kind}|{self.who}|{self.detail}"


class EventTrace:
    """Append-only, replayable record of one chaos run."""

    def __init__(self) -> None:
        self._events: List[ChaosEvent] = []

    def emit(
        self, t: float, round_id: int, kind: str, who: str = "", detail: str = ""
    ) -> None:
        """Append one event. With telemetry enabled the event is also
        mirrored onto the process tracer's ``chaos`` track (an instant
        event carrying the virtual time), so a chaos cell replays as a
        timeline correlated with the host spans of whatever fabric the
        cell drove — the digest is computed from the trace's own events
        only and is bit-identical with telemetry on or off."""
        self._events.append(ChaosEvent(float(t), int(round_id), kind, who, detail))
        if _obs_runtime.STATE.enabled:
            _obs_tracing.instant(
                f"chaos.{kind}",
                track="chaos",
                vt=float(t),
                round=int(round_id),
                who=who,
                detail=detail,
            )

    def __len__(self) -> int:
        return len(self._events)

    def __iter__(self) -> Iterator[ChaosEvent]:
        return iter(self._events)

    def digest(self) -> str:
        """SHA-256 over every event's canonical line — the determinism
        contract: two runs of the same :class:`~byzpy_tpu.chaos.Scenario`
        (same seed) must produce equal digests."""
        h = hashlib.sha256()
        for ev in self._events:
            h.update(ev.canonical().encode())
            h.update(b"\n")
        return h.hexdigest()

    def counts(self) -> Dict[str, int]:
        """Events per kind (trace summary for reports/bench rows)."""
        out: Dict[str, int] = {}
        for ev in self._events:
            out[ev.kind] = out.get(ev.kind, 0) + 1
        return out

    def of_kind(self, kind: str) -> List[ChaosEvent]:
        """All events of one kind, in emission order."""
        return [ev for ev in self._events if ev.kind == kind]

    def to_chrome_trace(self, path: str) -> int:
        """Write the trace as chrome-trace JSON on the VIRTUAL clock
        (``ts`` = virtual seconds → µs): a chaos cell replays as a
        Perfetto timeline — arrivals/crashes/rejections as instants,
        each round a complete span — summarizable by
        ``python -m byzpy_tpu.observability``. Returns the event count."""
        import os

        pid = os.getpid()
        events: List[dict] = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": 1,
                "args": {"name": "chaos (virtual time)"},
            }
        ]
        round_start: Dict[int, float] = {}
        for ev in self._events:
            round_start.setdefault(ev.round_id, ev.t)
            if ev.kind == "round_close":
                t0 = round_start[ev.round_id]
                events.append(
                    {
                        "name": "chaos.round",
                        "ph": "X",
                        "pid": pid,
                        "tid": 1,
                        "ts": t0 * 1e6,
                        "dur": max(0.0, ev.t - t0) * 1e6,
                        "args": {"round": ev.round_id, "detail": ev.detail},
                    }
                )
                continue
            events.append(
                {
                    "name": f"chaos.{ev.kind}",
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": 1,
                    "ts": ev.t * 1e6,
                    "args": {
                        "round": ev.round_id,
                        "who": ev.who,
                        "detail": ev.detail,
                    },
                }
            )
        with open(path, "w") as fh:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
        return len(events)

    def to_jsonl(self, path: str) -> None:
        """Write the full trace as JSONL (one event per line)."""
        with open(path, "w") as fh:
            for ev in self._events:
                fh.write(
                    json.dumps(
                        {
                            "t": ev.t,
                            "round": ev.round_id,
                            "kind": ev.kind,
                            "who": ev.who,
                            "detail": ev.detail,
                        }
                    )
                    + "\n"
                )


__all__ = ["KINDS", "ChaosEvent", "EventTrace", "array_digest"]

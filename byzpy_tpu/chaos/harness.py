"""The chaos harness: one :class:`Scenario` in, one replayable run out.

Virtual time, real fabric. The harness owns a deterministic virtual
clock (rounds advance it by the scenario window) and expands the
scenario's fault plan into per-round chaos — arrivals, straggles past
the window, mid-round crashes, restarts, partitions/rejoins — while the
actual *data path* of each round is the repo's production code:

* ``engine="direct"`` — cohorts pad into a
  :class:`~byzpy_tpu.serving.buckets.BucketLadder` bucket and reduce
  through :meth:`Aggregator.aggregate_masked`, the serving tier's
  masked-finalize door (host dispatch per round);
* ``engine="spmd"`` — the REAL fused serving step
  (:func:`~byzpy_tpu.parallel.ps.jit_serving_ps_step`): params,
  optimizer state, cohort matrix + mask + staleness weights through one
  jitted program per bucket — the single-program analogue of the fused
  SPMD parameter server;
* ``engine="actor"`` — the real actor-mode
  :class:`~byzpy_tpu.engine.parameter_server.ParameterServer` over
  in-process simulated nodes, byzantine nodes fed through the
  :meth:`observe_round` observation channel;
* ``engine="serving"`` — the real :class:`~byzpy_tpu.serving.ServingFrontend`
  admission path (shape/staleness/credit/queue gates, the production
  ``submit``) under an injected virtual clock, rounds closed through
  :meth:`~byzpy_tpu.serving.ServingFrontend.close_round_nowait`.

Adaptive attacks receive a
:class:`~byzpy_tpu.attacks.adaptive.PublicRoundState` after every round
(broadcast aggregate, published selection where the aggregator has one,
each attacker's own admission verdicts) and optimize their next
submission; the per-round displacement they buy is measured by
``chaos.influence``. Every observable is appended to an
:class:`~byzpy_tpu.chaos.events.EventTrace` whose digest is the
replay/determinism contract (``tests/test_chaos_harness.py``).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .clients import SimClient
from .events import EventTrace, array_digest
from .influence import attacker_influence, selection_mask
from .scenario import Scenario, build_aggregator, build_attack


@dataclass
class ChaosReport:
    """One chaos run's outcome.

    ``final_error`` is ``||w - mean(honest targets)||₂`` at the end —
    comparable across attacks within a scenario family (the bench pairs
    each cell with its attack-free twin for the contained/breached
    verdict). ``influences`` is the per-closed-round displacement the
    byzantine rows bought; ``last_selected_round`` the last round a
    byzantine row survived the aggregator's published selection (-1 =
    never selected, or no selection published); ``verdict_counts`` the
    admission-ack tally (serving engine). ``submissions`` holds the
    byzantine rows actually submitted (parity tests compare them
    bit-for-bit across engines)."""

    scenario: Scenario
    rounds_completed: int = 0
    final_params: Optional[np.ndarray] = None
    final_error: float = 0.0
    influences: List[float] = field(default_factory=list)
    last_selected_round: int = -1
    verdict_counts: Dict[str, int] = field(default_factory=dict)
    submissions: List[np.ndarray] = field(default_factory=list)
    trace: EventTrace = field(default_factory=EventTrace)
    #: virtual-clock SLO evaluation (serving engine with a
    #: ``Scenario.slo`` attached): final watchdog state + the breach
    #: rows in virtual-round order. A pure observer — kept OUT of the
    #: event trace so digests are bit-identical with SLOs on or off
    slo: Optional[Dict[str, Any]] = None
    #: per-round :class:`~byzpy_tpu.forensics.evidence.RoundEvidence`
    #: when the harness was built with a forensics config — the SAME
    #: schema the online serving plane produces, kept OUT of the event
    #: trace so digests are bit-identical with forensics on or off
    evidence: List[Any] = field(default_factory=list)

    @property
    def influence_mean(self) -> float:
        """Mean per-round byzantine displacement (0.0 with no rounds)."""
        return float(np.mean(self.influences)) if self.influences else 0.0

    @property
    def influence_max(self) -> float:
        """Largest single-round byzantine displacement."""
        return float(np.max(self.influences)) if self.influences else 0.0

    def forensics_summary(self) -> Dict[str, Any]:
        """Detection metrics over the collected evidence (empty-run
        safe): per-client first-flag round, flags by detector, and the
        precision/recall/false-positive accounting the chaos bench's
        ``forensics`` lane scores detectors with (byzantine clients are
        the simulator's ``byz…`` ids — ground truth the DETECTORS never
        see)."""
        first_flag: Dict[str, int] = {}
        flags_by_detector: Dict[str, int] = {}
        honest_records = honest_flagged_records = 0
        for ev in self.evidence:
            for rec in ev.records:
                is_byz = rec.client.startswith("byz")
                if not is_byz:
                    honest_records += 1
                    if rec.flags:
                        honest_flagged_records += 1
                if rec.flags:
                    first_flag.setdefault(rec.client, ev.round_id)
                    for fl in rec.flags:
                        flags_by_detector[fl] = flags_by_detector.get(fl, 0) + 1
        byz_clients = {
            rec.client
            for ev in self.evidence
            for rec in ev.records
            if rec.client.startswith("byz")
        }
        flagged = set(first_flag)
        flagged_byz = {c for c in flagged if c.startswith("byz")}
        return {
            "rounds_with_evidence": len(self.evidence),
            "first_flag_round": dict(sorted(first_flag.items())),
            "flags_by_detector": flags_by_detector,
            "byz_present": len(byz_clients),
            "byz_flagged": len(flagged_byz),
            "honest_flagged": len(flagged - flagged_byz),
            "first_byz_flag_round": (
                min(first_flag[c] for c in flagged_byz) if flagged_byz else None
            ),
            "recall": (
                len(flagged_byz) / len(byz_clients) if byz_clients else None
            ),
            "precision": (
                len(flagged_byz) / len(flagged) if flagged else None
            ),
            "honest_fp_rate": (
                honest_flagged_records / honest_records
                if honest_records
                else 0.0
            ),
        }

    def summary(self) -> Dict[str, Any]:
        """JSON-ready cell row for the chaos grid."""
        row = {
            "scenario": self.scenario.name,
            "engine": self.scenario.engine,
            "aggregator": self.scenario.aggregator,
            "attack": self.scenario.attack.name,
            "precision": self.scenario.precision,
            "rounds": self.rounds_completed,
            "final_error": round(self.final_error, 6),
            "influence_mean": round(self.influence_mean, 6),
            "influence_max": round(self.influence_max, 6),
            "last_selected_round": self.last_selected_round,
            "verdicts": dict(self.verdict_counts),
            "events": self.trace.counts(),
            "trace_digest": self.trace.digest(),
        }
        if self.slo is not None:
            row["slo_breaches"] = len(self.slo["breaches"])
        return row


class ChaosHarness:
    """Deterministic executor for one :class:`Scenario` (module docstring).

    ``forensics`` (optional :class:`~byzpy_tpu.forensics.ForensicsConfig`)
    attaches the SAME per-client attribution plane the serving tier runs
    online: every closed round of the ``direct``/``spmd``/``serving``
    engines yields a :class:`~byzpy_tpu.forensics.evidence.RoundEvidence`
    into ``report.evidence`` (one schema, two producers). The plane is
    a pure observer — event-trace digests and aggregates are
    bit-identical with it on or off. The ``actor`` engine runs the real
    PS round loop, which never exposes the cohort matrix, so it
    collects no evidence."""

    def __init__(
        self, scenario: Scenario, *, forensics: Optional[Any] = None
    ) -> None:
        self.s = scenario
        self._forensics_cfg = forensics
        # independent, order-stable randomness: schedule (faults/timing),
        # per-client noise, per-attack state
        seeds = np.random.SeedSequence(scenario.seed).spawn(
            2 + scenario.n_clients
        )
        self._sched_rng = np.random.default_rng(seeds[0])
        values_rng = np.random.default_rng(seeds[1])
        if scenario.client_values is not None:
            values = np.asarray(scenario.client_values, np.float32)
        else:
            values = values_rng.normal(1.0, 0.5, scenario.n_clients).astype(
                np.float32
            )
        self.clients: List[SimClient] = []
        for i in range(scenario.n_clients):
            byz = i >= scenario.n_honest
            cid = f"{'byz' if byz else 'c'}{i:04d}"
            attack = (
                build_attack(scenario, seed=scenario.seed * 1000 + i, client_id=cid)
                if byz
                else None
            )
            self.clients.append(
                SimClient(
                    cid,
                    scenario.dim,
                    np.full((scenario.dim,), values[i], np.float32),
                    seed=int(
                        np.random.default_rng(seeds[2 + i]).integers(2**31)
                    ),
                    noise=scenario.noise,
                    attack=attack,
                )
            )
        # partition membership fixed up front: explicit members, or a
        # deterministic draw from the schedule stream
        self._partition_members: List[frozenset] = []
        for part in scenario.faults.partitions:
            if part.members is not None:
                self._partition_members.append(
                    frozenset(int(i) % scenario.n_clients for i in part.members)
                )
                continue
            k = max(1, int(round(part.fraction * scenario.n_clients)))
            members = self._sched_rng.choice(
                scenario.n_clients, size=k, replace=False
            )
            self._partition_members.append(frozenset(int(i) for i in members))
        self.honest_target = np.full(
            (scenario.dim,),
            float(np.mean(values[: scenario.n_honest])),
            np.float32,
        )

    # -- shared chaos schedule (one round) --------------------------------

    def _round_presence(
        self, r: int, t: float, trace: EventTrace
    ) -> List[Tuple[SimClient, int]]:
        """Expand the fault plan for round ``r``: restarts, partitions,
        crashes, arrival counts, straggler draws. Returns the
        ``(client, n_submissions)`` list of clients whose submissions
        make this round's window, emitting every event."""
        s = self.s
        # partition boundaries first (they gate everything below)
        for part, members in zip(
            s.faults.partitions, self._partition_members, strict=True
        ):
            for i in sorted(members):
                c = self.clients[i]
                if r == part.start_round and not c.partitioned:
                    c.partitioned = True
                    trace.emit(t, r, "partition", c.cid)
                elif r == part.end_round and c.partitioned:
                    c.partitioned = False
                    trace.emit(t, r, "rejoin", c.cid)
        present: List[Tuple[SimClient, int]] = []
        crash = s.faults.crash
        strag = s.faults.stragglers
        for idx, c in enumerate(self.clients):
            # restart due?
            if not c.alive and crash.restart_after_rounds is not None:
                if r - c.down_since_round >= crash.restart_after_rounds:
                    c.alive = True
                    trace.emit(t, r, "restart", c.cid)
            if not c.alive or c.partitioned:
                continue
            # how many submissions does this client offer?
            if s.arrivals.kind == "every_round":
                offered = 1
            elif s.arrivals.kind == "bernoulli":
                offered = int(self._sched_rng.random() < s.arrivals.p)
            else:  # poisson
                offered = int(self._sched_rng.poisson(s.arrivals.p))
            # mid-round crash: the in-flight submission dies with the
            # worker (the SIGKILL drill's shape) — targeted
            # (at_round/victims) or sampled (prob_per_round)
            targeted = (
                crash.at_round == r
                and crash.victim_indices is not None
                and idx in crash.victim_indices
            )
            sampled = crash.prob_per_round > 0 and (
                self._sched_rng.random() < crash.prob_per_round
            )
            if targeted or sampled:
                c.alive = False
                c.down_since_round = r
                trace.emit(t, r, "crash", c.cid, "midround")
                continue
            landed = 0
            for _ in range(offered):
                if strag.kind == "none":
                    delay = 0.0
                elif strag.kind == "lognormal":
                    delay = float(
                        np.exp(
                            strag.mu
                            + strag.sigma * self._sched_rng.standard_normal()
                        )
                    )
                else:  # bimodal
                    if self._sched_rng.random() < strag.tail_prob:
                        delay = strag.tail_s
                    else:
                        delay = float(
                            np.exp(
                                strag.mu
                                + strag.sigma
                                * self._sched_rng.standard_normal()
                            )
                        )
                if delay > s.window_s:
                    trace.emit(t, r, "straggle", c.cid, f"{delay:.4f}s")
                    continue
                landed += 1
                trace.emit(t + delay, r, "arrive", c.cid)
            if landed:
                present.append((c, landed))
        return present

    # -- submission assembly ----------------------------------------------

    def _round_rows(
        self,
        present: List[Tuple[SimClient, int]],
        w: np.ndarray,
        report: ChaosReport,
        *,
        pace: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray, List[SimClient]]:
        """Compute every present client's submission (honest first, in
        client order — the canonical stack order both PS modes use).
        Returns ``(matrix (m, d), byz_mask (m,), owners)``; multiple
        arrivals from one client contribute one row per arrival.
        ``pace=True`` (serving engine) honors an attack's
        ``should_submit`` credit pacing BEFORE the row is computed, so
        ``report.submissions`` records only rows that really went out.
        A byzantine client whose attack needs honest context sits out a
        round with no honest arrivals (nothing to mimic) instead of
        killing the run."""
        honest_rows: List[np.ndarray] = []
        honest_owners: List[SimClient] = []
        for c, k in present:
            if c.byzantine:
                continue
            for _ in range(k):
                honest_rows.append(c.honest_gradient(w))
                honest_owners.append(c)
        honest_matrix = (
            np.stack(honest_rows)
            if honest_rows
            else np.zeros((0, self.s.dim), np.float32)
        )
        rows = list(honest_rows)
        owners = list(honest_owners)
        byz_flags = [False] * len(honest_rows)
        for c, k in present:
            if not c.byzantine:
                continue
            if not honest_rows and getattr(
                c.attack, "uses_honest_grads", False
            ):
                continue
            if pace and hasattr(c.attack, "should_submit") and not (
                c.attack.should_submit()
            ):
                continue
            for _ in range(k):
                row = c.submission(w, honest_rows=honest_matrix)
                report.submissions.append(row)
                rows.append(row)
                owners.append(c)
                byz_flags.append(True)
        matrix = (
            np.stack(rows) if rows else np.zeros((0, self.s.dim), np.float32)
        )
        return matrix, np.asarray(byz_flags, bool), owners

    def _apply_precision(self, matrix: np.ndarray) -> np.ndarray:
        """Round-trip the cohort through the scenario's wire precision
        (the PR-3 blockwise codec) — the grid's precision axis."""
        if self.s.precision == "off" or matrix.size == 0:
            return matrix
        import jax.numpy as jnp

        if self.s.precision == "bf16":
            return np.asarray(
                jnp.asarray(matrix).astype(jnp.bfloat16).astype(jnp.float32)
            )
        from ..parallel.quantization import (
            dequantize_blockwise,
            encode_blockwise,
        )

        return np.asarray(
            dequantize_blockwise(
                encode_blockwise(jnp.asarray(matrix), self.s.precision)
            )
        )

    # -- public API --------------------------------------------------------

    def run(self) -> ChaosReport:
        """Execute the scenario; returns the :class:`ChaosReport`."""
        if self.s.engine == "actor":
            return asyncio.run(self._run_actor())
        if self.s.engine == "serving":
            return self._run_serving()
        return self._run_matrix()

    def _make_plane(self):
        """A FRESH forensics plane per run (replays must not inherit
        trust state from a prior run), or None when not configured."""
        if self._forensics_cfg is None:
            return None
        from ..forensics.plane import ForensicsPlane

        return ForensicsPlane("chaos", self._forensics_cfg)

    # -- direct / spmd engines ---------------------------------------------

    def _run_matrix(self) -> ChaosReport:
        """The two matrix engines: pad each round's cohort into a bucket
        and reduce through the masked program — host door (``direct``)
        or the jitted serving step (``spmd``)."""
        from ..serving.buckets import BucketLadder

        s = self.s
        report = ChaosReport(scenario=s)
        ladder = BucketLadder(max(2, s.n_clients), min_bucket=2)
        aggregator = build_aggregator(s)
        plane = self._make_plane()
        w = np.zeros((s.dim,), np.float32)
        step = opt_state = None
        if s.engine == "spmd":
            step, opt_state = self._build_spmd_step(w)
        for r in range(s.rounds):
            t = r * s.window_s
            present = self._round_presence(r, t, report.trace)
            matrix, byz_mask, owners = self._round_rows(present, w, report)
            m = matrix.shape[0]
            try:
                aggregator.validate_n(m)
                admissible = m >= 1
            except ValueError:
                admissible = False
            if not admissible:
                report.trace.emit(t, r, "round_close", "", f"held m={m}")
                continue
            matrix = self._apply_precision(matrix)
            bucket = ladder.bucket_for(m)
            padded = np.zeros((bucket, s.dim), np.float32)
            padded[:m] = matrix
            valid = np.zeros((bucket,), bool)
            valid[:m] = True
            byz = np.zeros((bucket,), bool)
            byz[:m] = byz_mask
            # the published aggregate goes through the masked door in
            # BOTH engines (bit-identical programs — the observation
            # feed must not depend on which engine closed the round);
            # the spmd engine's params trajectory then comes from the
            # real fused step
            agg = np.asarray(
                aggregator.aggregate_masked(padded, valid), np.float32
            )
            if s.engine == "spmd":
                w, opt_state = self._spmd_round(
                    step, w, opt_state, padded, valid
                )
            else:
                w = (w - np.float32(s.learning_rate) * agg).astype(np.float32)
            report.influences.append(
                attacker_influence(aggregator, padded, valid, byz)
            )
            if plane is not None:
                ev = plane.observe_round(
                    r, padded, valid,
                    [o.cid for o in owners], agg,
                    aggregator=aggregator,
                    deltas=[0] * m, bucket=bucket,
                )
                report.evidence.append(ev)
                # the plane already computed the aggregator's selection
                # view (same matrix — no weights on this engine):
                # reconstruct the padded keep mask from the evidence
                # instead of paying the O(m²·d) score pass twice
                if ev.records and ev.records[0].selected is not None:
                    sel = np.zeros((bucket,), bool)
                    for rec in ev.records:
                        sel[rec.slot] = bool(rec.selected)
                else:
                    sel = None
            else:
                sel = selection_mask(aggregator, padded, valid)
            accepted: Dict[str, bool] = {}
            if sel is not None:
                for i, owner in enumerate(owners):
                    # a client with several rows is accepted if any survived
                    accepted[owner.cid] = accepted.get(owner.cid, False) or bool(
                        sel[i]
                    )
                if bool(sel[valid & byz].any()):
                    report.last_selected_round = r
                for i, owner in enumerate(owners):
                    if byz[i] and not sel[i]:
                        report.trace.emit(t, r, "exclude", owner.cid)
            self._publish(report, r, agg, accepted, {})
            report.trace.emit(
                t + s.window_s, r, "round_close", "",
                f"m={m} bucket={bucket} agg={array_digest(agg)}",
            )
            report.rounds_completed += 1
        report.final_params = w
        report.final_error = float(np.linalg.norm(w - self.honest_target))
        return report

    def _build_spmd_step(self, w: np.ndarray):
        """The real fused serving step over the scenario's quadratic
        task: plain SGD at the scenario's learning rate, so the spmd
        engine's update arithmetic matches the direct engine's
        ``w - lr · agg`` exactly."""
        import optax

        from ..models.bundle import ModelBundle
        from ..parallel.ps import jit_serving_ps_step

        bundle = ModelBundle(
            apply_fn=lambda params, x: x,
            params=np.asarray(w, np.float32),
            loss_fn=lambda params, x, y: 0.0,
        )
        aggregator = build_aggregator(self.s)
        masked = aggregator.masked_matrix_fn()
        if masked is None:
            raise ValueError(
                f"engine='spmd' needs a masked aggregator program; "
                f"{self.s.aggregator!r} has none — use engine='direct'"
            )
        return jit_serving_ps_step(
            bundle,
            masked,
            optimizer=optax.sgd(self.s.learning_rate),
        )

    def _spmd_round(self, step, w, opt_state, padded, valid):
        """One jitted serving-step dispatch (params + opt state in, new
        params out; the step applies SGD internally)."""
        import jax.numpy as jnp

        from ..observability import tracing as obs_tracing

        weights = valid.astype(np.float32)
        with obs_tracing.device_span(
            "spmd.device_step", track="chaos", bucket=int(padded.shape[0])
        ):
            new_w, opt_state, _metrics = step(
                jnp.asarray(w),
                opt_state,
                jnp.asarray(padded),
                jnp.asarray(valid),
                jnp.asarray(weights),
            )
        # compile-cache observability: any growth past the bucket set
        # shows up as byzpy_jit_compiles_total{site="chaos.spmd_step"}
        try:
            from ..observability import jitstats as obs_jitstats

            obs_jitstats.note_cache_size("chaos.spmd_step", step._cache_size())
        except Exception:  # noqa: BLE001 — introspection only
            pass
        return np.asarray(new_w, np.float32), opt_state

    def _publish(
        self,
        report: ChaosReport,
        r: int,
        agg: np.ndarray,
        accepted: Dict[str, bool],
        verdicts: Dict[str, str],
    ) -> None:
        """Deliver the round's public state to every adaptive attack."""
        from ..attacks.adaptive import PublicRoundState

        state = PublicRoundState(
            round_id=r,
            aggregate=np.asarray(agg, np.float32),
            accepted=accepted,
            verdicts=verdicts,
            server_round=r + 1,
        )
        for c in self.clients:
            if c.attack is not None and getattr(c.attack, "is_adaptive", False):
                c.attack.observe_round(state)

    # -- actor engine --------------------------------------------------------

    async def _run_actor(self) -> ChaosReport:
        """The real actor-mode :class:`ParameterServer` over in-process
        sim nodes. Fault injection is limited to what the PS fabric
        observes (full-round crash = the node's slot missing), since a
        real deployment's SIGKILL drills live in
        ``tests/test_multihost.py``; the chaos value here is the
        adaptive observation channel riding the production round loop.
        A scenario that ASKS for fault/arrival/precision injection is
        rejected rather than silently run fault-free — its trace would
        otherwise pin a run its config never describes."""
        from ..engine.parameter_server import ParameterServer
        from .scenario import ArrivalModel, FaultPlan

        s = self.s
        if (
            s.faults != FaultPlan()
            or s.arrivals != ArrivalModel()
            or s.precision != "off"
        ):
            raise ValueError(
                "engine='actor' drives the real ParameterServer round "
                "loop, where the harness cannot inject faults, arrival "
                "models, or wire precision — use engine='direct'/'spmd'/"
                "'serving' for fault plans, or clear them for actor runs"
            )
        report = ChaosReport(scenario=s)
        harness = self

        class _HonestSimNode:
            def __init__(self, client: SimClient) -> None:
                self.client = client

            def honest_gradient_for_next_batch(self):
                return self.client.honest_gradient(harness._actor_w)

            def apply_server_gradient(self, g):  # update handled centrally
                pass

        class _ByzSimNode:
            def __init__(self, client: SimClient) -> None:
                self.client = client

            def byzantine_gradient_for_next_batch(self, honest_grads):
                row = self.client.submission(
                    harness._actor_w,
                    honest_rows=np.stack(
                        [np.asarray(g, np.float32) for g in honest_grads]
                    )
                    if honest_grads
                    else np.zeros((0, s.dim), np.float32),
                )
                report.submissions.append(row)
                return row

            def apply_server_gradient(self, g):
                pass

            def observe_round(self, state):
                if getattr(self.client.attack, "is_adaptive", False):
                    self.client.attack.observe_round(state)

        self._actor_w = np.zeros((s.dim,), np.float32)
        ps = ParameterServer(
            honest_nodes=[
                _HonestSimNode(c) for c in self.clients if not c.byzantine
            ],
            byzantine_nodes=[
                _ByzSimNode(c) for c in self.clients if c.byzantine
            ],
            aggregator=build_aggregator(s),
        )
        for r in range(s.rounds):
            t = r * s.window_s
            agg = np.asarray(await ps.round(), np.float32)
            self._actor_w = (
                self._actor_w - np.float32(s.learning_rate) * agg
            ).astype(np.float32)
            for c in self.clients:
                report.trace.emit(t, r, "arrive", c.cid)
            report.trace.emit(
                t + s.window_s, r, "round_close", "",
                f"agg={array_digest(agg)}",
            )
            report.rounds_completed += 1
        report.final_params = self._actor_w
        report.final_error = float(
            np.linalg.norm(self._actor_w - self.honest_target)
        )
        return report

    # -- serving engine ------------------------------------------------------

    def _run_serving(self) -> ChaosReport:
        """The real serving admission path under a virtual clock: every
        submission goes through ``ServingFrontend.submit`` (shape,
        staleness-cutoff, credit and queue gates — production code),
        rounds close through ``close_round_nowait``, and each client
        observes the public feed plus its OWN ack verdicts."""
        from ..serving import ServingFrontend, TenantConfig
        from ..serving.credits import CreditPolicy
        from ..serving.staleness import StalenessPolicy

        s = self.s
        report = ChaosReport(scenario=s)
        aggregator = build_aggregator(s)
        plane = self._make_plane()
        self._vclock = 0.0
        watchdog = None
        breaches: List[dict] = []
        telemetry_was_on = True
        if s.slo is not None:
            from .. import observability as _obs
            from ..observability.slo import SLOWatchdog, TenantSLO

            # the watchdog reads the registry the frontend publishes
            # into, and the frontend only publishes with telemetry ON —
            # a Scenario.slo without telemetry would score every window
            # as a silent, plausible-looking zero. Enable for the run
            # (restored below); digests are pinned identical telemetry
            # AND SLO on/off, so this changes no replay contract.
            telemetry_was_on = _obs.enabled()
            _obs.enable()
            # the watchdog ticks on the harness's VIRTUAL clock: SLO
            # burn under injected faults is replayable per seed
            watchdog = SLOWatchdog(
                [
                    TenantSLO(
                        tenant="chaos",
                        accepted_p99_s=s.slo.accepted_p99_s,
                        failed_round_rate=s.slo.failed_round_rate,
                        quarantine_rate=s.slo.quarantine_rate,
                        window_s=s.slo.window_s,
                        burn_threshold=s.slo.burn_threshold,
                    )
                ],
                clock=lambda: self._vclock,
            )

        def slo_tick(round_idx: int, window_end: float) -> None:
            """One virtual-clock watchdog evaluation at a round window's
            close (shared by the held/failed and completed branches)."""
            if watchdog is None:
                return
            self._vclock = window_end
            breaches.extend(
                {**row, "round": round_idx}
                for row in watchdog.evaluate()
                if row["breached"]
            )

        fe = ServingFrontend(
            [
                TenantConfig(
                    name="chaos",
                    aggregator=aggregator,
                    dim=s.dim,
                    window_s=s.window_s,
                    cohort_cap=max(2, s.n_clients),
                    queue_capacity=max(4, 4 * s.n_clients),
                    credit=CreditPolicy(
                        rate_per_s=s.credit_rate_per_s, burst=s.credit_burst
                    ),
                    staleness=StalenessPolicy(
                        kind=s.staleness_kind,
                        gamma=s.staleness_gamma,
                        cutoff=s.staleness_cutoff,
                    ),
                )
            ],
            clock=lambda: self._vclock,
        )
        w = np.zeros((s.dim,), np.float32)
        failed_seen = 0
        for r in range(s.rounds):
            t = r * s.window_s
            self._vclock = t
            present = self._round_presence(r, t, report.trace)
            matrix, _byz_mask, owners = self._round_rows(
                present, w, report, pace=True
            )
            matrix = self._apply_precision(matrix)
            server_round = fe.round_of("chaos")
            round_acks: Dict[str, str] = {}
            blockwise = s.precision not in ("off", "bf16")
            for i, owner in enumerate(owners):
                stamp = server_round
                attack = owner.attack
                if attack is not None and hasattr(attack, "next_round_stamp"):
                    stamp = attack.next_round_stamp(server_round)
                # pre-decode wire forensics, as the TCP ingress would
                # measure it — which means NOTHING off the blockwise
                # fabrics: an off/bf16 frame carries no per-block
                # scales, so even a shaping attack's ratio is
                # unobservable there (the real ingress would stamp
                # None). On a coded fabric the attack exposes its
                # shaped ratio; every other client's honest encode
                # sits at exactly 1.0.
                if blockwise:
                    wi = getattr(attack, "wire_inflation", None)
                    if wi is None:
                        wi = 1.0
                else:
                    wi = None
                ok, reason = fe.submit(
                    "chaos", owner.cid, stamp, matrix[i], wire_inflation=wi
                )
                # a client with several arrivals keeps its ACCEPTED ack:
                # the submission that folded defines the round's outcome
                # for the adversary (a partial rate-rejection must not
                # mask that its row entered the aggregate)
                if round_acks.get(owner.cid) != "accepted":
                    round_acks[owner.cid] = reason
                report.verdict_counts[reason] = (
                    report.verdict_counts.get(reason, 0) + 1
                )
                kind = "submit" if ok else "reject"
                report.trace.emit(t, r, kind, owner.cid, reason)
            closed = fe.close_round_nowait("chaos")
            if closed is None:
                # distinguish a window legitimately held open from a
                # crash-guarded FAILED round (submissions consumed and
                # dropped) — a replay trace must not narrate dropped
                # rows as still pending
                failed_now = fe.stats()["chaos"]["failed_rounds"]
                detail = "failed" if failed_now > failed_seen else "held"
                failed_seen = failed_now
                report.trace.emit(t + s.window_s, r, "round_close", "", detail)
                slo_tick(r, t + s.window_s)
                continue
            round_id, cohort, agg_vec = closed
            agg = np.asarray(agg_vec, np.float32)
            if plane is not None:
                report.evidence.append(
                    plane.observe_round(
                        round_id, cohort.matrix, cohort.valid,
                        cohort.clients, agg,
                        aggregator=aggregator,
                        weights=cohort.weights, bucket=cohort.bucket,
                        wire_inflations=(
                            cohort.wire_inflations
                            if cohort.wire_inflations
                            else None
                        ),
                    )
                )
            w = (w - np.float32(s.learning_rate) * agg).astype(np.float32)
            byz_ids = {c.cid for c in self.clients if c.byzantine}
            cohort_byz = np.asarray(
                [cid in byz_ids for cid in cohort.clients], bool
            )
            pad = np.zeros((cohort.bucket - len(cohort.clients),), bool)
            discounted = cohort.matrix * cohort.weights[:, None]
            report.influences.append(
                attacker_influence(
                    aggregator,
                    discounted,
                    cohort.valid,
                    np.concatenate([cohort_byz, pad]),
                )
            )
            state = fe.public_state("chaos")
            from ..attacks.adaptive import PublicRoundState

            for c in self.clients:
                if c.attack is not None and getattr(
                    c.attack, "is_adaptive", False
                ):
                    # each client observes the shared public feed plus
                    # its OWN admission acks — never another client's.
                    # A client that submitted but is absent from the
                    # published cohort KNOWS it was left out: surface
                    # that as an explicit accepted=False — but only
                    # when every accepted row actually folded this
                    # round (an overflow past cohort_cap leaves
                    # admitted rows queued for the NEXT round, and a
                    # still-pending row is not an exclusion)
                    accepted_acks = sum(
                        1 for v in round_acks.values() if v == "accepted"
                    )
                    unambiguous = accepted_acks <= cohort.m
                    own = (
                        {c.cid: round_acks[c.cid]}
                        if c.cid in round_acks
                        else {}
                    )
                    accepted = dict(state.accepted)
                    if (
                        unambiguous
                        and c.cid in round_acks
                        and c.cid not in accepted
                    ):
                        accepted[c.cid] = False
                    c.attack.observe_round(
                        PublicRoundState(
                            round_id=state.round_id,
                            aggregate=np.asarray(agg, np.float32),
                            accepted=accepted,
                            verdicts=own,
                            server_round=state.server_round,
                        )
                    )
            report.trace.emit(
                t + s.window_s, r, "round_close", "",
                f"m={cohort.m} round={round_id} agg={array_digest(agg)}",
            )
            report.rounds_completed += 1
            slo_tick(r, t + s.window_s)
        report.final_params = w
        report.final_error = float(np.linalg.norm(w - self.honest_target))
        if watchdog is not None:
            report.slo = {
                "state": watchdog.state()["objectives"],
                "breaches": breaches,
            }
            watchdog.close()
            if not telemetry_was_on:
                from .. import observability as _obs

                _obs.disable()
        return report


__all__ = ["ChaosHarness", "ChaosReport"]

"""Adversarial-influence metrics for the chaos grid.

Two observables turn "did the attack work" into numbers:

* :func:`attacker_influence` — the leave-the-attackers-out norm: how far
  the round's aggregate moved because the byzantine rows were present
  (``|| agg(all rows) - agg(honest rows) ||₂``). Zero when the
  aggregator fully excluded/trimmed the attack; the adaptive lane's
  headline is this metric's uplift over the static counterpart.
* :func:`selection_mask` — for selection aggregators (Krum families,
  CGE, MoNNA), which rows the aggregator actually kept, computed
  host-side from the same score programs ``ops.robust`` uses. Feeds the
  ``exclusion_round`` metric (how long a mimic stays selected) and the
  public ``accepted`` verdicts adaptive attackers observe.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def attacker_influence(
    aggregator, matrix: np.ndarray, valid: np.ndarray, byz: np.ndarray
) -> float:
    """``||agg(valid rows) - agg(valid honest rows)||₂`` — the realized
    displacement the byzantine rows bought this round.

    ``matrix`` is the padded ``(n, d)`` cohort, ``valid`` the row mask,
    ``byz`` the byzantine-row mask. Returns 0.0 when no byzantine row is
    present, or when removing them leaves an inadmissible cohort (the
    honest-only reference is undefined — e.g. all-byzantine)."""
    valid = np.asarray(valid, bool)
    byz = np.asarray(byz, bool)
    if not bool((valid & byz).any()):
        return 0.0
    honest_valid = valid & ~byz
    if not bool(honest_valid.any()):
        return 0.0
    try:
        with_byz = np.asarray(aggregator.aggregate_masked(matrix, valid))
        without = np.asarray(aggregator.aggregate_masked(matrix, honest_valid))
    except ValueError:
        return 0.0
    return float(np.linalg.norm(with_byz - without))


def selection_mask(
    aggregator, matrix: np.ndarray, valid: np.ndarray
) -> Optional[np.ndarray]:
    """Which VALID rows the aggregator's selection kept, or ``None`` for
    non-selection aggregators (means/medians use every row).

    Computed host-side from the published score functions
    (``ops.robust.krum_scores`` for the Krum families; per-row norm
    ranking for CGE), over the compacted valid rows, then scattered back
    to padded positions — the tie rules match the aggregation programs
    (stable lowest-``q``/lowest-``(n-f)`` pick)."""
    import jax.numpy as jnp

    from ..aggregators import (
        ComparativeGradientElimination,
        MoNNA,
        MultiKrum,
    )
    from ..ops import robust

    valid = np.asarray(valid, bool)
    idx = np.flatnonzero(valid)
    m = int(idx.size)
    if m == 0:
        return None
    try:
        # an m the aggregator would reject has no defined selection —
        # without this, the m <= f slices below go negative and
        # fabricate a non-empty "selected" set
        aggregator.validate_n(m)
    except ValueError:
        return None
    rows = jnp.asarray(np.asarray(matrix, np.float32)[idx])
    if isinstance(aggregator, MultiKrum):  # Krum subclasses MultiKrum (q=1)
        scores = np.asarray(robust.krum_scores(rows, f=int(aggregator.f)))
        keep = np.argsort(scores, kind="stable")[: int(aggregator.q)]
    elif isinstance(aggregator, ComparativeGradientElimination):
        norms = np.asarray(jnp.linalg.norm(rows, axis=1))
        keep = np.argsort(norms, kind="stable")[: m - int(aggregator.f)]
    elif isinstance(aggregator, MoNNA):
        ref = rows[int(getattr(aggregator, "reference_index", 0)) % m]
        d2 = np.asarray(jnp.sum((rows - ref[None, :]) ** 2, axis=1))
        keep = np.argsort(d2, kind="stable")[: m - int(aggregator.f)]
    else:
        return None
    mask = np.zeros(valid.shape, bool)
    mask[idx[np.asarray(keep)]] = True
    return mask


__all__ = ["attacker_influence", "selection_mask"]

"""Adversarial-influence metrics for the chaos grid.

Two observables turn "did the attack work" into numbers:

* :func:`attacker_influence` — the leave-the-attackers-out norm: how far
  the round's aggregate moved because the byzantine rows were present
  (``|| agg(all rows) - agg(honest rows) ||₂``). Zero when the
  aggregator fully excluded/trimmed the attack; the adaptive lane's
  headline is this metric's uplift over the static counterpart.
* :func:`selection_mask` — for selection aggregators (Krum families,
  CGE, MoNNA), which rows the aggregator actually kept, computed
  host-side from the same score programs ``ops.robust`` uses. Feeds the
  ``exclusion_round`` metric (how long a mimic stays selected) and the
  public ``accepted`` verdicts adaptive attackers observe.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def attacker_influence(
    aggregator, matrix: np.ndarray, valid: np.ndarray, byz: np.ndarray
) -> float:
    """``||agg(valid rows) - agg(valid honest rows)||₂`` — the realized
    displacement the byzantine rows bought this round.

    ``matrix`` is the padded ``(n, d)`` cohort, ``valid`` the row mask,
    ``byz`` the byzantine-row mask. Returns 0.0 when no byzantine row is
    present, or when removing them leaves an inadmissible cohort (the
    honest-only reference is undefined — e.g. all-byzantine)."""
    valid = np.asarray(valid, bool)
    byz = np.asarray(byz, bool)
    if not bool((valid & byz).any()):
        return 0.0
    honest_valid = valid & ~byz
    if not bool(honest_valid.any()):
        return 0.0
    try:
        with_byz = np.asarray(aggregator.aggregate_masked(matrix, valid))
        without = np.asarray(aggregator.aggregate_masked(matrix, honest_valid))
    except ValueError:
        return 0.0
    return float(np.linalg.norm(with_byz - without))


def selection_mask(
    aggregator, matrix: np.ndarray, valid: np.ndarray
) -> Optional[np.ndarray]:
    """Which VALID rows the aggregator's selection kept, or ``None`` for
    non-selection aggregators (means/medians use every row).

    Since PR 10 this is a view over the shared forensics evidence
    schema: :meth:`~byzpy_tpu.aggregators.base.Aggregator.
    round_evidence` computes the published per-row scores host-side
    (``ops.robust.krum_scores`` for the Krum families, per-row norms
    for CGE, reference distances for MoNNA — the exact code that lived
    here until PR 10, tie rules unchanged: stable lowest-``q``/
    lowest-``(n-f)`` pick) and this function returns its ``keep`` mask
    — one schema, two producers (offline influence studies and the
    online forensics plane), pinned comparable by
    ``tests/test_forensics.py``. An inadmissible ``m`` (``validate_n``
    rejects it) has no defined selection and returns ``None``.
    Aggregators whose evidence view carries scores but no keep set
    (``evidence_selects`` False — trimmed mean's clip fractions, the
    center-distance views) short-circuit to ``None`` without paying
    the score computation."""
    if not getattr(aggregator, "evidence_selects", False):
        return None
    view = aggregator.round_evidence(matrix, valid)
    if view is None:
        return None
    keep = view.get("keep")
    return None if keep is None else np.asarray(keep, bool)


__all__ = ["attacker_influence", "selection_mask"]

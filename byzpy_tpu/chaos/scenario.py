"""Declarative chaos scenarios: one config, one seed, one replayable run.

A :class:`Scenario` is a frozen, JSON-round-trippable description of a
simulated deployment: how many clients, which robust aggregator, which
attack the byzantine fraction runs, and the fault plan (straggler
distribution, crash/restart model, partition events). The harness
(``chaos/harness.py``) expands it into a deterministic event schedule
from the single ``seed`` — the same config replays the same run
bit-for-bit, which is what lets the chaos grid act as a regression wall
(``benchmarks/chaos_bench.py``) and lets a failing cell be rerun in
isolation from its committed config.

Attack and aggregator references are registry *names* (plus a params
mapping), not instances, so configs stay serializable; the four
hand-written fault drills of ``tests/test_multihost.py`` are promoted to
these configs in ``chaos/drills.py``.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Dict, Mapping, Optional, Tuple

_ARRIVAL_KINDS = ("every_round", "bernoulli", "poisson")
_STRAGGLER_KINDS = ("none", "lognormal", "bimodal")
_ENGINES = ("direct", "spmd", "actor", "serving")
_PRECISIONS = ("off", "bf16", "int8", "fp8", "fp8_e5m2", "s4")


@dataclass(frozen=True)
class ArrivalModel:
    """When clients offer submissions.

    ``every_round`` — each live client submits once per round (the PS
    fabric's fixed-worker-set assumption); ``bernoulli`` — each live
    client submits with probability ``p`` per round (serving-style
    sparse participation); ``poisson`` — each live client offers
    ``Poisson(p)`` submissions per round (flooding clients exist)."""

    kind: str = "every_round"
    p: float = 1.0

    def __post_init__(self) -> None:
        if self.kind not in _ARRIVAL_KINDS:
            raise ValueError(f"arrival kind must be one of {_ARRIVAL_KINDS}")
        if self.p < 0:
            raise ValueError("p must be >= 0")


@dataclass(frozen=True)
class StragglerModel:
    """Per-submission delay distribution (virtual seconds).

    ``none`` — everything lands instantly; ``lognormal`` — delays are
    ``exp(N(mu, sigma))``; ``bimodal`` — fast ``exp(N(mu, sigma))``
    bulk with probability ``1 - tail_prob``, else a ``tail_s``-second
    straggler (the skewed two-population shape the overlap bench uses).
    A submission whose delay exceeds the round window misses the cohort
    (event ``straggle``)."""

    kind: str = "none"
    mu: float = -4.0
    sigma: float = 0.5
    tail_prob: float = 0.1
    tail_s: float = 0.5

    def __post_init__(self) -> None:
        if self.kind not in _STRAGGLER_KINDS:
            raise ValueError(f"straggler kind must be one of {_STRAGGLER_KINDS}")
        if not 0.0 <= self.tail_prob <= 1.0:
            raise ValueError("tail_prob must be in [0, 1]")


@dataclass(frozen=True)
class CrashModel:
    """Worker crash/restart process.

    Each live client crashes with ``prob_per_round`` per round (drawn
    from the scenario seed's schedule stream); a targeted drill instead
    pins ``at_round`` + ``victim_indices`` (those clients crash
    deterministically at that round). A crash is mid-round: the round's
    in-flight submission is lost with the worker. A crashed client
    restarts after ``restart_after_rounds`` rounds (event ``restart``),
    or stays dead forever when ``None`` — the SIGKILL drill shape."""

    prob_per_round: float = 0.0
    restart_after_rounds: Optional[int] = None
    at_round: Optional[int] = None
    victim_indices: Optional[Tuple[int, ...]] = None

    def __post_init__(self) -> None:
        if not 0.0 <= self.prob_per_round <= 1.0:
            raise ValueError("prob_per_round must be in [0, 1]")
        if self.restart_after_rounds is not None and self.restart_after_rounds < 1:
            raise ValueError("restart_after_rounds must be >= 1")
        if (self.at_round is None) != (self.victim_indices is None):
            raise ValueError(
                "at_round and victim_indices must be set together"
            )


@dataclass(frozen=True)
class PartitionEvent:
    """A network partition: some clients are unreachable for rounds
    ``[start_round, end_round)``, then rejoin. Membership is either an
    explicit ``members`` index tuple (targeted drills) or ``fraction``
    of the population, deterministically drawn from the scenario seed."""

    start_round: int
    end_round: int
    fraction: float = 0.25
    members: Optional[Tuple[int, ...]] = None

    def __post_init__(self) -> None:
        if not 0 <= self.start_round < self.end_round:
            raise ValueError("need 0 <= start_round < end_round")
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError("fraction must be in (0, 1]")


@dataclass(frozen=True)
class FaultPlan:
    """The scenario's fault injection: stragglers + crashes + partitions."""

    stragglers: StragglerModel = field(default_factory=StragglerModel)
    crash: CrashModel = field(default_factory=CrashModel)
    partitions: Tuple[PartitionEvent, ...] = ()


@dataclass(frozen=True)
class AttackSpec:
    """Registry reference to the byzantine clients' attack: a
    :data:`ATTACKS` name plus constructor params (``"none"`` = no
    byzantine behavior even if ``n_byzantine > 0`` — crash-only
    faults)."""

    name: str = "none"
    params: Mapping[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class SLOSpec:
    """Serving-engine SLO objectives, evaluated per round on the
    harness's VIRTUAL clock (``observability.slo.SLOWatchdog`` with
    ``clock=`` the harness clock): declarative targets for accepted
    p99 latency (virtual seconds), failed-round rate and quarantine
    rate, scored over ``window_s`` of virtual time. A pure observer —
    trace digests and aggregates are bit-identical with or without an
    SLO attached (the watchdog only reads the metrics registry, which
    requires telemetry to be enabled to be populated)."""

    accepted_p99_s: Optional[float] = None
    failed_round_rate: Optional[float] = None
    quarantine_rate: Optional[float] = None
    window_s: float = 1.0
    burn_threshold: float = 1.0

    def __post_init__(self) -> None:
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if self.burn_threshold <= 0:
            raise ValueError("burn_threshold must be > 0")


@dataclass(frozen=True)
class Scenario:
    """One replayable chaos run (see module docstring).

    The simulated learning task is a quadratic: client ``i`` holds a
    target scalar (``client_values[i]``, or drawn from ``N(1, 0.25)``)
    and submits ``2 (w - target_i) + noise`` against the broadcast
    parameter vector ``w`` — rich enough that robust aggregation,
    staleness and adaptive drag all have measurable consequences, cheap
    enough to run thousands of clients on a CPU mesh. ``engine`` picks
    the fabric under test: ``direct`` (host masked-aggregate door),
    ``spmd`` (jitted masked step, the fused-PS analogue), ``actor``
    (the real actor-mode :class:`ParameterServer`), or ``serving`` (the
    real :class:`ServingFrontend` admission path under a virtual
    clock). ``precision`` round-trips every submission through the
    blockwise wire codec first (the PR-3 fabric)."""

    name: str
    seed: int = 0
    n_clients: int = 16
    n_byzantine: int = 0
    dim: int = 64
    rounds: int = 20
    aggregator: str = "trimmed_mean"
    aggregator_params: Mapping[str, Any] = field(default_factory=dict)
    attack: AttackSpec = field(default_factory=AttackSpec)
    faults: FaultPlan = field(default_factory=FaultPlan)
    arrivals: ArrivalModel = field(default_factory=ArrivalModel)
    engine: str = "direct"
    precision: str = "off"
    window_s: float = 0.1
    learning_rate: float = 0.1
    noise: float = 0.05
    client_values: Optional[Tuple[float, ...]] = None
    # serving-engine knobs (ignored elsewhere)
    staleness_kind: str = "none"
    staleness_gamma: float = 0.5
    staleness_cutoff: Optional[int] = None
    credit_rate_per_s: float = 0.0
    credit_burst: float = 20.0
    #: serving-engine SLO objectives evaluated on the virtual clock
    #: (None = no watchdog; pure observer either way)
    slo: Optional[SLOSpec] = None

    def __post_init__(self) -> None:
        if self.n_clients < 1:
            raise ValueError("n_clients must be >= 1")
        if not 0 <= self.n_byzantine < self.n_clients:
            raise ValueError("need 0 <= n_byzantine < n_clients")
        if self.rounds < 1 or self.dim < 1:
            raise ValueError("rounds and dim must be >= 1")
        if self.engine not in _ENGINES:
            raise ValueError(f"engine must be one of {_ENGINES}")
        if self.precision not in _PRECISIONS:
            raise ValueError(f"precision must be one of {_PRECISIONS}")
        if self.aggregator not in AGGREGATORS:
            raise ValueError(
                f"unknown aggregator {self.aggregator!r} "
                f"(have {sorted(AGGREGATORS)})"
            )
        if self.attack.name not in ATTACKS:
            raise ValueError(
                f"unknown attack {self.attack.name!r} (have {sorted(ATTACKS)})"
            )
        if self.client_values is not None and len(self.client_values) != self.n_clients:
            raise ValueError("client_values must have n_clients entries")

    @property
    def n_honest(self) -> int:
        """Honest client count."""
        return self.n_clients - self.n_byzantine

    def to_dict(self) -> Dict[str, Any]:
        """JSON-ready dict (inverse of :meth:`from_dict`)."""
        return asdict(self)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "Scenario":
        """Rebuild a scenario from :meth:`to_dict` output (accepts plain
        JSON: nested dicts/lists become the frozen config types)."""
        d = dict(data)
        if isinstance(d.get("attack"), Mapping):
            d["attack"] = AttackSpec(**d["attack"])
        if isinstance(d.get("arrivals"), Mapping):
            d["arrivals"] = ArrivalModel(**d["arrivals"])
        if isinstance(d.get("faults"), Mapping):
            f = dict(d["faults"])
            if isinstance(f.get("stragglers"), Mapping):
                f["stragglers"] = StragglerModel(**f["stragglers"])
            if isinstance(f.get("crash"), Mapping):
                c = dict(f["crash"])
                if c.get("victim_indices") is not None:
                    c["victim_indices"] = tuple(
                        int(i) for i in c["victim_indices"]
                    )
                f["crash"] = CrashModel(**c)
            parts = []
            for p in f.get("partitions", ()):
                if isinstance(p, Mapping):
                    p = dict(p)
                    if p.get("members") is not None:
                        p["members"] = tuple(int(i) for i in p["members"])
                    p = PartitionEvent(**p)
                parts.append(p)
            f["partitions"] = tuple(parts)
            d["faults"] = FaultPlan(**f)
        if d.get("client_values") is not None:
            d["client_values"] = tuple(float(v) for v in d["client_values"])
        if isinstance(d.get("slo"), Mapping):
            d["slo"] = SLOSpec(**d["slo"])
        return cls(**d)

    def to_json(self) -> str:
        """Canonical JSON rendering (sorted keys)."""
        return json.dumps(self.to_dict(), sort_keys=True)

    def with_(self, **kwargs: Any) -> "Scenario":
        """A copy with fields replaced (``dataclasses.replace``) —
        grid sweeps derive cells from one base config this way."""
        return replace(self, **kwargs)


# ---------------------------------------------------------------------------
# registries (names keep Scenario serializable; instances built per run)
# ---------------------------------------------------------------------------


def _trimmed(p: Mapping[str, Any]):
    from ..aggregators import CoordinateWiseTrimmedMean

    return CoordinateWiseTrimmedMean(f=int(p.get("f", 1)))


def _median(p: Mapping[str, Any]):
    from ..aggregators import CoordinateWiseMedian

    return CoordinateWiseMedian()


def _multi_krum(p: Mapping[str, Any]):
    from ..aggregators import MultiKrum

    return MultiKrum(f=int(p.get("f", 1)), q=int(p.get("q", 3)))


def _cge(p: Mapping[str, Any]):
    from ..aggregators import ComparativeGradientElimination

    return ComparativeGradientElimination(f=int(p.get("f", 1)))


def _geomed(p: Mapping[str, Any]):
    from ..aggregators import GeometricMedian

    return GeometricMedian()


def _mean_of_medians(p: Mapping[str, Any]):
    from ..aggregators import MeanOfMedians

    return MeanOfMedians(f=int(p.get("f", 1)))


def _mda(p: Mapping[str, Any]):
    from ..aggregators import MinimumDiameterAveraging

    return MinimumDiameterAveraging(f=int(p.get("f", 1)))


#: Aggregator registry: scenario name -> builder(params) -> Aggregator.
AGGREGATORS = {
    "trimmed_mean": _trimmed,
    "median": _median,
    "multi_krum": _multi_krum,
    "cge": _cge,
    "geometric_median": _geomed,
    "mean_of_medians": _mean_of_medians,
    "mda": _mda,
}


def build_aggregator(scenario: Scenario):
    """Instantiate the scenario's aggregator from the registry."""
    return AGGREGATORS[scenario.aggregator](scenario.aggregator_params)


def _a_none(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    return None


def _a_sign_flip(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    # the REAL attack class, reference sign convention (scale < 0 flips)
    from ..attacks import SignFlipAttack

    return SignFlipAttack(scale=float(p.get("scale", -4.0)))


def _a_empire(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from ..attacks import EmpireAttack

    return EmpireAttack(scale=float(p.get("scale", -1.1)))


def _a_little(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from .clients import StaticVectorAttack

    return StaticVectorAttack(
        dim, mode="little", scale=float(p.get("scale", 1.0))
    )


def _a_outlier(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from .clients import StaticVectorAttack

    return StaticVectorAttack(
        dim, mode="outlier", scale=float(p.get("scale", 1e3))
    )


def _a_influence(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from ..attacks.adaptive import InfluenceAscentAttack

    return InfluenceAscentAttack(
        dim,
        scale0=float(p.get("scale0", 0.05)),
        grow=float(p.get("grow", 1.6)),
        shrink=float(p.get("shrink", 0.5)),
        seed=seed,
        client_id=client_id,
    )


def _a_krum_evasion(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from ..attacks.adaptive import KrumEvasionAttack

    return KrumEvasionAttack(
        dim,
        eps0=float(p.get("eps0", 0.01)),
        grow=float(p.get("grow", 1.5)),
        shrink=float(p.get("shrink", 0.25)),
        seed=seed,
        client_id=client_id,
    )


def _a_residual_shaping(
    dim: int, p: Mapping[str, Any], seed: int, client_id: str
):
    from ..attacks.adaptive import ResidualShapingAttack

    return ResidualShapingAttack(
        dim,
        mode=str(p.get("mode", "s4")),
        block=int(p.get("block", 256)),
        kappa=float(p.get("kappa", 4.0)),
        scale0=float(p.get("scale0", 0.05)),
        grow=float(p.get("grow", 1.6)),
        shrink=float(p.get("shrink", 0.5)),
        seed=seed,
        client_id=client_id,
    )


def _a_staleness(dim: int, p: Mapping[str, Any], seed: int, client_id: str):
    from ..attacks.adaptive import StalenessAbuseAttack
    from ..serving.staleness import StalenessPolicy

    cutoff = p.get("cutoff", 4)
    return StalenessAbuseAttack(
        dim,
        staleness=StalenessPolicy(
            kind=str(p.get("kind", "exponential")),
            gamma=float(p.get("gamma", 0.5)),
            cutoff=None if cutoff is None else int(cutoff),
        ),
        scale=float(p.get("scale", 1.0)),
        seed=seed,
        client_id=client_id,
    )


#: Attack registry: spec name -> builder(dim, params, seed, client_id).
ATTACKS = {
    "none": _a_none,
    "sign_flip": _a_sign_flip,
    "empire": _a_empire,
    "little": _a_little,
    "outlier": _a_outlier,
    "influence_ascent": _a_influence,
    "krum_evasion": _a_krum_evasion,
    "residual_shaping": _a_residual_shaping,
    "staleness_abuse": _a_staleness,
}


def build_attack(scenario: Scenario, *, seed: int, client_id: str):
    """Instantiate ONE byzantine client's attack from the registry
    (``None`` for spec ``"none"``). Adaptive attacks get a per-client
    seed so replicas don't emit identical noise.

    ``staleness_abuse`` defaults its assumed policy to the SCENARIO's
    own ``staleness_*`` fields (params still override): the attack's
    whole premise is cancelling the tier's published discount, so the
    two configs must agree unless a cell deliberately mis-informs the
    attacker. With the scenario default (``kind='none'``) the attack
    correctly degenerates to fresh, uninflated submissions — nothing
    to abuse."""
    params = scenario.attack.params
    if scenario.attack.name == "staleness_abuse":
        merged = dict(params)
        merged.setdefault("kind", scenario.staleness_kind)
        merged.setdefault("gamma", scenario.staleness_gamma)
        merged.setdefault("cutoff", scenario.staleness_cutoff)
        params = merged
    return ATTACKS[scenario.attack.name](
        scenario.dim, params, seed, client_id
    )


__all__ = [
    "AGGREGATORS",
    "ATTACKS",
    "ArrivalModel",
    "AttackSpec",
    "CrashModel",
    "FaultPlan",
    "PartitionEvent",
    "SLOSpec",
    "Scenario",
    "StragglerModel",
    "build_aggregator",
    "build_attack",
]

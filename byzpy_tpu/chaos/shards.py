"""Compromised-shard adversaries for the chaos wall.

The sharded frontend tier (``byzpy_tpu.serving.sharded``) introduces a
new adversary CLASS: a Byzantine *shard* — a whole ingress replica that
forges its per-round :class:`~byzpy_tpu.serving.PartialFold` instead of
(or on top of) hosting Byzantine clients. This module wraps a real
:class:`~byzpy_tpu.serving.ShardFrontend` with deterministic forgery
modes so the chaos wall can replay the attack and assert the root's
cross-checks catch it (``benchmarks/chaos_bench.py --lanes shard``):

* ``"bitflip"`` — tamper the shipped rows AFTER the digest was taken
  (wire corruption, bit rot, or a lazy forger): the root recomputes the
  digest from the row bits and excludes the partial;
* ``"ghost_clients"`` — append fabricated rows for client ids the
  shard does not own: sticky routing makes the claim a protocol
  violation the root detects from the ids alone;
* ``"replay_seqs"`` — re-claim ``(client, seq)`` pairs the root
  already folded (the double-fold attack): the root's cross-shard
  dedup authority drops the rows as ``root_duplicate``;
* ``"extras"`` — ship honest rows + honest digest but forged streaming
  accumulators (a poisoned Gram block would corrupt the root's fused
  forensics score view): caught by ``extras_policy="verify"``
  (deterministic recompute) — and harmless to the AGGREGATE under any
  policy, because the merged finalize reads only the rows.

A shard that forges *consistently* — fabricated rows with a matching
digest for clients it legitimately owns — is indistinguishable from a
shard whose clients are Byzantine, and is bounded the same way (the
robust aggregator's f-out-of-n contract plus the per-shard row cap);
``docs/serving.md`` §sharded tier spells the threat model out.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..serving.sharded import PartialFold, ShardFrontend, shard_for
from ..forensics.evidence import evidence_digest

FORGE_MODES = ("bitflip", "ghost_clients", "replay_seqs", "extras")


class CompromisedShard:
    """A Byzantine ingress shard: proxies a real
    :class:`~byzpy_tpu.serving.ShardFrontend` (admission, drains,
    confirms all run the production code) but forges every
    :class:`PartialFold` it ships to the root, per ``mode``.

    Deterministic: same seed ⇒ same forged bits (the chaos wall's
    replay contract). Install with ``coordinator.shards[i] =
    CompromisedShard(coordinator.shards[i], mode=...)``."""

    def __init__(
        self,
        shard: ShardFrontend,
        *,
        mode: str = "bitflip",
        seed: int = 0,
        scale: float = 1e3,
        n_shards: Optional[int] = None,
    ) -> None:
        if mode not in FORGE_MODES:
            raise ValueError(f"mode must be one of {FORGE_MODES}")
        if mode == "ghost_clients" and not n_shards:
            # without the shard count the ghost id cannot be made
            # provably foreign — it could hash to the sender's own
            # shard, pass every root check, and silently stop being an
            # attack the lane can assert on
            raise ValueError(
                "ghost_clients mode requires n_shards (the ghost id "
                "must provably belong to ANOTHER shard)"
            )
        self._shard = shard
        self.mode = mode
        self.rng = np.random.default_rng(seed)
        self.scale = float(scale)
        self.n_shards = n_shards
        #: partials this shard forged (the lane's ground truth)
        self.forged_sent = 0
        #: ``(client, seq)`` pairs to re-claim in ``replay_seqs`` mode
        #: (the lane feeds it pairs the root already folded)
        self.replay_pairs: list = []

    def __getattr__(self, name):
        return getattr(self._shard, name)

    # -- forged close path -------------------------------------------------

    def build_partial(self, tenant, subs, cohort) -> PartialFold:
        honest = self._shard.build_partial(tenant, subs, cohort)
        return self._forge(honest)

    def close_partial(self, tenant: str) -> Optional[PartialFold]:
        drained = self._shard.drain_cohort(tenant)
        if drained is None:
            return None
        return self.build_partial(tenant, *drained)

    def _forge(self, p: PartialFold) -> PartialFold:
        self.forged_sent += 1
        if self.mode == "bitflip":
            rows = np.array(p.rows, copy=True)
            if rows.size:
                rows[0] = rows[0] * np.float32(self.scale) + np.float32(1.0)
            # digest deliberately NOT recomputed: the claim describes
            # the honest rows, the payload carries the forged ones
            return dataclasses.replace(p, rows=rows)
        if self.mode == "ghost_clients":
            d = p.rows.shape[1] if p.rows.ndim == 2 else 0
            ghost = (
                self.rng.normal(size=(1, d)).astype(np.float32) * self.scale
            )
            rows = np.concatenate([p.rows, ghost], axis=0)
            name, k = "ghost-0", 0
            if self.n_shards:
                # provably foreign: an id whose home shard is NOT the
                # sender (the attack being modeled)
                while shard_for(name, self.n_shards) == p.shard:
                    k += 1
                    name = f"ghost-{k}"
            # a consistent forger recomputes the digest — the home-shard
            # check catches the claim anyway
            return dataclasses.replace(
                p,
                rows=rows,
                clients=(*p.clients, name),
                seqs=(*p.seqs, 0),
                wal_ids=(*p.wal_ids, None),
                extras={},
                digest=evidence_digest(rows),
            )
        if self.mode == "replay_seqs":
            if not self.replay_pairs:
                return p
            client, seq, row = self.replay_pairs[0]
            rows = np.concatenate([p.rows, row[None, :]], axis=0)
            return dataclasses.replace(
                p,
                rows=rows,
                clients=(*p.clients, client),
                seqs=(*p.seqs, seq),
                wal_ids=(*p.wal_ids, None),
                extras={},
                digest=evidence_digest(rows),
            )
        # "extras": honest rows, honest digest, poisoned accumulators
        if not p.extras:
            return p  # family ships no extras: nothing to poison
        extras = {
            k: np.zeros_like(np.asarray(v)) if hasattr(v, "shape") else v
            for k, v in p.extras.items()
        }
        return dataclasses.replace(p, extras=extras)


__all__ = ["FORGE_MODES", "CompromisedShard"]

"""byzpy-tpu command-line interface.

API parity: ``byzpy/cli.py:122-164`` — subcommands ``version``, ``doctor``
(environment report; the reference probes torch/CUDA/cupy/UCX at
cli.py:38-74, here we probe the JAX platform, device inventory, and
native-extension availability), and ``list aggregators|attacks|
pre-aggregators`` via subclass discovery (ref: cli.py:14-35).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Type

from .version import __version__


def _subclasses_of(base: Type) -> List[Type]:
    """All concrete registered subclasses, sorted by name (the package
    __init__ imports every built-in, so walking the subclass tree is the
    same discovery the reference does by scanning packages)."""
    seen: Dict[str, Type] = {}
    stack = list(base.__subclasses__())
    while stack:
        cls = stack.pop()
        stack.extend(cls.__subclasses__())
        if not getattr(cls, "__abstractmethods__", None):
            seen[cls.__name__] = cls
    return [seen[k] for k in sorted(seen)]


def _collect(kind: str) -> List[Type]:
    if kind == "aggregators":
        import byzpy_tpu.aggregators as pkg
        from byzpy_tpu.aggregators.base import Aggregator as base
    elif kind == "attacks":
        import byzpy_tpu.attacks as pkg  # noqa: F401 — import registers subclasses
        from byzpy_tpu.attacks.base import Attack as base
    elif kind == "pre-aggregators":
        import byzpy_tpu.pre_aggregators as pkg  # noqa: F401
        from byzpy_tpu.pre_aggregators.base import PreAggregator as base
    else:  # pragma: no cover - argparse choices guard this
        raise ValueError(kind)
    return _subclasses_of(base)


def cmd_version(_args: argparse.Namespace) -> int:
    """``byzpy-tpu version``: print the package version."""
    print(__version__)
    return 0


def _devices_with_timeout(jax_mod, timeout_s: float = 20.0):
    """``jax.devices()`` bounded by a timeout: platform plugins that dial a
    remote accelerator (e.g. a tunneled TPU) can block indefinitely when
    the link is down, and a diagnostics command must degrade, not hang.
    The probe thread is daemonic — if it never returns it dies with the
    process. Override via BYZPY_TPU_DOCTOR_TIMEOUT (seconds)."""
    import os
    import threading

    try:
        timeout_s = float(os.environ.get("BYZPY_TPU_DOCTOR_TIMEOUT", timeout_s))
    except ValueError:
        pass  # malformed override (e.g. "20s"): keep the default
    result: list = []

    def probe() -> None:
        try:
            result.append(("ok", jax_mod.devices()))
        except Exception as exc:  # noqa: BLE001 — forwarded to caller
            result.append(("err", exc))

    # plain daemon thread: a ThreadPoolExecutor worker is non-daemonic and
    # its atexit join would hang interpreter shutdown on a stuck probe
    t = threading.Thread(target=probe, name="doctor-device-probe", daemon=True)
    t.start()
    t.join(timeout_s)
    if not result:
        raise TimeoutError(
            f"device platform did not initialize within {timeout_s:g}s "
            "(accelerator link down?)"
        )
    kind, value = result[0]
    if kind == "err":
        raise value
    return value


def doctor_report() -> Dict[str, Any]:
    """Environment probe (ref: ``byzpy doctor``, cli.py:38-74)."""
    report: Dict[str, Any] = {"version": __version__, "python": sys.version.split()[0]}
    try:
        import jax

        report["jax"] = {"version": jax.__version__, "ok": True}
        try:
            devices = _devices_with_timeout(jax)
            report["devices"] = [
                {
                    "id": d.id,
                    "platform": d.platform,
                    "kind": getattr(d, "device_kind", "?"),
                    "process": getattr(d, "process_index", 0),
                }
                for d in devices
            ]
            report["default_backend"] = jax.default_backend()
            report["device_count"] = len(devices)
            report["process_count"] = jax.process_count()
        except Exception as exc:  # noqa: BLE001 — report, don't crash doctor
            report["devices_error"] = repr(exc)
    except Exception as exc:  # noqa: BLE001
        report["jax"] = {"ok": False, "error": repr(exc)}
    for mod in ("flax", "optax", "cloudpickle"):
        try:
            m = __import__(mod)
            report[mod] = {"ok": True, "version": getattr(m, "__version__", "?")}
        except Exception as exc:  # noqa: BLE001
            report[mod] = {"ok": False, "error": repr(exc)}
    try:
        from .engine.storage import native_store

        report["native_shm_store"] = {"ok": native_store.available()}
    except Exception:  # noqa: BLE001 — optional native extension
        report["native_shm_store"] = {"ok": False}
    return report


def cmd_doctor(args: argparse.Namespace) -> int:
    """``byzpy-tpu doctor``: print the environment probe (text or json)."""
    report = doctor_report()
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for key, value in sorted(report.items()):
            print(f"{key}: {value}")
    return 0


def cmd_list(args: argparse.Namespace) -> int:
    """``byzpy-tpu list``: enumerate registered aggregators/attacks/pre-aggregators."""
    for cls in _collect(args.kind):
        name = getattr(cls, "name", None) or cls.__name__
        print(f"{cls.__name__}\t({name})")
    return 0


def bench_report(*, n: int = 16, d: int = 65_536, repeat: int = 10) -> Dict[str, Any]:
    """Quick on-device micro-benchmark of the hot aggregators (one JSON
    row per op, milliseconds per call) — the sanity companion to
    ``doctor``: is this device delivering the expected order of
    magnitude? Full methodology and the measured grid live in
    ``benchmarks/`` (this uses the same chained-timing helper)."""
    import jax
    import jax.numpy as jnp

    from .ops import robust
    from .observability.compat import timed_call_s

    try:
        devices = _devices_with_timeout(jax)
    except Exception as exc:  # noqa: BLE001 — report, don't hang/crash bench
        return {"error": f"device probe failed: {type(exc).__name__}: {exc}"}
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    rows: Dict[str, Any] = {
        "device": str(devices[0]),
        "shape": [n, d],
        "repeat": repeat,
    }
    from functools import partial

    f = max(1, n // 8)
    ops = {
        "coordinate_median": robust.coordinate_median,
        "trimmed_mean": partial(robust.trimmed_mean, f=f),
        "multi_krum": partial(robust.multi_krum, f=f, q=max(1, n // 4)),
        "geometric_median": partial(robust.geometric_median, max_iter=32),
    }
    for name, fn in ops.items():
        try:
            ms = timed_call_s(jax.jit(fn), x, warmup=2, repeat=repeat) * 1e3
            rows[name] = {"ms": round(ms, 3)}
        except Exception as exc:  # noqa: BLE001 — report, don't crash bench
            rows[name] = {"error": f"{type(exc).__name__}: {exc}"}
    return rows


def cmd_bench(args: argparse.Namespace) -> int:
    """``byzpy-tpu bench``: print the on-device micro-benchmark as JSON."""
    report = bench_report(n=args.nodes, d=args.dim, repeat=args.repeat)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def cmd_lint(args: argparse.Namespace) -> int:
    """``byzpy-tpu lint``: run the byzlint static-analysis gate (exactly
    equivalent to ``python -m byzpy_tpu.analysis``; see
    ``docs/static_analysis.md`` for the rule catalog)."""
    from .analysis import main as lint_main

    argv: List[str] = list(args.paths)
    if args.format != "text":
        argv += ["--format", args.format]
    if args.select:
        argv += ["--select", args.select]
    if args.list_rules:
        argv += ["--list-rules"]
    return lint_main(argv)


def cmd_study(args: argparse.Namespace) -> int:
    """``byzpy-tpu study``: one accuracy-under-attack cell pair on real
    data — the 30-second proof that robust aggregation rescues training a
    byzantine attack destroys (full grid: ``benchmarks/robust_learning.py``)."""
    from .utils.robust_study import StudyConfig, results_table, run_study

    cfg = StudyConfig(rounds=args.rounds, eval_every=max(1, args.rounds // 3))
    aggregators = tuple(dict.fromkeys(("mean", args.aggregator)))
    results = run_study(
        aggregators=aggregators,
        attacks=(args.attack,),
        cfg=cfg,
        verbose=True,
    )
    print()
    print(results_table(results))
    return 0


def build_parser() -> argparse.ArgumentParser:
    """Assemble the ``byzpy-tpu`` argument parser (one subcommand per cmd_*)."""
    parser = argparse.ArgumentParser(
        prog="byzpy-tpu",
        description="TPU-native Byzantine-robust distributed learning framework",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_version = sub.add_parser("version", help="print the package version")
    p_version.set_defaults(fn=cmd_version)

    p_doctor = sub.add_parser("doctor", help="report the JAX/TPU environment")
    p_doctor.add_argument("--format", choices=("text", "json"), default="text")
    p_doctor.set_defaults(fn=cmd_doctor)

    p_list = sub.add_parser("list", help="list available operator classes")
    p_list.add_argument(
        "kind", choices=("aggregators", "attacks", "pre-aggregators")
    )
    p_list.set_defaults(fn=cmd_list)

    p_bench = sub.add_parser(
        "bench", help="quick on-device micro-benchmark of the hot aggregators"
    )
    p_bench.add_argument("--nodes", type=int, default=16)
    p_bench.add_argument("--dim", type=int, default=65_536)
    p_bench.add_argument("--repeat", type=int, default=10)
    p_bench.set_defaults(fn=cmd_bench)

    p_lint = sub.add_parser(
        "lint",
        help="run byzlint, the JAX-aware static-analysis gate "
        "(trace-safety, donation, collective-axis, async hazards)",
    )
    p_lint.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to scan (default: byzpy_tpu benchmarks examples)",
    )
    p_lint.add_argument("--format", choices=("text", "json"), default="text")
    p_lint.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run",
    )
    p_lint.add_argument("--list-rules", action="store_true")
    p_lint.set_defaults(fn=cmd_lint)

    p_study = sub.add_parser(
        "study",
        help="robust-learning demo: mean vs a robust aggregator under attack",
    )
    # mirrors utils.robust_study.STUDY_AGGREGATORS/STUDY_ATTACKS (kept
    # literal so `byzpy-tpu version` never imports jax; sync pinned by
    # tests/test_cli_utils_configs.py)
    p_study.add_argument(
        "--aggregator",
        default="trimmed_mean",
        choices=(
            "mean", "median", "trimmed_mean", "multi_krum",
            "geometric_median", "nnm_trimmed_mean",
        ),
    )
    p_study.add_argument(
        "--attack",
        default="sign_flip",
        choices=("none", "sign_flip", "empire", "little", "gaussian", "mimic"),
    )
    p_study.add_argument("--rounds", type=int, default=120)
    p_study.set_defaults(fn=cmd_study)

    return parser


def main(argv: List[str] | None = None) -> int:
    """Console entry point (``byzpy-tpu`` in pyproject scripts)."""
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())

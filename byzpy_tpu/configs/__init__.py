from .actor import get_actor, set_actor, use_actor
from .mesh import get_default_mesh, set_default_mesh, use_mesh

__all__ = [
    "set_actor",
    "get_actor",
    "use_actor",
    "set_default_mesh",
    "get_default_mesh",
    "use_mesh",
]

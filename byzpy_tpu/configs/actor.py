"""Global default actor-backend spec (API parity:
``byzpy/configs/actor.py:1-30`` — ``set_actor``/``get_actor`` plus a
context-manager override).

Specs are the strings ``resolve_backend`` understands: ``"thread"``,
``"process"``, ``"tpu"``/``"tpu:N"``, ``"tcp://host:port"``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

_DEFAULT_ACTOR = "thread"
_actor_spec = _DEFAULT_ACTOR


def set_actor(spec: str) -> None:
    """Set the process-wide default actor backend spec."""
    if not isinstance(spec, str) or not spec:
        raise ValueError(f"invalid actor spec {spec!r}")
    _validate(spec)
    global _actor_spec
    _actor_spec = spec


def _validate(spec: str) -> None:
    if spec in ("thread", "process", "tpu"):
        return
    if spec.startswith("tpu:"):
        try:
            int(spec.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"tpu spec must be tpu:<device-index> (got {spec!r})"
            ) from None
        return
    if spec.startswith("tcp://"):
        host, _, port = spec[len("tcp://"):].rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"tcp spec must be tcp://host:port (got {spec!r})")
        return
    raise ValueError(f"unknown actor backend spec {spec!r}")


def get_actor() -> str:
    """Current default actor-backend spec string (see ``set_actor``)."""
    return _actor_spec


@contextlib.contextmanager
def use_actor(spec: str) -> Iterator[None]:
    """Temporarily override the default actor spec."""
    global _actor_spec
    _validate(spec)
    previous = _actor_spec
    _actor_spec = spec
    try:
        yield
    finally:
        _actor_spec = previous


__all__ = ["set_actor", "get_actor", "use_actor"]

"""Global default device mesh.

The reference's ``configs/backend.py`` selects the global tensor backend
(``byzpy/configs/backend.py:12-34``); the TPU-native analogue is selecting
the global *device mesh* that sharded aggregation and SPMD training steps
use when none is passed explicitly.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from jax.sharding import Mesh

_default_mesh: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    """Set (or clear, with ``None``) the process-wide default mesh."""
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh(*, create: bool = False) -> Optional[Mesh]:
    """The configured default mesh. With ``create=True`` and nothing
    configured, builds a 1-D ``nodes`` mesh over all visible devices."""
    if _default_mesh is not None:
        return _default_mesh
    if create:
        from ..parallel.mesh import node_mesh

        return node_mesh()
    return None


@contextlib.contextmanager
def use_mesh(mesh: Mesh) -> Iterator[Mesh]:
    """Temporarily set the default mesh."""
    global _default_mesh
    previous = _default_mesh
    _default_mesh = mesh
    try:
        yield mesh
    finally:
        _default_mesh = previous


__all__ = ["set_default_mesh", "get_default_mesh", "use_mesh"]

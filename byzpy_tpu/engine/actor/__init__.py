from .base import ActorBackend, ActorRef
from .channels import ChannelRef, Endpoint, open_channel
from .factory import resolve_backend

__all__ = ["ActorBackend", "ActorRef", "ChannelRef", "Endpoint", "open_channel", "resolve_backend"]

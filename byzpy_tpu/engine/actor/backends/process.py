"""Process actor backend: actor hosted in a spawned child process.

Unlike the reference's lock-step pipe protocol (one in-flight request,
ref: ``byzpy/engine/actor/backends/process.py:111-321`` with its ``_io_lock``
pipe-race note), this backend tags every frame with a request id and runs an
asyncio loop in the child, so multiple requests (e.g. a blocking ``chan_get``
plus a ``call``) are in flight concurrently without deadlock.

Useful on TPU hosts for CPU-side work (data loading, combinatorial subset
enumeration) that must not block the device-driving process. Payloads cross
the pipe as cloudpickle frames with device arrays converted to numpy
(``wire.host_view``) — tensors never move between chips this way.
"""

from __future__ import annotations

import asyncio
import inspect
import itertools
import multiprocessing as mp
import os
import traceback
import uuid
from typing import Any, Dict, Optional

import cloudpickle

from .. import ipc, wire
from ..channels import Endpoint
from ..router import channel_router

_counter = itertools.count()

# BYZPY_TPU_SHM=0 forces all payloads inline through the pipe
_SHM_ENABLED = os.environ.get("BYZPY_TPU_SHM", "1") != "0"


# ---------------------------------------------------------------------------
# Child-process side
# ---------------------------------------------------------------------------


def _worker_main(conn) -> None:  # pragma: no cover - runs in child process
    asyncio.run(_worker_loop(conn))


async def _worker_loop(conn) -> None:  # pragma: no cover - runs in child process
    loop = asyncio.get_running_loop()
    obj_holder: Dict[str, Any] = {}
    mailboxes: Dict[str, asyncio.Queue] = {}
    send_lock = asyncio.Lock()
    stopping = asyncio.Event()

    async def reply(req_id: int, ok: bool, payload: Any) -> None:
        blob = cloudpickle.dumps((req_id, ok, payload))
        async with send_lock:
            await loop.run_in_executor(None, conn.send_bytes, blob)

    async def handle(req_id: int, op: str, data: Any) -> None:
        try:
            if op == "construct":
                target, args, kwargs = data
                args, kwargs = ipc.unwrap_payload((args, kwargs), copy=True, close=True)
                obj_holder["obj"] = target(*args, **kwargs)
                result = None
            elif op == "call":
                method, args, kwargs = data
                args, kwargs = ipc.unwrap_payload((args, kwargs), copy=True, close=True)
                obj = obj_holder.get("obj")
                if obj is None:
                    raise RuntimeError("actor not constructed")
                fn = getattr(obj, method)
                result = fn(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
                result = wire.host_view(result)
            elif op == "chan_open":
                mailboxes.setdefault(data, asyncio.Queue())
                result = None
            elif op == "chan_put":
                name, payload = data
                # copy shm payloads out now: the sender unlinks its segments
                # as soon as this request is acknowledged, and the mailbox
                # may be drained much later
                payload = ipc.unwrap_payload(payload, copy=True, close=True)
                await mailboxes.setdefault(name, asyncio.Queue()).put(payload)
                result = None
            elif op == "chan_get":
                result = await mailboxes.setdefault(data, asyncio.Queue()).get()
            elif op == "stop":
                stopping.set()
                result = None
            else:
                raise ValueError(f"unknown op {op!r}")
            await reply(req_id, True, result)
        except BaseException as exc:  # noqa: BLE001 - report to parent
            await reply(req_id, False, (type(exc).__name__, str(exc), traceback.format_exc()))

    async def read_frames() -> None:
        while not stopping.is_set():
            try:
                blob = await loop.run_in_executor(None, conn.recv_bytes)
            except (EOFError, OSError):
                break
            req_id, op, data = cloudpickle.loads(blob)
            asyncio.ensure_future(handle(req_id, op, data))

    reader = asyncio.ensure_future(read_frames())
    await stopping.wait()
    reader.cancel()


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


class ProcessActorBackend:
    """Subprocess backend: one spawned process per actor, cloudpickle frames over a pipe with request-id correlation."""
    scheme = "process"

    def __init__(
        self, *, actor_id: str | None = None, child_platform: str = "cpu"
    ) -> None:
        self.actor_id = actor_id or f"proc-{next(_counter)}-{uuid.uuid4().hex[:6]}"
        self._child_platform = (
            os.environ.get("BYZPY_TPU_CHILD_PLATFORM") or child_platform
        )
        self._proc: mp.process.BaseProcess | None = None
        self._conn = None
        self._reader_task: asyncio.Task | None = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_ids = itertools.count()
        self._send_lock: asyncio.Lock | None = None
        self._started = False

    async def start(self) -> None:
        if self._started:
            return
        ctx = mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(target=_worker_main, args=(child_conn,), daemon=True)
        # children must not inherit the parent's accelerator bindings: a TPU
        # chip admits one process, so a child re-registering the plugin
        # would deadlock against the parent (same guard as ProcessContext)
        patch = {"JAX_PLATFORMS": self._child_platform, "PALLAS_AXON_POOL_IPS": ""}
        saved = {k: os.environ.get(k) for k in patch}
        os.environ.update(patch)
        try:
            self._proc.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        child_conn.close()
        self._conn = parent_conn
        self._send_lock = asyncio.Lock()
        self._reader_task = asyncio.ensure_future(self._read_replies())
        channel_router.register(self.get_endpoint(), self)
        self._started = True

    async def _read_replies(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                blob = await loop.run_in_executor(None, self._conn.recv_bytes)
                req_id, ok, payload = cloudpickle.loads(blob)
                fut = self._pending.pop(req_id, None)
                if fut is None or fut.done():
                    continue
                if ok:
                    fut.set_result(payload)
                else:
                    name, msg, tb = payload
                    fut.set_exception(RuntimeError(f"{name} in actor process: {msg}\n{tb}"))
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 - fail pending, don't hang them
            err = exc if not isinstance(exc, (EOFError, OSError)) else None
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        ConnectionError(f"actor process pipe closed{f': {err!r}' if err else ''}")
                    )
            self._pending.clear()

    async def _request(self, op: str, data: Any) -> Any:
        self._ensure_started()
        if self._reader_task is not None and self._reader_task.done():
            raise ConnectionError("actor process pipe closed (reader exited)")
        req_id = next(self._req_ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        blob = cloudpickle.dumps((req_id, op, data))
        loop = asyncio.get_running_loop()
        async with self._send_lock:
            await loop.run_in_executor(None, self._conn.send_bytes, blob)
        return await fut

    async def construct(self, target: Any, /, *args: Any, **kwargs: Any) -> None:
        await self._shm_request("construct", target, args, kwargs)

    async def call(self, method: str, /, *args: Any, **kwargs: Any) -> Any:
        return await self._shm_request("call", method, args, kwargs)

    async def _shm_request(self, op: str, head: Any, args: Any, kwargs: Any) -> Any:
        """Ship large host arrays via the native shm store instead of the
        pipe (ref: the reference's wrap_payload on every process hop,
        ``byzpy/engine/actor/ipc.py:20-42``); the child copies out and
        unmaps, the parent unlinks after the reply."""
        payload = wire.host_view((args, kwargs))
        if _SHM_ENABLED:
            payload, handles = ipc.wrap_payload(payload)
        else:
            handles = []
        try:
            return await self._request(op, (head, payload[0], payload[1]))
        finally:
            ipc.cleanup_handles(handles)

    async def close(self) -> None:
        if not self._started:
            return
        channel_router.unregister(self.get_endpoint())
        try:
            await asyncio.wait_for(self._request("stop", None), timeout=5)
        except Exception:
            pass
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._conn is not None:
            # EOF lets the child's blocked conn.recv_bytes thread exit so the
            # child terminates promptly instead of riding out join+kill.
            self._conn.close()
        # snapshot-and-null BEFORE awaiting: the off-loop join suspends
        # this coroutine, and a concurrent close() must not re-enter the
        # join/kill sequence or dereference a nulled _proc
        proc, self._proc = self._proc, None
        self._conn = None
        self._started = False
        if proc is not None:
            # join() blocks up to its timeout: run it off-loop so a slow
            # child cannot stall every other actor sharing this event loop
            # (same pattern as node/process_context.py shutdown)
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, proc.join, 5)
            if proc.is_alive():
                proc.kill()
                await loop.run_in_executor(None, proc.join, 5)

    def get_endpoint(self) -> Endpoint:
        return Endpoint(self.scheme, "local", self.actor_id)

    async def chan_open(self, name: str) -> None:
        await self._request("chan_open", name)

    async def deliver_local(self, name: str, payload: Any) -> None:
        hosted = wire.host_view(payload)
        if _SHM_ENABLED:
            wrapped, handles = ipc.wrap_payload(hosted)
        else:
            wrapped, handles = hosted, []
        try:
            await self._request("chan_put", (name, wrapped))
        finally:
            ipc.cleanup_handles(handles)

    async def chan_put(
        self, name: str, payload: Any, *, endpoint: Optional[Endpoint] = None
    ) -> None:
        if endpoint is None or endpoint == self.get_endpoint():
            await self.deliver_local(name, payload)
            return
        if await channel_router.deliver(endpoint, name, payload):
            return
        if endpoint.scheme == "tcp":
            from ..transports import tcp

            await tcp.chan_put(endpoint, name, payload)
            return
        raise LookupError(f"no route to endpoint {endpoint}")

    async def chan_get(self, name: str) -> Any:
        return await self._request("chan_get", name)

    def _ensure_started(self) -> None:
        if not self._started:
            raise RuntimeError("backend not started; call start() first")


__all__ = ["ProcessActorBackend"]

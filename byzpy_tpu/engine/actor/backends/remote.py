"""Remote TCP actor backend + multi-actor server.

Host-side control plane for multi-host deployments (ref:
``byzpy/engine/actor/backends/remote.py:19-433``): the server hosts many
actors keyed by actor id; clients construct/call/use channels over
length-prefixed cloudpickle frames. Request-id tagging lets one connection
carry overlapping requests (a blocking ``chan_get`` never stalls calls).

On TPU pods this wire is for orchestration only — gradient tensors move
between chips via XLA collectives over ICI/DCN (``byzpy_tpu.parallel``),
not through this socket.

Security: frames are cloudpickle — remote code execution for anyone
who can reach the socket. Trusted/firewalled networks or loopback
only; see ``byzpy_tpu.engine.actor.wire.warn_untrusted_bind``.
"""

from __future__ import annotations

import asyncio
import inspect
import itertools
import logging
import traceback
import uuid
from typing import Any, Dict, Optional

from .. import wire
from ..channels import Endpoint
from ..router import channel_router

logger = logging.getLogger(__name__)


class RemoteActorServer:
    """Hosts actors for remote clients. One instance per host process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._actors: Dict[str, Any] = {}
        self._mailboxes: Dict[str, Dict[str, asyncio.Queue]] = {}
        self._connections: set[asyncio.StreamWriter] = set()
        self._handler_tasks: set[asyncio.Task] = set()

    async def start(self) -> None:
        wire.warn_untrusted_bind(self.host, "RemoteActorServer")
        self._server = await asyncio.start_server(self._on_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # Drop live connections first: Python 3.12's Server.wait_closed()
            # waits for connection handlers, which otherwise sit in recv forever.
            for writer in list(self._connections):
                writer.close()
            await self._server.wait_closed()
            self._server = None
        # cancel handlers still parked on empty mailboxes (abandoned chan_get)
        for task in list(self._handler_tasks):
            task.cancel()
        if self._handler_tasks:
            await asyncio.gather(*self._handler_tasks, return_exceptions=True)
        self._handler_tasks.clear()
        self._actors.clear()
        self._mailboxes.clear()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        send_lock = asyncio.Lock()

        async def reply(req_id: Any, ok: bool, payload: Any) -> None:
            async with send_lock:
                try:
                    await wire.send_obj(writer, {"req_id": req_id, "ok": ok, "result": payload})
                except (ConnectionError, OSError):
                    pass

        async def handle(msg: Dict[str, Any]) -> None:
            req_id = msg.get("req_id")
            try:
                result = await self._dispatch(msg)
                await reply(req_id, True, wire.host_view(result))
            except BaseException as exc:  # noqa: BLE001 - reported to client
                await reply(req_id, False, (type(exc).__name__, str(exc), traceback.format_exc()))

        self._connections.add(writer)
        try:
            while True:
                msg = await wire.recv_obj(reader)
                task = asyncio.ensure_future(handle(msg))
                self._handler_tasks.add(task)
                task.add_done_callback(self._handler_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except ValueError as exc:
            # unauthenticated/tampered frame (wire HMAC) — drop the peer
            logger.warning("dropping connection: %s", exc)
        finally:
            self._connections.discard(writer)
            writer.close()

    async def _dispatch(self, msg: Dict[str, Any]) -> Any:
        op = msg["op"]
        actor_id = msg.get("actor_id")
        if op == "construct":
            target, args, kwargs = msg["payload"]
            obj = target(*args, **kwargs)
            self._actors[actor_id] = obj
            self._mailboxes.setdefault(actor_id, {})
            return None
        if op == "call":
            obj = self._actors.get(actor_id)
            if obj is None:
                raise KeyError(f"unknown actor {actor_id!r}")
            method, args, kwargs = msg["payload"]
            fn = getattr(obj, method)
            result = fn(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            return result
        if op == "chan_open":
            self._mailboxes.setdefault(actor_id, {}).setdefault(msg["name"], asyncio.Queue())
            return None
        if op == "chan_put":
            boxes = self._mailboxes.setdefault(actor_id, {})
            await boxes.setdefault(msg["name"], asyncio.Queue()).put(msg["payload"])
            return None
        if op == "chan_get":
            boxes = self._mailboxes.setdefault(actor_id, {})
            return await boxes.setdefault(msg["name"], asyncio.Queue()).get()
        if op == "close":
            self._actors.pop(actor_id, None)
            self._mailboxes.pop(actor_id, None)
            return None
        raise ValueError(f"unknown op {op!r}")


class RemoteActorBackend:
    """Client backend: hosts its actor on a remote ``RemoteActorServer``."""

    scheme = "tcp"
    _counter = itertools.count()

    def __init__(self, host: str, port: int, *, actor_id: str | None = None) -> None:
        self.host = host
        self.port = int(port)
        self.actor_id = actor_id or f"remote-{next(self._counter)}-{uuid.uuid4().hex[:6]}"
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_ids = itertools.count()
        self._send_lock: asyncio.Lock | None = None
        self._started = False

    async def start(self) -> None:
        if self._started:
            return
        # dial under the shared retry policy (decorrelated-jitter
        # backoff): a remote host still booting — or restarting after a
        # crash the elastic PS is about to readmit it from — is ridden
        # out instead of failing the caller on the first RST. In-flight
        # REQUESTS are never replayed (no idempotency key on the actor
        # wire); only the connect leg retries.
        from ...actor.transports.tcp import dial_policy
        from ....resilience.retry import connect_with_retry

        self._reader, self._writer = await connect_with_retry(
            self.host, self.port, policy=dial_policy(),
            component="remote_actor",
        )
        self._send_lock = asyncio.Lock()
        self._reader_task = asyncio.ensure_future(self._read_replies())
        channel_router.register(self.get_endpoint(), self)
        self._started = True

    async def _read_replies(self) -> None:
        try:
            while True:
                msg = await wire.recv_obj(self._reader)
                fut = self._pending.pop(msg.get("req_id"), None)
                if fut is None or fut.done():
                    continue
                if msg["ok"]:
                    fut.set_result(msg["result"])
                else:
                    name, text, tb = msg["result"]
                    fut.set_exception(RuntimeError(f"{name} on remote server: {text}\n{tb}"))
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 - any reader death must fail pending
            io_error = isinstance(exc, (asyncio.IncompleteReadError, ConnectionError, OSError))
            detail = "" if io_error else f": {exc!r}"
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"remote actor connection lost{detail}"))
            self._pending.clear()

    async def _request(self, msg: Dict[str, Any]) -> Any:
        self._ensure_started()
        if self._reader_task is not None and self._reader_task.done():
            raise ConnectionError(
                "remote actor connection lost (reader exited); reconnect with start()"
            )
        req_id = next(self._req_ids)
        msg = {**msg, "req_id": req_id, "actor_id": self.actor_id}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._send_lock:
            await wire.send_obj(self._writer, msg)
        return await fut

    async def construct(self, target: Any, /, *args: Any, **kwargs: Any) -> None:
        await self._request(
            {"op": "construct", "payload": (target, wire.host_view(args), wire.host_view(kwargs))}
        )

    async def call(self, method: str, /, *args: Any, **kwargs: Any) -> Any:
        return await self._request(
            {"op": "call", "payload": (method, wire.host_view(args), wire.host_view(kwargs))}
        )

    async def close(self) -> None:
        if not self._started:
            return
        channel_router.unregister(self.get_endpoint())
        try:
            await asyncio.wait_for(self._request({"op": "close"}), timeout=5)
        except Exception:
            pass
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
        self._reader = self._writer = None
        self._started = False

    def get_endpoint(self) -> Endpoint:
        return Endpoint(self.scheme, f"{self.host}:{self.port}", self.actor_id)

    async def chan_open(self, name: str) -> None:
        await self._request({"op": "chan_open", "name": name})

    async def deliver_local(self, name: str, payload: Any) -> None:
        await self._request({"op": "chan_put", "name": name, "payload": wire.host_view(payload)})

    async def chan_put(
        self, name: str, payload: Any, *, endpoint: Optional[Endpoint] = None
    ) -> None:
        if endpoint is None or endpoint == self.get_endpoint():
            await self.deliver_local(name, payload)
            return
        if await channel_router.deliver(endpoint, name, payload):
            return
        if endpoint.scheme == "tcp":
            from ..transports import tcp

            await tcp.chan_put(endpoint, name, payload)
            return
        raise LookupError(f"no route to endpoint {endpoint}")

    async def chan_get(self, name: str) -> Any:
        return await self._request({"op": "chan_get", "name": name})

    def _ensure_started(self) -> None:
        if not self._started:
            raise RuntimeError("backend not started; call start() first")


__all__ = ["RemoteActorServer", "RemoteActorBackend"]

"""Thread actor backend: one dedicated OS thread per actor.

Concurrency-safety by construction, as in the reference
(ref: ``byzpy/engine/actor/backends/thread.py:14-125``): every method of the
hosted object executes on the actor's single thread, so actor state needs no
locks. Mailboxes are asyncio queues owned by the event loop. Channel sends
to peers of any local scheme route through the process-local
``channel_router``; TCP endpoints fall back to the network transport.
"""

from __future__ import annotations

import asyncio
import inspect
import itertools
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ..channels import Endpoint
from ..router import channel_router

_counter = itertools.count()


class ThreadActorBackend:
    """In-process backend: each actor is a daemon thread draining a mailbox queue."""
    scheme = "thread"

    def __init__(self, *, actor_id: str | None = None) -> None:
        self.actor_id = actor_id or f"thread-{next(_counter)}-{uuid.uuid4().hex[:6]}"
        self._executor: ThreadPoolExecutor | None = None
        self._obj: Any = None
        self._mailboxes: Dict[str, asyncio.Queue] = {}
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"actor-{self.actor_id}"
        )
        channel_router.register(self.get_endpoint(), self)
        self._started = True

    async def construct(self, target: Any, /, *args: Any, **kwargs: Any) -> None:
        self._ensure_started()
        loop = asyncio.get_running_loop()
        self._obj = await loop.run_in_executor(
            self._executor, lambda: target(*args, **kwargs)
        )

    async def call(self, method: str, /, *args: Any, **kwargs: Any) -> Any:
        self._ensure_started()
        if self._obj is None:
            raise RuntimeError("actor not constructed")
        fn = getattr(self._obj, method)
        loop = asyncio.get_running_loop()
        if inspect.iscoroutinefunction(fn):
            # Run the coroutine to completion on the actor's own thread (its
            # own mini event loop) so the single-thread actor invariant holds
            # for async methods too.
            return await loop.run_in_executor(
                self._executor, lambda: asyncio.run(fn(*args, **kwargs))
            )
        result = await loop.run_in_executor(self._executor, lambda: fn(*args, **kwargs))
        if inspect.isawaitable(result):
            result = await result
        return result

    async def close(self) -> None:
        if not self._started:
            return
        channel_router.unregister(self.get_endpoint())
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._obj = None
        self._started = False

    # -- endpoint & channels ------------------------------------------------

    def get_endpoint(self) -> Endpoint:
        return Endpoint(self.scheme, "local", self.actor_id)

    async def chan_open(self, name: str) -> None:
        self._mailboxes.setdefault(name, asyncio.Queue())

    async def deliver_local(self, name: str, payload: Any) -> None:
        await self._mailboxes.setdefault(name, asyncio.Queue()).put(payload)

    async def chan_put(
        self, name: str, payload: Any, *, endpoint: Optional[Endpoint] = None
    ) -> None:
        if endpoint is None or endpoint == self.get_endpoint():
            await self.deliver_local(name, payload)
            return
        if await channel_router.deliver(endpoint, name, payload):
            return
        if endpoint.scheme == "tcp":
            from ..transports import tcp

            await tcp.chan_put(endpoint, name, payload)
            return
        raise LookupError(f"no route to endpoint {endpoint}")

    async def chan_get(self, name: str) -> Any:
        queue = self._mailboxes.setdefault(name, asyncio.Queue())
        return await queue.get()

    # -- helpers ------------------------------------------------------------

    def _ensure_started(self) -> None:
        if not self._started:
            raise RuntimeError("backend not started; call start() first")


__all__ = ["ThreadActorBackend"]

"""TPU actor backend: one actor pinned to one chip (jax device).

This is the TPU-native replacement for the reference's CUDA ``GPUActorBackend``
(ref: ``byzpy/engine/actor/backends/gpu.py:23-204``). Instead of cupy streams
and UCX device-to-device copies:

* ``construct`` instantiates the actor with ``jax.default_device`` pinned to
  its chip, so every array the actor creates lives in that chip's HBM;
* ``call`` runs methods on the actor's dedicated thread under the same device
  context — jitted functions compile for and execute on that chip;
* channel payloads are passed **by reference** in-process: a ``jax.Array``
  enqueued to a peer on the same host is zero-copy; actual cross-chip data
  movement belongs to collectives (``byzpy_tpu.parallel``), never mailboxes.
"""

from __future__ import annotations

import asyncio
import inspect
import itertools
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import jax

from ..channels import Endpoint
from ..router import channel_router

_counter = itertools.count()


class TpuActorBackend:
    """Device-pinned backend: one actor per TPU chip; construct/call run with inputs committed to that actor's device and channel payloads pass by reference."""
    scheme = "tpu"

    def __init__(
        self, *, device_index: int = 0, actor_id: str | None = None
    ) -> None:
        devices = jax.devices()
        if not 0 <= device_index < len(devices):
            raise ValueError(
                f"device_index {device_index} out of range; {len(devices)} devices visible"
            )
        self.device = devices[device_index]
        self.device_index = device_index
        self.actor_id = actor_id or f"tpu{device_index}-{next(_counter)}-{uuid.uuid4().hex[:6]}"
        self._executor: ThreadPoolExecutor | None = None
        self._obj: Any = None
        self._mailboxes: Dict[str, asyncio.Queue] = {}
        self._started = False

    async def start(self) -> None:
        if self._started:
            return
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"tpu-actor-{self.actor_id}"
        )
        channel_router.register(self.get_endpoint(), self)
        self._started = True

    async def construct(self, target: Any, /, *args: Any, **kwargs: Any) -> None:
        self._ensure_started()

        def build():
            with jax.default_device(self.device):
                return target(*args, **kwargs)

        loop = asyncio.get_running_loop()
        self._obj = await loop.run_in_executor(self._executor, build)

    async def call(self, method: str, /, *args: Any, **kwargs: Any) -> Any:
        self._ensure_started()
        if self._obj is None:
            raise RuntimeError("actor not constructed")
        fn = getattr(self._obj, method)

        def run():
            with jax.default_device(self.device):
                if inspect.iscoroutinefunction(fn):
                    # complete the coroutine on the actor thread so the device
                    # pin covers async methods too
                    return asyncio.run(fn(*args, **kwargs))
                return fn(*args, **kwargs)

        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(self._executor, run)
        if inspect.isawaitable(result):
            result = await result
        return result

    async def close(self) -> None:
        if not self._started:
            return
        channel_router.unregister(self.get_endpoint())
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._obj = None
        self._started = False

    def get_endpoint(self) -> Endpoint:
        return Endpoint(self.scheme, f"tpu:{self.device_index}", self.actor_id)

    async def chan_open(self, name: str) -> None:
        self._mailboxes.setdefault(name, asyncio.Queue())

    async def deliver_local(self, name: str, payload: Any) -> None:
        await self._mailboxes.setdefault(name, asyncio.Queue()).put(payload)

    async def chan_put(
        self, name: str, payload: Any, *, endpoint: Optional[Endpoint] = None
    ) -> None:
        if endpoint is None or endpoint == self.get_endpoint():
            await self.deliver_local(name, payload)
            return
        if await channel_router.deliver(endpoint, name, payload):
            return
        if endpoint.scheme == "tcp":
            from ..transports import tcp

            await tcp.chan_put(endpoint, name, payload)
            return
        raise LookupError(f"no route to endpoint {endpoint}")

    async def chan_get(self, name: str) -> Any:
        return await self._mailboxes.setdefault(name, asyncio.Queue()).get()

    def _ensure_started(self) -> None:
        if not self._started:
            raise RuntimeError("backend not started; call start() first")


__all__ = ["TpuActorBackend"]

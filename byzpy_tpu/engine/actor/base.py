"""Actor protocol and proxy.

Control-plane parity with the reference actor layer (ref:
``byzpy/engine/actor/base.py:8-60``): an ``ActorBackend`` hosts one actor
(thread, process, TPU-device, or remote), ``ActorRef`` turns attribute
access into async RPC. The TPU-native difference is in what travels over
these calls: bulk tensors stay device-resident ``jax.Array``s (in-process
backends pass references, never copies); only control messages and
host-bound payloads cross process/network boundaries.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

from .channels import ChannelRef, Endpoint


@runtime_checkable
class ActorBackend(Protocol):
    """Uniform async lifecycle + RPC + named-mailbox-channel interface."""

    scheme: str

    async def start(self) -> None: ...

    async def construct(self, target: Any, /, *args: Any, **kwargs: Any) -> None: ...

    async def call(self, method: str, /, *args: Any, **kwargs: Any) -> Any: ...

    async def close(self) -> None: ...

    def get_endpoint(self) -> Endpoint: ...

    async def chan_open(self, name: str) -> None: ...

    async def chan_put(self, name: str, payload: Any, *, endpoint: Endpoint | None = None) -> None: ...

    async def chan_get(self, name: str) -> Any: ...


class ActorRef:
    """Proxy whose attribute access becomes an async RPC on the backend.

    >>> ref = ActorRef(backend)
    >>> await ref.train_step(batch)     # -> backend.call("train_step", batch)

    Also an async context manager: entering starts the backend, exiting
    closes it.
    """

    __slots__ = ("_backend",)

    def __init__(self, backend: ActorBackend) -> None:
        object.__setattr__(self, "_backend", backend)

    @property
    def backend(self) -> ActorBackend:
        return self._backend

    @property
    def endpoint(self) -> Endpoint:
        return self._backend.get_endpoint()

    def channel(self, name: str) -> ChannelRef:
        return ChannelRef(self._backend, name)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        backend = self._backend

        async def _rpc(*args: Any, **kwargs: Any) -> Any:
            return await backend.call(name, *args, **kwargs)

        _rpc.__name__ = name
        return _rpc

    async def __aenter__(self) -> "ActorRef":
        await self._backend.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self._backend.close()


async def spawn_actor(backend: ActorBackend, target: Any, /, *args: Any, **kwargs: Any) -> ActorRef:
    """Start a backend and construct ``target(*args, **kwargs)`` in it."""
    await backend.start()
    await backend.construct(target, *args, **kwargs)
    return ActorRef(backend)


__all__ = ["ActorBackend", "ActorRef", "spawn_actor"]

"""Named mailbox channels and endpoints (ref: ``byzpy/engine/actor/channels.py``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from .base import ActorBackend


@dataclass(frozen=True)
class Endpoint:
    """Addressable location of an actor: transport scheme + address + id.

    Examples: ``Endpoint("thread", "local", "a1")``,
    ``Endpoint("tpu", "tpu:0", "worker-3")``,
    ``Endpoint("tcp", "10.0.0.2:7777", "node-b")``.
    """

    scheme: str
    address: str
    actor_id: str


class ChannelRef:
    """A named channel bound to one actor's mailbox.

    ``send(payload, to=endpoint)`` delivers into the *target* actor's mailbox
    of the same name (local or remote); ``recv()`` pops from this actor's own
    mailbox.
    """

    __slots__ = ("_backend", "name")

    def __init__(self, backend: "ActorBackend", name: str) -> None:
        self._backend = backend
        self.name = name

    async def send(self, payload: Any, *, to: Endpoint | None = None) -> None:
        await self._backend.chan_put(self.name, payload, endpoint=to)

    async def recv(self) -> Any:
        return await self._backend.chan_get(self.name)


async def open_channel(backend: "ActorBackend", name: str) -> ChannelRef:
    """Open (or attach to) the named channel on ``backend`` and wrap it as a :class:`ChannelRef`."""
    await backend.chan_open(name)
    return ChannelRef(backend, name)


__all__ = ["Endpoint", "ChannelRef", "open_channel"]

"""Backend spec resolution (ref: ``byzpy/engine/actor/factory.py:14-67``).

Specs:

* ``"thread"`` — dedicated-thread actor in this process (default);
* ``"process"`` — spawned child process actor;
* ``"tpu"`` / ``"tpu:N"`` — actor pinned to local chip N (the TPU-native
  replacement for the reference's ``"gpu"`` scheme);
* ``"tcp://host:port"`` — actor hosted on a remote ``RemoteActorServer``.
"""

from __future__ import annotations

from typing import Any

from .backends.process import ProcessActorBackend
from .backends.remote import RemoteActorBackend
from .backends.thread import ThreadActorBackend
from .backends.tpu import TpuActorBackend


def resolve_backend(spec: str = "thread", **kwargs: Any):
    """Build an actor backend from a spec string: ``thread``, ``process``, ``tpu[:N]``, or ``tcp://host:port``."""
    if not isinstance(spec, str) or not spec:
        raise ValueError(f"invalid backend spec {spec!r}")
    if spec == "thread":
        return ThreadActorBackend(**kwargs)
    if spec == "process":
        return ProcessActorBackend(**kwargs)
    if spec == "tpu":
        return TpuActorBackend(**kwargs)
    if spec.startswith("tpu:"):
        return TpuActorBackend(device_index=int(spec.split(":", 1)[1]), **kwargs)
    if spec.startswith("tcp://"):
        addr = spec[len("tcp://") :]
        host, _, port = addr.rpartition(":")
        if not host or not port:
            raise ValueError(f"tcp spec must be tcp://host:port (got {spec!r})")
        return RemoteActorBackend(host, int(port), **kwargs)
    raise ValueError(f"unknown actor backend spec {spec!r}")


__all__ = ["resolve_backend"]

"""Cross-process payload wrapping via the native shm store.

Behavior parity: ``byzpy/engine/actor/ipc.py:20-56`` — large host arrays
in a payload pytree are swapped for shm handles before pickling, and
swapped back (as zero-copy views) on the receiving side. Device arrays are
first brought to host (this wire is host-side only; chips exchange tensors
via collectives).

Arrays smaller than ``min_bytes`` travel inline — the pickle round-trip is
cheaper than two mmap syscalls for small payloads.

``wrap_payload(..., precision="int8"|"bf16")`` composes with the wire
tier's compressed tensor frames (:mod:`.wire`): large float arrays are
quantized FIRST, so what lands in shm (and what a downstream pickle
ships) is the int8/uint16 codes + per-block scales — the
:class:`~byzpy_tpu.engine.actor.wire.QuantizedWireArray` dataclass
envelope recurses through the shm swap like any other dataclass.
``unwrap_payload`` reverses both layers. Default stays lossless.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from . import wire as _wire
from ..storage import native_store

_TAG = "__BYZPY_SHARED_TENSOR__"
DEFAULT_MIN_BYTES = 64 * 1024


def _is_dataclass_instance(x: Any) -> bool:
    import dataclasses

    return dataclasses.is_dataclass(x) and not isinstance(x, type)


def _rebuild_tuple(x: tuple, values: list) -> tuple:
    # preserve namedtuples (and tuple subclasses with a sequence ctor)
    if hasattr(x, "_fields"):
        return type(x)(*values)
    if type(x) is not tuple:
        try:
            return type(x)(values)
        except TypeError:
            pass
    return tuple(values)


def wrap_payload(
    obj: Any,
    *,
    min_bytes: int = DEFAULT_MIN_BYTES,
    precision: Optional[str] = None,
) -> Tuple[Any, List[native_store.SharedTensorHandle]]:
    """Recursively replace large arrays with shm handles. Returns the
    wrapped payload and the handles registered (caller owns cleanup; on
    error, everything registered so far is unlinked before the raise).

    ``precision`` (``"int8"``/``"bf16"``) quantizes large float arrays
    into :class:`~byzpy_tpu.engine.actor.wire.QuantizedWireArray` frames
    before the shm swap — 4x (2x) fewer shm/pickle bytes, lossy;
    ``unwrap_payload`` dequantizes. Device (jax/duck) arrays are brought
    to host first so they compress too. ``None`` (default) is lossless;
    an unrecognized mode raises (an explicit argument must not silently
    ship full-size payloads)."""
    if precision is not None:
        if precision not in ("int8", "bf16"):
            raise ValueError(
                f"precision must be None, 'int8', or 'bf16' (got {precision!r})"
            )
        obj = _wire.compress_payload(_wire.host_view(obj), precision)
    handles: List[native_store.SharedTensorHandle] = []

    def wrap(x: Any) -> Any:
        if isinstance(x, np.ndarray) and x.nbytes >= min_bytes and not x.dtype.hasobject:
            handle = native_store.register_tensor(x)
            handles.append(handle)
            return (_TAG, handle)
        if hasattr(x, "__array__") and not isinstance(x, np.ndarray):
            # jax.Array / torch-style duck arrays: host copy first
            arr = np.asarray(x)
            if arr.nbytes >= min_bytes and not arr.dtype.hasobject:
                handle = native_store.register_tensor(arr)
                handles.append(handle)
                return (_TAG, handle)
            return x
        if _is_dataclass_instance(x):
            import dataclasses

            return dataclasses.replace(
                x,
                **{
                    f.name: wrap(getattr(x, f.name))
                    for f in dataclasses.fields(x)
                },
            )
        if isinstance(x, dict):
            return {k: wrap(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return _rebuild_tuple(x, [wrap(v) for v in x])
        if isinstance(x, list):
            return [wrap(v) for v in x]
        return x

    try:
        return wrap(obj), handles
    except BaseException:
        cleanup_handles(handles)
        raise


def unwrap_payload(obj: Any, *, copy: bool = False, close: bool = False) -> Any:
    """Swap shm handles back for arrays. With ``copy=False`` the arrays are
    zero-copy views into the segment — valid only while the segment lives;
    pass ``copy=True`` when the result must outlive the sender's cleanup.
    ``close=True`` (requires ``copy``) unmaps each segment right after
    copying — the receiving-process pattern, so per-call mappings don't
    accumulate. Quantized frames produced by ``wrap_payload(...,
    precision=...)`` are dequantized back to (lossy) float arrays."""
    if close and not copy:
        raise ValueError("close=True requires copy=True (views need the mapping)")

    def unwrap(x: Any) -> Any:
        if (
            isinstance(x, tuple)
            and len(x) == 2
            # isinstance check first: comparing an ndarray to _TAG would
            # produce an ambiguous-truth-value array
            and isinstance(x[0], str)
            and x[0] == _TAG
            and isinstance(x[1], native_store.SharedTensorHandle)
        ):
            view = native_store.open_tensor(x[1])
            if copy:
                out = view.copy()
                if close:
                    del view  # the mapping can't close under a live view
                    native_store.close_tensor(x[1])
                return out
            return view
        if _is_dataclass_instance(x):
            import dataclasses

            return dataclasses.replace(
                x,
                **{
                    f.name: unwrap(getattr(x, f.name))
                    for f in dataclasses.fields(x)
                },
            )
        if isinstance(x, dict):
            return {k: unwrap(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return _rebuild_tuple(x, [unwrap(v) for v in x])
        if isinstance(x, list):
            return [unwrap(v) for v in x]
        return x

    return _wire.decompress_payload(unwrap(obj))


def cleanup_handles(handles: List[native_store.SharedTensorHandle]) -> None:
    """Unlink the shm segments behind ``handles`` (receiver-side teardown)."""
    for handle in handles:
        try:
            native_store.cleanup_tensor(handle)
        except OSError:
            pass


__all__ = ["wrap_payload", "unwrap_payload", "cleanup_handles", "DEFAULT_MIN_BYTES"]

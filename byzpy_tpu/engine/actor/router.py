"""Process-local channel router.

Registry mapping ``(scheme, actor_id) -> backend`` so a backend can deliver
a channel payload to a peer actor of a *different* scheme without importing
its module (avoids import cycles; ref: ``byzpy/engine/actor/router.py``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Dict, Optional, Tuple

if TYPE_CHECKING:
    from .base import ActorBackend
    from .channels import Endpoint


class ChannelRouter:
    """Fan-in router: forwards items from many source channels into per-destination queues by a key function."""
    def __init__(self) -> None:
        self._backends: Dict[Tuple[str, str], "ActorBackend"] = {}

    def register(self, endpoint: "Endpoint", backend: "ActorBackend") -> None:
        self._backends[(endpoint.scheme, endpoint.actor_id)] = backend

    def unregister(self, endpoint: "Endpoint") -> None:
        self._backends.pop((endpoint.scheme, endpoint.actor_id), None)

    def lookup(self, endpoint: "Endpoint") -> Optional["ActorBackend"]:
        return self._backends.get((endpoint.scheme, endpoint.actor_id))

    async def deliver(self, endpoint: "Endpoint", name: str, payload: Any) -> bool:
        """Deliver into a locally-registered peer's mailbox; False if unknown."""
        backend = self.lookup(endpoint)
        if backend is None:
            return False
        await backend.deliver_local(name, payload)  # type: ignore[attr-defined]
        return True

    def clear(self) -> None:
        self._backends.clear()


channel_router = ChannelRouter()

__all__ = ["ChannelRouter", "channel_router"]

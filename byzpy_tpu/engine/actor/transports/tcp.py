"""Connection-per-request TCP channel transport.

Used when a local backend must deliver a channel payload to an actor hosted
on a remote ``RemoteActorServer`` and no persistent client connection exists
(ref: ``byzpy/engine/actor/transports/tcp.py:27-67``).

Resilience: the DIAL is retried under a
:class:`~byzpy_tpu.resilience.retry.RetryPolicy` (decorrelated-jitter
backoff — a restarting remote server is ridden out instead of failing the
round), but a request that was already SENT is never replayed: channel
puts are at-least-once effects with no idempotency key, so an ambiguous
send/receive failure surfaces to the caller (the elastic PS layer treats
it as a node failure, which is the correct semantic). Tune via
``BYZPY_TPU_TCP_RETRIES`` / ``BYZPY_TPU_TCP_RETRY_DEADLINE_S`` (dial
attempts and total seconds; ``BYZPY_TPU_TCP_RETRIES=1`` restores the
pre-retry single-try dial).
"""

from __future__ import annotations

import asyncio
import os
from typing import Any

from ....resilience.retry import RetryPolicy, connect_with_retry
from .. import wire
from ..channels import Endpoint


def _split(address: str) -> tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


def dial_policy() -> RetryPolicy:
    """Dial retry policy from the environment (read per call — cheap,
    and tests can flip it without reimporting)."""
    try:
        attempts = int(os.environ.get("BYZPY_TPU_TCP_RETRIES", "4"))
    except ValueError:
        attempts = 4
    try:
        deadline = float(
            os.environ.get("BYZPY_TPU_TCP_RETRY_DEADLINE_S", "10")
        )
    except ValueError:
        deadline = 10.0
    return RetryPolicy(
        max_attempts=max(1, attempts),
        base_s=0.05,
        cap_s=1.0,
        deadline_s=max(0.1, deadline),
    )


async def _roundtrip(address: str, msg: dict) -> Any:
    host, port = _split(address)
    reader, writer = await connect_with_retry(
        host, port, policy=dial_policy(), component="actor_tcp"
    )
    try:
        await wire.send_obj(writer, {**msg, "req_id": 0})
        reply = await wire.recv_obj(reader)
        if not reply["ok"]:
            name, text, tb = reply["result"]
            raise RuntimeError(f"{name} on remote server: {text}\n{tb}")
        return reply["result"]
    finally:
        writer.close()


async def chan_put(endpoint: Endpoint, name: str, payload: Any) -> None:
    """Send ``payload`` into the remote channel ``name`` over this TCP endpoint."""
    await _roundtrip(
        endpoint.address,
        {
            "op": "chan_put",
            "actor_id": endpoint.actor_id,
            "name": name,
            "payload": wire.host_view(payload),
        },
    )


async def chan_get(endpoint: Endpoint, name: str) -> Any:
    """Receive the next item from the remote channel ``name`` (blocks server-side)."""
    return await _roundtrip(
        endpoint.address, {"op": "chan_get", "actor_id": endpoint.actor_id, "name": name}
    )


__all__ = ["chan_get", "chan_put", "dial_policy"]

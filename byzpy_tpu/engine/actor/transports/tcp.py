"""Connection-per-request TCP channel transport.

Used when a local backend must deliver a channel payload to an actor hosted
on a remote ``RemoteActorServer`` and no persistent client connection exists
(ref: ``byzpy/engine/actor/transports/tcp.py:27-67``).
"""

from __future__ import annotations

import asyncio
from typing import Any

from .. import wire
from ..channels import Endpoint


def _split(address: str) -> tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


async def _roundtrip(address: str, msg: dict) -> Any:
    host, port = _split(address)
    reader, writer = await asyncio.open_connection(host, port)
    try:
        await wire.send_obj(writer, {**msg, "req_id": 0})
        reply = await wire.recv_obj(reader)
        if not reply["ok"]:
            name, text, tb = reply["result"]
            raise RuntimeError(f"{name} on remote server: {text}\n{tb}")
        return reply["result"]
    finally:
        writer.close()


async def chan_put(endpoint: Endpoint, name: str, payload: Any) -> None:
    """Send ``payload`` into the remote channel ``name`` over this TCP endpoint."""
    await _roundtrip(
        endpoint.address,
        {
            "op": "chan_put",
            "actor_id": endpoint.actor_id,
            "name": name,
            "payload": wire.host_view(payload),
        },
    )


async def chan_get(endpoint: Endpoint, name: str) -> Any:
    """Receive the next item from the remote channel ``name`` (blocks server-side)."""
    return await _roundtrip(
        endpoint.address, {"op": "chan_get", "actor_id": endpoint.actor_id, "name": name}
    )


__all__ = ["chan_put", "chan_get"]

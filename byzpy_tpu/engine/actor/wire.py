"""Length-prefixed binary frames over asyncio streams.

Control-plane wire format (ref: ``byzpy/engine/actor/_wire.py:8-18``): a
4-byte big-endian length followed by a cloudpickle body. Device arrays are
converted to numpy on serialization — bulk tensor movement between chips
never goes through this wire; it rides XLA collectives (see
``byzpy_tpu.parallel``).

.. warning:: **Trusted networks only.** Frames are cloudpickle: anyone who
   can reach the socket can execute arbitrary code in the receiving
   process (same property as the reference's pickle wire). Bind servers to
   loopback or a private, firewalled fabric. Setting ``BYZPY_TPU_WIRE_KEY``
   (a shared secret, same value on every host) prepends an HMAC-SHA256 tag
   to every frame and rejects unsigned/forged ones — the analogue of the
   reference's signed pickle frames (ref:
   ``examples/ps/remote_tcp/ps_node.py:1-56``). Signing authenticates the
   sender; it does not encrypt.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import struct
import warnings
from typing import Any, Optional, Sequence, Tuple

import asyncio

import cloudpickle
import numpy as np

from ...observability import metrics as _obs_metrics
from ...observability import runtime as _obs_runtime
from ...observability import tracing as _obs_tracing

_HEADER = struct.Struct(">I")
MAX_FRAME = 1 << 31
_SIG_LEN = hashlib.sha256().digest_size

#: Env opt-in for compressed tensor frames: "off" (default, lossless
#: cloudpickle), "bf16", "int8" (blockwise symmetric, per-block f32
#: scales carried in the frame), or the sub-int8 tier "fp8"/"fp8_e5m2"
#: (blockwise-scaled float8 — one byte per value, format-relative
#: accuracy) and "s4" (two 4-bit codes packed per byte, ~7.9x fewer
#: payload bytes). Lossy — see docs/performance.md §quantized comms and
#: §sub-int8 fabric.
_WIRE_PRECISION_ENV = "BYZPY_TPU_WIRE_PRECISION"
_WIRE_BLOCK_ENV = "BYZPY_TPU_WIRE_BLOCK"
#: Every lossy wire mode, and the blockwise subset carrying per-block
#: scale headers (pre-decode forensics — the residual-shaping detector
#: — applies to these).
WIRE_MODES = ("bf16", "int8", "fp8", "fp8_e5m2", "s4")
BLOCKWISE_WIRE_MODES = ("int8", "fp8", "fp8_e5m2", "s4")
#: Per-mode code maximum in the scaled domain: an honest blockwise
#: encoder maps each block's absmax to EXACTLY this code magnitude, so
#: the pre-decode inflation ratio qmax/max|code| of every nonzero block
#: is 1.0 — the invariant the residual-shaping detector leans on.
_WIRE_QMAX = {"int8": 127.0, "s4": 7.0, "fp8": 448.0, "fp8_e5m2": 57344.0}
#: Arrays below this element count always travel lossless (the scale
#: header would rival the payload).
WIRE_QUANT_MIN_SIZE = 1024
_WIRE_DEFAULT_BLOCK = 256


def _ml_f8_dtype(mode: str):
    import ml_dtypes

    return (
        ml_dtypes.float8_e4m3fn if mode == "fp8" else ml_dtypes.float8_e5m2
    )


def _wire_key() -> bytes | None:
    key = os.environ.get("BYZPY_TPU_WIRE_KEY")
    return key.encode() if key else None


#: Keyed HMAC bases, one per wire key ever seen (in practice: one).
#: ``hmac.new(key, ...)`` pays two SHA-256 block compressions just to
#: absorb the padded key; cloning a cached keyed base skips that setup,
#: which matters once ingress verifies whole batches of frames per
#: event-loop wakeup. Keys rotate via env restarts, so the cache is
#: bounded by construction; cleared defensively if it ever grows.
_HMAC_BASE: dict = {}


def _hmac_base(key: bytes) -> "hmac.HMAC":
    base = _HMAC_BASE.get(key)
    if base is None:
        if len(_HMAC_BASE) > 8:
            _HMAC_BASE.clear()
        base = _HMAC_BASE[key] = hmac.new(key, b"", hashlib.sha256)
    return base


def _sign(body, key: bytes) -> bytes:
    mac = _hmac_base(key).copy()
    mac.update(body)
    return mac.digest()

_LOOPBACK = {"127.0.0.1", "::1", "localhost"}  # "" binds ALL interfaces — warn


def warn_untrusted_bind(host: str, component: str) -> None:
    """One-line safety rail: surface a RuntimeWarning when a cloudpickle
    control-plane server binds beyond loopback, where deserializing frames
    means remote code execution for anyone who can reach the port."""
    if host not in _LOOPBACK:
        warnings.warn(
            f"{component} binding to {host!r}: the control-plane wire "
            "deserializes cloudpickle frames, which allows arbitrary code "
            "execution by anyone able to reach this socket. Use only on "
            "trusted/firewalled networks (or keep to loopback).",
            RuntimeWarning,
            stacklevel=3,
        )


def wire_precision() -> str:
    """Resolved ``BYZPY_TPU_WIRE_PRECISION`` policy: ``"off"``
    (default), ``"bf16"``, ``"int8"``, ``"fp8"``, ``"fp8_e5m2"``, or
    ``"s4"``. Unknown values degrade to ``"off"`` — the wire must never
    fail on a typo'd env var."""
    mode = os.environ.get(_WIRE_PRECISION_ENV, "off").lower()
    return mode if mode in WIRE_MODES else "off"


def _wire_block() -> int:
    try:
        block = int(os.environ.get(_WIRE_BLOCK_ENV, _WIRE_DEFAULT_BLOCK))
    except ValueError:
        return _WIRE_DEFAULT_BLOCK
    return block if block > 0 else _WIRE_DEFAULT_BLOCK


@dataclasses.dataclass(frozen=True)
class QuantizedWireArray:
    """One compressed tensor inside a wire frame: ``codes`` (int8 for
    ``int8`` mode, uint16 bf16 bit patterns for ``bf16``, uint8 float8
    bit patterns for ``fp8``/``fp8_e5m2``, block-padded packed nibbles
    for ``s4``), the per-block f32 ``scales`` header (``None`` for
    bf16), and enough metadata to reconstruct shape/dtype. Pickles
    alongside the rest of the payload, so the frame HMAC covers codes
    AND scales — a tampered scale block fails :func:`decode` before any
    dequantization runs."""

    mode: str
    codes: np.ndarray
    scales: Optional[np.ndarray]
    block: int
    shape: Tuple[int, ...]
    dtype: str


def _np_quantize(
    arr: np.ndarray, block: int
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Blockwise symmetric int8 over the flattened array (numpy mirror of
    ``parallel.quantization.quantize_blockwise``; parity is pinned by
    ``tests/test_quantized_wire.py``). The third return is False when any
    block's absmax is non-finite (an inf OR NaN input poisoned it — note
    a NaN absmax yields a *finite* scale of 1.0, so the caller must test
    this flag, not the scales) — the wire then ships the array lossless,
    preserving attack vectors verbatim."""
    flat = np.ascontiguousarray(arr, dtype=np.float32).ravel()
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    xb = flat.reshape(nb, block)
    absmax = np.max(np.abs(xb), axis=1)  # propagates inf AND NaN
    finite = bool(np.isfinite(absmax).all())
    scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    with np.errstate(invalid="ignore", over="ignore"):
        codes = np.clip(np.rint(xb / scales[:, None]), -127, 127).astype(np.int8)
    return codes.ravel()[:n], scales, finite


def _np_dequantize(
    codes: np.ndarray, scales: np.ndarray, block: int, shape, dtype
) -> np.ndarray:
    n = codes.size
    nb = scales.size
    pad = nb * block - n
    flat = codes.astype(np.float32)
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    out = (flat.reshape(nb, block) * scales[:, None]).ravel()[:n]
    return out.astype(dtype).reshape(shape)


def _np_blockwise_encode(
    arr: np.ndarray, block: int, mode: str
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Mode-generic blockwise encode over the flattened array (numpy
    mirror of ``parallel.quantization.encode_blockwise``; parity pinned
    by ``tests/test_quantized_wire.py``). Returns ``(codes, scales,
    finite)`` — ``finite=False`` means a block's absmax is non-finite
    and the frame must travel lossless (same contract as the int8
    codec). Codes are int8 for ``int8``, uint8 float8 bit patterns for
    ``fp8``/``fp8_e5m2``, and block-padded packed nibbles (uint8, two
    codes per byte) for ``s4``."""
    if mode == "int8":
        return _np_quantize(arr, block)
    flat = np.ascontiguousarray(arr, dtype=np.float32).ravel()
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    xb = flat.reshape(nb, block)
    absmax = np.max(np.abs(xb), axis=1)  # propagates inf AND NaN
    finite = bool(np.isfinite(absmax).all())
    qmax = _WIRE_QMAX[mode]
    scales = np.where(absmax > 0, absmax / qmax, 1.0).astype(np.float32)
    with np.errstate(invalid="ignore", over="ignore"):
        y = xb / scales[:, None]
        if mode == "s4":
            q = np.clip(np.rint(y), -7, 7).astype(np.int8)
            nib = (q + np.int8(8)).astype(np.uint8).reshape(-1)
            codes = nib[0::2] | (nib[1::2] << 4)  # padded: nb*block//2 bytes
        else:
            y = np.clip(y, -qmax, qmax)
            codes = y.astype(_ml_f8_dtype(mode)).view(np.uint8).ravel()[:n]
    return codes, scales, finite


def _code_values_f32(codes: np.ndarray, mode: str) -> np.ndarray:
    """Decoded f32 code values BEFORE the per-block scale multiply —
    the expensive half of a blockwise decode (the fp8 bit-pattern cast
    alone is ~57 % of that mode's decode; ``ROUND19_NOTES.md``), shared
    by dequantization and the pre-decode inflation forensics so
    :func:`decode_with_stats` converts each frame's codes exactly once.
    Per-frame analogue of :func:`_rows_code_values`."""
    if mode == "int8":
        return codes.astype(np.float32)
    if mode == "s4":
        nib = np.empty(codes.size * 2, np.uint8)
        nib[0::2] = codes & np.uint8(0xF)
        nib[1::2] = codes >> 4
        return nib.astype(np.float32) - 8.0
    return codes.view(_ml_f8_dtype(mode)).astype(np.float32)


def _dequant_values(
    values: np.ndarray, scales: np.ndarray, block: int, shape, dtype
) -> np.ndarray:
    """The cheap tail of a blockwise decode: pad the f32 code values to
    whole blocks, apply the per-block scales, trim and reshape."""
    nb = scales.size
    n = 1
    for s in shape:
        n *= s
    pad = nb * block - values.size
    if pad > 0:
        values = np.concatenate([values, np.zeros(pad, np.float32)])
    out = (values.reshape(nb, block) * scales[:, None]).ravel()[:n]
    return out.astype(dtype).reshape(shape)


def _np_blockwise_decode(
    codes: np.ndarray, scales: np.ndarray, block: int, shape, dtype, mode: str
) -> np.ndarray:
    """Inverse of :func:`_np_blockwise_encode` (lossy)."""
    return _dequant_values(
        _code_values_f32(codes, mode), scales, block, shape, dtype
    )


def _np_to_bf16(arr: np.ndarray) -> Tuple[np.ndarray, bool]:
    """f32 -> bf16 bit patterns (uint16) with round-to-nearest-even.
    The second return is False when the frame must travel lossless:
    non-finite INPUTS (checked on the source exponent bits — a negative
    NaN's rounding add wraps uint32 and would otherwise encode as +0.0,
    silently sanitizing an adversarial payload) or finite values that
    overflow to inf in bf16 (checked on the output exponent bits)."""
    u = np.ascontiguousarray(arr, dtype=np.float32).view(np.uint32)
    exp_mask = np.uint32(0x7F800000)
    nonfinite_in = bool(np.any((u & exp_mask) == exp_mask))
    rounded = u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1))
    codes = (rounded >> np.uint32(16)).astype(np.uint16)
    overflow_out = bool(
        np.any((codes & np.uint16(0x7F80)) == np.uint16(0x7F80))
    )
    return codes, not (nonfinite_in or overflow_out)


def _np_from_bf16(codes: np.ndarray, shape, dtype) -> np.ndarray:
    u = codes.astype(np.uint32) << 16
    return u.view(np.float32).astype(dtype).reshape(shape)


def _quantizable(arr: np.ndarray, min_size: int) -> bool:
    # lossless fallback for everything the blockwise codec can't carry
    # faithfully enough: non-float dtypes, object payloads, small arrays.
    # Non-finite payloads also fall back, but that is detected from the
    # codec's own per-block reductions (a NaN/inf absmax poisons its
    # scale, an overflowing bf16 cast sets exponent bits) instead of an
    # extra full-array isfinite pass on the hot encode path.
    return (
        isinstance(arr, np.ndarray)
        and arr.dtype.kind == "f"
        and arr.dtype.itemsize >= 4
        and arr.size >= min_size
        and not arr.dtype.hasobject
    )


def _map_payload_leaves(leaf_fn, obj: Any) -> Any:
    """Copy-on-write recursion over the wire payload containers
    (dataclasses, dicts, tuples/namedtuples, lists): ``leaf_fn`` maps a
    leaf to its replacement or returns it unchanged (identity). Untouched
    subtrees are returned AS-IS — a frame with nothing to transform pays
    one traversal and zero rebuilds, and payload dataclasses that cannot
    be ``dataclasses.replace``'d (e.g. ``init=False`` fields) only fail
    if a transformed leaf actually lives inside them. Both codec
    directions (:func:`compress_payload` / :func:`decompress_payload`)
    walk through here so the container semantics cannot drift; the shm
    tier's wrap/unwrap and the jax-aware :func:`host_view` keep their own
    walks (error-cleanup and registered-pytree semantics respectively)."""

    def walk(x: Any) -> Any:
        out = leaf_fn(x)
        if out is not x:
            return out
        if isinstance(x, QuantizedWireArray):
            # atomic: never descend into a frame (its scales header is a
            # float array a compress pass must not re-quantize)
            return x
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            new = {f.name: walk(getattr(x, f.name))
                   for f in dataclasses.fields(x)}
            if all(new[f.name] is getattr(x, f.name)
                   for f in dataclasses.fields(x)):
                return x
            return dataclasses.replace(x, **new)
        if isinstance(x, dict):
            new = {k: walk(v) for k, v in x.items()}
            if all(new[k] is v for k, v in x.items()):
                return x
            return new
        if isinstance(x, (tuple, list)):
            vals = [walk(v) for v in x]
            if all(a is b for a, b in zip(vals, x, strict=True)):
                return x
            if isinstance(x, list):
                return vals
            if hasattr(x, "_fields"):
                return type(x)(*vals)
            return tuple(vals)
        return x

    return walk(obj)


def compress_payload(
    obj: Any, mode: str, *, block: Optional[int] = None,
    min_size: int = WIRE_QUANT_MIN_SIZE,
) -> Any:
    """Swap large finite float arrays in a payload pytree for
    :class:`QuantizedWireArray` frames (``mode`` one of
    :data:`WIRE_MODES`; anything else returns ``obj`` unchanged).
    Non-float, object-dtype, small, and non-finite arrays pass through
    lossless (attack vectors arrive verbatim, the reference's
    semantics). Untouched subtrees are returned as-is."""
    if mode not in WIRE_MODES:
        return obj
    if type(obj) is dict and not any(
        isinstance(v, (np.ndarray, QuantizedWireArray, dict, list, tuple))
        or dataclasses.is_dataclass(v)
        for v in obj.values()
    ):
        return obj  # scalar-only frame (acks, control) — nothing to swap
    block = block or _wire_block()

    def leaf(x: Any) -> Any:
        if isinstance(x, QuantizedWireArray):
            return x
        if isinstance(x, np.ndarray) and _quantizable(x, min_size):
            if mode == "bf16":
                codes, ok = _np_to_bf16(x)
                if not ok:
                    return x
                return QuantizedWireArray(
                    "bf16", codes, None, block, x.shape, str(x.dtype)
                )
            codes, scales, finite = _np_blockwise_encode(x, block, mode)
            # cheap post-hoc non-finite detection from the codec's own
            # per-block absmax reduction (no extra full-array pass)
            if not finite:
                return x
            return QuantizedWireArray(
                mode, codes, scales, block, x.shape, str(x.dtype)
            )
        return x

    return _map_payload_leaves(leaf, obj)


def decompress_payload(obj: Any) -> Any:
    """Inverse of :func:`compress_payload`: every
    :class:`QuantizedWireArray` becomes a (lossy) numpy array again;
    everything else — including the whole payload when no compressed
    frame is present — passes through untouched."""

    def leaf(x: Any) -> Any:
        if isinstance(x, QuantizedWireArray):
            if x.mode == "bf16":
                return _np_from_bf16(x.codes, x.shape, x.dtype)
            return _np_blockwise_decode(
                x.codes, x.scales, x.block, x.shape, x.dtype, x.mode
            )
        return x

    return _map_payload_leaves(leaf, obj)


def frame_inflation(
    qwa: QuantizedWireArray, *, _values: Optional[np.ndarray] = None
) -> Optional[float]:
    """PRE-decode per-block inflation ratio of one blockwise frame:
    ``max over nonzero blocks of qmax / max|code|``.

    An honest blockwise encoder maps each block's absmax to exactly the
    code maximum (127 / 7 / the fp8 format max), so every nonzero
    block's ratio is 1.0 (stochastic rounding can dip one code step).
    A residual-shaping client inflates its per-block SCALES relative to
    the content it encodes — buying itself a coarser grid whose
    "quantization error" it steers via error feedback — which is
    invisible post-decode but shows pre-decode as max|code| well under
    qmax. Computed from the codes alone (no dequantization, no scale
    trust); ``None`` for non-blockwise frames (bf16 carries no scale
    header to shape). All-zero payloads report 1.0. ``_values`` lets
    the fused stats+decode walk hand in the frame's already-converted
    :func:`_code_values_f32` instead of converting again."""
    if qwa.mode not in BLOCKWISE_WIRE_MODES or qwa.scales is None:
        return None
    qmax = _WIRE_QMAX[qwa.mode]
    block = qwa.block
    vals = (
        _values
        if _values is not None
        else _code_values_f32(qwa.codes, qwa.mode)
    )
    if qwa.mode == "s4":
        # nibble 0 decodes to -8, outside the honest encoder's [-7, 7]
        # codomain; clamp so a hostile -8 cannot fake EXTRA magnitude
        mags = np.minimum(np.abs(vals), qmax)
    elif qwa.mode == "int8":
        mags = np.abs(vals)
    else:
        mags = np.minimum(np.abs(np.where(np.isfinite(vals), vals, qmax)), qmax)
    n = mags.size
    nb = qwa.scales.size
    pad = nb * block - n
    if pad > 0:
        mags = np.concatenate([mags, np.zeros(pad, np.float32)])
    blockmax = mags[: nb * block].reshape(nb, block).max(axis=1)
    nonzero = blockmax > 0
    if not nonzero.any():
        return 1.0
    return float(qmax / blockmax[nonzero].min())


def payload_block_stats(obj: Any) -> Optional[dict]:
    """Pre-decode wire forensics over a still-compressed payload: the
    worst :func:`frame_inflation` across every blockwise
    :class:`QuantizedWireArray` in the pytree (``None`` when the
    payload carries none — lossless and bf16 frames have no per-block
    scale header to shape). The serving ingress computes this BEFORE
    :func:`decompress_payload` runs and threads it into the forensics
    plane as the submission's ``wire_inflation`` feature."""
    worst: Optional[float] = None
    frames = 0

    def leaf(x: Any) -> Any:
        nonlocal worst, frames
        if isinstance(x, QuantizedWireArray):
            infl = frame_inflation(x)
            if infl is not None:
                frames += 1
                worst = infl if worst is None else max(worst, infl)
        return x

    _map_payload_leaves(leaf, obj)
    if worst is None:
        return None
    return {"max_inflation": worst, "frames": frames}


def _decompress_with_stats(raw: Any) -> Tuple[Any, Optional[dict]]:
    """:func:`payload_block_stats` + :func:`decompress_payload` in ONE
    pytree walk, with each blockwise frame's codes→f32 conversion done
    once and shared between the inflation forensics and the
    dequantization (the per-frame door previously ran it twice under
    ``decode_with_stats`` — ~57 % of an fp8 decode; byte parity with
    the two-pass shape is pinned by ``tests/test_quantized_wire.py``)."""
    worst: Optional[float] = None
    frames = 0

    def leaf(x: Any) -> Any:
        nonlocal worst, frames
        if not isinstance(x, QuantizedWireArray):
            return x
        if x.mode == "bf16":
            return _np_from_bf16(x.codes, x.shape, x.dtype)
        values = _code_values_f32(x.codes, x.mode)
        infl = frame_inflation(x, _values=values)
        if infl is not None:
            frames += 1
            worst = infl if worst is None else max(worst, infl)
        return _dequant_values(values, x.scales, x.block, x.shape, x.dtype)

    obj = _map_payload_leaves(leaf, raw)
    stats = (
        None if worst is None else {"max_inflation": worst, "frames": frames}
    )
    return obj, stats


_MAG_LUT: dict = {}


def _byte_mag_lut(mode: str) -> Tuple[np.ndarray, np.ndarray]:
    """Rank-compressed forensics table: ``(rank, mag_of_rank)`` where
    ``rank`` is a ``(256,)`` uint8 mapping each code byte to the RANK of
    the clamped magnitude the per-frame :func:`frame_inflation` assigns
    it (for s4, the max of the byte's two nibble magnitudes — valid per
    block whenever blocks hold whole bytes), and ``mag_of_rank`` maps
    ranks back to the exact f32 magnitudes. Block maxima run over uint8
    ranks (SIMD-max over a quarter the bytes of an f32 expansion); the
    rank order is magnitude-isomorphic, so mapping the winning rank
    back yields bit-for-bit the per-frame path's block maximum. Rank 0
    is always magnitude 0.0 (bytes 0x00 / 0x88 decode to zero), so
    zero-padding ragged tails in rank space is exact too."""
    ent = _MAG_LUT.get(mode)
    if ent is None:
        b = np.arange(256, dtype=np.uint8)
        qmax = _WIRE_QMAX[mode]
        if mode == "s4":
            lo = np.abs((b & np.uint8(0xF)).astype(np.float32) - 8.0)
            hi = np.abs((b >> 4).astype(np.float32) - 8.0)
            lut = np.minimum(np.maximum(lo, hi), qmax).astype(np.float32)
        elif mode == "int8":
            lut = np.abs(b.view(np.int8).astype(np.float32))
        else:
            vals = b.view(_ml_f8_dtype(mode)).astype(np.float32)
            lut = np.minimum(
                np.abs(np.where(np.isfinite(vals), vals, qmax)), qmax
            ).astype(np.float32)
        mag_of_rank = np.unique(lut)  # sorted ascending, <= 256 entries
        rank = np.searchsorted(mag_of_rank, lut).astype(np.uint8)
        _MAG_LUT[mode] = ent = (rank, mag_of_rank.astype(np.float32))
    return ent


def _rows_code_values(codes: np.ndarray, mode: str) -> np.ndarray:
    """Row-batched code -> f32 value expansion shared by the batched
    dequantizer and the batched forensics pass: ``codes`` is ``(R,
    ncodes)`` stacked wire codes, the result ``(R, nvals)`` f32 code
    values BEFORE scaling (s4 nibbles unpacked and recentred, fp8 bit
    patterns reinterpreted — non-finite patterns propagate, exactly as
    the per-frame codec's)."""
    if mode == "s4":
        nib = np.empty((codes.shape[0], codes.shape[1] * 2), np.uint8)
        nib[:, 0::2] = codes & np.uint8(0xF)
        nib[:, 1::2] = codes >> 4
        return nib.astype(np.float32) - 8.0
    if mode == "int8":
        return codes.astype(np.float32)
    return codes.view(_ml_f8_dtype(mode)).astype(np.float32)


def decode_rows_np(
    codes: np.ndarray, scales: np.ndarray, *, mode: str, block: int,
    d: int, dtype=np.float32,
) -> np.ndarray:
    """Row-batched numpy mirror of :func:`_np_blockwise_decode` over
    ``R`` stacked ``(d,)`` frames: ``codes`` is ``(R, ncodes)`` (``d``
    codes per row for int8/fp8, ``nb*block//2`` packed nibble bytes for
    s4), ``scales`` ``(R, nb)`` f32. Every arithmetic step is the
    per-frame codec's, applied elementwise across the row axis, so each
    output row is bit-identical to decoding its frame alone — the
    invariant the batched-vs-per-frame parity tests pin. This is also
    the host reference the in-jit ``parallel.quantization
    .dequantize_rows`` mirrors."""
    codes = np.asarray(codes)
    scales = np.asarray(scales)
    rows, nb = scales.shape
    flat = _rows_code_values(codes, mode)
    pad = nb * block - flat.shape[1]
    if pad > 0:
        flat = np.concatenate(
            [flat, np.zeros((rows, pad), np.float32)], axis=1
        )
    out = (flat.reshape(rows, nb, block) * scales[:, :, None]).reshape(
        rows, -1
    )[:, :d]
    return np.ascontiguousarray(out).astype(dtype, copy=False)


def rows_code_absmax(
    codes: np.ndarray, *, mode: str, block: int, nb: int
) -> np.ndarray:
    """Row-batched per-block max |code value| — ``(R, nb)`` f32 from
    ``(R, ncodes)`` stacked codes, UNclamped (a hostile s4 ``-8``
    nibble reports 8, a non-finite fp8 pattern propagates), so
    ``isfinite(absmax * scales)`` decides finiteness of the dequantized
    rows without materializing them: IEEE multiply is magnitude-
    monotone, hence the max-magnitude code's product is finite iff
    every code's product in that block is."""
    mags = np.abs(_rows_code_values(np.asarray(codes), mode))
    rows = mags.shape[0]
    pad = nb * block - mags.shape[1]
    if pad > 0:
        mags = np.concatenate(
            [mags, np.zeros((rows, pad), np.float32)], axis=1
        )
    return mags.reshape(rows, nb, block).max(axis=2)


def ef_precompensate(
    arr: np.ndarray,
    residual: Optional[np.ndarray],
    mode: Optional[str] = None,
    *,
    block: Optional[int] = None,
    min_size: int = WIRE_QUANT_MIN_SIZE,
) -> Tuple[np.ndarray, np.ndarray]:
    """Client-side error feedback for the lossy wire fabric: fold the
    previous frame's quantization residual into ``arr`` and return
    ``(compensated, new_residual)``.

    ``compensated`` is what the caller hands to :func:`encode` — the
    wire's own (deterministic) blockwise encode then reproduces exactly
    the encoding this function measured, so ``new_residual`` is
    precisely the error the receiver's decode will see this round and
    the transmitted stream telescopes across frames (the numpy mirror
    of ``parallel.quantization.ef_encode``). Frames the wire would ship
    LOSSLESS (small/non-finite payloads, ``mode`` off/bf16-less-stateful)
    deliver the compensation exactly, so the residual returns to zero.
    ``mode=None`` resolves ``BYZPY_TPU_WIRE_PRECISION``."""
    mode = wire_precision() if mode is None else mode
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    comp = arr if residual is None else arr + residual.astype(np.float32)
    zero = np.zeros_like(comp)
    if mode not in BLOCKWISE_WIRE_MODES:
        # bf16/off: no blockwise codec on the wire. bf16's cast error
        # is below the EF signal; carrying state for it buys nothing.
        return comp, zero
    if not _quantizable(comp, min_size):
        return comp, zero  # travels lossless: fully delivered
    block = block or _wire_block()
    codes, scales, finite = _np_blockwise_encode(comp, block, mode)
    if not finite:
        return comp, zero  # lossless fallback path delivers exactly
    dec = _np_blockwise_decode(
        codes, scales, block, comp.shape, np.float32, mode
    )
    return comp, comp - dec


#: (frames, bytes) counter pairs per direction, resolved ONCE on the
#: first telemetry-enabled frame — encode/decode are per-frame hot
#: paths and must not pay a registry get-or-create lookup per call.
_FRAME_COUNTER_CACHE: dict = {}


def _frame_counters(direction: str, nbytes: int) -> None:
    """Publish one wire frame into the process registry (telemetry-
    enabled path only; callers hold the flag check). Per-direction
    frame/byte counters are the measured side of the ingress/wire laws
    EQuARX-style comms tuning needs in flight."""
    pair = _FRAME_COUNTER_CACHE.get(direction)
    if pair is None:
        reg = _obs_metrics.registry()
        labels = {"direction": direction}
        pair = _FRAME_COUNTER_CACHE[direction] = (
            reg.counter(
                "byzpy_wire_frames_total",
                help="actor-wire frames encoded (tx) / decoded (rx)",
                labels=labels,
            ),
            reg.counter(
                "byzpy_wire_bytes_total",
                help="actor-wire frame bytes incl. length prefix and HMAC tag",
                labels=labels,
            ),
        )
    frames, nbytes_counter = pair
    frames.inc()
    nbytes_counter.inc(nbytes)


#: Reserved frame key carrying the sender's ``(trace_id, span_id)``
#: trace context across the process boundary (dict frames only; popped
#: and restored on decode — consumers never see it).
TRACE_CTX_KEY = "_trace_ctx"


def encode(obj: Any, *, precision: Optional[str] = None) -> bytes:
    """Pickle ``obj`` into a length-prefixed (optionally HMAC-signed) frame
    body. With ``BYZPY_TPU_WIRE_PRECISION`` set (``bf16``/``int8``), large
    finite float arrays ship as compressed frames (per-block scales in the
    header); the HMAC — unchanged — signs the whole body, compressed
    payload and scale headers included. ``precision`` overrides the env
    policy for THIS frame (``"off"`` forces lossless — frames whose bits
    are load-bearing, e.g. the sharded tier's partial folds, must not
    ride the lossy submit fabric).

    Trace propagation: with telemetry enabled and a span open in the
    caller (``tracing.wire_context()``), dict frames are stamped with a
    ``_trace_ctx`` key so the receiver's spans link as children of the
    sender's (client submit → shard admission, shard close → root
    merge). The stamp rides INSIDE the signed body — no frame-format
    change — and never touches the payload the consumer decodes
    (:func:`decode` pops it). Telemetry disabled: one flag check, the
    frame bytes are byte-identical to the pre-propagation wire."""
    mode = wire_precision() if precision is None else (
        precision if precision in WIRE_MODES else "off"
    )
    if _obs_runtime.STATE.enabled and type(obj) is dict:
        ctx = _obs_tracing.wire_context()
        if ctx is not None and TRACE_CTX_KEY not in obj:
            obj = {**obj, TRACE_CTX_KEY: (ctx[0], ctx[1])}
    body = cloudpickle.dumps(compress_payload(obj, mode))
    key = _wire_key()
    if key is not None:
        body = _sign(body, key) + body
    if _obs_runtime.STATE.enabled:
        _frame_counters("tx", _HEADER.size + len(body))
    return _HEADER.pack(len(body)) + body


def decode(body: bytes) -> Any:
    """Inverse of :func:`encode` (verifies the HMAC when signing is
    configured, then expands any compressed tensor frames — so a tampered
    code or scale byte fails verification before dequantization).

    A ``_trace_ctx`` stamp on a dict frame is popped (consumers see the
    payload they were sent) and — when telemetry is enabled — restored
    as the decoding task's current trace context, so the very next span
    this task opens (the admission span, the root's merge span) becomes
    the remote sender's child. Frames without a stamp leave the local
    context untouched (a decode inside an open local span must not
    orphan it)."""
    return _decode_impl(body, want_stats=False)[0]


def decode_with_stats(body: bytes) -> Tuple[Any, Optional[dict]]:
    """:func:`decode` plus the PRE-decode :func:`payload_block_stats` of
    the frame's compressed payload, captured between unpickle and
    dequantization (after HMAC verification — stats from a forged frame
    would be attacker-free ink). The serving ingress uses this so the
    forensics plane sees each submission's wire-side block-inflation
    ratio; stats are ``None`` for frames carrying no blockwise
    payload."""
    return _decode_impl(body, want_stats=True)


def _decode_impl(body: bytes, *, want_stats: bool) -> Tuple[Any, Optional[dict]]:
    if _obs_runtime.STATE.enabled:
        _frame_counters("rx", _HEADER.size + len(body))
    key = _wire_key()
    if key is not None:
        if len(body) < _SIG_LEN:
            raise ValueError("frame too short to carry an HMAC signature")
        sig, body = body[:_SIG_LEN], body[_SIG_LEN:]
        if not hmac.compare_digest(sig, _sign(body, key)):
            raise ValueError(
                "frame HMAC verification failed: wrong BYZPY_TPU_WIRE_KEY "
                "or tampered/unsigned frame"
            )
    raw = cloudpickle.loads(body)
    if want_stats:
        obj, stats = _decompress_with_stats(raw)
    else:
        obj, stats = decompress_payload(raw), None
    if type(obj) is dict and TRACE_CTX_KEY in obj:
        ctx = obj.pop(TRACE_CTX_KEY)
        if _obs_runtime.STATE.enabled:
            _obs_tracing.adopt_context(ctx)
    return obj, stats


@dataclasses.dataclass
class DecodedFrame:
    """One :func:`decode_batch` result slot: the decoded payload and its
    pre-decode forensics stats (:func:`payload_block_stats` semantics),
    or the exception the frame's verify/decode raised. A batch result
    is truncated at the first error slot — exactly the frames the
    per-frame path would have served before dropping the peer."""

    obj: Any = None
    stats: Optional[dict] = None
    error: Optional[BaseException] = None
    #: the frame's popped ``_trace_ctx`` stamp (None when unstamped) —
    #: a batched ingress adopts it per frame so each admission span
    #: stays the SENDING client's child, exactly like the per-frame
    #: door's decode-time adoption
    trace_ctx: Optional[Any] = None


def _qwa_group_key(q: QuantizedWireArray):
    codes = q.codes
    scales = q.scales
    return (
        q.mode, q.block, getattr(codes, "size", -1),
        str(getattr(codes, "dtype", "?")),
        -1 if scales is None else getattr(scales, "size", -1),
    )


def _qwa_honest_layout(q: QuantizedWireArray) -> bool:
    """True when the frame has exactly the layout the honest encoder
    emits — the precondition for the row-batched decode. Anything else
    (hand-crafted pickles with inconsistent code/scale sizes) takes the
    per-frame codec verbatim, so hostile frames fail — or pass — with
    exactly the per-frame path's semantics."""
    try:
        n = 1
        for s in q.shape:
            n *= int(s)
        codes = q.codes
        if not isinstance(codes, np.ndarray):
            return False
        if q.mode == "bf16":
            return q.scales is None and codes.size == n
        scales = q.scales
        if not isinstance(scales, np.ndarray) or q.block <= 0:
            return False
        nb = -(-n // q.block)
        if scales.size != nb:
            return False
        if q.mode == "s4":
            return codes.size * 2 == nb * q.block
        return codes.size == n
    except Exception:
        return False


def _batch_inflations(group: list) -> list:
    """:func:`frame_inflation` over a group of same-layout blockwise
    frames in one vectorized pass (bit-identical per frame: every step
    is the per-frame codec's, applied along a stacked row axis; the
    final division is done per frame with the same scalar types)."""
    q0 = group[0]
    qmax = _WIRE_QMAX[q0.mode]
    block = q0.block
    nb = group[0].scales.size
    codes = np.stack([q.codes.ravel() for q in group])
    canonical = codes.dtype == (
        np.dtype(np.int8) if q0.mode == "int8" else np.dtype(np.uint8)
    )
    if canonical and (q0.mode != "s4" or block % 2 == 0):
        # rank-LUT gather per code byte, block maxima in uint8 rank
        # space, winners mapped back to exact f32 magnitudes (for s4
        # the byte-level maxima equal nibble-level ones because blocks
        # hold whole bytes)
        rank_lut, mag_of_rank = _byte_mag_lut(q0.mode)
        ranks = np.take(rank_lut, codes.view(np.uint8))
        per_block = block // 2 if q0.mode == "s4" else block
        pad = nb * per_block - ranks.shape[1]
        if pad > 0:
            ranks = np.concatenate(
                [ranks, np.zeros((len(group), pad), np.uint8)], axis=1
            )
        blockmax = mag_of_rank[
            ranks[:, : nb * per_block]
            .reshape(len(group), nb, per_block)
            .max(axis=2)
        ]
    else:
        vals = _rows_code_values(codes, q0.mode)
        if q0.mode == "s4":
            mags = np.minimum(np.abs(vals), qmax)
        elif q0.mode == "int8":
            mags = np.abs(vals)
        else:
            mags = np.minimum(
                np.abs(np.where(np.isfinite(vals), vals, qmax)), qmax
            )
        pad = nb * block - mags.shape[1]
        if pad > 0:
            mags = np.concatenate(
                [mags, np.zeros((len(group), pad), np.float32)], axis=1
            )
        blockmax = mags[:, : nb * block].reshape(
            len(group), nb, block
        ).max(axis=2)
    masked = np.where(blockmax > 0, blockmax, np.float32(np.inf))
    mins = masked.min(axis=1)
    return [
        1.0 if not np.isfinite(mn) else float(qmax / mn) for mn in mins
    ]


def _batch_decode_group(group: list) -> list:
    """Vectorized :func:`_np_blockwise_decode` / :func:`_np_from_bf16`
    over a group of same-layout frames (honest layout pre-checked)."""
    q0 = group[0]
    codes = np.stack([q.codes.ravel() for q in group])
    if q0.mode == "bf16":
        flat = (codes.astype(np.uint32) << 16).view(np.float32)
        return [
            flat[i].astype(q.dtype).reshape(q.shape)
            for i, q in enumerate(group)
        ]
    scales = np.stack([q.scales.ravel() for q in group])
    rows = decode_rows_np(
        codes, scales, mode=q0.mode, block=q0.block,
        d=flat_size(q0.shape),
    )
    return [
        rows[i].astype(q.dtype, copy=False).reshape(q.shape)
        for i, q in enumerate(group)
    ]


def flat_size(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def decode_batch(
    bodies: Sequence, *, keep_quantized: bool = False
) -> list:
    """Batched :func:`decode_with_stats` over many frame bodies (bytes
    or memoryviews, length prefixes stripped): HMAC verification rides
    a cloned keyed base (the per-frame key schedule is amortized away),
    and the numpy codec mirrors + pre-decode block-inflation forensics
    run vectorized across every same-layout compressed tensor in the
    batch — one pass over the stacked codes instead of one per frame.
    Results are bit-identical to calling :func:`decode_with_stats` per
    frame (pinned by the ingress parity tests); frames whose payloads
    don't group (lossless, object, odd layouts) fall back to the
    per-frame codec inside the same call.

    ``keep_quantized=True`` leaves a dict frame's top-level
    ``"gradient"`` :class:`QuantizedWireArray` COMPRESSED when it is a
    well-formed 1-D blockwise float frame — the serving ingress admits
    codes+scales and dequantization happens inside the ragged fold's
    jitted program (device-side), not here. Stats are still computed
    for kept frames; ill-formed frames are decoded (and fail) exactly
    as the per-frame path would.

    Returns a list of :class:`DecodedFrame`, truncated after the first
    error slot: the per-frame TCP door drops a peer at the first bad
    frame, so later frames in the batch must not be served either.
    Trace context: the first stamped frame's ``_trace_ctx`` is adopted
    for the batch (the batch's admission span links to that sender);
    every frame's stamp is popped regardless."""
    telemetry = _obs_runtime.STATE.enabled
    key = _wire_key()
    base = _hmac_base(key) if key is not None else None
    out: list = []
    raws: list = []
    for body in bodies:
        if telemetry:
            _frame_counters("rx", _HEADER.size + len(body))
        try:
            payload = body
            if key is not None:
                if len(body) < _SIG_LEN:
                    raise ValueError(
                        "frame too short to carry an HMAC signature"
                    )
                sig, payload = body[:_SIG_LEN], body[_SIG_LEN:]
                mac = base.copy()
                mac.update(payload)
                if not hmac.compare_digest(bytes(sig), mac.digest()):
                    raise ValueError(
                        "frame HMAC verification failed: wrong "
                        "BYZPY_TPU_WIRE_KEY or tampered/unsigned frame"
                    )
            raw = cloudpickle.loads(payload)
        except Exception as exc:  # noqa: BLE001 — per-frame error slot
            out.append(DecodedFrame(error=exc))
            return out
        raws.append(raw)
        out.append(DecodedFrame(obj=raw))

    # one walk per frame collects its compressed tensors; same-layout
    # tensors across the whole batch then share one vectorized pass
    # (flat dicts — every honest submit frame — skip the generic
    # recursive walk for one shallow scan over the values)
    per_frame: list = []
    groups: dict = {}
    for raw in raws:
        qwas: list = []
        flat = type(raw) is dict
        if flat:
            for v in raw.values():
                if isinstance(v, QuantizedWireArray):
                    qwas.append(v)
                elif isinstance(v, (dict, list, tuple)) or (
                    dataclasses.is_dataclass(v) and not isinstance(v, type)
                ):
                    flat = False
                    qwas.clear()
                    break
        if not flat:

            def leaf(x, _q=qwas):
                if isinstance(x, QuantizedWireArray):
                    _q.append(x)
                return x

            _map_payload_leaves(leaf, raw)
        per_frame.append(qwas)
        for q in qwas:
            if _qwa_honest_layout(q):
                groups.setdefault(_qwa_group_key(q), []).append(q)

    infl: dict = {}
    dec: dict = {}
    keep: set = set()
    if keep_quantized:
        for raw in raws:
            if type(raw) is not dict:
                continue
            g = raw.get("gradient")
            if (
                isinstance(g, QuantizedWireArray)
                and g.mode in BLOCKWISE_WIRE_MODES
                and len(g.shape) == 1
                and _qwa_honest_layout(g)
            ):
                try:
                    if np.dtype(g.dtype).kind == "f":
                        keep.add(id(g))
                except TypeError:
                    pass
    for gkey, group in groups.items():
        mode = gkey[0]
        if mode in BLOCKWISE_WIRE_MODES:
            try:
                for q, r in zip(group, _batch_inflations(group)):
                    infl[id(q)] = r
            except Exception:  # noqa: BLE001 — per-frame fallback below
                pass
        to_decode = [q for q in group if id(q) not in keep]
        if not to_decode:
            continue
        try:
            for q, row in zip(to_decode, _batch_decode_group(to_decode)):
                dec[id(q)] = row
        except Exception:  # noqa: BLE001 — per-frame fallback below
            pass

    adopted = False
    for i, raw in enumerate(raws):
        qwas = per_frame[i]
        worst = None
        frames = 0
        try:
            for q in qwas:
                r = infl.get(id(q))
                if r is None:
                    r = frame_inflation(q)
                if r is not None:
                    frames += 1
                    worst = r if worst is None else max(worst, r)
            stats = (
                None if worst is None
                else {"max_inflation": worst, "frames": frames}
            )

            def leaf(x):
                if isinstance(x, QuantizedWireArray):
                    if id(x) in keep:
                        return x
                    row = dec.get(id(x))
                    if row is not None:
                        return row
                    if x.mode == "bf16":
                        return _np_from_bf16(x.codes, x.shape, x.dtype)
                    return _np_blockwise_decode(
                        x.codes, x.scales, x.block, x.shape, x.dtype,
                        x.mode,
                    )
                return x

            needs_map = any(id(q) not in keep for q in qwas)
            obj = _map_payload_leaves(leaf, raw) if needs_map else raw
        except Exception as exc:  # noqa: BLE001 — per-frame error slot
            del out[i:]
            out.append(DecodedFrame(error=exc))
            return out
        ctx = None
        if type(obj) is dict and TRACE_CTX_KEY in obj:
            ctx = obj.pop(TRACE_CTX_KEY)
            if telemetry and not adopted:
                adopted = True
                _obs_tracing.adopt_context(ctx)
        out[i] = DecodedFrame(obj=obj, stats=stats, trace_ctx=ctx)
    return out


def host_view(obj: Any) -> Any:
    """Convert any jax.Arrays in a payload pytree to numpy before it crosses
    a process or network boundary (device buffers don't pickle portably and
    must never transit the control plane anyway). Dataclass envelopes
    (e.g. ``Message``) are rebuilt field-by-field — they are not registered
    pytrees, so a plain ``tree_map`` would pass their device arrays through
    untouched."""
    import dataclasses

    import jax
    import numpy as np

    def _is_dc(x: Any) -> bool:
        return dataclasses.is_dataclass(x) and not isinstance(x, type)

    if _is_dc(obj):
        return dataclasses.replace(
            obj,
            **{
                f.name: host_view(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        )

    def conv(leaf: Any) -> Any:
        if _is_dc(leaf):
            return host_view(leaf)
        if isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        return leaf

    return jax.tree_util.tree_map(conv, obj, is_leaf=_is_dc)


async def send_obj(writer: asyncio.StreamWriter, obj: Any) -> None:
    """Write one encoded frame to the stream and drain."""
    writer.write(encode(obj))
    await writer.drain()


async def recv_obj(reader: asyncio.StreamReader) -> Any:
    """Read exactly one frame from the stream and decode it."""
    header = await reader.readexactly(_HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return decode(body)


__all__ = [
    "BLOCKWISE_WIRE_MODES",
    "TRACE_CTX_KEY",
    "WIRE_MODES",
    "send_obj",
    "recv_obj",
    "encode",
    "decode",
    "decode_batch",
    "decode_rows_np",
    "decode_with_stats",
    "DecodedFrame",
    "rows_code_absmax",
    "ef_precompensate",
    "frame_inflation",
    "host_view",
    "payload_block_stats",
    "warn_untrusted_bind",
    "wire_precision",
    "compress_payload",
    "decompress_payload",
    "QuantizedWireArray",
    "WIRE_QUANT_MIN_SIZE",
]

"""Length-prefixed binary frames over asyncio streams.

Control-plane wire format (ref: ``byzpy/engine/actor/_wire.py:8-18``): a
4-byte big-endian length followed by a cloudpickle body. Device arrays are
converted to numpy on serialization — bulk tensor movement between chips
never goes through this wire; it rides XLA collectives (see
``byzpy_tpu.parallel``).

.. warning:: **Trusted networks only.** Frames are cloudpickle: anyone who
   can reach the socket can execute arbitrary code in the receiving
   process (same property as the reference's pickle wire). Bind servers to
   loopback or a private, firewalled fabric. Setting ``BYZPY_TPU_WIRE_KEY``
   (a shared secret, same value on every host) prepends an HMAC-SHA256 tag
   to every frame and rejects unsigned/forged ones — the analogue of the
   reference's signed pickle frames (ref:
   ``examples/ps/remote_tcp/ps_node.py:1-56``). Signing authenticates the
   sender; it does not encrypt.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
import warnings
from typing import Any

import asyncio

import cloudpickle

_HEADER = struct.Struct(">I")
MAX_FRAME = 1 << 31
_SIG_LEN = hashlib.sha256().digest_size


def _wire_key() -> bytes | None:
    key = os.environ.get("BYZPY_TPU_WIRE_KEY")
    return key.encode() if key else None


def _sign(body: bytes, key: bytes) -> bytes:
    return hmac.new(key, body, hashlib.sha256).digest()

_LOOPBACK = {"127.0.0.1", "::1", "localhost"}  # "" binds ALL interfaces — warn


def warn_untrusted_bind(host: str, component: str) -> None:
    """One-line safety rail: surface a RuntimeWarning when a cloudpickle
    control-plane server binds beyond loopback, where deserializing frames
    means remote code execution for anyone who can reach the port."""
    if host not in _LOOPBACK:
        warnings.warn(
            f"{component} binding to {host!r}: the control-plane wire "
            "deserializes cloudpickle frames, which allows arbitrary code "
            "execution by anyone able to reach this socket. Use only on "
            "trusted/firewalled networks (or keep to loopback).",
            RuntimeWarning,
            stacklevel=3,
        )


def encode(obj: Any) -> bytes:
    """Pickle ``obj`` into a length-prefixed (optionally HMAC-signed) frame body."""
    body = cloudpickle.dumps(obj)
    key = _wire_key()
    if key is not None:
        body = _sign(body, key) + body
    return _HEADER.pack(len(body)) + body


def decode(body: bytes) -> Any:
    """Inverse of :func:`encode` (verifies the HMAC when signing is configured)."""
    key = _wire_key()
    if key is not None:
        if len(body) < _SIG_LEN:
            raise ValueError("frame too short to carry an HMAC signature")
        sig, body = body[:_SIG_LEN], body[_SIG_LEN:]
        if not hmac.compare_digest(sig, _sign(body, key)):
            raise ValueError(
                "frame HMAC verification failed: wrong BYZPY_TPU_WIRE_KEY "
                "or tampered/unsigned frame"
            )
    return cloudpickle.loads(body)


def host_view(obj: Any) -> Any:
    """Convert any jax.Arrays in a payload pytree to numpy before it crosses
    a process or network boundary (device buffers don't pickle portably and
    must never transit the control plane anyway). Dataclass envelopes
    (e.g. ``Message``) are rebuilt field-by-field — they are not registered
    pytrees, so a plain ``tree_map`` would pass their device arrays through
    untouched."""
    import dataclasses

    import jax
    import numpy as np

    def _is_dc(x: Any) -> bool:
        return dataclasses.is_dataclass(x) and not isinstance(x, type)

    if _is_dc(obj):
        return dataclasses.replace(
            obj,
            **{
                f.name: host_view(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        )

    def conv(leaf: Any) -> Any:
        if _is_dc(leaf):
            return host_view(leaf)
        if isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        return leaf

    return jax.tree_util.tree_map(conv, obj, is_leaf=_is_dc)


async def send_obj(writer: asyncio.StreamWriter, obj: Any) -> None:
    """Write one encoded frame to the stream and drain."""
    writer.write(encode(obj))
    await writer.drain()


async def recv_obj(reader: asyncio.StreamReader) -> Any:
    """Read exactly one frame from the stream and decode it."""
    header = await reader.readexactly(_HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return decode(body)


__all__ = ["send_obj", "recv_obj", "encode", "decode", "host_view", "warn_untrusted_bind"]

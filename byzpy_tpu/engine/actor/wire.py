"""Length-prefixed binary frames over asyncio streams.

Control-plane wire format (ref: ``byzpy/engine/actor/_wire.py:8-18``): a
4-byte big-endian length followed by a cloudpickle body. Device arrays are
converted to numpy on serialization — bulk tensor movement between chips
never goes through this wire; it rides XLA collectives (see
``byzpy_tpu.parallel``).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import cloudpickle

_HEADER = struct.Struct(">I")
MAX_FRAME = 1 << 31


def encode(obj: Any) -> bytes:
    body = cloudpickle.dumps(obj)
    return _HEADER.pack(len(body)) + body


def decode(body: bytes) -> Any:
    return cloudpickle.loads(body)


def host_view(obj: Any) -> Any:
    """Convert any jax.Arrays in a payload pytree to numpy before it crosses
    a process or network boundary (device buffers don't pickle portably and
    must never transit the control plane anyway). Dataclass envelopes
    (e.g. ``Message``) are rebuilt field-by-field — they are not registered
    pytrees, so a plain ``tree_map`` would pass their device arrays through
    untouched."""
    import dataclasses

    import jax
    import numpy as np

    def _is_dc(x: Any) -> bool:
        return dataclasses.is_dataclass(x) and not isinstance(x, type)

    if _is_dc(obj):
        return dataclasses.replace(
            obj,
            **{
                f.name: host_view(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        )

    def conv(leaf: Any) -> Any:
        if _is_dc(leaf):
            return host_view(leaf)
        if isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        return leaf

    return jax.tree_util.tree_map(conv, obj, is_leaf=_is_dc)


async def send_obj(writer: asyncio.StreamWriter, obj: Any) -> None:
    writer.write(encode(obj))
    await writer.drain()


async def recv_obj(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_HEADER.size)
    (length,) = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return decode(body)


__all__ = ["send_obj", "recv_obj", "encode", "decode", "host_view"]

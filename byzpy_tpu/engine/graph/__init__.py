from .chunking import select_adaptive_chunk_size
from .executor import OperatorExecutor, run_operator
from .graph import ComputationGraph, GraphInput, GraphNode, graph_input
from .lazy import GraphBuilder, LazyNode
from .operator import MessageTriggerOp, OpContext, Operator
from .ops import CallableOp, RemoteCallableOp, make_single_operator_graph
from .parallel_scheduler import ParallelScheduler
from .pool import ActorPool, ActorPoolChannel, ActorPoolConfig
from .scheduler import MessageAwareNodeScheduler, MessageSource, NodeScheduler
from .session import ExecutionFuture, ExecutionSession
from .subtask import SubTask

__all__ = [
    "select_adaptive_chunk_size",
    "OperatorExecutor",
    "run_operator",
    "ComputationGraph",
    "GraphInput",
    "GraphNode",
    "graph_input",
    "GraphBuilder",
    "LazyNode",
    "MessageTriggerOp",
    "OpContext",
    "Operator",
    "CallableOp",
    "RemoteCallableOp",
    "make_single_operator_graph",
    "ParallelScheduler",
    "ActorPool",
    "ActorPoolChannel",
    "ActorPoolConfig",
    "MessageAwareNodeScheduler",
    "MessageSource",
    "NodeScheduler",
    "ExecutionFuture",
    "ExecutionSession",
    "SubTask",
]

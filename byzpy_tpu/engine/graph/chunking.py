"""Adaptive chunk sizing for subtask fan-out.

Same contract as the reference heuristic
(ref: ``byzpy/aggregators/_chunking.py:41-72``): keep at least
``min_per_worker`` chunks per pool worker so the window pipeline stays full,
but never shrink the configured chunk below ``configured / max_shrink``.
Env overrides: ``BYZPY_TPU_CHUNK_MIN_PER_WORKER``,
``BYZPY_TPU_CHUNK_MAX_SHRINK``, ``BYZPY_TPU_CHUNK_TARGET_FACTOR``.

On TPU, chunking matters mainly for *host-side* subtasks (combinatorial
enumeration, data loading): device-side aggregation is one jitted program,
not many small chunks.
"""

from __future__ import annotations

import math
import os


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def select_adaptive_chunk_size(
    total: int,
    configured: int,
    *,
    pool_size: int = 0,
    min_per_worker: int | None = None,
    max_shrink: int | None = None,
    target_factor: int | None = None,
) -> int:
    """Pick a chunk size for splitting ``total`` items across a pool."""
    if total <= 0 or configured <= 0:
        return max(1, configured)
    if pool_size <= 1:
        return configured

    if min_per_worker is None:
        min_per_worker = _env_int("BYZPY_TPU_CHUNK_MIN_PER_WORKER", 4)
    if max_shrink is None:
        max_shrink = _env_int("BYZPY_TPU_CHUNK_MAX_SHRINK", 8)
    if target_factor is None:
        target_factor = _env_int("BYZPY_TPU_CHUNK_TARGET_FACTOR", 1)
    min_per_worker = max(1, min_per_worker)

    target_chunks = pool_size * min_per_worker * max(1, target_factor)
    ideal = max(1, math.ceil(total / target_chunks))
    floor = max(1, configured // max(1, max_shrink))
    return max(floor, min(configured, ideal))


__all__ = ["select_adaptive_chunk_size", "pool_size_from_context"]


def pool_size_from_context(context) -> int:
    """Worker count the scheduler injected into operator metadata (0 when
    running without a pool); single source of truth for every chunked
    operator's adaptive sizing."""
    metadata = getattr(context, "metadata", None) or {}
    return int(metadata.get("pool_size") or 0)

"""Operator executor — the package front door
(ref: ``byzpy/engine/graph/executor.py:71-291``; re-exported at top level as
``byzpy_tpu.run_operator`` like the reference's ``byzpy/__init__.py``).

``run_operator(op, inputs)`` wraps the operator in a one-node graph, runs it
on a scheduler (optionally over an ``ActorPool``), and returns the single
result. Input-key detection mirrors the reference: aggregators consume
``gradients``, pre-aggregators ``vectors``; attacks declare multiple needs
so they require an explicit mapping.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from .graph import ComputationGraph, GraphInput, GraphNode
from .operator import Operator
from .pool import ActorPool, ActorPoolConfig
from .scheduler import NodeScheduler


def _is_mapping(value: Any) -> bool:
    return isinstance(value, Mapping)


class OperatorExecutor:
    """Reusable executor: owns (or borrows) a pool, caches the graph."""

    def __init__(
        self,
        op: Operator,
        *,
        pool: Optional[ActorPool] = None,
        pool_config: Optional[ActorPoolConfig | Sequence[ActorPoolConfig]] = None,
        input_key: Optional[str] = None,
    ) -> None:
        self.op = op
        self._external_pool = pool
        self._pool_config = pool_config
        self._pool: Optional[ActorPool] = pool
        self._owns_pool = pool is None and pool_config is not None
        self.input_key = input_key or getattr(op, "input_key", None)
        self._graph_cache: dict[tuple[str, ...], ComputationGraph] = {}

    async def _ensure_pool(self) -> Optional[ActorPool]:
        if self._pool is None and self._pool_config is not None:
            self._pool = ActorPool(self._pool_config)
        if self._pool is not None:
            await self._pool.start()
        return self._pool

    def _build_graph(self, input_names: Sequence[str]) -> ComputationGraph:
        inputs = {name: GraphInput(name) for name in input_names}
        return ComputationGraph(
            [GraphNode(name=self.op.name or "op", op=self.op, inputs=inputs)]
        )

    async def run(self, inputs: Any) -> Any:
        """Run the operator. ``inputs`` may be the bare value for the
        operator's input key, or a full mapping of input names."""
        if not _is_mapping(inputs):
            if self.input_key is None:
                raise ValueError(
                    f"operator {self.op.name!r} has no input_key; pass a mapping of inputs"
                )
            inputs = {self.input_key: inputs}
        cache_key = tuple(sorted(inputs.keys()))
        graph = self._graph_cache.get(cache_key)
        if graph is None:
            graph = self._build_graph(list(inputs.keys()))
            self._graph_cache[cache_key] = graph
        pool = await self._ensure_pool()
        scheduler = NodeScheduler(graph, pool=pool)
        results = await scheduler.run(inputs)
        return results[graph.outputs[0]]

    async def close(self) -> None:
        if self._owns_pool and self._pool is not None:
            await self._pool.close()
            self._pool = None


async def run_operator(
    op: Operator,
    inputs: Any,
    *,
    pool: Optional[ActorPool] = None,
    pool_config: Optional[ActorPoolConfig | Sequence[ActorPoolConfig]] = None,
    input_key: Optional[str] = None,
) -> Any:
    """One-shot convenience around :class:`OperatorExecutor`
    (ref: ``executor.py:266-291``)."""
    executor = OperatorExecutor(
        op, pool=pool, pool_config=pool_config, input_key=input_key
    )
    try:
        return await executor.run(inputs)
    finally:
        await executor.close()


__all__ = ["OperatorExecutor", "run_operator"]

"""Computation DAG (ref: ``byzpy/engine/graph/graph.py:23-128``).

Nodes wrap operators; edges are declared per-node as an ``inputs`` mapping
from the operator's input key to either a ``GraphInput`` (application-supplied
value), another node's name (string), or a ``MessageSource`` (resolved by a
message-aware scheduler).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, Iterable, List, Mapping, Optional, Sequence, Set, Union

from .operator import Operator

if TYPE_CHECKING:
    from .scheduler import MessageSource


@dataclass(frozen=True)
class GraphInput:
    """Opaque reference to data supplied by the application layer."""

    name: str

    @classmethod
    def from_message(
        cls,
        message_type: str,
        field: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> "MessageSource":
        from .scheduler import MessageSource

        return MessageSource(message_type=message_type, field=field, timeout=timeout)


def graph_input(name: str) -> GraphInput:
    """Shorthand constructor for a named :class:`GraphInput` placeholder."""
    return GraphInput(name)


@dataclass(frozen=True)
class GraphNode:
    name: str
    op: Operator
    inputs: Mapping[str, Union[str, GraphInput, "MessageSource"]] = field(default_factory=dict)


class ComputationGraph:
    """A DAG of named operator nodes with deterministic topological order."""

    def __init__(
        self,
        nodes: Sequence[GraphNode],
        *,
        outputs: Optional[Sequence[str]] = None,
    ) -> None:
        if not nodes:
            raise ValueError("graph requires at least one node")
        names = [n.name for n in nodes]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate node names: {dupes}")
        self._nodes: Dict[str, GraphNode] = {n.name: n for n in nodes}
        self._order: List[str] = self._topo_sort(nodes)
        if outputs is None:
            outputs = [self._order[-1]]
        unknown = [o for o in outputs if o not in self._nodes]
        if unknown:
            raise ValueError(f"unknown output nodes: {unknown}")
        self.outputs: List[str] = list(outputs)

    # -- structure ----------------------------------------------------------

    @property
    def nodes(self) -> Mapping[str, GraphNode]:
        return self._nodes

    def node(self, name: str) -> GraphNode:
        return self._nodes[name]

    def nodes_in_order(self) -> Iterable[GraphNode]:
        return (self._nodes[name] for name in self._order)

    def dependencies(self, name: str) -> Set[str]:
        """Names of graph nodes this node consumes."""
        return {
            src
            for src in self._nodes[name].inputs.values()
            if isinstance(src, str) and src in self._nodes
        }

    def required_inputs(self) -> Set[str]:
        """Names of ``GraphInput``s the application must supply."""
        required: Set[str] = set()
        for node in self._nodes.values():
            for src in node.inputs.values():
                if isinstance(src, GraphInput):
                    required.add(src.name)
                elif isinstance(src, str) and src not in self._nodes:
                    raise ValueError(
                        f"node {node.name!r} references unknown node {src!r}"
                    )
        return required

    # -- topo ---------------------------------------------------------------

    def _topo_sort(self, nodes: Sequence[GraphNode]) -> List[str]:
        known = {n.name for n in nodes}
        indegree: Dict[str, int] = {n.name: 0 for n in nodes}
        consumers: Dict[str, List[str]] = {n.name: [] for n in nodes}
        for node in nodes:
            for src in node.inputs.values():
                if isinstance(src, str) and src in known:
                    indegree[node.name] += 1
                    consumers[src].append(node.name)
        # Kahn's algorithm; insertion order keeps it deterministic.
        ready = [name for name, deg in indegree.items() if deg == 0]
        order: List[str] = []
        while ready:
            name = ready.pop(0)
            order.append(name)
            for consumer in consumers[name]:
                indegree[consumer] -= 1
                if indegree[consumer] == 0:
                    ready.append(consumer)
        if len(order) != len(nodes):
            cyclic = sorted(name for name, deg in indegree.items() if deg > 0)
            raise ValueError(f"graph contains a cycle involving: {cyclic}")
        return order


__all__ = ["GraphInput", "graph_input", "GraphNode", "ComputationGraph"]

"""Fluent graph builder (ref: ``byzpy/engine/graph/lazy.py:24-226``).

>>> b = GraphBuilder()
>>> out = (b.input("gradients")
...         .apply(Clipping(threshold=1.0))
...         .apply(CoordinateWiseMedian()))
>>> graph = b.build(out)
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Mapping, Optional, Sequence, Union

from .graph import ComputationGraph, GraphInput, GraphNode
from .operator import Operator


class LazyNode:
    """Handle to a graph input or an applied operator's output."""

    def __init__(self, builder: "GraphBuilder", source: Union[str, GraphInput]) -> None:
        self._builder = builder
        self._source = source

    @property
    def source(self) -> Union[str, GraphInput]:
        return self._source

    def apply(
        self,
        op: Operator,
        *,
        input_key: Optional[str] = None,
        extra_inputs: Optional[Mapping[str, Any]] = None,
        name: Optional[str] = None,
    ) -> "LazyNode":
        return self._builder._apply(
            self, op, input_key=input_key, extra_inputs=extra_inputs, name=name
        )


class GraphBuilder:
    """Lazy graph construction: record operator calls as :class:`LazyNode` handles and materialize a ComputationGraph on build()."""
    def __init__(self) -> None:
        self._nodes: List[GraphNode] = []
        self._name_counter = itertools.count()
        self._names: set[str] = set()

    def input(self, name: str) -> LazyNode:
        return LazyNode(self, GraphInput(name))

    def _unique_name(self, base: str) -> str:
        name = base
        while name in self._names:
            name = f"{base}_{next(self._name_counter)}"
        self._names.add(name)
        return name

    def _apply(
        self,
        upstream: LazyNode,
        op: Operator,
        *,
        input_key: Optional[str],
        extra_inputs: Optional[Mapping[str, Any]],
        name: Optional[str],
    ) -> LazyNode:
        key = input_key or getattr(op, "input_key", None)
        if key is None:
            raise ValueError(
                f"operator {op.name!r} has no input_key; pass input_key= explicitly"
            )
        inputs: Dict[str, Any] = {key: upstream.source}
        for extra_key, src in (extra_inputs or {}).items():
            if isinstance(src, LazyNode):
                src = src.source
            inputs[extra_key] = src
        node_name = self._unique_name(name or op.name or f"node_{next(self._name_counter)}")
        self._nodes.append(GraphNode(name=node_name, op=op, inputs=inputs))
        return LazyNode(self, node_name)

    def build(
        self, outputs: Union[LazyNode, Sequence[LazyNode], None] = None
    ) -> ComputationGraph:
        if not self._nodes:
            raise ValueError("no operators applied; nothing to build")
        out_names: Optional[List[str]] = None
        if outputs is not None:
            if isinstance(outputs, LazyNode):
                outputs = [outputs]
            out_names = []
            for out in outputs:
                if not isinstance(out.source, str):
                    raise ValueError("graph outputs must be applied operators, not raw inputs")
                out_names.append(out.source)
        return ComputationGraph(list(self._nodes), outputs=out_names)


__all__ = ["GraphBuilder", "LazyNode"]

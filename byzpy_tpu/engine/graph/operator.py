"""Operator protocol: the schedulable unit of a computation graph.

API parity with the reference (ref: ``byzpy/engine/graph/operator.py:13-220``)
with the same three execution modes:

* plain ``compute`` — on TPU this is usually one jitted call over the whole
  stacked gradient matrix (the fast path);
* fan-out ``create_subtasks`` / ``reduce_subtasks`` — used when a pool of
  worker actors is attached and the op opts in (host-side work, or chunked
  device work across multiple chips without a mesh);
* iterative ``run_barriered_subtasks`` — per-iteration fan-out + barrier.
  TPU-native ops rarely need this (iteration lives inside ``lax`` loops);
  it exists for custom host-side iterative operators.
"""

from __future__ import annotations

import asyncio
import inspect
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, AsyncIterator, Iterable, Mapping, Optional, Sequence

from .subtask import SubTask

if TYPE_CHECKING:
    from .pool import ActorPool


@dataclass(frozen=True)
class OpContext:
    """Runtime metadata passed to each operator invocation."""

    node_name: str
    metadata: Mapping[str, Any] | None = None


class Operator:
    """Schedulable unit of work: a named compute with optional windowed or
    barriered subtask fan-out and pool affinity (the graph engine's common
    currency; aggregators/attacks/pre-aggregators all subclass this)."""

    name: str = "operator"
    supports_subtasks: bool = False
    supports_barriered_subtasks: bool = False
    #: max in-flight subtasks; None -> pool.size * 8; 0 -> unlimited window
    max_subtasks_inflight: int | None = None

    def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        raise NotImplementedError

    def create_subtasks(
        self, inputs: Mapping[str, Any], *, context: OpContext
    ) -> Iterable[SubTask]:
        return []

    def reduce_subtasks(
        self,
        partials: Sequence[Any],
        inputs: Mapping[str, Any],
        *,
        context: OpContext,
    ) -> Any:
        raise RuntimeError(f"Operator {self.name} does not implement reduce_subtasks().")

    async def run_barriered_subtasks(
        self, inputs: Mapping[str, Any], *, context: OpContext, pool: "ActorPool"
    ) -> Any:
        raise RuntimeError(f"Operator {self.name} does not implement barriered subtasks.")

    async def run(
        self,
        inputs: Mapping[str, Any],
        *,
        context: OpContext,
        pool: Optional["ActorPool"],
    ) -> Any:
        if self.supports_barriered_subtasks and pool is not None:
            return await _maybe_await(
                self.run_barriered_subtasks(inputs, context=context, pool=pool)
            )

        if self.supports_subtasks and pool is not None and pool.size > 1:
            subtasks = self.create_subtasks(inputs, context=context)
            partials = await self._run_subtasks(pool, subtasks, context)
            if partials:
                return await _maybe_await(
                    self.reduce_subtasks(partials, inputs, context=context)
                )

        return await _maybe_await(self.compute(inputs, context=context))

    async def _run_subtasks(
        self,
        pool: "ActorPool",
        subtasks: Iterable[SubTask],
        context: OpContext,
    ) -> list[Any]:
        metadata = context.metadata or {}
        affinities = metadata.get("worker_affinities")
        if affinities:
            subtasks = _with_affinities(subtasks, affinities)
        limit = self.max_subtasks_inflight
        if limit is None:
            limit = pool.size * 8
        semaphore = metadata.get("subtask_semaphore")
        return await run_subtasks_windowed(pool, subtasks, limit=limit, semaphore=semaphore)


async def run_subtasks_windowed(
    pool: "ActorPool",
    subtasks: Iterable[SubTask],
    *,
    limit: int = 0,
    semaphore: asyncio.Semaphore | None = None,
) -> list[Any]:
    """Run subtasks keeping at most ``limit`` in flight (0 = unbounded).

    Results are returned in submission order. The optional shared semaphore
    bounds in-flight subtasks *across* concurrently-running operators
    (ref: sliding-window refill loop at ``operator.py:96-179``; the
    release-on-failure discipline avoids the deadlock the reference guards
    against at ``operator.py:150-163``).
    """
    results: dict[int, Any] = {}
    in_flight: set[asyncio.Task] = set()
    idx = 0

    async def launch(i: int, st: SubTask) -> None:
        if semaphore is not None:
            await semaphore.acquire()
        try:
            results[i] = await pool.run_subtask(st)
        finally:
            if semaphore is not None:
                semaphore.release()

    iterator = iter(subtasks)
    try:
        while True:
            while iterator is not None and (limit <= 0 or len(in_flight) < limit):
                try:
                    st = next(iterator)
                except StopIteration:
                    iterator = None
                    break
                task = asyncio.ensure_future(launch(idx, st))
                in_flight.add(task)
                idx += 1
            if not in_flight:
                break
            done, in_flight = await asyncio.wait(
                in_flight, return_when=asyncio.FIRST_COMPLETED
            )
            # retrieve every exception in the batch, then raise the first, so
            # siblings don't emit "exception was never retrieved" warnings
            failures = [t.exception() for t in done if t.exception() is not None]
            if failures:
                raise failures[0]
    finally:
        if in_flight:
            for t in in_flight:
                t.cancel()
            # await cancellations so a shared semaphore is fully released
            # before control returns to concurrently-running operators
            await asyncio.gather(*in_flight, return_exceptions=True)
    return [results[i] for i in range(idx)]


def _with_affinities(
    subtasks: Iterable[SubTask], affinities: Sequence[str]
) -> AsyncIterator[SubTask] | Iterable[SubTask]:
    """Round-robin worker affinity assignment for subtasks lacking one
    (ref: ``operator.py:182-196``)."""

    def gen():
        i = 0
        for st in subtasks:
            if st.affinity is None and affinities:
                st = SubTask(
                    fn=st.fn,
                    args=st.args,
                    kwargs=st.kwargs,
                    name=st.name,
                    affinity=affinities[i % len(affinities)],
                    max_retries=st.max_retries,
                )
                i += 1
            yield st

    return gen()


class MessageTriggerOp(Operator):
    """Blocks until the scheduler delivers a message of ``message_type``,
    then returns it (optionally a single field)
    (ref: ``operator.py:199-217``). Requires a message-aware scheduler to
    inject a ``wait_for_message`` callable into metadata.
    """

    name = "message-trigger"

    def __init__(
        self, message_type: str, *, field: str | None = None, timeout: float | None = None
    ) -> None:
        self.message_type = message_type
        self.field = field
        self.timeout = timeout

    async def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        metadata = context.metadata or {}
        wait = metadata.get("wait_for_message")
        if wait is None:
            raise RuntimeError(
                "MessageTriggerOp requires a message-aware scheduler "
                "(metadata['wait_for_message'] missing)"
            )
        message = await wait(self.message_type, timeout=self.timeout)
        if self.field is not None:
            return message[self.field]
        return message


async def _maybe_await(value: Any) -> Any:
    if inspect.isawaitable(value):
        return await value
    return value


__all__ = ["OpContext", "Operator", "MessageTriggerOp", "run_subtasks_windowed"]

"""Convenience operators (ref: ``byzpy/engine/graph/ops.py:10-92``)."""

from __future__ import annotations

import inspect
from typing import Any, Callable, Mapping, Optional

from .graph import ComputationGraph, GraphInput, GraphNode
from .operator import OpContext, Operator
from .subtask import SubTask


class CallableOp(Operator):
    """Wraps a plain (sync or async) callable as an inline operator.

    The callable receives the node's resolved inputs as keyword arguments.
    """

    def __init__(self, fn: Callable[..., Any], *, name: Optional[str] = None) -> None:
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "callable-op")

    async def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        result = self.fn(**inputs)
        if inspect.isawaitable(result):
            result = await result
        return result


class RemoteCallableOp(Operator):
    """Runs a callable as a single subtask on the pool (one worker hop)."""

    supports_subtasks = True

    def __init__(
        self,
        fn: Callable[..., Any],
        *,
        name: Optional[str] = None,
        affinity: Optional[str] = None,
        max_retries: int = 0,
        cache_fn: bool = True,
    ) -> None:
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "remote-callable-op")
        self.affinity = affinity
        self.max_retries = max_retries
        self.cache_fn = cache_fn

    def create_subtasks(self, inputs: Mapping[str, Any], *, context: OpContext):
        yield SubTask(
            fn=self.fn,
            kwargs=dict(inputs),
            name=self.name,
            affinity=self.affinity,
            max_retries=self.max_retries,
            cache_fn=self.cache_fn,
        )

    def reduce_subtasks(self, partials, inputs, *, context: OpContext) -> Any:
        return partials[0]

    async def compute(self, inputs: Mapping[str, Any], *, context: OpContext) -> Any:
        # no pool (or single worker): run inline
        result = self.fn(**inputs)
        if inspect.isawaitable(result):
            result = await result
        return result


def make_single_operator_graph(
    op: Operator,
    *,
    input_keys: Optional[Mapping[str, str]] = None,
    node_name: str = "op",
) -> ComputationGraph:
    """Wrap one operator into a one-node graph. ``input_keys`` maps the
    operator's input keys to application input names (defaults to identity
    on ``op.input_key`` when present)."""
    if input_keys is None:
        key = getattr(op, "input_key", None)
        input_keys = {key: key} if key else {}
    inputs = {k: GraphInput(v) for k, v in input_keys.items()}
    return ComputationGraph([GraphNode(name=node_name, op=op, inputs=inputs)])


__all__ = ["CallableOp", "RemoteCallableOp", "make_single_operator_graph"]

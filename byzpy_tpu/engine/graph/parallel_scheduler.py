"""Concurrent DAG scheduler
(ref: ``byzpy/engine/graph/parallel_scheduler.py:19-275``).

Tracks in-degrees and launches every ready node as its own task, bounded by
``max_concurrent_nodes``; a shared semaphore bounds total in-flight subtasks
across concurrently-running operators (``max_pending_subtasks``, default
``pool.size * 8``).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Mapping, Optional

from .graph import ComputationGraph, GraphInput
from .operator import OpContext
from .pool import ActorPool
from .scheduler import MessageSource


class ParallelScheduler:
    """Topological wave scheduler: runs every ready node of a ComputationGraph concurrently on the pool, equivalent to NodeScheduler on any DAG (fuzz-pinned)."""
    def __init__(
        self,
        graph: ComputationGraph,
        *,
        pool: Optional[ActorPool] = None,
        metadata: Optional[Mapping[str, Any]] = None,
        max_concurrent_nodes: int = 0,
        max_pending_subtasks: Optional[int] = None,
    ) -> None:
        self.graph = graph
        self.pool = pool
        self._metadata = dict(metadata or {})
        self.max_concurrent_nodes = max_concurrent_nodes
        if max_pending_subtasks is None and pool is not None:
            max_pending_subtasks = pool.size * 8
        self.max_pending_subtasks = max_pending_subtasks

    async def run(self, inputs: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
        inputs = dict(inputs or {})
        results: Dict[str, Any] = {}
        metadata = dict(self._metadata)
        if self.pool is not None:
            metadata.setdefault("pool_size", self.pool.size)
        if self.max_pending_subtasks:
            metadata.setdefault(
                "subtask_semaphore", asyncio.Semaphore(self.max_pending_subtasks)
            )

        indegree: Dict[str, int] = {}
        consumers: Dict[str, list[str]] = {name: [] for name in self.graph.nodes}
        for name in self.graph.nodes:
            deps = self.graph.dependencies(name)
            indegree[name] = len(deps)
            for dep in deps:
                consumers[dep].append(name)

        node_gate = (
            asyncio.Semaphore(self.max_concurrent_nodes)
            if self.max_concurrent_nodes > 0
            else None
        )
        done_events: Dict[str, asyncio.Event] = {
            name: asyncio.Event() for name in self.graph.nodes
        }

        async def resolve(src: Any, node_name: str, key: str) -> Any:
            if isinstance(src, GraphInput):
                if src.name not in inputs:
                    raise KeyError(
                        f"node {node_name!r} requires application input {src.name!r}"
                    )
                return inputs[src.name]
            if isinstance(src, MessageSource):
                raise RuntimeError(
                    "message inputs require MessageAwareNodeScheduler, not ParallelScheduler"
                )
            if isinstance(src, str):
                if src in self.graph.nodes:
                    await done_events[src].wait()
                    return results[src]
                if src in inputs:
                    return inputs[src]
                raise KeyError(
                    f"node {node_name!r} input {key!r} references unknown source {src!r}"
                )
            raise TypeError(f"invalid input source {src!r}")

        async def run_node(name: str) -> None:
            node = self.graph.node(name)
            node_inputs = {
                key: await resolve(src, name, key) for key, src in node.inputs.items()
            }
            context = OpContext(node_name=name, metadata=metadata)
            if node_gate is not None:
                async with node_gate:
                    results[name] = await node.op.run(
                        node_inputs, context=context, pool=self.pool
                    )
            else:
                results[name] = await node.op.run(
                    node_inputs, context=context, pool=self.pool
                )
            done_events[name].set()

        tasks = [asyncio.ensure_future(run_node(name)) for name in self.graph.nodes]
        try:
            await asyncio.gather(*tasks)
        finally:
            for t in tasks:
                t.cancel()
        return {name: results[name] for name in self.graph.outputs}


__all__ = ["ParallelScheduler"]

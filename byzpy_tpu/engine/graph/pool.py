"""Heterogeneous worker-actor pool (ref: ``byzpy/engine/graph/pool.py:37-374``).

An ``ActorPool`` owns worker actors built from one or more
``ActorPoolConfig``s — e.g. 4 TPU-chip actors plus 2 CPU process actors —
and schedules ``SubTask``s onto them with capability-aware affinity,
rotation, waiter futures, and per-subtask retry.

Worker capabilities are inferred from the backend spec (``tpu`` backends get
``{"tpu"}``; thread/process get ``{"cpu"}``) and an affinity on a subtask
("tpu"/"cpu") steers it to a matching worker. For in-process backends the
subtask callable is passed by reference (zero-copy args, device arrays
stay resident); for process/remote backends it ships as cloudpickle bytes
with an LRU cache on the worker so hot functions deserialize once.
"""

from __future__ import annotations

import asyncio
import itertools
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import cloudpickle

from ..actor.base import ActorRef
from ..actor.factory import resolve_backend
from .subtask import SubTask

_IN_PROCESS_SCHEMES = {"thread", "tpu"}


def _infer_capabilities(backend_spec: str) -> frozenset[str]:
    if backend_spec.startswith("tpu"):
        return frozenset({"tpu"})
    if backend_spec.startswith("tcp://"):
        return frozenset({"cpu", "remote"})
    return frozenset({"cpu"})


@dataclass(frozen=True)
class ActorPoolConfig:
    # None means "the configured default" (configs.actor.set_actor/get_actor)
    backend: Optional[str] = None
    count: int = 1
    capabilities: Optional[Sequence[str]] = None
    name: Optional[str] = None

    def resolved_backend(self) -> str:
        if self.backend is not None:
            return self.backend
        from ...configs.actor import get_actor

        return get_actor()

    def resolved_capabilities(
        self, backend: Optional[str] = None
    ) -> frozenset[str]:
        """Capabilities for ``backend`` (pass the value from one
        ``resolved_backend()`` call — resolving twice races the mutable
        config default)."""
        if self.capabilities is not None:
            return frozenset(self.capabilities)
        return _infer_capabilities(backend or self.resolved_backend())


class _SubTaskWorker:
    """Generic executor object constructed inside every worker backend."""

    def __init__(self) -> None:
        self._fn_cache: OrderedDict[bytes, Any] = OrderedDict()

    def execute(self, fn, args, kwargs):
        return fn(*args, **kwargs)

    def execute_blob(self, blob: bytes, args, kwargs):
        fn = self._fn_cache.get(blob)
        if fn is None:
            fn = cloudpickle.loads(blob)
            self._fn_cache[blob] = fn
            while len(self._fn_cache) > 64:
                self._fn_cache.popitem(last=False)
        else:
            self._fn_cache.move_to_end(blob)
        return fn(*args, **kwargs)


class _PoolWorker:
    def __init__(self, name: str, backend_spec: str, capabilities: frozenset[str]) -> None:
        self.name = name
        self.backend_spec = backend_spec
        self.capabilities = capabilities
        self.backend = resolve_backend(backend_spec, actor_id=name)
        self.ref = ActorRef(self.backend)
        self._in_process = self.backend.scheme in _IN_PROCESS_SCHEMES
        # id(fn) -> (fn, blob): holding fn pins the id so it can't be reused
        # by a GC'd-then-reallocated callable (which would serve a stale blob).
        self._blob_cache: OrderedDict[int, tuple[Any, bytes]] = OrderedDict()

    async def start(self) -> None:
        await self.backend.start()
        await self.backend.construct(_SubTaskWorker)

    async def run(self, st: SubTask) -> Any:
        if self._in_process:
            return await self.backend.call("execute", st.fn, tuple(st.args), dict(st.kwargs))
        if not st.cache_fn:
            # stateful fn: fresh pickle every run so the worker sees current
            # state (the worker-side cache keys on blob bytes, so changed
            # state means a changed key — stale entries just age out)
            blob = cloudpickle.dumps(st.fn)
            return await self.backend.call(
                "execute_blob", blob, tuple(st.args), dict(st.kwargs)
            )
        entry = self._blob_cache.get(id(st.fn))
        if entry is not None and entry[0] is st.fn:
            blob = entry[1]
            self._blob_cache.move_to_end(id(st.fn))
        else:
            blob = cloudpickle.dumps(st.fn)
            self._blob_cache[id(st.fn)] = (st.fn, blob)
            while len(self._blob_cache) > 256:
                self._blob_cache.popitem(last=False)
        return await self.backend.call("execute_blob", blob, tuple(st.args), dict(st.kwargs))

    async def close(self) -> None:
        await self.backend.close()


class ActorPool:
    """Pool of worker actors with affinity-aware acquisition."""

    _pool_ids = itertools.count()

    def __init__(
        self, configs: Sequence[ActorPoolConfig] | ActorPoolConfig | None = None
    ) -> None:
        if configs is None:
            configs = [ActorPoolConfig()]
        if isinstance(configs, ActorPoolConfig):
            configs = [configs]
        pool_id = next(self._pool_ids)
        self._workers: List[_PoolWorker] = []
        for ci, cfg in enumerate(configs):
            backend = cfg.resolved_backend()
            caps = cfg.resolved_capabilities(backend)
            for wi in range(cfg.count):
                base = cfg.name or f"pool{pool_id}-{backend.split('://')[0].replace(':', '_')}"
                name = f"{base}-{ci}-{wi}"
                self._workers.append(_PoolWorker(name, backend, caps))
        self._free: List[_PoolWorker] = []
        self._waiters: List[tuple[Optional[str], asyncio.Future]] = []
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        await asyncio.gather(*(w.start() for w in self._workers))
        self._free = list(self._workers)
        self._started = True

    async def close(self) -> None:
        if not self._started:
            return
        await asyncio.gather(*(w.close() for w in self._workers), return_exceptions=True)
        self._free.clear()
        for _, fut in self._waiters:
            if not fut.done():
                fut.cancel()
        self._waiters.clear()
        self._started = False

    async def __aenter__(self) -> "ActorPool":
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    # -- introspection ------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._workers)

    @property
    def worker_names(self) -> List[str]:
        return [w.name for w in self._workers]

    @property
    def worker_capabilities(self) -> Dict[str, frozenset[str]]:
        return {w.name: w.capabilities for w in self._workers}

    def worker(self, name: str) -> _PoolWorker:
        for w in self._workers:
            if w.name == name:
                return w
        raise KeyError(f"no worker named {name!r}")

    def has_capability(self, capability: str) -> bool:
        return any(capability in w.capabilities for w in self._workers)

    # -- scheduling ---------------------------------------------------------

    async def run_subtask(self, st: SubTask) -> Any:
        """Run one subtask with affinity-aware placement and retry
        (ref: retry loop at ``pool.py:202-219``)."""
        if not self._started:
            raise RuntimeError("pool not started")
        attempts = max(0, int(st.max_retries)) + 1
        last_exc: BaseException | None = None
        for _ in range(attempts):
            worker = await self._acquire(st.affinity)
            try:
                return await worker.run(st)
            except asyncio.CancelledError:
                raise  # cancellation is not a retryable failure
            except BaseException as exc:  # noqa: BLE001 - retried/reported
                last_exc = exc
            finally:
                self._release(worker)
        raise last_exc  # type: ignore[misc]

    async def run_many(self, subtasks: Sequence[SubTask]) -> List[Any]:
        return list(await asyncio.gather(*(self.run_subtask(st) for st in subtasks)))

    async def _acquire(self, affinity: Optional[str]) -> _PoolWorker:
        # Only honor an affinity some worker can actually satisfy; otherwise
        # any worker may take the subtask (ref: pool.py:224-273).
        effective = affinity if affinity and self.has_capability(affinity) else None
        while True:
            for i, w in enumerate(self._free):
                if effective is None or effective in w.capabilities:
                    # rotation: take from the front, re-append on release
                    return self._free.pop(i)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters.append((effective, fut))
            worker = await fut
            if effective is None or effective in worker.capabilities:
                return worker
            # woken with a non-matching worker (race) — put it back, retry
            self._release(worker)

    def _release(self, worker: _PoolWorker) -> None:
        # drop dead waiters (e.g. cancelled by wait_for) as we scan
        self._waiters = [(aff, fut) for aff, fut in self._waiters if not fut.done()]
        for i, (aff, fut) in enumerate(self._waiters):
            if aff is None or aff in worker.capabilities:
                self._waiters.pop(i)
                fut.set_result(worker)
                return
        self._free.append(worker)

    # -- channels -----------------------------------------------------------

    async def open_channel(self, name: str) -> "ActorPoolChannel":
        """Bind a named mailbox on every worker
        (ref: ``pool.py:164-189, 334-374``)."""
        for w in self._workers:
            await w.backend.chan_open(name)
        return ActorPoolChannel(self, name)


class ActorPoolChannel:
    """Named channel spanning all pool workers: any worker (or the
    coordinator) can send to any worker's mailbox by name."""

    def __init__(self, pool: ActorPool, name: str) -> None:
        self._pool = pool
        self.name = name

    async def send(self, sender: Optional[str], recipient: str, payload: Any) -> None:
        worker = self._pool.worker(recipient)
        await worker.backend.chan_put(
            self.name, {"sender": sender, "payload": payload}
        )

    async def broadcast(self, sender: Optional[str], payload: Any) -> None:
        await asyncio.gather(
            *(
                self.send(sender, w.name, payload)
                for w in self._pool._workers
                if w.name != sender
            )
        )

    async def recv(self, worker_name: str) -> Any:
        worker = self._pool.worker(worker_name)
        return await worker.backend.chan_get(self.name)


__all__ = ["ActorPoolConfig", "ActorPool", "ActorPoolChannel"]

"""Sequential and message-aware graph schedulers
(ref: ``byzpy/engine/graph/scheduler.py:12-269``).

``NodeScheduler`` executes a ``ComputationGraph`` in topological order,
resolving node inputs from application inputs, upstream results, or
messages. ``MessageAwareNodeScheduler`` adds an inbox: ``deliver_message``
wakes ``wait_for_message`` waiters (or caches until asked), which is how
decentralized nodes trigger pipelines off gossip traffic.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Mapping, Optional

from .graph import ComputationGraph, GraphInput
from .operator import OpContext
from .pool import ActorPool


@dataclass(frozen=True)
class MessageSource:
    """Graph-input placeholder resolved by waiting for a message."""

    message_type: str
    field: Optional[str] = None
    timeout: Optional[float] = None


class NodeScheduler:
    """Runs graph nodes sequentially in topological order."""

    def __init__(
        self,
        graph: ComputationGraph,
        *,
        pool: Optional[ActorPool] = None,
        metadata: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.graph = graph
        self.pool = pool
        self._metadata = dict(metadata or {})

    def _context_metadata(self) -> Dict[str, Any]:
        md = dict(self._metadata)
        if self.pool is not None:
            md.setdefault("pool_size", self.pool.size)
            md.setdefault("worker_affinities", [])
        return md

    async def _resolve_input(self, src: Any, inputs: Mapping[str, Any], results: Dict[str, Any], node_name: str, key: str) -> Any:
        if isinstance(src, GraphInput):
            if src.name not in inputs:
                raise KeyError(
                    f"node {node_name!r} requires application input {src.name!r}"
                )
            return inputs[src.name]
        if isinstance(src, MessageSource):
            return await self._resolve_message(src)
        if isinstance(src, str):
            if src in results:
                return results[src]
            if src in inputs:
                return inputs[src]
            raise KeyError(
                f"node {node_name!r} input {key!r} references unknown source {src!r}"
            )
        raise TypeError(f"invalid input source {src!r} for node {node_name!r}")

    async def _resolve_message(self, src: MessageSource) -> Any:
        raise RuntimeError(
            "graph uses message inputs; run it on a MessageAwareNodeScheduler"
        )

    async def run(self, inputs: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
        inputs = dict(inputs or {})
        results: Dict[str, Any] = {}
        metadata = self._context_metadata()
        for node in self.graph.nodes_in_order():
            node_inputs = {
                key: await self._resolve_input(src, inputs, results, node.name, key)
                for key, src in node.inputs.items()
            }
            context = OpContext(node_name=node.name, metadata=metadata)
            results[node.name] = await node.op.run(
                node_inputs, context=context, pool=self.pool
            )
        return {name: results[name] for name in self.graph.outputs}


class MessageAwareNodeScheduler(NodeScheduler):
    """NodeScheduler + inbox with waiter futures and a type-keyed cache.

    The cache is bounded per message type (``max_cached_per_type``): a node
    that consumes some traffic only through handlers would otherwise
    accumulate every delivered message forever. On overflow the oldest
    message of that type is dropped (and logged at debug level).
    """

    def __init__(
        self,
        graph: ComputationGraph,
        *,
        pool: Optional[ActorPool] = None,
        metadata: Optional[Mapping[str, Any]] = None,
        max_cached_per_type: int = 1024,
    ) -> None:
        super().__init__(graph, pool=pool, metadata=metadata)
        self._cached: Dict[str, Deque[Any]] = {}
        self._waiters: Dict[str, List[asyncio.Future]] = {}
        self._max_cached = max(1, int(max_cached_per_type))

    def swap_graph(self, graph: ComputationGraph) -> None:
        """Replace the scheduled graph (decentralized nodes swap per-pipeline
        graphs into one scheduler; ref: ``decentralized.py:44-67``)."""
        self.graph = graph

    # -- messaging ----------------------------------------------------------

    async def deliver_message(self, message_type: str, message: Any) -> None:
        waiters = self._waiters.get(message_type)
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result(message)
                return
        cache = self._cached.setdefault(
            message_type, deque(maxlen=self._max_cached)
        )
        if len(cache) == self._max_cached:
            logging.getLogger(__name__).debug(
                "message cache for %r full (%d); dropping oldest",
                message_type, self._max_cached,
            )
        cache.append(message)

    async def wait_for_message(
        self, message_type: str, *, timeout: Optional[float] = None
    ) -> Any:
        cached = self._cached.get(message_type)
        if cached:
            return cached.popleft()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(message_type, []).append(fut)
        if timeout is None:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(
                f"timed out after {timeout}s waiting for message {message_type!r}"
            ) from None

    def pending_message_count(self, message_type: str) -> int:
        return len(self._cached.get(message_type, []))

    # -- overrides ----------------------------------------------------------

    async def _resolve_message(self, src: MessageSource) -> Any:
        message = await self.wait_for_message(src.message_type, timeout=src.timeout)
        if src.field is not None:
            return message[src.field]
        return message

    def _context_metadata(self) -> Dict[str, Any]:
        md = super()._context_metadata()
        md.setdefault("wait_for_message", self.wait_for_message)
        return md


__all__ = ["MessageSource", "NodeScheduler", "MessageAwareNodeScheduler"]

"""Execution sessions with intermediate-result caching
(ref: ``byzpy/engine/graph/session.py:27-416``).

``ExecutionSession.execute`` skips nodes whose results are already cached
(their cached values feed downstream nodes as plain inputs), runs the
remainder on a ``ParallelScheduler``, and caches every intermediate.
``execute_async`` returns an ``ExecutionFuture`` for non-blocking graphs.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Mapping, Optional, Sequence

from .graph import ComputationGraph, GraphNode
from .parallel_scheduler import ParallelScheduler
from .pool import ActorPool


class ExecutionFuture:
    """Handle to an in-flight graph execution (done/cancel/wait/result)."""

    def __init__(self, task: "asyncio.Task[Dict[str, Any]]") -> None:
        self._task = task

    def done(self) -> bool:
        return self._task.done()

    def cancel(self) -> bool:
        return self._task.cancel()

    async def wait(self, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(asyncio.shield(self._task), timeout)
        except asyncio.TimeoutError:
            return False
        except asyncio.CancelledError:
            # only swallow when it's the graph task that was cancelled;
            # cancellation of the *waiting* coroutine must propagate
            if not self._task.cancelled():
                raise
        except Exception:
            pass  # task failure is surfaced by result(), not wait()
        return self._task.done()

    async def result(self) -> Dict[str, Any]:
        return await self._task


class ExecutionSession:
    """Caches node results across executions of (sub)graphs."""

    def __init__(
        self,
        *,
        pool: Optional[ActorPool] = None,
        metadata: Optional[Mapping[str, Any]] = None,
        max_concurrent_nodes: int = 0,
    ) -> None:
        self.pool = pool
        self._metadata = dict(metadata or {})
        self._max_concurrent_nodes = max_concurrent_nodes
        self._cache: Dict[str, Any] = {}

    # -- cache management ---------------------------------------------------

    @property
    def cached_nodes(self) -> Sequence[str]:
        return list(self._cache.keys())

    def invalidate(self, names: Optional[Sequence[str]] = None) -> None:
        if names is None:
            self._cache.clear()
        else:
            for name in names:
                self._cache.pop(name, None)

    def seed(self, name: str, value: Any) -> None:
        """Pre-populate the cache (e.g. re-using a value across graphs)."""
        self._cache[name] = value

    # -- execution ----------------------------------------------------------

    async def execute(
        self,
        graph: ComputationGraph,
        inputs: Optional[Mapping[str, Any]] = None,
        *,
        use_cache: bool = True,
    ) -> Dict[str, Any]:
        inputs = dict(inputs or {})
        cached = {
            name: self._cache[name]
            for name in graph.nodes
            if use_cache and name in self._cache
        }
        remaining: list[GraphNode] = [
            node for name, node in graph.nodes.items() if name not in cached
        ]

        if remaining:
            # Cached upstream values are injected as plain inputs; the
            # scheduler resolves string sources from `inputs` when the name
            # is not a live graph node.
            sub = ComputationGraph(remaining, outputs=[n.name for n in remaining])
            scheduler = ParallelScheduler(
                sub,
                pool=self.pool,
                metadata=self._metadata,
                max_concurrent_nodes=self._max_concurrent_nodes,
            )
            fresh = await scheduler.run({**inputs, **cached})
            self._cache.update(fresh)
        return {
            name: self._cache[name] for name in graph.outputs if name in self._cache
        } | {name: cached[name] for name in graph.outputs if name in cached}

    def execute_async(
        self,
        graph: ComputationGraph,
        inputs: Optional[Mapping[str, Any]] = None,
        *,
        use_cache: bool = True,
    ) -> ExecutionFuture:
        task = asyncio.ensure_future(self.execute(graph, inputs, use_cache=use_cache))
        return ExecutionFuture(task)


__all__ = ["ExecutionSession", "ExecutionFuture"]

"""Schedulable unit of work (ref: ``byzpy/engine/graph/subtask.py:7-18``).

On TPU the typical subtask ``fn`` is a jit-compiled shard computation;
``affinity`` names a capability (``"tpu"``/``"cpu"``) so the pool can place
device work on device actors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional, Sequence


@dataclass(frozen=True)
class SubTask:
    fn: Callable[..., Any]
    args: Sequence[Any] = field(default_factory=tuple)
    kwargs: Mapping[str, Any] = field(default_factory=dict)
    name: Optional[str] = None
    affinity: Optional[str] = None
    max_retries: int = 0
    # False for fns closing over mutable state (e.g. bound methods of a
    # training node): the pool must re-serialize on every run instead of
    # caching the first pickle, or workers see frozen state forever
    cache_fn: bool = True


__all__ = ["SubTask"]

"""Prototype-lineage runtime (parity with the reference's earlier stack:
``byzpy/engine/node_runner.py``, ``node_cluster.py``, ``engine/transport/``,
``engine/parameter_server/runner.py`` — SURVEY §2 "Prototype runners").

The modern runtime is ``byzpy_tpu.engine.node`` (DecentralizedNode +
contexts); these simpler pieces are kept, as the reference keeps its own,
for minimal step-loop demos: polled mailbox transports and a
process-per-node runner with cmd/result queues.
"""

from .runner import NodeCluster, NodeRunner, StepParameterServer
from .transport import LocalMailbox, TcpMailbox, Transport

__all__ = [
    "Transport",
    "LocalMailbox",
    "TcpMailbox",
    "NodeRunner",
    "NodeCluster",
    "StepParameterServer",
]

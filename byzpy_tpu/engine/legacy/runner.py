"""Process-per-node step loop (parity: ``byzpy/engine/node_runner.py:33-174``,
``node_cluster.py:16-60``, ``engine/parameter_server/runner.py`` — the
reference's earlier prototype runtime, SURVEY §2 "Prototype runners").

A :class:`NodeRunner` hosts one node object in a spawned child process and
drives it by commands: ``step`` invokes ``node.step(payload)`` (returning
the result to the parent), ``call`` invokes an arbitrary method,
``deliver`` hands a message to ``node.handle_message``. Auto-stepping runs
``step`` continuously without parent prompts (ref: node_runner.py:33-88).

The children pin the CPU platform (a TPU chip admits one process); the
modern per-chip runtime is ``byzpy_tpu.engine.node``.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import cloudpickle


def _runner_main(blob: bytes, cmd_q, result_q, inbox_q, auto_step: bool,
                 step_interval: float, platform: str) -> None:
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    node_factory = cloudpickle.loads(blob)
    node = node_factory()
    running = True
    while running:
        progressed = False
        try:
            cmd = cmd_q.get_nowait()
            progressed = True
        except queue.Empty:
            cmd = None
        if cmd is not None:
            kind, req_id, payload = cmd
            try:
                if kind == "stop":
                    running = False
                    result = None
                elif kind == "step":
                    result = node.step(payload) if payload is not None else node.step()
                elif kind == "call":
                    method, args, kwargs = payload
                    result = getattr(node, method)(*args, **kwargs)
                else:
                    raise ValueError(f"unknown cmd {kind!r}")
                result_q.put((req_id, True, result))
            except Exception as exc:  # noqa: BLE001 — report to parent
                result_q.put((req_id, False, repr(exc)))
        try:
            msg = inbox_q.get_nowait()
            progressed = True
        except queue.Empty:
            msg = None
        if msg is not None and hasattr(node, "handle_message"):
            node.handle_message(msg)
        if auto_step and not progressed:
            try:
                node.step()
            except Exception:  # noqa: BLE001 — auto loop keeps running
                pass
            time.sleep(step_interval)
        elif not progressed:
            time.sleep(0.001)


class NodeRunner:
    """Parent-side handle for a node stepped in a child process."""

    def __init__(
        self,
        node_factory: Callable[[], Any],
        *,
        auto_step: bool = False,
        step_interval: float = 0.01,
        child_platform: str = "cpu",
    ) -> None:
        self._blob = cloudpickle.dumps(node_factory)
        self._auto_step = auto_step
        self._step_interval = step_interval
        self._platform = child_platform
        ctx = mp.get_context("spawn")
        self._cmd = ctx.Queue()
        self._result = ctx.Queue()
        self._inbox = ctx.Queue()
        self._ctx = ctx
        self._proc: Optional[mp.process.BaseProcess] = None
        self._done: Dict[str, Any] = {}  # results drained for other req_ids

    def start(self) -> None:
        if self._proc is not None:
            return
        self._proc = self._ctx.Process(
            target=_runner_main,
            args=(self._blob, self._cmd, self._result, self._inbox,
                  self._auto_step, self._step_interval, self._platform),
            daemon=True,
        )
        patch = {"JAX_PLATFORMS": self._platform, "PALLAS_AXON_POOL_IPS": ""}
        saved = {k: os.environ.get(k) for k in patch}
        os.environ.update(patch)
        try:
            self._proc.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def submit(self, kind: str, payload: Any = None) -> str:
        """Enqueue a command without waiting; returns the request id for
        :meth:`wait` (lets a cluster pipeline N children concurrently)."""
        if self._proc is None or not self._proc.is_alive():
            raise ConnectionError("runner is not running")
        req_id = uuid.uuid4().hex
        self._cmd.put((kind, req_id, payload))
        return req_id

    def wait(self, req_id: str, timeout: float = 60.0) -> Any:
        deadline = time.monotonic() + timeout
        cached = self._done.pop(req_id, None)
        if cached is not None:
            ok, result = cached
            if not ok:
                raise RuntimeError(f"node raised: {result}")
            return result
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"runner request {req_id} timed out")
            try:
                rid, ok, result = self._result.get(timeout=min(remaining, 0.5))
            except queue.Empty:
                if self._proc is None or not self._proc.is_alive():
                    raise ConnectionError("runner died") from None
                continue
            if rid != req_id:
                # out-of-order completion of another outstanding request
                self._done[rid] = (ok, result)
                continue
            if not ok:
                raise RuntimeError(f"node raised: {result}")
            return result

    def _request(self, kind: str, payload: Any = None, timeout: float = 60.0) -> Any:
        return self.wait(self.submit(kind, payload), timeout=timeout)

    def step(self, payload: Any = None) -> Any:
        return self._request("step", payload)

    def call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        return self._request("call", (method, args, kwargs))

    def deliver(self, message: Any) -> None:
        self._inbox.put(message)

    def stop(self) -> None:
        if self._proc is None:
            return
        try:
            self._request("stop", timeout=5.0)
        except Exception:  # noqa: BLE001 — force below
            pass
        self._proc.join(timeout=5)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)
        self._proc = None


class NodeCluster:
    """A set of named runners with broadcast helpers
    (ref: ``node_cluster.py:16-60``)."""

    def __init__(self) -> None:
        self._runners: Dict[str, NodeRunner] = {}

    def add(self, name: str, runner: NodeRunner) -> None:
        if name in self._runners:
            raise ValueError(f"duplicate runner {name!r}")
        self._runners[name] = runner

    def runner(self, name: str) -> NodeRunner:
        return self._runners[name]

    @property
    def names(self) -> List[str]:
        return sorted(self._runners)

    def start_all(self) -> None:
        started = []
        try:
            for runner in self._runners.values():
                runner.start()
                started.append(runner)
        except BaseException:
            for runner in reversed(started):
                runner.stop()
            raise

    def step_all(self, payload: Any = None) -> Dict[str, Any]:
        """Step every runner concurrently: all commands go out before any
        result is awaited, so N children overlap instead of serializing."""
        pending = {
            name: r.submit("step", payload) for name, r in self._runners.items()
        }
        return {
            name: self._runners[name].wait(rid) for name, rid in pending.items()
        }

    def stop_all(self) -> None:
        for runner in self._runners.values():
            runner.stop()

    def __enter__(self) -> "NodeCluster":
        self.start_all()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop_all()


class StepParameterServer:
    """Prototype PS over runners (ref: ``engine/parameter_server/runner.py``):
    each round steps every runner (collecting gradients), aggregates with
    the provided function, and pushes the update back via ``call``."""

    def __init__(
        self,
        cluster: NodeCluster,
        aggregate_fn: Callable[[Sequence[Any]], Any],
        *,
        apply_method: str = "apply_update",
    ) -> None:
        self.cluster = cluster
        self.aggregate_fn = aggregate_fn
        self.apply_method = apply_method
        self.rounds_completed = 0

    def round(self) -> Any:
        grads = list(self.cluster.step_all().values())
        update = self.aggregate_fn(grads)
        for name in self.cluster.names:
            self.cluster.runner(name).call(self.apply_method, update)
        self.rounds_completed += 1
        return update


__all__ = ["NodeRunner", "NodeCluster", "StepParameterServer"]

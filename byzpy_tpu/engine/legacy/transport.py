"""Polled mailbox transports (parity: ``byzpy/engine/transport/`` —
``base.py`` ABC, ``local.py`` in-process queues, ``tcp_simple.py``
thread-polled TCP mailboxes, SURVEY §2).

A mailbox is the simplest possible endpoint: ``send(target, payload)``
delivers a pickled message into the target's queue; ``recv(timeout)``
polls it. No topology, no handlers — the step-loop demos poll explicitly.
"""

from __future__ import annotations

import abc
import queue
import socket
import struct
import threading
from typing import Any, ClassVar, Dict, Optional, Tuple

import cloudpickle

_HEADER = struct.Struct(">I")


class Transport(abc.ABC):
    """Mailbox endpoint (ref: ``transport/base.py``)."""

    name: str

    @abc.abstractmethod
    def send(self, target: str, payload: Any) -> None: ...

    @abc.abstractmethod
    def recv(self, timeout: Optional[float] = None) -> Any:
        """Next message, or raise ``queue.Empty`` on timeout."""

    @abc.abstractmethod
    def close(self) -> None: ...


class LocalMailbox(Transport):
    """In-process mailboxes over a class-level registry
    (ref: ``transport/local.py``)."""

    _registry: ClassVar[Dict[str, "LocalMailbox"]] = {}

    def __init__(self, name: str) -> None:
        if name in self._registry:
            raise ValueError(f"mailbox {name!r} already exists")
        self.name = name
        self._q: queue.Queue = queue.Queue()
        self._registry[name] = self

    @classmethod
    def clear_registry(cls) -> None:
        cls._registry.clear()

    def send(self, target: str, payload: Any) -> None:
        box = self._registry.get(target)
        if box is None:
            raise ConnectionError(f"no mailbox {target!r}")
        box._q.put((self.name, payload))

    def recv(self, timeout: Optional[float] = None) -> Any:
        return self._q.get(timeout=timeout)

    def close(self) -> None:
        self._registry.pop(self.name, None)


class TcpMailbox(Transport):
    """Thread-polled TCP mailbox (ref: ``transport/tcp_simple.py:34-80``):
    an accept-loop thread drains length-prefixed cloudpickle frames into a
    local queue; ``send`` opens a connection per message. ``peers`` maps
    mailbox names to ``(host, port)``."""

    def __init__(
        self,
        name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        peers: Optional[Dict[str, Tuple[str, int]]] = None,
    ) -> None:
        self.name = name
        self.peers: Dict[str, Tuple[str, int]] = dict(peers or {})
        self._q: queue.Queue = queue.Queue()
        self._server = socket.create_server((host, port))
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()[:2]
        self._closing = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def add_peer(self, name: str, address: Tuple[str, int]) -> None:
        self.peers[name] = address

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._server.accept()
            except (socket.timeout, OSError):
                continue
            # a stalled/half-open peer must not wedge the serial accept
            # loop: bound every read on this connection
            conn.settimeout(5.0)
            try:
                with conn:
                    header = _recv_exact(conn, _HEADER.size)
                    if header is None:
                        continue
                    (length,) = _HEADER.unpack(header)
                    body = _recv_exact(conn, length)
                    if body is None:
                        continue
                    self._q.put(cloudpickle.loads(body))
            except (socket.timeout, OSError):
                continue

    def send(self, target: str, payload: Any) -> None:
        address = self.peers.get(target)
        if address is None:
            raise ConnectionError(f"no address for mailbox {target!r}")
        body = cloudpickle.dumps((self.name, payload))
        with socket.create_connection(address, timeout=10) as conn:
            conn.sendall(_HEADER.pack(len(body)) + body)

    def recv(self, timeout: Optional[float] = None) -> Any:
        return self._q.get(timeout=timeout)

    def close(self) -> None:
        self._closing.set()
        self._thread.join(timeout=2)
        self._server.close()


def _recv_exact(conn: socket.socket, nbytes: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < nbytes:
        chunk = conn.recv(nbytes - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


__all__ = ["Transport", "LocalMailbox", "TcpMailbox"]

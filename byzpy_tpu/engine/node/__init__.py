from .actors import ByzantineNodeActor, HonestNodeActor, NodeActor
from .application import (
    ByzantineNodeApplication,
    HonestNodeApplication,
    NodeApplication,
)
from .base import ByzantineNode, HonestNode, Node
from .distributed import DistributedByzantineNode, DistributedHonestNode
from .mesh_context import MeshRemoteContext
from .remote import (
    RemoteClientContext,
    RemoteNodeClient,
    RemoteNodeServer,
    ServerNodeContext,
)
from .cluster import DecentralizedCluster
from .liveness import HeartbeatMonitor, PeerLiveness
from .context import InProcessContext, NodeContext
from .decentralized import DecentralizedNode
from .process_context import ProcessContext
from .router import MessageRouter

__all__ = [
    "Node",
    "HonestNode",
    "ByzantineNode",
    "NodeActor",
    "HonestNodeActor",
    "ByzantineNodeActor",
    "NodeApplication",
    "HonestNodeApplication",
    "ByzantineNodeApplication",
    "DistributedHonestNode",
    "DistributedByzantineNode",
    "RemoteNodeServer",
    "RemoteNodeClient",
    "RemoteClientContext",
    "ServerNodeContext",
    "MeshRemoteContext",
    "NodeContext",
    "InProcessContext",
    "ProcessContext",
    "DecentralizedNode",
    "DecentralizedCluster",
    "HeartbeatMonitor",
    "PeerLiveness",
    "MessageRouter",
]

from .actors import ByzantineNodeActor, HonestNodeActor, NodeActor
from .base import ByzantineNode, HonestNode, Node
from .cluster import DecentralizedCluster
from .context import InProcessContext, NodeContext
from .decentralized import DecentralizedNode
from .process_context import ProcessContext
from .router import MessageRouter

__all__ = [
    "Node",
    "HonestNode",
    "ByzantineNode",
    "NodeActor",
    "HonestNodeActor",
    "ByzantineNodeActor",
    "NodeContext",
    "InProcessContext",
    "ProcessContext",
    "DecentralizedNode",
    "DecentralizedCluster",
    "MessageRouter",
]
